"""Hyperparameter search: Sobol random search + GP Bayesian optimization.

Reference: photon-lib hyperparameter/search/RandomSearch.scala (Sobol
low-discrepancy draws in [0,1]^d, optional per-index discretization,
findWithPriors / findWithPriorObservations / find protocol) and
GaussianProcessSearch.scala (EI over a Sobol candidate pool, observation
and prior-observation accumulation, mean-centered evals, fallback to
random draws until observations exceed the parameter count).

The evaluation function MINIMIZES its value (negate bigger-is-better
metrics in the glue — reference convention).

Determinism contract: the primary Sobol stream serves ONLY emitted
candidates. Scrambled Sobol is position-stateful with
``random(a) + random(b) == random(a + b)`` element-wise, so the emitted
candidate sequence for a given seed is identical across runs and across
ask-batch sizes. The GP's acquisition candidate pool draws from a
SEPARATE derived-seed stream (``draw_pool``) — pooling used to consume
the primary stream, which made the candidate sequence depend on when
the GP kicked in.

Batch protocol for lane-batched evaluation (optim/batched): ``ask(q)``
returns q candidates to evaluate as one batched solve; ``tell``
records the observed values. The sequential ``find*`` protocol
delegates to the same internals and is unchanged.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np
from scipy.stats import qmc

from photon_tpu.hyperparameter.criteria import ExpectedImprovement
from photon_tpu.hyperparameter.gp import GaussianProcessEstimator
from photon_tpu.hyperparameter.kernels import Matern52, StationaryKernel

# evaluation: candidate in [0,1]^d -> (value to minimize, fitted artifact)
EvaluationFunction = Callable[[np.ndarray], Tuple[float, Any]]

Observation = Tuple[np.ndarray, float]


class RandomSearch:
    """Sobol-sequence search (reference: RandomSearch.scala:34)."""

    def __init__(self, num_params: int,
                 evaluation_function: Optional[EvaluationFunction] = None,
                 discrete_params: Optional[Dict[int, int]] = None,
                 kernel: StationaryKernel = Matern52(),
                 seed: int = 0):
        assert num_params > 0
        self.num_params = num_params
        self.evaluation_function = evaluation_function
        self.discrete_params = dict(discrete_params or {})
        self.kernel = kernel
        self.seed = seed
        self._sobol = qmc.Sobol(d=num_params, scramble=True, seed=seed)

    # -- batch protocol (ask/tell — lane-batched evaluation) -----------------

    def ask(self, q: int) -> np.ndarray:
        """The next ``q`` candidates ``[q, num_params]`` to evaluate as one
        batch. Pure Sobol here: ``ask(a); ask(b)`` emits the exact same
        candidates as ``ask(a + b)``."""
        assert q > 0
        return np.stack([self._discretize(c)
                         for c in self.draw_candidates(q)])

    def tell(self, candidates: np.ndarray,
             values: Sequence[float]) -> None:
        """Record one batch of observed (candidate, value) pairs."""
        assert len(candidates) == len(values)
        for c, v in zip(candidates, values):
            self._on_observation(np.asarray(c, float), float(v))

    # -- protocol ------------------------------------------------------------

    def find(self, n: int) -> List[Any]:
        return self.find_with_prior_observations(n, [])

    def find_with_prior_observations(self, n: int,
                                     prior_observations: Sequence[Observation]
                                     ) -> List[Any]:
        assert n > 0
        candidate = self._discretize(self.draw_candidates(1)[0])
        value, model = self.evaluation_function(candidate)
        if n == 1:
            return [model]
        return [model] + self.find_with_priors(
            n - 1, [(candidate, value)], prior_observations)

    def find_with_priors(self, n: int, observations: Sequence[Observation],
                         prior_observations: Sequence[Observation]) -> List[Any]:
        assert n > 0 and len(observations) > 0
        for point, value in observations[:-1]:
            self._on_observation(point, value)
        for point, value in prior_observations:
            self._on_prior_observation(point, value)
        last_point, last_value = observations[-1]
        models = []
        for _ in range(n):
            candidate = self._discretize(self._next(last_point, last_value))
            value, model = self.evaluation_function(candidate)
            models.append(model)
            last_point, last_value = candidate, value
        return models

    # -- extension points (GP search overrides) ------------------------------

    def _next(self, last_point: np.ndarray, last_value: float) -> np.ndarray:
        return self.draw_candidates(1)[0]

    def _on_observation(self, point: np.ndarray, value: float) -> None:
        pass

    def _on_prior_observation(self, point: np.ndarray, value: float) -> None:
        pass

    # -- helpers -------------------------------------------------------------

    def draw_candidates(self, n: int) -> np.ndarray:
        return self._sobol.random(n)

    def _discretize(self, candidate: np.ndarray) -> np.ndarray:
        out = candidate.copy()
        for idx, levels in self.discrete_params.items():
            out[idx] = np.floor(out[idx] * levels) / levels
        return out


class GaussianProcessSearch(RandomSearch):
    """Bayesian optimization (reference: GaussianProcessSearch.scala:52)."""

    def __init__(self, num_params: int,
                 evaluation_function: Optional[EvaluationFunction] = None,
                 discrete_params: Optional[Dict[int, int]] = None,
                 kernel: StationaryKernel = Matern52(),
                 candidate_pool_size: int = 250,
                 noisy_target: bool = True,
                 seed: int = 0):
        super().__init__(num_params, evaluation_function, discrete_params,
                         kernel, seed)
        self.candidate_pool_size = candidate_pool_size
        self.noisy_target = noisy_target
        # acquisition pool stream, seed-derived but DISJOINT from the
        # primary candidate stream: pool draws must not advance the
        # emitted-candidate sequence (see module docstring)
        self._pool_sobol = qmc.Sobol(
            d=num_params, scramble=True,
            seed=np.random.default_rng([seed, 0x9E3779B9]))
        self._points: List[np.ndarray] = []
        self._values: List[float] = []
        self._best = np.inf
        self._prior_points: List[np.ndarray] = []
        self._prior_values: List[float] = []
        self._prior_best = np.inf
        self.last_model = None

    def _on_observation(self, point: np.ndarray, value: float) -> None:
        self._points.append(np.asarray(point, float))
        self._values.append(float(value))
        self._best = min(self._best, float(value))

    def _on_prior_observation(self, point: np.ndarray, value: float) -> None:
        self._prior_points.append(np.asarray(point, float))
        self._prior_values.append(float(value))
        self._prior_best = min(self._prior_best, float(value))

    def draw_pool(self, n: int) -> np.ndarray:
        """Acquisition-pool draws — a separate stream from the emitted
        candidates (the determinism fix; see module docstring)."""
        return self._pool_sobol.random(n)

    def _fit_acquisition_model(self):
        """Fit the GP on all observations; returns (model, transformation)."""
        evals = np.asarray(self._values)
        current_mean = float(np.mean(evals))
        overall_best = min(self._prior_best, self._best - current_mean)
        transformation = ExpectedImprovement(overall_best)

        points = np.vstack(self._points)
        centered = evals - current_mean
        if self._prior_points:
            points = np.vstack([points, np.vstack(self._prior_points)])
            centered = np.concatenate([centered, self._prior_values])

        estimator = GaussianProcessEstimator(
            kernel=self.kernel, normalize_labels=False,
            noisy_target=self.noisy_target, transformation=transformation,
            seed=self.seed)
        model = estimator.fit(points, centered)
        self.last_model = model
        return model, transformation

    def _next(self, last_point: np.ndarray, last_value: float) -> np.ndarray:
        self._on_observation(last_point, last_value)
        # under-determined -> uniform draws until we exceed num_params obs
        if len(self._points) <= self.num_params:
            return super()._next(last_point, last_value)

        candidates = self.draw_pool(self.candidate_pool_size)
        model, transformation = self._fit_acquisition_model()
        predictions = model.predict_transformed(candidates)
        idx = (np.argmax(predictions) if transformation.is_max_opt
               else np.argmin(predictions))
        return candidates[idx]

    def ask(self, q: int) -> np.ndarray:
        """Top-q of the acquisition pool (one GP fit per round); Sobol
        exploration from the primary stream while under-determined, so
        the exploration-phase sequence is batch-size invariant."""
        assert 0 < q <= self.candidate_pool_size
        if len(self._points) <= self.num_params:
            return super().ask(q)
        pool = self.draw_pool(self.candidate_pool_size)
        model, transformation = self._fit_acquisition_model()
        predictions = model.predict_transformed(pool)
        order = np.argsort(-predictions if transformation.is_max_opt
                           else predictions)
        return np.stack([self._discretize(pool[i]) for i in order[:q]])
