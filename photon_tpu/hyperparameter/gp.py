"""Gaussian-process estimator/model with slice-sampled kernel posteriors.

Reference: photon-lib hyperparameter/estimators/GaussianProcessEstimator
.scala (fit = burn-in + Monte-Carlo kernel-parameter samples via slice
sampling, amplitude/noise sampled jointly along a random direction,
length scales dimension-wise), GaussianProcessModel.scala (precomputed
Cholesky/alpha per sampled kernel; posterior mean/variance per GPML
algorithm 2.1 lines 4-6, averaged over kernel samples),
PredictionTransformation.scala.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Sequence, Tuple

import numpy as np

from photon_tpu.hyperparameter.kernels import (
    DEFAULT_NOISE,
    Matern52,
    StationaryKernel,
)
from photon_tpu.hyperparameter.slice_sampler import SliceSampler

# transformation(means, variances) -> acquisition values
PredictionTransformation = Callable[[np.ndarray, np.ndarray], np.ndarray]


class GaussianProcessModel:
    """Posterior over sampled kernels (reference: GaussianProcessModel.scala)."""

    def __init__(self, x_train: np.ndarray, y_train: np.ndarray, y_mean: float,
                 kernels: Sequence[StationaryKernel],
                 transformation: Optional[PredictionTransformation] = None):
        assert x_train.ndim == 2 and len(x_train) == len(y_train)
        self.x_train = x_train
        self.y_train = y_train
        self.y_mean = y_mean
        self.transformation = transformation
        self._factors: List[Tuple[StationaryKernel, np.ndarray, np.ndarray]] = []
        for k in kernels:
            chol, alpha = k.posterior_factors(x_train, y_train)
            self._factors.append((k, chol, alpha))

    def _predict_one(self, x: np.ndarray, kernel, chol, alpha
                     ) -> Tuple[np.ndarray, np.ndarray]:
        ktrans = kernel.cross(self.x_train, x)          # [train, m]
        mean = ktrans.T @ alpha + self.y_mean           # GPML 2.1 l.4
        v = np.linalg.solve(chol, ktrans)               # l.5
        kx = kernel.gram(x)                              # l.6
        var = np.diag(kx - v.T @ v)
        return mean, var

    def predict(self, x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Posterior (mean, variance), averaged over kernel samples."""
        means, variances = zip(*(self._predict_one(x, *f) for f in self._factors))
        return np.mean(means, axis=0), np.mean(variances, axis=0)

    def predict_transformed(self, x: np.ndarray) -> np.ndarray:
        """Acquisition values, averaged over kernel samples."""
        outs = []
        for f in self._factors:
            mean, var = self._predict_one(x, *f)
            outs.append(self.transformation(mean, var)
                        if self.transformation else mean)
        return np.mean(outs, axis=0)


class GaussianProcessEstimator:
    """Reference: GaussianProcessEstimator.scala."""

    def __init__(self,
                 kernel: StationaryKernel = Matern52(),
                 normalize_labels: bool = False,
                 noisy_target: bool = False,
                 transformation: Optional[PredictionTransformation] = None,
                 num_burn_in_samples: int = 100,
                 num_samples: int = 10,
                 seed: int = 0):
        self.kernel = kernel
        self.normalize_labels = normalize_labels
        self.noisy_target = noisy_target
        self.transformation = transformation
        self.num_burn_in_samples = num_burn_in_samples
        self.num_samples = num_samples
        self.rng = np.random.default_rng(seed)

    def fit(self, x: np.ndarray, y: np.ndarray) -> GaussianProcessModel:
        x = np.asarray(x, float)
        y = np.asarray(y, float)
        y_mean = 0.0
        if self.normalize_labels:
            y_mean = float(np.mean(y))
            y = y - y_mean
        kernels = self._estimate_kernel_params(x, y)
        return GaussianProcessModel(x, y, y_mean, kernels, self.transformation)

    # -- kernel-hyperparameter posterior sampling ----------------------------

    def _estimate_kernel_params(self, x, y) -> List[StationaryKernel]:
        theta = self.kernel.initial_for(x, y).params
        for _ in range(self.num_burn_in_samples):
            theta = self._sample_next(theta, x, y)
        samples = []
        for _ in range(self.num_samples):
            theta = self._sample_next(theta, x, y)
            samples.append(self.kernel.with_params(theta))
        return samples

    def _sample_next(self, theta: np.ndarray, x, y) -> np.ndarray:
        """Amplitude(+noise) along a random direction, then length scales
        dimension-wise — sampled separately because of their interplay
        (reference: GaussianProcessEstimator.sampleNext)."""
        sampler = SliceSampler(rng=self.rng)
        amp_noise, ls = theta[:2], theta[2:]

        if self.noisy_target:
            amp_noise = sampler.draw(
                amp_noise,
                lambda an: self.kernel.with_params(
                    np.concatenate([an, ls])).log_likelihood(x, y))
        else:
            amp = sampler.draw(
                amp_noise[:1],
                lambda a: self.kernel.with_params(
                    np.concatenate([a, [DEFAULT_NOISE], ls])).log_likelihood(x, y))
            amp_noise = np.concatenate([amp, [DEFAULT_NOISE]])

        ls = sampler.draw_dimension_wise(
            ls,
            lambda l: self.kernel.with_params(
                np.concatenate([amp_noise, l])).log_likelihood(x, y))
        return np.concatenate([amp_noise, ls])
