"""Stationary GP covariance kernels with marginal likelihood + priors.

Reference: photon-lib hyperparameter/estimators/kernels/StationaryKernel
.scala (pairwise distances over length-scaled inputs, GPML-2.1 log
marginal likelihood with lognormal amplitude prior, horseshoe noise
prior, tophat length-scale prior), RBF.scala:70 (exp(-d/2)),
Matern52.scala:82 ((1 + sqrt(5d) + 5d/3) exp(-sqrt(5d))), Kernel.scala.

Host-side math: GP fits see tens of observations, so this is numpy on
the driver — the TPU is for the training jobs the search launches.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

DEFAULT_NOISE = 1e-4


def _pairwise_sq_dists(x1: np.ndarray, x2: Optional[np.ndarray] = None) -> np.ndarray:
    if x2 is None:
        x2 = x1
    d = x1[:, None, :] - x2[None, :, :]
    return np.sum(d * d, axis=-1)


@dataclasses.dataclass(frozen=True)
class StationaryKernel:
    """amplitude * f(pairwise dists of x / lengthscale) + noise * I."""

    amplitude: float = 1.0
    noise: float = DEFAULT_NOISE
    length_scale: np.ndarray = dataclasses.field(
        default_factory=lambda: np.ones(1))

    # priors (reference: StationaryKernel.scala)
    amplitude_scale: float = 1.0     # lognormal
    noise_scale: float = 0.1         # horseshoe
    length_scale_max: float = 2.0    # tophat

    def _from_sq_dists(self, d: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    def _ls(self, dim: int) -> np.ndarray:
        ls = np.asarray(self.length_scale, float).ravel()
        if ls.size == 1:
            return np.full(dim, ls[0])
        assert ls.size == dim, f"length scale dim {ls.size} != {dim}"
        return ls

    def gram(self, x: np.ndarray) -> np.ndarray:
        ls = self._ls(x.shape[1])
        d = _pairwise_sq_dists(x / ls)
        return self.amplitude * self._from_sq_dists(d) + \
            self.noise * np.eye(x.shape[0])

    def cross(self, x1: np.ndarray, x2: np.ndarray) -> np.ndarray:
        ls = self._ls(x1.shape[1])
        d = _pairwise_sq_dists(x1 / ls, x2 / ls)
        return self.amplitude * self._from_sq_dists(d)

    # -- parameter vector (amplitude, noise, *length_scale) ------------------

    @property
    def params(self) -> np.ndarray:
        return np.concatenate([[self.amplitude, self.noise],
                               np.atleast_1d(self.length_scale)])

    def with_params(self, theta: np.ndarray) -> "StationaryKernel":
        return dataclasses.replace(
            self, amplitude=float(theta[0]), noise=float(theta[1]),
            length_scale=np.asarray(theta[2:], float))

    def initial_for(self, x: np.ndarray, y: np.ndarray) -> "StationaryKernel":
        """Initial kernel from data (reference: amplitude = stddev(y))."""
        amp = float(np.std(y, ddof=1)) if len(y) > 1 else 1.0
        return dataclasses.replace(self, amplitude=amp or 1.0,
                                   length_scale=np.ones(x.shape[1]))

    # -- GPML 2.1 ------------------------------------------------------------

    def log_likelihood(self, x: np.ndarray, y: np.ndarray) -> float:
        ls = np.atleast_1d(np.asarray(self.length_scale, float))
        if self.amplitude < 0.0 or self.noise < 0.0 or np.any(ls < 0.0):
            return -np.inf
        if np.any(ls > self.length_scale_max):  # tophat prior
            return -np.inf
        k = self.gram(x)
        try:
            chol = np.linalg.cholesky(k)
        except np.linalg.LinAlgError:
            return -np.inf
        alpha = np.linalg.solve(chol.T, np.linalg.solve(chol, y))
        ll = (-0.5 * float(y @ alpha)
              - float(np.sum(np.log(np.diag(chol))))
              - 0.5 * len(y) * np.log(2 * np.pi))
        # lognormal amplitude prior + horseshoe noise prior
        if self.amplitude > 0:
            ll += -0.5 * np.log(np.sqrt(self.amplitude / self.amplitude_scale)) ** 2
        if self.noise > 0:
            ll += np.log(np.log1p((self.noise_scale / self.noise) ** 2))
        return ll

    def posterior_factors(self, x: np.ndarray, y: np.ndarray
                          ) -> Tuple[np.ndarray, np.ndarray]:
        """(cholesky L, alpha) for posterior prediction (GPML 2.1 l.2-3)."""
        chol = np.linalg.cholesky(self.gram(x))
        alpha = np.linalg.solve(chol.T, np.linalg.solve(chol, y))
        return chol, alpha


@dataclasses.dataclass(frozen=True)
class RBF(StationaryKernel):
    """Squared-exponential (reference: RBF.scala:70)."""

    def _from_sq_dists(self, d: np.ndarray) -> np.ndarray:
        return np.exp(-0.5 * d)


@dataclasses.dataclass(frozen=True)
class Matern52(StationaryKernel):
    """Matern nu=5/2 (reference: Matern52.scala:82)."""

    def _from_sq_dists(self, d: np.ndarray) -> np.ndarray:
        f = np.sqrt(5.0 * d)
        return (1.0 + f + 5.0 * d / 3.0) * np.exp(-f)
