"""Acquisition criteria for Bayesian optimization.

Reference: photon-lib hyperparameter/criteria/ExpectedImprovement.scala
(PBO eqs. 1-2, maximized to minimize the target) and ConfidenceBound
.scala (lower confidence bound mean - k*std, minimized).
"""

from __future__ import annotations

import dataclasses

import numpy as np
from scipy.stats import norm as _norm


@dataclasses.dataclass(frozen=True)
class ExpectedImprovement:
    """EI below the incumbent best (we minimize the evaluation value)."""

    best_evaluation: float
    is_max_opt: bool = True  # maximize EI

    def __call__(self, means: np.ndarray, variances: np.ndarray) -> np.ndarray:
        std = np.sqrt(np.maximum(variances, 1e-18))
        gamma = -(means - self.best_evaluation) / std
        return std * (gamma * _norm.cdf(gamma) + _norm.pdf(gamma))


@dataclasses.dataclass(frozen=True)
class ConfidenceBound:
    """Lower confidence bound, minimized."""

    exploration_factor: float = 2.0
    is_max_opt: bool = False

    def __call__(self, means: np.ndarray, variances: np.ndarray) -> np.ndarray:
        return means - self.exploration_factor * np.sqrt(np.maximum(variances, 0))
