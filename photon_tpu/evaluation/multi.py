"""Grouped (multi) evaluators as segment ops + the evaluation suite.

Reference: photon-lib evaluation/MultiEvaluator.scala:36 (join scores
with an id tag, groupByKey, local metric per group, drop non-finite,
unweighted mean across groups), MultiEvaluatorType.scala:52 ("AUC:idTag",
"PRECISION@k:idTag" names, ':' splitter), photon-api evaluation/
AreaUnderROCCurveMultiEvaluator.scala, PrecisionAtKMultiEvaluator,
EvaluationSuite.scala:33 (cached (label, offset, weight), score join,
primary evaluator), EvaluationResults.

TPU re-design: the groupByKey shuffle becomes ONE lexsort by (group,
score) plus segment cumsums — every per-group metric evaluates in a
single jitted pass with no ragged structure. Group ids are dense ints
built on the host from the id-tag strings.
"""

from __future__ import annotations

import dataclasses
import functools
import re
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.evaluation.evaluators import (
    EVALUATORS,
    EvaluatorType,
    evaluate as evaluate_single,
)

Array = jax.Array

ID_SPLITTER = ":"  # reference: MultiEvaluatorType.shardedEvaluatorIdNameSplitter
_PRECISION_RE = re.compile(r"(?i)PRECISION@(\d+)")


# ---------------------------------------------------------------------------
# evaluator specs: "AUC", "RMSE", "PRECISION@5", "AUC:userId", "PRECISION@1:qid"
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class EvaluatorSpec:
    """Parsed evaluator name (single or grouped-by-id-tag)."""

    base: EvaluatorType
    k: Optional[int] = None          # for PRECISION@k
    id_tag: Optional[str] = None     # grouped when set

    @property
    def is_multi(self) -> bool:
        return self.id_tag is not None

    @property
    def name(self) -> str:
        base = f"PRECISION@{self.k}" if self.k is not None else self.base.value
        return f"{base}{ID_SPLITTER}{self.id_tag}" if self.id_tag else base

    @property
    def bigger_is_better(self) -> bool:
        return True if self.k is not None else self.base.bigger_is_better

    def better_than(self, a: float, b: float) -> bool:
        return a > b if self.bigger_is_better else a < b


def parse_evaluator(name: Union[str, EvaluatorType, EvaluatorSpec]) -> EvaluatorSpec:
    """Reference: EvaluatorType/MultiEvaluatorType name parsing."""
    if isinstance(name, EvaluatorSpec):
        return name
    if isinstance(name, EvaluatorType):
        return EvaluatorSpec(name)
    base, _, id_tag = str(name).partition(ID_SPLITTER)
    m = _PRECISION_RE.fullmatch(base.strip())
    if m:
        return EvaluatorSpec(EvaluatorType.AUC, k=int(m.group(1)),
                             id_tag=id_tag.strip() or None)
    return EvaluatorSpec(EvaluatorType(base.strip().upper()),
                         id_tag=id_tag.strip() or None)


# ---------------------------------------------------------------------------
# segment machinery
# ---------------------------------------------------------------------------


def build_group_index(ids: Sequence[str]) -> Tuple[np.ndarray, List[str]]:
    """Host-side: id-tag strings -> dense group ints + group names."""
    mapping: Dict[str, int] = {}
    names: List[str] = []
    out = np.empty(len(ids), np.int32)
    for i, s in enumerate(ids):
        g = mapping.get(s)
        if g is None:
            g = len(names)
            mapping[s] = g
            names.append(s)
        out[i] = g
    return out, names


def _segment_layout(groups_sorted: Array, keys_sorted: Array):
    """(segment starts, tie-run starts, tie-run ends) over sorted arrays."""
    n = groups_sorted.shape[0]
    idx = jnp.arange(n)
    seg_new = jnp.concatenate([jnp.ones(1, bool),
                               groups_sorted[1:] != groups_sorted[:-1]])
    run_new = seg_new | jnp.concatenate([jnp.ones(1, bool),
                                         keys_sorted[1:] != keys_sorted[:-1]])
    run_start = jax.lax.cummax(jnp.where(run_new, idx, 0))
    run_last = jnp.concatenate([run_new[1:], jnp.ones(1, bool)])
    run_end = jnp.flip(jax.lax.cummin(jnp.where(run_last, idx, n - 1)[::-1]))
    return seg_new, run_start, run_end


def _csum_at(cs: Array, j: Array) -> Array:
    """Inclusive cumsum evaluated at index j, with C(-1) = 0."""
    return jnp.where(j >= 0, cs[jnp.maximum(j, 0)], 0.0)


@functools.partial(jax.jit, static_argnums=(4,))
def _grouped_auc_values(scores, labels, weights, groups, num_groups: int):
    """Per-group weighted tie-corrected AUC + validity mask — one lexsort +
    segment cumsums (replaces the reference's groupByKey + local sorts)."""
    order = jnp.lexsort((scores, groups))
    s, g = scores[order], groups[order]
    y = labels[order] > 0.5
    w = weights[order]

    seg_new, run_start, run_end = _segment_layout(g, s)
    idx = jnp.arange(s.shape[0])
    seg_start = jax.lax.cummax(jnp.where(seg_new, idx, 0))

    neg_w = jnp.where(y, 0.0, w)
    cneg = jnp.cumsum(neg_w)
    # negatives strictly below this tie run, within the group
    below = _csum_at(cneg, run_start - 1) - _csum_at(cneg, seg_start - 1)
    eq = _csum_at(cneg, run_end) - _csum_at(cneg, run_start - 1)

    pos_w = jnp.where(y, w, 0.0)
    num = jax.ops.segment_sum(pos_w * (below + 0.5 * eq), g,
                              num_segments=num_groups)
    w_pos = jax.ops.segment_sum(pos_w, g, num_segments=num_groups)
    w_neg = jax.ops.segment_sum(neg_w, g, num_segments=num_groups)
    valid = (w_pos > 0) & (w_neg > 0)
    auc_g = num / jnp.maximum(w_pos * w_neg, jnp.finfo(s.dtype).tiny)
    return auc_g, valid


@functools.partial(jax.jit, static_argnums=(0, 5))
def _grouped_precision_at_k_values(k: int, scores, labels, weights, groups,
                                   num_groups: int):
    """Per-group precision@k: rank within group by descending score; only
    positive-weight rows rank (pads carry weight 0)."""
    masked = jnp.where(weights > 0, scores, -jnp.inf)
    order = jnp.lexsort((-masked, groups))
    g = groups[order]
    y = labels[order] > 0.5
    w = weights[order]

    idx = jnp.arange(g.shape[0])
    seg_new = jnp.concatenate([jnp.ones(1, bool), g[1:] != g[:-1]])
    seg_start = jax.lax.cummax(jnp.where(seg_new, idx, 0))
    pos_in_group = idx - seg_start

    hit = (pos_in_group < k) & y & (w > 0)
    hits = jax.ops.segment_sum(hit.astype(scores.dtype), g,
                               num_segments=num_groups)
    count = jax.ops.segment_sum((w > 0).astype(scores.dtype), g,
                                num_segments=num_groups)
    return hits / k, count > 0


@functools.partial(jax.jit, static_argnums=(4,))
def _grouped_rmse_values(scores, labels, weights, groups, num_groups: int):
    se = jax.ops.segment_sum(weights * (scores - labels) ** 2, groups,
                             num_segments=num_groups)
    wsum = jax.ops.segment_sum(weights, groups, num_segments=num_groups)
    return jnp.sqrt(se / jnp.maximum(wsum, 1e-30)), wsum > 0


def _masked_mean(values: Array, valid: Array) -> Array:
    """Unweighted mean over valid groups, dropping non-finite results
    (reference: MultiEvaluator filters !isInfinite && !isNaN then mean)."""
    ok = valid & jnp.isfinite(values)
    return jnp.sum(jnp.where(ok, values, 0.0)) / jnp.maximum(
        jnp.sum(ok), 1)


def evaluate_multi(spec: EvaluatorSpec, scores: Array, labels: Array,
                   weights: Optional[Array], groups: Array,
                   num_groups: int) -> Array:
    w = jnp.ones_like(scores) if weights is None else weights
    if spec.k is not None:
        vals, valid = _grouped_precision_at_k_values(
            spec.k, scores, labels, w, groups, num_groups)
    elif spec.base == EvaluatorType.AUC:
        vals, valid = _grouped_auc_values(scores, labels, w, groups, num_groups)
    elif spec.base == EvaluatorType.RMSE:
        vals, valid = _grouped_rmse_values(scores, labels, w, groups, num_groups)
    else:
        raise ValueError(f"unsupported grouped evaluator: {spec.name}")
    return _masked_mean(vals, valid)


# ---------------------------------------------------------------------------
# evaluation suite
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class EvaluationResults:
    """Reference: EvaluationResults(evaluations, primaryEvaluator)."""

    evaluations: Dict[str, float]
    primary: str

    @property
    def primary_value(self) -> float:
        return self.evaluations[self.primary]


class EvaluationSuite:
    """Precomputed (labels, offsets, weights, group indexes) for a frame;
    every `evaluate(scores)` call is then one jitted pass per evaluator
    (reference: EvaluationSuite.scala:33)."""

    def __init__(self, evaluators: Sequence[Union[str, EvaluatorType, EvaluatorSpec]],
                 labels: np.ndarray,
                 offsets: Optional[np.ndarray] = None,
                 weights: Optional[np.ndarray] = None,
                 id_tags: Optional[Dict[str, Sequence[str]]] = None,
                 dtype=jnp.float32):
        self.specs = [parse_evaluator(e) for e in evaluators]
        if not self.specs:
            raise ValueError("evaluator set cannot be empty")
        self.primary = self.specs[0]
        self.labels = jnp.asarray(labels, dtype)
        self.offsets = None if offsets is None else jnp.asarray(offsets, dtype)
        self.weights = None if weights is None else jnp.asarray(weights, dtype)
        self._groups: Dict[str, Tuple[Array, int]] = {}
        for spec in self.specs:
            if spec.is_multi:
                if id_tags is None or spec.id_tag not in id_tags:
                    raise KeyError(
                        f"evaluator {spec.name} needs id tag {spec.id_tag!r}")
                if spec.id_tag not in self._groups:
                    gi, names = build_group_index(id_tags[spec.id_tag])
                    self._groups[spec.id_tag] = (jnp.asarray(gi), len(names))

    def evaluate(self, scores: Array) -> EvaluationResults:
        s = scores if self.offsets is None else scores + self.offsets
        out = {}
        for spec in self.specs:
            if spec.is_multi:
                groups, num_groups = self._groups[spec.id_tag]
                v = evaluate_multi(spec, s, self.labels, self.weights,
                                   groups, num_groups)
            elif spec.k is not None:
                from photon_tpu.evaluation.evaluators import precision_at_k
                v = precision_at_k(spec.k, s, self.labels, self.weights)
            else:
                v = evaluate_single(spec.base, s, self.labels, self.weights)
            out[spec.name] = float(v)
        return EvaluationResults(out, self.primary.name)
