"""Evaluation metrics as jittable sharded sorts / segment ops.

Reference: photon-lib evaluation/EvaluatorType.scala:56-65 (AUC, AUPR,
RMSE, LogisticLoss, PoissonLoss, SmoothedHingeLoss, SquaredLoss, each with
a better-than direction), photon-api evaluation/
AreaUnderROCCurveLocalEvaluator.scala:33 (Mann-Whitney with tie handling),
PrecisionAtKLocalEvaluator, RMSEEvaluator.

All metrics are weighted and tie-correct; scores/labels/weights are [n]
arrays (pad samples get weight 0, so static-shape padding is safe).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from photon_tpu.ops import losses as L

Array = jax.Array


class EvaluatorType(enum.Enum):
    AUC = "AUC"
    AUPR = "AUPR"
    RMSE = "RMSE"
    LOGISTIC_LOSS = "LOGISTIC_LOSS"
    POISSON_LOSS = "POISSON_LOSS"
    SMOOTHED_HINGE_LOSS = "SMOOTHED_HINGE_LOSS"
    SQUARED_LOSS = "SQUARED_LOSS"

    @property
    def bigger_is_better(self) -> bool:
        return self in (EvaluatorType.AUC, EvaluatorType.AUPR)

    def better_than(self, a: float, b: float) -> bool:
        """Reference: EvaluatorType's per-metric comparison op."""
        return a > b if self.bigger_is_better else a < b

    @property
    def metadata(self) -> "MetricMetadata":
        return METRIC_METADATA[self]


@dataclasses.dataclass(frozen=True)
class MetricMetadata:
    """Descriptive metadata for reporting (reference:
    photon-diagnostics .../metric/MetricMetadata.scala — name,
    description, worst-to-best ordering, optional (min, max) range)."""

    name: str
    description: str
    bigger_is_better: bool            # worstToBestOrdering direction
    value_range: Optional[Tuple[float, float]] = None

    def sort_worst_to_best(self, values):
        return sorted(values, reverse=not self.bigger_is_better)


METRIC_METADATA: Dict["EvaluatorType", MetricMetadata] = {
    EvaluatorType.AUC: MetricMetadata(
        "AUC", "Binary classification metric", True, (0.0, 1.0)),
    EvaluatorType.AUPR: MetricMetadata(
        "AUPR", "Binary classification metric", True, (0.0, 1.0)),
    EvaluatorType.RMSE: MetricMetadata(
        "RMSE", "Regression metric", False),
    EvaluatorType.LOGISTIC_LOSS: MetricMetadata(
        "LOGISTIC_LOSS", "Binary classification loss", False),
    EvaluatorType.POISSON_LOSS: MetricMetadata(
        "POISSON_LOSS", "Count-regression loss", False),
    EvaluatorType.SMOOTHED_HINGE_LOSS: MetricMetadata(
        "SMOOTHED_HINGE_LOSS", "Classification loss", False),
    EvaluatorType.SQUARED_LOSS: MetricMetadata(
        "SQUARED_LOSS", "Regression loss", False),
}


def _weights(scores: Array, weights: Optional[Array]) -> Array:
    return jnp.ones_like(scores) if weights is None else weights


def auc(scores: Array, labels: Array, weights: Optional[Array] = None) -> Array:
    """Weighted, tie-corrected area under the ROC curve via Mann-Whitney:
    AUC = sum_{pos i} w_i (W_neg<s_i + W_neg=s_i / 2) / (W_pos W_neg)."""
    w = _weights(scores, weights)
    order = jnp.argsort(scores)
    s = scores[order]
    y = labels[order] > 0.5
    ww = w[order]

    neg_w = jnp.where(y, 0.0, ww)
    cum_neg = jnp.cumsum(neg_w)
    # tie-group boundaries (searchsorted is jittable on sorted input)
    first = jnp.searchsorted(s, s, side="left")
    last = jnp.searchsorted(s, s, side="right")
    below = jnp.where(first > 0, cum_neg[jnp.maximum(first - 1, 0)], 0.0)
    upto = cum_neg[last - 1]
    eq = upto - below

    pos_w = jnp.where(y, ww, 0.0)
    num = jnp.sum(pos_w * (below + 0.5 * eq))
    w_pos = jnp.sum(pos_w)
    w_neg = jnp.sum(neg_w)
    return num / jnp.maximum(w_pos * w_neg, jnp.finfo(scores.dtype).tiny)


def aupr(scores: Array, labels: Array, weights: Optional[Array] = None) -> Array:
    """Weighted average precision (step interpolation, sklearn-style)."""
    w = _weights(scores, weights)
    order = jnp.argsort(-scores)
    y = labels[order] > 0.5
    ww = w[order]
    pos_w = jnp.where(y, ww, 0.0)
    cum_pos = jnp.cumsum(pos_w)
    cum_all = jnp.cumsum(ww)
    precision = cum_pos / jnp.maximum(cum_all, jnp.finfo(scores.dtype).tiny)
    total_pos = jnp.maximum(cum_pos[-1], jnp.finfo(scores.dtype).tiny)
    return jnp.sum(precision * pos_w) / total_pos


def rmse(scores: Array, labels: Array, weights: Optional[Array] = None) -> Array:
    w = _weights(scores, weights)
    se = w * (scores - labels) ** 2
    return jnp.sqrt(jnp.sum(se) / jnp.maximum(jnp.sum(w), 1e-30))


def _mean_loss(loss: L.PointwiseLoss) -> Callable[..., Array]:
    def fn(scores: Array, labels: Array, weights: Optional[Array] = None) -> Array:
        w = _weights(scores, weights)
        l, _ = loss.loss_and_dz(scores, labels)
        return jnp.sum(w * l) / jnp.maximum(jnp.sum(w), 1e-30)

    return fn


logistic_loss_eval = _mean_loss(L.LogisticLoss)
poisson_loss_eval = _mean_loss(L.PoissonLoss)
smoothed_hinge_loss_eval = _mean_loss(L.SmoothedHingeLoss)


def squared_loss_eval(scores: Array, labels: Array,
                      weights: Optional[Array] = None) -> Array:
    w = _weights(scores, weights)
    l, _ = L.SquaredLoss.loss_and_dz(scores, labels)
    return jnp.sum(w * l) / jnp.maximum(jnp.sum(w), 1e-30)


def precision_at_k(k: int, scores: Array, labels: Array,
                   weights: Optional[Array] = None) -> Array:
    """Unweighted precision@k (reference: PrecisionAtKLocalEvaluator; weights
    are ignored there too, but padded samples must carry weight 0 and are
    excluded here via -inf scores)."""
    w = _weights(scores, weights)
    masked = jnp.where(w > 0, scores, -jnp.inf)
    order = jnp.argsort(-masked)
    topk = order[:k]
    # zero-weight pad rows may enter the top-k when fewer than k valid
    # samples exist; they must not count as hits. The denominator stays k
    # (reference: PrecisionAtKLocalEvaluator computes hits / k).
    valid = w[topk] > 0
    return jnp.sum((labels[topk] > 0.5) & valid) / k


EVALUATORS: Dict[EvaluatorType, Callable[..., Array]] = {
    EvaluatorType.AUC: auc,
    EvaluatorType.AUPR: aupr,
    EvaluatorType.RMSE: rmse,
    EvaluatorType.LOGISTIC_LOSS: logistic_loss_eval,
    EvaluatorType.POISSON_LOSS: poisson_loss_eval,
    EvaluatorType.SMOOTHED_HINGE_LOSS: smoothed_hinge_loss_eval,
    EvaluatorType.SQUARED_LOSS: squared_loss_eval,
}


def evaluate(evaluator: EvaluatorType, scores: Array, labels: Array,
             weights: Optional[Array] = None) -> Array:
    return EVALUATORS[evaluator](scores, labels, weights)


def default_evaluator_for_task(task) -> EvaluatorType:
    """Reference: the per-task primary metric used for model selection."""
    from photon_tpu.types import TaskType

    return {
        TaskType.LOGISTIC_REGRESSION: EvaluatorType.AUC,
        TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM: EvaluatorType.AUC,
        TaskType.LINEAR_REGRESSION: EvaluatorType.RMSE,
        TaskType.POISSON_REGRESSION: EvaluatorType.POISSON_LOSS,
    }[task]
