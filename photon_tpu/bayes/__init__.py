"""Posterior-uncertainty subsystem: diagonal-Hessian Laplace variances.

``laplace`` computes per-coefficient posterior variances
``sigma^2 = 1 / (H_ii + lambda)`` at a fitted optimum — the Bayesian
output the reference repo's model contract (``BayesianLinearModelAvro``
means + variances) has carried since day one. Downstream they persist
through the checkpoint / cold-store / Avro schemas and open the
Thompson-sampling serving mode (``serving/scorer.py`` mode
``"thompson"``).
"""

from photon_tpu.bayes.laplace import (
    StreamedLaplace,
    entity_variances_blocked,
    fixed_effect_variances_streamed,
)

__all__ = [
    "StreamedLaplace",
    "entity_variances_blocked",
    "fixed_effect_variances_streamed",
]
