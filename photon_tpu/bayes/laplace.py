"""Post-fit diagonal-Hessian Laplace pass: posterior variances at the
optimum.

The Laplace approximation around a fitted GLM optimum gives a diagonal
Gaussian posterior ``theta_i ~ N(mu_i, 1 / (H_ii + lambda))`` where
``H_ii`` is the data term of the Hessian diagonal at the optimum (the
reference's SIMPLE variance semantics,
DistributedOptimizationProblem.computeVariances). The aggregator kernels
already form these diagonals (``ops/aggregators.hessian_diagonal``), so
the pass is pure reuse:

- **Fixed effect, streamed**: ``StreamedLaplace`` folds chunk after
  chunk from a ``data.streaming.ChunkLoader`` into a device-resident
  ``[dim]`` diagonal accumulator — the same carry/partial/finalize
  structure as ``optim/streaming.StreamedProblem``. On a mesh the carry
  stays SHARD-LOCAL ``[n_shards, dim]`` through the whole pass, the
  per-chunk partial contains NO collectives, and the finalize issues
  exactly one staged ICI-then-DCN psum. The single host crossing of the
  pass is the ``np.asarray`` pull of the finished variances.

- **Random effects, blocked**: ``entity_variances_blocked`` rides the
  PR 17 block-staging machinery — each size bucket's K entities are one
  staged device program (a vmap over the bucket's entity lanes, exactly
  the lane axis the flattened-lane solver batches over), with
  ``game/block_stream.BlockPrefetcher`` staging bucket b+1 while bucket
  b computes. Staging order and per-bucket programs are fixed, so two
  runs are bitwise identical.

Both entry points refuse losses without a Hessian (smoothed hinge is
first-order only in the reference too) with a typed ``ValueError``.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from photon_tpu.data.dataset import DataBatch
from photon_tpu.function.objective import GLMObjective, Hyper
from photon_tpu.ops import features as F
from photon_tpu.optim.base import jit_donating
from photon_tpu.utils import jitcache

Array = jax.Array

_NO_HESSIAN = ("Laplace variances need a twice-differentiable loss; "
               "{loss} has no Hessian (has_hessian=False) — the posterior "
               "is undefined under the reference's first-order treatment")


def _check_hessian(objective: GLMObjective) -> None:
    if not objective.loss.has_hessian:
        raise ValueError(_NO_HESSIAN.format(loss=type(objective.loss)))


def _variance_from_diag(diag: Array, l2: Array) -> Array:
    d = diag + l2
    return 1.0 / jnp.maximum(d, jnp.finfo(d.dtype).tiny)


class StreamedLaplace:
    """One streamed pass over a chunk store -> fixed-effect posterior
    variances ``1 / (H_ii + l2)`` at ``coef``.

    Mirrors ``optim/streaming.StreamedProblem``'s evaluation structure:
    a device-resident diagonal accumulator updated by one jitted partial
    per chunk (donated carry, zero host syncs, zero per-chunk
    collectives), finalized by a single program that — on a mesh —
    issues the pass's one staged ICI->DCN all-psum before adding the L2
    ridge and inverting.
    """

    def __init__(self, objective: GLMObjective, loader,
                 l2_weight: float = 0.0, dim: Optional[int] = None,
                 dtype=None):
        _check_hessian(objective)
        self.objective = objective
        self.loader = loader
        self.mesh = loader.mesh
        self.dim = int(dim if dim is not None else loader.source.dim)
        self.dtype = np.dtype(dtype if dtype is not None else loader.dtype)
        self.l2_weight = float(l2_weight)
        self._l2_dev = jnp.asarray(self.l2_weight, self.dtype)
        zero = Hyper(l2_weight=0.0)
        if self.mesh is None:
            self._partial = jit_donating(
                lambda carry, coef, batch: carry
                + objective.hessian_diagonal(coef, batch, zero),
                donate_argnums=(0,))
            self._finalize = jax.jit(_variance_from_diag)
        else:
            self._build_meshed()

    def _build_meshed(self):
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from photon_tpu.optim.hier import (
            _mesh_factors,
            _sample_axes,
            _staged_all_psum,
        )
        from photon_tpu.parallel import mesh as M

        mesh, obj = self.mesh, self.objective
        zero = Hyper(l2_weight=0.0)
        sample_axes = _sample_axes(mesh)
        self._n_shards, self._replicas = _mesh_factors(mesh, sample_axes)
        spec_axis = sample_axes if len(sample_axes) > 1 else sample_axes[0]
        carry_spec = P(spec_axis, None)
        self._carry_sharding = NamedSharding(mesh, carry_spec)
        replicas = self._replicas

        def partial_body(cd, coef, batch):
            # shard-local accumulate: cd [1, dim] — NO collectives
            return (cd[0] + obj.hessian_diagonal(coef, batch, zero))[None]

        def finalize_body(cd, l2):
            # the pass's single reduction: one staged ICI-then-DCN psum;
            # model-axis replicas hold identical copies, so the all-psum
            # overcounts by exactly that factor
            diag = _staged_all_psum(cd[0], mesh) / replicas
            return _variance_from_diag(diag, l2)

        def partial(carry, coef, batch):
            specs = jax.tree.map(
                lambda a: P(spec_axis, *([None] * (a.ndim - 1))), batch)
            return M.shard_map(partial_body, mesh=mesh,
                               in_specs=(carry_spec, P(), specs),
                               out_specs=carry_spec,
                               check_rep=False)(carry, coef, batch)

        def finalize(carry, l2):
            return M.shard_map(finalize_body, mesh=mesh,
                               in_specs=(carry_spec, P()),
                               out_specs=P(),
                               check_rep=False)(carry, l2)

        self._partial = jit_donating(partial, donate_argnums=(0,))
        self._finalize = jax.jit(finalize)

    def init_carry(self):
        if self.mesh is None:
            return jnp.zeros((self.dim,), self.dtype)
        return jax.device_put(
            np.zeros((self._n_shards, self.dim), self.dtype),
            self._carry_sharding)

    def _put_coef(self, coef):
        if self.mesh is None:
            return jnp.asarray(coef, self.dtype)
        from photon_tpu.parallel import mesh as M
        return M.replicate(jnp.asarray(coef, self.dtype), self.mesh)

    def variances(self, coef) -> np.ndarray:
        """One full streamed pass -> host ``[dim]`` posterior variances.

        The chunk loop is pure async dispatch; the np.asarray pull of the
        finalized variances is the pass's single host crossing.
        """
        coef_dev = self._put_coef(coef)
        carry = self.init_carry()
        for chunk in self.loader.stream():
            carry = self._partial(carry, coef_dev, chunk.batch)
            # zero-copy consumption token: the new carry's readiness
            # implies this chunk's reads are done, freeing its buffer
            self.loader.release(chunk, carry)
        var_dev = self._finalize(carry, self._l2_dev)
        # pass boundary: the single deliberate sync — host-sync-ok
        return np.asarray(var_dev)


def fixed_effect_variances_streamed(objective: GLMObjective, loader, coef,
                                    l2_weight: float = 0.0,
                                    dim: Optional[int] = None,
                                    dtype=None) -> np.ndarray:
    """Convenience wrapper: build a :class:`StreamedLaplace` and run one
    pass at ``coef``."""
    return StreamedLaplace(objective, loader, l2_weight=l2_weight,
                           dim=dim, dtype=dtype).variances(coef)


# =========================================================================
# Random effects: blocked, lane-batched per-entity diagonals
# =========================================================================


def _block_variance_fn(coord):
    """The per-bucket diagonal program for one coordinate: a vmap over
    the bucket's K entity lanes of the SIMPLE per-entity variance,
    jitted once per bucket shape (the same compile economics as the
    bucket solvers). Cached on the coordinate's task like
    ``RandomEffectCoordinate._variance_fn``."""
    obj = coord.objective

    def build():
        def one(feat_idx, feat_val, labels, offsets, weights, coef, l2):
            batch = DataBatch(F.SparseFeatures(feat_idx, feat_val),
                              labels, offsets, weights)
            d = obj.hessian_diagonal(coef, batch, Hyper(l2_weight=0.0))
            var = _variance_from_diag(d, l2)
            has_data = jnp.sum(weights) > 0
            return jnp.where(has_data, var, 0.0)

        @jax.jit
        def var_block(blk, residual_rows, coefs_b, l2):
            offsets = blk.offsets
            if residual_rows is not None:
                offsets = offsets + residual_rows
            return jax.vmap(one, in_axes=(0, 0, 0, 0, 0, 0, None))(
                blk.features.indices, blk.features.values,
                blk.labels, offsets, blk.weights, coefs_b, l2)

        return var_block

    return jitcache.get_or_build(("bayes_re_var_block", coord.task), build)


def entity_variances_blocked(coord, coefficients,
                             residual_scores=None, *,
                             prefetch: bool = True) -> np.ndarray:
    """Blocked per-entity posterior variances for a
    ``RandomEffectCoordinate``: ``[E, K]`` with ``var[e, k] =
    1 / (H_kk(entity e) + l2)`` at the entity's fitted ``coefficients``
    row (zero rows for entities with no data — they have no posterior
    beyond the prior, matching ``_variance_fn``).

    Device memory holds ONE staged bucket at a time (+ one in flight
    when ``prefetch``): each size bucket's K entity lanes run as one
    vmapped program while ``BlockPrefetcher`` stages the next bucket,
    exactly the PR 17 staging discipline of ``update_model_blocked``.
    Prefetching never changes bytes — staging order and per-bucket
    programs are fixed, so the result is bitwise run-to-run.
    """
    _check_hessian(coord.objective)
    ds = coord.dataset
    E_pad = ds.num_entities
    K = ds.projected_dim
    dtype = np.dtype(ds.blocks[0].labels.dtype) if ds.blocks \
        else np.dtype(np.float32)
    table = np.zeros((E_pad, K), dtype)
    w = np.asarray(coefficients, dtype)
    table[: min(E_pad, w.shape[0])] = w[:E_pad]
    lam = coord.config.regularization_weight
    l2 = jnp.asarray(coord.config.regularization.l2_weight(lam), dtype)
    out = np.zeros((E_pad, K), dtype)
    var_fn = _block_variance_fn(coord)
    res_flat = (None if residual_scores is None
                else jnp.asarray(residual_scores, dtype))
    n_blocks = len(ds.blocks)
    from photon_tpu.game.block_stream import BlockPrefetcher
    stream = None
    if prefetch and n_blocks > 1:
        stream = BlockPrefetcher(ds.blocks)
    try:
        for bi, blk in enumerate(ds.blocks):
            ents = np.asarray(blk.entity_rows)
            valid = (ents >= 0) & (ents < E_pad)
            x = np.zeros((ents.shape[0], K), dtype)
            x[valid] = table[ents[valid]]
            staged = stream.get(bi) if stream is not None else blk
            res_rows = None
            if res_flat is not None:
                res_rows = res_flat.at[staged.sample_rows].get(
                    mode="fill", fill_value=0.0)
            var_b = var_fn(staged, res_rows, jnp.asarray(x), l2)
            # the per-bucket host round-trip IS the design (cf.
            # update_model_blocked): results land in host RAM, device
            # peak stays one bucket
            out[ents[valid]] = np.asarray(var_b)[valid]
            if stream is not None:
                stream.release()
    finally:
        if stream is not None:
            stream.close()
    return out[:coord._num_entities_orig]
