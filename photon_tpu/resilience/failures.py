"""Failure-event log, typed training aborts, and driver exit codes.

One process-wide, thread-safe event list: every guard trip, rollback,
retry give-up, and preemption records here. The obs RunReport pulls
``snapshot()`` into its ``failures`` section so post-mortems read one
manifest instead of grepping logs; each record also bumps the
``resilience.failures`` counter (labelled by kind) in the metrics
registry.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List

logger = logging.getLogger(__name__)

# Distinct driver exit codes (cli/train.py): 75 follows the sysexits
# EX_TEMPFAIL convention — the run was healthy and is resumable.
EXIT_PREEMPTED = 75
EXIT_COORDINATE_FAILURE = 76

_lock = threading.Lock()
_events: List[Dict[str, Any]] = []


class PreemptionRequested(RuntimeError):
    """Graceful-stop honored at a coordinate boundary; the emergency
    checkpoint (when a checkpoint dir is configured) is already on disk
    when this propagates."""

    def __init__(self, checkpoint_path=None, sweep=None, coordinate=None):
        self.checkpoint_path = checkpoint_path
        self.sweep = sweep
        self.coordinate = coordinate
        super().__init__(
            f"preemption honored at sweep {sweep}, coordinate {coordinate!r}"
            + (f"; emergency checkpoint at {checkpoint_path}"
               if checkpoint_path else " (no checkpoint directory configured)"))


class CoordinateFailureError(RuntimeError):
    """Structured abort: one coordinate failed N consecutive sweeps."""

    def __init__(self, coordinate, sweep, consecutive, checkpoint_path=None):
        self.coordinate = coordinate
        self.sweep = sweep
        self.consecutive = consecutive
        self.checkpoint_path = checkpoint_path
        super().__init__(
            f"coordinate {coordinate!r} failed {consecutive} consecutive "
            f"sweeps (last at sweep {sweep})"
            + (f"; resumable checkpoint at {checkpoint_path}"
               if checkpoint_path else ""))


def record_failure(kind: str, **info: Any) -> Dict[str, Any]:
    """Append one failure/recovery event; returns the recorded dict."""
    event = {"kind": kind, "unix": time.time(), **info}
    with _lock:
        _events.append(event)
    try:
        from photon_tpu.obs.metrics import registry
        registry.counter("resilience.failures", kind=kind).inc()
    except Exception:  # metrics must never mask the failure being recorded
        logger.debug("failure-event metrics emission failed", exc_info=True)
    logger.warning("resilience event: %s", event)
    return event


def snapshot() -> List[Dict[str, Any]]:
    with _lock:
        return [dict(e) for e in _events]


def clear() -> None:
    with _lock:
        _events.clear()
