"""Durable, retrying file primitives: fsync-audited atomic publish.

Every byte-level write in ``io/`` and ``game/checkpoint.py`` funnels
through here so the durability contract lives in one place:

    write tmp -> fsync(tmp) -> [chaos.at_publish] -> rename -> fsync(dir)

A crash before the rename leaves only a tmp file/dir that readers
ignore; a crash after it leaves the complete new artifact. Reads and
publishes both run under the retry budget (resilience/retry.py).
"""

from __future__ import annotations

import logging
import os
from typing import Optional

from photon_tpu.resilience import chaos
from photon_tpu.resilience.retry import RetryPolicy, with_retries

logger = logging.getLogger(__name__)


def fsync_file(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def fsync_dir(path: str) -> None:
    """Persist a rename/creation in its directory (POSIX requires syncing
    the directory entry separately from the file data)."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # platforms without O_RDONLY dirs — best effort
        return
    try:
        os.fsync(fd)
    except OSError:
        logger.debug("directory fsync unsupported for %s", path)
    finally:
        os.close(fd)


def fsync_tree(path: str) -> None:
    """fsync every regular file under ``path``, then the dirs bottom-up."""
    for root, dirs, files in os.walk(path, topdown=False):
        for name in files:
            fsync_file(os.path.join(root, name))
        fsync_dir(root)


def read_bytes(path: str, op: str = "read",
               policy: Optional[RetryPolicy] = None) -> bytes:
    def _read() -> bytes:
        with open(path, "rb") as f:
            return f.read()
    return with_retries(_read, op=op, policy=policy)


def atomic_write_bytes(path: str, data: bytes, op: str = "write",
                       policy: Optional[RetryPolicy] = None) -> None:
    """Atomically publish ``data`` at ``path`` with fsync-before-rename.

    Retried as a unit: each attempt rewrites its own tmp file, so a
    transient failure mid-publish never leaves a half-written final
    artifact. ``chaos.SimulatedKill`` (not an OSError) propagates without
    cleanup, leaving the tmp file behind like a real kill would.
    """
    d = os.path.dirname(path) or "."
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"

    def _publish() -> None:
        try:
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            chaos.at_publish(op)
            os.replace(tmp, path)
            fsync_dir(d)
        except chaos.SimulatedKill:
            raise  # a real kill leaves the tmp file — so does the simulated one
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    with_retries(_publish, op=op, policy=policy)
