"""Fault containment for TPU-native GAME training.

The reference Photon ML leans on Spark lineage for every failure class:
a lost partition is recomputed deterministically and a poisoned solve
dies with its executor. This rebuild replaced lineage with sweep-granular
bitwise checkpoint/resume (game/checkpoint.py); this package supplies the
in-band defenses that lineage never had to provide:

- ``FailureMode`` + device-side non-finite guards inside every solver
  while_loop (optim/*.py) — NaN/Inf in loss/gradient/step rejects the
  step and terminates the solve with a typed failure instead of
  propagating NaNs, with no host synchronization in the hot loop.
- coordinate-level isolation (game/descent.py): a failed coordinate
  solve rolls back to that coordinate's previous model and the sweep
  continues; repeated failures abort with a resumable checkpoint.
- preemption-aware shutdown (``shutdown``): SIGTERM/SIGINT request a
  graceful stop at the next coordinate boundary; an emergency partial
  checkpoint keeps the continuation bitwise-equal.
- retrying I/O (``retry``/``io``): exponential backoff with
  deterministic jitter around ingest reads and atomic, fsync-audited
  publishes of checkpoints/models/indexes.
- a deterministic chaos harness (``chaos``) injecting NaN solves,
  transient I/O errors, simulated preemption, and kill-mid-write, so
  tests/test_resilience.py exercises every path above reproducibly.

Every failure/retry/rollback event is recorded through ``failures`` and
lands in the obs metrics registry plus the RunReport ``failures``
section.
"""

from photon_tpu.optim.base import FailureMode
from photon_tpu.resilience.failures import (
    EXIT_COORDINATE_FAILURE,
    EXIT_PREEMPTED,
    CoordinateFailureError,
    PreemptionRequested,
    record_failure,
)
from photon_tpu.resilience.retry import RetryPolicy, with_retries

__all__ = [
    "FailureMode",
    "EXIT_COORDINATE_FAILURE",
    "EXIT_PREEMPTED",
    "CoordinateFailureError",
    "PreemptionRequested",
    "record_failure",
    "RetryPolicy",
    "with_retries",
]
