"""Deterministic fault injection for resilience tests.

One process-wide optional ``ChaosConfig``; when installed, well-known
hook points consult it:

- ``should_poison_nan(coordinate, sweep)`` — game/descent.py asks before
  each coordinate update; a hit makes that update train against NaN
  offsets, driving the solver's non-finite guards end to end.
- ``before_io(op)`` — retry.with_retries calls it at the top of every
  attempt; configured ops raise ``ChaosIOError`` (an OSError, so the
  retry budget applies) a fixed number of times, then succeed.
- ``maybe_preempt(sweep, coordinate)`` — game/descent.py asks at each
  coordinate boundary; a hit flips the same flag a real SIGTERM would
  (resilience/shutdown.py), exercising the emergency-checkpoint path.
- ``at_publish(op)`` — resilience/io.py + game/checkpoint.py call it
  between tmp-write and rename; a hit raises ``SimulatedKill``, which
  deliberately bypasses tmp cleanup so the partial state stays on disk
  exactly as a real SIGKILL would leave it.
- ``straggler_delay(coordinate, sweep)`` — game/descent.py's parallel
  sweep asks in each group member's worker thread; a hit sleeps that
  member's solve, making it a straggler inside its concurrency group
  while the other members keep overlapping.
- ``scorer_delay()`` — serving/engine.py asks inside the scorer stage;
  returns seconds to sleep for the first ``scorer_delay_batches``
  batches, driving the serving circuit breaker's latency trip.
- ``should_poison_swap_candidate()`` — serving/swap.py asks after
  loading a candidate model; a hit NaN-poisons one coefficient table so
  the swap's finite/shadow gates must reject it.
- ``corrupt_model_dir(path, seed)`` — deterministic torn-directory
  helper: truncates one file (chosen by seed) to half its bytes, the
  on-disk shape a kill mid-copy leaves behind; the swap's crc32
  manifest gate must refuse the directory.
- ``cold_read_delay()`` — serving/coeff_store's background transfer
  thread asks before each cold-tier row read; returns seconds to sleep
  for the first ``cold_read_delay_reads`` reads, simulating a slow /
  page-faulting host-RAM cold tier. The score hot path must stay
  typed-degradation-only (``COLD_MISS``) while prefetch lags.
- ``corrupt_cold_store(path, seed)`` — deterministic cold-file
  corruption helper: flips one payload byte (chosen by seed) so the
  cold store's crc32 footer check must refuse the file.
- ``chunk_read_delay()`` — data/streaming's reader thread asks before
  each raw chunk read; returns seconds to sleep for the first
  ``slow_chunk_reads`` reads, simulating a slow disk / page-faulting
  host source. The consumer must keep computing on already-staged
  chunks while the reader lags (overlap, not stall).
- ``chunk_read_error()`` — same reader thread, same hook point as
  ``before_io`` but budgeted separately so a streaming test can fail
  chunk reads without touching checkpoint I/O: raises ``ChaosIOError``
  for the first ``chunk_read_errors`` reads, then succeeds (the
  resilience/retry budget applies).
- ``should_kill_stream(pass_idx, chunk_idx)`` — the streamed solver's
  per-chunk checkpoint hook asks after accumulating each chunk; a hit
  at the configured ``stream_kill_at`` writes the chunk-cursor
  checkpoint and raises ``SimulatedKill`` (fires once), the mid-epoch
  preemption the bitwise-resume test replays.
- ``re_block_read_delay()`` / ``re_block_read_error()`` — the blocked
  random-effect trainer's prefetch thread asks before staging each
  entity bucket; the delay simulates a slow cold-tier / host-RAM read
  while bucket b solves (overlap, not stall), the error raises
  ``ChaosIOError`` for the first ``re_block_read_errors`` stagings
  (retried under the ``resilience/retry`` budget).
- ``should_kill_re_block(block_idx)`` — the blocked random-effect
  trainer asks after each bucket's checkpoint hook (``on_block``) has
  fired; a hit at the configured ``re_block_kill_at`` raises
  ``SimulatedKill`` (fires once) — the durable v4 ``re_block_cursor``
  plus the checkpointed table must resume bitwise, including with K>1
  λ lanes.
- ``should_kill_convert(unit_idx)`` — io/data_store.py's writer asks
  after fsyncing each input unit's section bytes, BEFORE advancing the
  conversion cursor; a hit raises ``SimulatedKill`` at that harshest
  point (durable but unclaimed bytes), and resume must truncate back to
  the cursor and land on a byte-identical store (fires once).
- data-store injectors (``datastore_torn_manifest``,
  ``datastore_corrupt_section``) — deterministic helpers that tear a
  training-data store's manifest to half its bytes or bit-flip one
  section byte; ``io/data_store.DataStore`` must refuse both with a
  typed ``DataStoreCorruptError`` — never a silent short read into a
  fit.
- ``should_poison_publish_row()`` — nearline/publisher.py asks while
  building the final commit payload (AFTER the gate ladder has passed);
  a hit NaN-poisons one published row so the post-apply readback verify
  must detect the mismatch and drive the bitwise rollback path (fires
  once).
- event-log injectors (``torn_tail_write``, ``duplicate_shard_replay``,
  ``shuffle_shard_records``) — deterministic helpers that mutate an
  on-disk nearline event log into the three shapes a real log pipeline
  produces under failure: a half-appended final record, a re-delivered
  shard, and out-of-order delivery. The event reader must stop before
  the torn tail, dedup replayed sequence numbers, and re-sort the rest.
- ``shard_killed(shard_id)`` — serving/fleet.py's shard clients ask
  before every routed call; the configured shard answers nothing (a
  dead process / unreachable host). The router must degrade that
  shard's random effects with typed ``SHARD_UNAVAILABLE`` — never a
  hot-path exception — while the surviving shards keep serving.
- ``shard_response_delay(shard_id)`` — same hook point; the configured
  shard's first ``shard_slow_requests`` calls sleep
  ``shard_slow_s`` before serving, driving the router's hedged
  fan-out (the hedge must win while the primary attempt lags).
- ``manifest_torn_write(fleet_dir)`` — deterministic fleet-manifest
  tear: truncates ``fleet-manifest.json`` to half its bytes (a kill
  mid-publish). ``read_fleet_manifest``'s crc gate must refuse the
  torn document; a router must never boot on guessed shard ownership.
- ``should_kill_capture(record_idx)`` — serving/replay.py's traffic
  recorder asks before flushing each capture record; a hit at the
  configured ``capture_kill_at`` writes HALF the record's bytes and
  raises ``SimulatedKill`` (fires once) — a recorder killed mid-append.
  The capture reader must hold back the torn tail and report a typed
  ``CAPTURE_TRUNCATED`` count, never parse a partial record.
- ``replay_torn_capture(capture_path)`` — post-hoc variant of the same
  failure: tears the final record of an on-disk capture file exactly
  like ``torn_tail_write`` does for event shards.
- ``replay_clock_skew(record_idx)`` — serving/replay.py's replayer asks
  per record; returns the seconds of virtual-clock skew to add to the
  record's recorded offset for the first ``replay_skew_records`` records
  at/after ``replay_skew_from`` (a capture whose recorder clock drifted
  or jumped). A NEGATIVE skew can drive a record's timestamp before the
  replayer's current virtual now; the replayer must clamp it monotone
  and count the clamp as typed ``CLOCK_SKEW_CLAMPED`` — a virtual clock
  never runs backwards.

Everything is counter-based off the installed config — two runs with the
same config and workload inject identically. ``seed`` feeds the optional
rate-based I/O mode (``io_error_rate``), which keys a hash on
(seed, op, attempt index) rather than any global RNG.
"""

from __future__ import annotations

import dataclasses
import threading
import zlib
from contextlib import contextmanager
from typing import Dict, Optional, Tuple


class ChaosIOError(OSError):
    """Injected transient I/O failure (retryable by design)."""


class SimulatedKill(RuntimeError):
    """Injected hard kill between tmp-write and atomic rename."""


@dataclasses.dataclass(frozen=True)
class ChaosConfig:
    seed: int = 0
    # (coordinate id, sweep) pairs whose update trains on NaN offsets
    nan_solve: Tuple[Tuple[str, int], ...] = ()
    # op prefix -> number of transient I/O errors to inject (then succeed)
    io_failures: Dict[str, int] = dataclasses.field(default_factory=dict)
    # probability of a transient error per attempt, keyed by (seed, op,
    # attempt counter) — deterministic, no global RNG
    io_error_rate: float = 0.0
    # (sweep, coordinate id): request graceful preemption at that boundary
    preempt_at: Optional[Tuple[int, str]] = None
    # ops whose atomic publish is killed between write and rename
    kill_publish_ops: Tuple[str, ...] = ()
    # number of successful publishes of a matching op before the kill
    kill_publish_after: int = 0
    # serving: seconds of artificial scorer-stage delay, applied to the
    # first scorer_delay_batches scored batches (then off)
    scorer_delay_s: float = 0.0
    scorer_delay_batches: int = 0
    # serving: NaN-poison the next loaded swap candidate's coefficients
    swap_poison_nan: bool = False
    # parallel CD: (coordinate id, sweep) whose group-member solve sleeps
    # straggler_delay_s before dispatch — a straggler inside a
    # concurrency group (fires once)
    straggler_at: Optional[Tuple[str, int]] = None
    straggler_delay_s: float = 0.0
    # serving cold tier: seconds of artificial cold-store read latency,
    # applied to the first cold_read_delay_reads transfer reads (then off)
    cold_read_delay_s: float = 0.0
    cold_read_delay_reads: int = 0
    # nearline: NaN-poison one row of the next delta publish's commit
    # payload AFTER the gate ladder passed — the post-apply readback
    # verify must catch it and roll the published rows back (fires once)
    publish_poison_row: bool = False
    # streaming loader: seconds of artificial raw-chunk-read latency,
    # applied to the first slow_chunk_reads reads (then off)
    slow_chunk_read_s: float = 0.0
    slow_chunk_reads: int = 0
    # streaming loader: number of transient chunk-read errors to inject
    # (ChaosIOError; the reader retries under the resilience/retry budget)
    chunk_read_errors: int = 0
    # streamed solver: (pass index, chunk index) after whose accumulation
    # the consumer checkpoints its chunk cursor and dies (fires once)
    stream_kill_at: Optional[Tuple[int, int]] = None
    # blocked random-effect training: seconds of injected entity-block
    # staging latency, applied on the prefetch thread to the first
    # re_block_read_delays block stagings (then off)
    re_block_read_delay_s: float = 0.0
    re_block_read_delays: int = 0
    # blocked random-effect training: number of transient block-staging
    # errors (ChaosIOError; the prefetch thread retries under the
    # resilience/retry budget) — separate from before_io so a blocked
    # test can fail stagings without touching checkpoint writes
    re_block_read_errors: int = 0
    # blocked random-effect training: bucket index after whose on_block
    # checkpoint hook the trainer dies (fires once) — the cursor is
    # durable, the resume must be bitwise
    re_block_kill_at: Optional[int] = None
    # data-store conversion: unit index after whose data write (fsynced,
    # cursor NOT yet advanced) the converter dies (fires once) — resume
    # must re-convert that unit and land on a byte-identical store
    convert_kill_at: Optional[int] = None
    # serving fleet: shard id whose clients answer nothing (a dead
    # process); stays dead for the config's lifetime — kill, not flake
    shard_kill_id: Optional[int] = None
    # serving fleet: shard id whose first shard_slow_requests routed
    # calls sleep shard_slow_s before serving (then back to speed) —
    # the router's hedged fan-out must win the race while it lags
    shard_slow_id: Optional[int] = None
    shard_slow_s: float = 0.0
    shard_slow_requests: int = 0
    # multi-tenant serving: the named tenant turns into a noisy neighbor
    # — every real submit for it fans out into tenant_hot_loop_burst
    # extra flood requests (duplicates of the same payload), up to
    # tenant_hot_loop_total injected floods. The tenant's own admission
    # budget must absorb the flood; other tenants' tails stay bounded.
    tenant_hot_loop: Optional[str] = None
    tenant_hot_loop_burst: int = 0
    tenant_hot_loop_total: int = 0
    # traffic capture: record index whose append is killed midway — half
    # the record's bytes land on disk, then SimulatedKill (fires once);
    # the capture reader must hold the torn tail back as a typed
    # CAPTURE_TRUNCATED count
    capture_kill_at: Optional[int] = None
    # traffic replay: add replay_skew_s of virtual-clock skew to the
    # recorded offsets of the first replay_skew_records records at/after
    # index replay_skew_from (0 records disables). Negative skew forces
    # the replayer's monotone clamp (typed CLOCK_SKEW_CLAMPED).
    replay_skew_s: float = 0.0
    replay_skew_from: int = 0
    replay_skew_records: int = 0


class _State:
    def __init__(self, config: ChaosConfig):
        self.config = config
        self.lock = threading.Lock()
        self.io_injected: Dict[str, int] = {}
        self.io_attempts: Dict[str, int] = {}
        self.publishes_seen = 0
        self.kill_fired = False
        self.preempt_fired = False
        self.scorer_delays_done = 0
        self.straggler_fired = False
        self.cold_read_delays_done = 0
        self.publish_poison_fired = False
        self.chunk_read_delays_done = 0
        self.chunk_read_errors_done = 0
        self.stream_kill_fired = False
        self.re_block_read_delays_done = 0
        self.re_block_read_errors_done = 0
        self.re_block_kill_fired = False
        self.convert_kill_fired = False
        self.shard_slow_done = 0
        self.tenant_floods_done = 0
        self.capture_kill_fired = False


_active: Optional[_State] = None


def install(config: ChaosConfig) -> None:
    global _active
    _active = _State(config)


def uninstall() -> None:
    global _active
    _active = None


def is_active() -> bool:
    return _active is not None


@contextmanager
def active(config: ChaosConfig):
    install(config)
    try:
        yield
    finally:
        uninstall()


def should_poison_nan(coordinate: str, sweep: int) -> bool:
    s = _active
    return s is not None and (coordinate, sweep) in s.config.nan_solve


def before_io(op: str) -> None:
    s = _active
    if s is None:
        return
    with s.lock:
        for prefix, budget in s.config.io_failures.items():
            if not op.startswith(prefix):
                continue
            done = s.io_injected.get(prefix, 0)
            if done < budget:
                s.io_injected[prefix] = done + 1
                raise ChaosIOError(
                    f"chaos: injected transient I/O error #{done + 1} "
                    f"for {op!r}")
        if s.config.io_error_rate > 0.0:
            i = s.io_attempts.get(op, 0)
            s.io_attempts[op] = i + 1
            h = zlib.crc32(f"{s.config.seed}:{op}:{i}".encode()) / 2**32
            if h < s.config.io_error_rate:
                raise ChaosIOError(
                    f"chaos: injected rate-based I/O error for {op!r} "
                    f"(attempt {i})")


def maybe_preempt(sweep: int, coordinate: str) -> None:
    s = _active
    if s is None or s.config.preempt_at is None:
        return
    with s.lock:
        if s.preempt_fired or s.config.preempt_at != (sweep, coordinate):
            return
        s.preempt_fired = True
    from photon_tpu.resilience import shutdown
    shutdown.request(f"chaos preemption at sweep {sweep}, "
                     f"coordinate {coordinate!r}")


def scorer_delay() -> float:
    """Seconds of injected scorer-stage latency for this batch (0 when
    inactive or the batch budget is spent). The delay is real wall time —
    the breaker's latency window sees genuine measured seconds."""
    s = _active
    if s is None or s.config.scorer_delay_s <= 0:
        return 0.0
    with s.lock:
        if s.scorer_delays_done >= s.config.scorer_delay_batches:
            return 0.0
        s.scorer_delays_done += 1
    return s.config.scorer_delay_s


def straggler_delay(coordinate: str, sweep: int) -> float:
    """Seconds this parallel-group member should sleep before its solve
    (0 when inactive / not the configured member / already fired). Real
    wall time, in the member's worker thread — the group's other members
    must keep overlapping while this one lags."""
    s = _active
    if (s is None or s.config.straggler_at is None
            or s.config.straggler_delay_s <= 0):
        return 0.0
    with s.lock:
        if s.straggler_fired or s.config.straggler_at != (coordinate, sweep):
            return 0.0
        s.straggler_fired = True
    return s.config.straggler_delay_s


def cold_read_delay() -> float:
    """Seconds of injected cold-tier read latency for this transfer (0
    when inactive or the read budget is spent). Applied on the background
    transfer thread only — the scoring hot path never blocks on it; a
    request whose rows are late gets typed ``COLD_MISS`` degradation."""
    s = _active
    if s is None or s.config.cold_read_delay_s <= 0:
        return 0.0
    with s.lock:
        if s.cold_read_delays_done >= s.config.cold_read_delay_reads:
            return 0.0
        s.cold_read_delays_done += 1
    return s.config.cold_read_delay_s


def chunk_read_delay() -> float:
    """Seconds of injected raw-chunk-read latency for this read (0 when
    inactive or the read budget is spent). Applied on the streaming
    loader's reader thread only — a correctly overlapped consumer keeps
    computing on already-staged chunks while the reader sleeps."""
    s = _active
    if s is None or s.config.slow_chunk_read_s <= 0:
        return 0.0
    with s.lock:
        if s.chunk_read_delays_done >= s.config.slow_chunk_reads:
            return 0.0
        s.chunk_read_delays_done += 1
    return s.config.slow_chunk_read_s


def chunk_read_error() -> None:
    """Raise ``ChaosIOError`` for the first ``chunk_read_errors`` raw
    chunk reads, then succeed. Budgeted separately from ``before_io`` so
    a streaming test can fail data reads without also failing the
    checkpoint writes that share the retry machinery."""
    s = _active
    if s is None or s.config.chunk_read_errors <= 0:
        return
    with s.lock:
        if s.chunk_read_errors_done >= s.config.chunk_read_errors:
            return
        s.chunk_read_errors_done += 1
        n = s.chunk_read_errors_done
    raise ChaosIOError(f"chaos: injected transient chunk-read error #{n}")


def should_kill_stream(pass_idx: int, chunk_idx: int) -> bool:
    """True exactly once when the streamed solver finishes accumulating
    chunk ``chunk_idx`` of evaluation pass ``pass_idx`` and the installed
    config names that point — the caller writes its chunk-cursor
    checkpoint and raises ``SimulatedKill``, the mid-epoch preemption the
    bitwise-resume test replays."""
    s = _active
    if s is None or s.config.stream_kill_at is None:
        return False
    with s.lock:
        if s.stream_kill_fired:
            return False
        if s.config.stream_kill_at != (pass_idx, chunk_idx):
            return False
        s.stream_kill_fired = True
    return True


def re_block_read_delay() -> float:
    """Seconds of injected entity-block staging latency for this read
    (0 when inactive or the budget is spent). Applied on the blocked
    random-effect trainer's PREFETCH thread only — a correctly
    double-buffered consumer keeps solving the already-staged bucket
    while the reader sleeps."""
    s = _active
    if s is None or s.config.re_block_read_delay_s <= 0:
        return 0.0
    with s.lock:
        if s.re_block_read_delays_done >= s.config.re_block_read_delays:
            return 0.0
        s.re_block_read_delays_done += 1
    return s.config.re_block_read_delay_s


def re_block_read_error() -> None:
    """Raise ``ChaosIOError`` for the first ``re_block_read_errors``
    entity-block stagings, then succeed. Budgeted separately from
    ``before_io`` so a blocked-training test can fail stagings without
    also failing the checkpoint writes that share the retry machinery."""
    s = _active
    if s is None or s.config.re_block_read_errors <= 0:
        return
    with s.lock:
        if s.re_block_read_errors_done >= s.config.re_block_read_errors:
            return
        s.re_block_read_errors_done += 1
        n = s.re_block_read_errors_done
    raise ChaosIOError(f"chaos: injected transient re-block staging "
                       f"error #{n}")


def should_kill_re_block(block_idx: int) -> bool:
    """True exactly once when the blocked random-effect trainer has
    fired bucket ``block_idx``'s checkpoint hook and the installed
    config names that bucket — the caller raises ``SimulatedKill``
    AFTER the cursor is durable, so resume from ``start_block =
    block_idx + 1`` with the checkpointed table must be bitwise (the v4
    ``re_block_cursor`` contract, K>1 lanes included)."""
    s = _active
    if s is None or s.config.re_block_kill_at is None:
        return False
    with s.lock:
        if s.re_block_kill_fired:
            return False
        if s.config.re_block_kill_at != block_idx:
            return False
        s.re_block_kill_fired = True
    return True


def should_kill_convert(unit_idx: int) -> bool:
    """True exactly once when the data-store converter finishes the data
    write (flushed + fsynced) of input unit ``unit_idx`` and the
    installed config names that index — the writer raises
    ``SimulatedKill`` BEFORE advancing its conversion cursor, the
    harshest kill point: the unit's bytes are durable but unclaimed, so
    resume must truncate them away and re-convert the unit to a
    byte-identical store."""
    s = _active
    if s is None or s.config.convert_kill_at is None:
        return False
    with s.lock:
        if s.convert_kill_fired:
            return False
        if s.config.convert_kill_at != unit_idx:
            return False
        s.convert_kill_fired = True
    return True


def datastore_torn_manifest(store_dir: str) -> int:
    """Tear a data store's manifest: truncate ``manifest.json`` to half
    its bytes — the shape a kill between tmp-write and rename (or a
    partial copy) leaves. Returns the number of bytes removed.
    ``io/data_store.DataStore``'s crc envelope must refuse the torn
    document with a typed ``DataStoreCorruptError``; a trainer must
    never fit on guessed row counts (a silent short read)."""
    import os

    path = os.path.join(store_dir, "manifest.json")
    size = os.path.getsize(path)
    if size < 2:
        raise ValueError(f"data-store manifest too small to tear: "
                         f"{path!r}")
    with open(path, "r+b") as f:
        f.truncate(size // 2)
    return size - size // 2


def datastore_corrupt_section(store_dir: str, seed: int = 0) -> Tuple[str,
                                                                      int]:
    """Deterministically flip one byte of one ``.sec`` section file in a
    data store (file and offset chosen by crc32(seed)) — silent media
    corruption aimed at the training bytes themselves. The store's
    per-section crc32 verify must refuse with ``DataStoreCorruptError``:
    a flipped label or feature value may never reach a fit. Returns
    (corrupted file path, flipped offset)."""
    import os

    secs = sorted(n for n in os.listdir(store_dir) if n.endswith(".sec")
                  and os.path.getsize(os.path.join(store_dir, n)) > 0)
    if not secs:
        raise ValueError(f"no non-empty sections under {store_dir!r}")
    name = secs[zlib.crc32(str(seed).encode()) % len(secs)]
    path = os.path.join(store_dir, name)
    size = os.path.getsize(path)
    offset = zlib.crc32(f"{seed}-offset".encode()) % size
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ 0xFF]))
    return path, offset


def corrupt_cold_store(path: str, seed: int = 0) -> int:
    """Deterministically flip one payload byte of a cold-store file
    (offset chosen by crc32(seed) over the body, past the magic, before
    the crc footer) — the signature of silent media corruption. The
    store's crc32 verify gate must refuse the file. Returns the flipped
    offset."""
    import os

    size = os.path.getsize(path)
    if size <= 24:
        raise ValueError(f"cold store file too small to corrupt: {path!r}")
    # keep the magic (first 8 bytes) and the crc footer (last 4) intact so
    # the failure is unambiguously a payload-checksum mismatch
    body = size - 8 - 4
    offset = 8 + zlib.crc32(str(seed).encode()) % body
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ 0xFF]))
    return offset


def should_poison_swap_candidate() -> bool:
    s = _active
    return s is not None and s.config.swap_poison_nan


def corrupt_model_dir(path: str, seed: int = 0) -> str:
    """Deterministically tear one file under ``path``: truncate it to half
    its bytes (what a kill mid-copy leaves). The victim is chosen by
    crc32(seed) over the sorted file list, so two runs with the same seed
    corrupt the same file. Returns the corrupted file's path."""
    import os

    files = []
    for root, _dirs, names in os.walk(path):
        for name in sorted(names):
            files.append(os.path.join(root, name))
    files.sort()
    if not files:
        raise ValueError(f"no files to corrupt under {path!r}")
    victim = files[zlib.crc32(str(seed).encode()) % len(files)]
    size = os.path.getsize(victim)
    with open(victim, "r+b") as f:
        f.truncate(size // 2)
    return victim


def should_poison_publish_row() -> bool:
    """True exactly once when ``publish_poison_row`` is configured — the
    nearline publisher poisons one committed row with NaN *after* its
    gate ladder passed, so only the post-apply readback verify (and the
    automatic rollback it triggers) stands between the poison and live
    traffic."""
    s = _active
    if s is None or not s.config.publish_poison_row:
        return False
    with s.lock:
        if s.publish_poison_fired:
            return False
        s.publish_poison_fired = True
    return True


def torn_tail_write(shard_path: str) -> int:
    """Tear the final record of a JSONL event shard: cut the file
    mid-way through its last line (no trailing newline), the exact shape
    an appender killed mid-write leaves. Returns the number of bytes
    removed. The event reader must consume every complete record before
    the tear and stop — never parse, skip, or advance past the partial
    tail."""
    import os

    size = os.path.getsize(shard_path)
    with open(shard_path, "rb") as f:
        data = f.read()
    body = data.rstrip(b"\n")
    last_nl = body.rfind(b"\n")
    last_line = body[last_nl + 1:]
    if not last_line:
        raise ValueError(f"no records to tear in {shard_path!r}")
    keep = last_nl + 1 + max(1, len(last_line) // 2)
    with open(shard_path, "r+b") as f:
        f.truncate(keep)
    return size - keep


def duplicate_shard_replay(log_dir: str, seed: int = 0) -> str:
    """Re-deliver one existing shard under a fresh (later-sorting) shard
    name — an at-least-once log pipeline retrying a delivery it already
    made. The victim is chosen by crc32(seed) over the sorted shard
    list. Every sequence number in the copy is a duplicate; the reader
    must drop all of them. Returns the replayed shard's path."""
    import os
    import shutil

    shards = sorted(n for n in os.listdir(log_dir)
                    if n.endswith((".jsonl", ".avro")))
    if not shards:
        raise ValueError(f"no shards to replay under {log_dir!r}")
    victim = shards[zlib.crc32(str(seed).encode()) % len(shards)]
    stem, ext = os.path.splitext(victim)
    replay = os.path.join(log_dir, f"{stem}.replay-{seed}{ext}")
    shutil.copyfile(os.path.join(log_dir, victim), replay)
    return replay


def shuffle_shard_records(shard_path: str, seed: int = 0) -> int:
    """Deterministically reorder a JSONL shard's complete records (keyed
    by crc32(seed, index)) so sequence numbers arrive out of order —
    cross-partition interleaving at delivery. Returns the number of
    records that changed position. The reader must re-sort its poll
    batch by sequence number and count the disorder."""
    with open(shard_path, "rb") as f:
        data = f.read()
    nl_terminated = data.endswith(b"\n")
    lines = data.rstrip(b"\n").split(b"\n") if data.strip() else []
    if len(lines) < 2:
        return 0
    order = sorted(range(len(lines)),
                   key=lambda i: zlib.crc32(f"{seed}:{i}".encode()))
    moved = sum(1 for i, j in enumerate(order) if i != j)
    shuffled = b"\n".join(lines[j] for j in order)
    if nl_terminated:
        shuffled += b"\n"
    with open(shard_path, "wb") as f:
        f.write(shuffled)
    return moved


def shard_killed(shard_id: int) -> bool:
    """True while the installed config names ``shard_id`` as killed.
    Unlike the fire-once injectors this is a STATE, not an event: a dead
    shard stays dead for the config's lifetime, so every routed call to
    it must come back as typed ``SHARD_UNAVAILABLE`` degradation."""
    s = _active
    return s is not None and s.config.shard_kill_id == shard_id


def shard_response_delay(shard_id: int) -> float:
    """Seconds this shard's routed call should sleep before serving (0
    when inactive / a different shard / the request budget is spent).
    Real wall time on the caller's fan-out thread — the router's hedged
    second attempt must overtake the lagging primary."""
    s = _active
    if (s is None or s.config.shard_slow_id != shard_id
            or s.config.shard_slow_s <= 0):
        return 0.0
    with s.lock:
        if s.shard_slow_done >= s.config.shard_slow_requests:
            return 0.0
        s.shard_slow_done += 1
    return s.config.shard_slow_s


def manifest_torn_write(fleet_dir: str) -> int:
    """Tear the fleet manifest: truncate ``fleet-manifest.json`` to half
    its bytes — the shape a kill between tmp-write and rename (or a
    partial copy) leaves. Returns the number of bytes removed.
    ``io/fleet_store.read_fleet_manifest``'s crc gate must refuse the
    torn document with a typed ``FleetManifestError``; a router must
    never boot on guessed shard ownership."""
    import os

    path = os.path.join(fleet_dir, "fleet-manifest.json")
    size = os.path.getsize(path)
    if size < 2:
        raise ValueError(f"fleet manifest too small to tear: {path!r}")
    with open(path, "r+b") as f:
        f.truncate(size // 2)
    return size - size // 2


def should_kill_capture(record_idx: int) -> bool:
    """True exactly once when the traffic recorder is about to append
    record ``record_idx`` and the installed config names that index —
    the recorder writes HALF the record's bytes (flushed, no newline)
    and raises ``SimulatedKill``, the torn tail a real kill mid-append
    leaves. The capture reader must stop before it with a typed
    ``CAPTURE_TRUNCATED`` count."""
    s = _active
    if s is None or s.config.capture_kill_at is None:
        return False
    with s.lock:
        if s.capture_kill_fired:
            return False
        if s.config.capture_kill_at != record_idx:
            return False
        s.capture_kill_fired = True
    return True


def replay_clock_skew(record_idx: int) -> float:
    """Seconds of virtual-clock skew to add to record ``record_idx``'s
    recorded offset (0 when inactive / outside the configured record
    range). Deterministic — the skewed replay is itself replayable. The
    replayer must clamp any resulting non-monotone timestamp and count
    it as typed ``CLOCK_SKEW_CLAMPED``."""
    s = _active
    if (s is None or s.config.replay_skew_records <= 0
            or s.config.replay_skew_s == 0.0):
        return 0.0
    lo = s.config.replay_skew_from
    if lo <= record_idx < lo + s.config.replay_skew_records:
        return s.config.replay_skew_s
    return 0.0


def replay_torn_capture(capture_path: str) -> int:
    """Tear the final record of an on-disk traffic capture: cut the file
    mid-way through its last line (no trailing newline) — the post-hoc
    twin of ``should_kill_capture``, for captures that already exist.
    Returns the number of bytes removed. ``serving/replay.read_capture``
    must consume every complete record before the tear and report the
    partial tail as a typed ``CAPTURE_TRUNCATED`` count."""
    return torn_tail_write(capture_path)


def at_publish(op: str) -> None:
    s = _active
    if s is None or not s.config.kill_publish_ops:
        return
    with s.lock:
        if s.kill_fired or not any(op.startswith(p)
                                   for p in s.config.kill_publish_ops):
            return
        if s.publishes_seen < s.config.kill_publish_after:
            s.publishes_seen += 1
            return
        s.kill_fired = True
    raise SimulatedKill(f"chaos: killed publish of {op!r} between "
                        f"tmp-write and rename")


def tenant_flood_burst(tenant: str) -> int:
    """Multi-tenant noisy neighbor: how many flood duplicates to inject
    for this submit of ``tenant``. Zero for every other tenant and once
    the configured flood total is spent — the injector stresses one
    tenant's admission path, deterministically, without a load
    generator."""
    s = _active
    if s is None or s.config.tenant_hot_loop != tenant \
            or s.config.tenant_hot_loop_burst <= 0:
        return 0
    with s.lock:
        left = s.config.tenant_hot_loop_total - s.tenant_floods_done
        n = max(0, min(s.config.tenant_hot_loop_burst, left))
        s.tenant_floods_done += n
    return n


def program_cache_corrupt(bundle_dir: str, seed: int = 0) -> str:
    """Deterministically bit-flip one byte of one serialized program in
    an AOT program bundle (file and offset chosen by crc32(seed)) — the
    silent-media-corruption signature, aimed at the executable payloads
    the loader would map into the process. The bundle loader's crc gate
    must refuse the WHOLE bundle and fall back to tracing warmup: a
    corrupt executable may never produce a score. Returns the corrupted
    file's path."""
    import json as _json
    import os

    with open(os.path.join(bundle_dir, "bundle-manifest.json")) as f:
        names = sorted(_json.load(f)["programs"])
    if not names:
        raise ValueError(f"no programs in bundle {bundle_dir!r}")
    name = names[zlib.crc32(str(seed).encode()) % len(names)]
    path = os.path.join(bundle_dir, name)
    size = os.path.getsize(path)
    offset = zlib.crc32(f"{seed}-offset".encode()) % size
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ 0xFF]))
    return path
