"""Retrying I/O: exponential backoff with deterministic jitter.

Transient filesystem/network hiccups must not abort a multi-hour GAME
fit; every ingest read and atomic publish in ``io/`` runs through
:func:`with_retries`. Jitter is a pure function of (op, attempt) — two
runs back off identically, keeping the chaos suite and any timing-
sensitive debugging reproducible (no global RNG involved).

Env knobs (read per call so tests/ops can tune a live process):

  PHOTON_TPU_IO_RETRIES       max attempts, default 4 (= 3 retries)
  PHOTON_TPU_IO_RETRY_BASE_S  first backoff delay, default 0.05
  PHOTON_TPU_IO_RETRY_MAX_S   backoff cap per attempt, default 2.0
"""

from __future__ import annotations

import dataclasses
import logging
import os
import time
import zlib
from typing import Callable, Optional, Tuple, Type, TypeVar

logger = logging.getLogger(__name__)

T = TypeVar("T")

ENV_ATTEMPTS = "PHOTON_TPU_IO_RETRIES"
ENV_BASE = "PHOTON_TPU_IO_RETRY_BASE_S"
ENV_MAX = "PHOTON_TPU_IO_RETRY_MAX_S"


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    max_attempts: int = 4
    base_delay_s: float = 0.05
    max_delay_s: float = 2.0
    retry_on: Tuple[Type[BaseException], ...] = (OSError,)

    @staticmethod
    def from_env() -> "RetryPolicy":
        return RetryPolicy(
            max_attempts=max(1, int(os.environ.get(ENV_ATTEMPTS, 4))),
            base_delay_s=float(os.environ.get(ENV_BASE, 0.05)),
            max_delay_s=float(os.environ.get(ENV_MAX, 2.0)),
        )


def backoff_delay(op: str, attempt: int, base: float, cap: float) -> float:
    """Delay before retry #``attempt`` (0-based): exponential, capped,
    with deterministic jitter in [0.5, 1.0) x the raw delay."""
    raw = min(cap, base * (2.0 ** attempt))
    h = zlib.crc32(f"{op}:{attempt}".encode()) / 2.0**32
    return raw * (0.5 + 0.5 * h)


def with_retries(
    fn: Callable[..., T],
    *args,
    op: str = "io",
    policy: Optional[RetryPolicy] = None,
    sleep: Callable[[float], None] = time.sleep,
    **kwargs,
) -> T:
    """Run ``fn(*args, **kwargs)``, retrying on ``policy.retry_on``.

    Each attempt first consults the chaos harness for ``op`` (injected
    transient errors count against the same budget as real ones). On
    give-up the last error propagates after being recorded as a
    ``resilience`` failure event.
    """
    from photon_tpu.resilience import chaos, failures

    if policy is None:
        policy = RetryPolicy.from_env()
    for attempt in range(policy.max_attempts):
        try:
            chaos.before_io(op)
            return fn(*args, **kwargs)
        except policy.retry_on as e:
            if attempt + 1 >= policy.max_attempts:
                failures.record_failure("io_giveup", op=op,
                                        attempts=policy.max_attempts,
                                        error=repr(e))
                raise
            delay = backoff_delay(op, attempt, policy.base_delay_s,
                                  policy.max_delay_s)
            try:
                from photon_tpu.obs.metrics import registry
                registry.counter("resilience.io_retry", op=op).inc()
            except Exception:
                logger.debug("retry metrics emission failed", exc_info=True)
            logger.warning("%s failed (attempt %d/%d): %r — retrying in "
                           "%.3fs", op, attempt + 1, policy.max_attempts, e,
                           delay)
            sleep(delay)
    raise AssertionError("unreachable: loop either returns or raises")
