"""Multi-host consistency guard for sweep boundaries.

SPMD coordinate descent assumes every process holds bitwise-identical
replicated state; a desync (a host-dependent reduction, a stray
down-sample, silent HBM corruption) otherwise trains on diverged models
for hours before anyone notices. At each sweep boundary — never in the
hot path — every process digests its fixed-effect coefficients and
allgathers the digests; any mismatch is a hard, immediately diagnosable
error listing the per-host values.

Only fully-addressable arrays enter the digest: model-axis-sharded
coefficients legitimately differ per host and are skipped (their
collectives are XLA's responsibility, not this guard's).

Disable with PHOTON_TPU_CONSISTENCY_GUARD=0.
"""

from __future__ import annotations

import logging
import os
import zlib
from typing import Dict

import numpy as np

logger = logging.getLogger(__name__)

ENV_FLAG = "PHOTON_TPU_CONSISTENCY_GUARD"


class MultiHostDesyncError(RuntimeError):
    def __init__(self, sweep: int, digests):
        self.sweep = sweep
        self.digests = list(digests)
        per_host = ", ".join(f"host {i}: {d:#010x}"
                             for i, d in enumerate(self.digests))
        super().__init__(
            f"fixed-effect coefficients diverged across hosts at sweep "
            f"{sweep}: {per_host}")


def enabled() -> bool:
    return os.environ.get(ENV_FLAG, "1") != "0"


def fixed_effect_digest(models: Dict[str, object]) -> int:
    """CRC32 over every fixed-effect coordinate's coefficient bytes, in
    coordinate-id order; 0 when nothing is digestible."""
    from photon_tpu.game.model import FixedEffectModel

    crc = 0
    for cid in sorted(models):
        m = models[cid]
        if not isinstance(m, FixedEffectModel):
            continue
        means = m.model.coefficients.means
        if not getattr(means, "is_fully_addressable", True):
            continue  # model-sharded theta: per-host shards differ by design
        crc = zlib.crc32(cid.encode(), crc)
        crc = zlib.crc32(np.ascontiguousarray(np.asarray(means)).tobytes(),
                         crc)
    return crc


def check_consistency(models: Dict[str, object], sweep: int) -> None:
    """Allgather + compare digests; collective, so every process must
    call it at the same boundary. No-op single-process or when disabled."""
    import jax

    if not enabled() or jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    digest = np.asarray([fixed_effect_digest(models)], np.uint32)
    gathered = np.asarray(
        multihost_utils.process_allgather(digest)).reshape(-1)
    if len(set(int(d) for d in gathered)) > 1:
        from photon_tpu.resilience import failures
        failures.record_failure(
            "multihost_desync", sweep=sweep,
            digests=[int(d) for d in gathered])
        raise MultiHostDesyncError(sweep, (int(d) for d in gathered))
    try:
        from photon_tpu.obs.metrics import registry
        registry.counter("resilience.consistency_checks").inc()
    except Exception:
        logger.debug("consistency-check metrics emission failed",
                     exc_info=True)
