"""Preemption-aware shutdown: signal -> flag -> coordinate boundary.

TPU preemption (and any orderly kill) delivers SIGTERM with a grace
window. The handler here only flips a flag — everything heavy (the
emergency checkpoint, the RunReport flush) happens at the next
coordinate boundary on the training thread, where device state is
consistent and the continuation stays bitwise-equal. A second SIGINT
falls through to the default KeyboardInterrupt so an interactive ^C ^C
still kills a hung run.
"""

from __future__ import annotations

import logging
import signal
import threading
from typing import Optional

logger = logging.getLogger(__name__)

_lock = threading.Lock()
_requested = False
_reason: Optional[str] = None
_previous: dict = {}


def request(reason: str = "requested") -> None:
    """Flip the stop flag (signal handler, chaos harness, or embedder)."""
    global _requested, _reason
    with _lock:
        if not _requested:
            _requested = True
            _reason = reason
            logger.warning("graceful shutdown requested (%s); stopping at "
                           "the next coordinate boundary", reason)


def requested() -> bool:
    return _requested


def reason() -> Optional[str]:
    return _reason


def reset() -> None:
    global _requested, _reason
    with _lock:
        _requested = False
        _reason = None


def _handler(signum, frame):
    if _requested and signum == signal.SIGINT:
        # operator insists: restore default behavior and interrupt now
        raise KeyboardInterrupt
    request(signal.Signals(signum).name)


def install(signums=(signal.SIGTERM, signal.SIGINT)) -> None:
    """Install the graceful handler (main thread only — callers off the
    main thread get a no-op, matching the signal module's own rule)."""
    if threading.current_thread() is not threading.main_thread():
        logger.debug("not on the main thread; shutdown handler not installed")
        return
    for s in signums:
        if s not in _previous:
            _previous[s] = signal.getsignal(s)
        signal.signal(s, _handler)


def uninstall() -> None:
    """Restore pre-install handlers (tests)."""
    if threading.current_thread() is not threading.main_thread():
        return
    for s, old in list(_previous.items()):
        signal.signal(s, old)
        del _previous[s]
