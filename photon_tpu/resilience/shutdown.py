"""Preemption-aware shutdown: signal -> flag -> coordinate boundary.

TPU preemption (and any orderly kill) delivers SIGTERM with a grace
window. The handler here only flips a flag — everything heavy (the
emergency checkpoint, the RunReport flush) happens at the next
coordinate boundary on the training thread, where device state is
consistent and the continuation stays bitwise-equal. A second SIGINT
falls through to the default KeyboardInterrupt so an interactive ^C ^C
still kills a hung run.
"""

from __future__ import annotations

import logging
import signal
import threading
from typing import Optional

logger = logging.getLogger(__name__)

_lock = threading.Lock()
_requested = False
_reason: Optional[str] = None
_previous: dict = {}
_callbacks: list = []


def add_callback(fn) -> None:
    """Register ``fn(reason)`` to run when shutdown is requested (e.g. a
    serving engine's ``begin_drain``). Callbacks may run inside a signal
    handler: they must be lock-free flag flips, never heavy work. A
    callback added after the request fires immediately."""
    fire_now = False
    with _lock:
        if fn not in _callbacks:
            _callbacks.append(fn)
        fire_now = _requested
    if fire_now:
        _run_callback(fn, _reason or "requested")


def remove_callback(fn) -> None:
    with _lock:
        if fn in _callbacks:
            _callbacks.remove(fn)


def _run_callback(fn, reason: str) -> None:
    try:
        fn(reason)
    except Exception:  # a broken callback must not break the shutdown path
        logger.exception("shutdown callback %r failed", fn)


def request(reason: str = "requested") -> None:
    """Flip the stop flag (signal handler, chaos harness, or embedder)."""
    global _requested, _reason
    to_fire = []
    with _lock:
        if not _requested:
            _requested = True
            _reason = reason
            to_fire = list(_callbacks)
            logger.warning("graceful shutdown requested (%s); stopping at "
                           "the next coordinate boundary", reason)
    for fn in to_fire:
        _run_callback(fn, reason)


def requested() -> bool:
    return _requested


def reason() -> Optional[str]:
    return _reason


def reset() -> None:
    global _requested, _reason
    with _lock:
        _requested = False
        _reason = None


def _handler(signum, frame):
    if _requested and signum == signal.SIGINT:
        # operator insists: restore default behavior and interrupt now
        raise KeyboardInterrupt
    request(signal.Signals(signum).name)


def install(signums=(signal.SIGTERM, signal.SIGINT)) -> None:
    """Install the graceful handler (main thread only — callers off the
    main thread get a no-op, matching the signal module's own rule)."""
    if threading.current_thread() is not threading.main_thread():
        logger.debug("not on the main thread; shutdown handler not installed")
        return
    for s in signums:
        if s not in _previous:
            _previous[s] = signal.getsignal(s)
        signal.signal(s, _handler)


def uninstall() -> None:
    """Restore pre-install handlers (tests)."""
    if threading.current_thread() is not threading.main_thread():
        return
    for s, old in list(_previous.items()):
        signal.signal(s, old)
        del _previous[s]
