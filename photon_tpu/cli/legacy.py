"""Legacy single-GLM driver: explicit stage machine with a lambda sweep.

Reference: photon-client Driver.scala:59 (run :145) — stages
INIT -> PREPROCESSED -> TRAINED -> VALIDATED (DriverStage.scala:20,45),
reg-weight sweep via ModelTraining, per-lambda validation metrics, best
model selection (ModelSelection.scala:26), coefficient text/Avro output
(io/deprecated/GLMSuite semantics); feature summary
(FeatureDataStatistics) and optional normalization.

Input formats: Avro TrainingExampleAvro directories or LibSVM text
(io/deprecated/LibSVMInputDataFormat).
"""

from __future__ import annotations

import argparse
import enum
import json
import logging
import os
from typing import Dict, List, Optional

import numpy as np
import jax.numpy as jnp

from photon_tpu.data.dataset import DataBatch
from photon_tpu.data.ingest import read_libsvm, to_batch
from photon_tpu.data.stats import compute_feature_stats
from photon_tpu.data.validators import DataValidationType, validate_dataframe
from photon_tpu.estimators.model_training import train_generalized_linear_model
from photon_tpu.evaluation.multi import EvaluationSuite
from photon_tpu.function.objective import (
    L1Regularization,
    L2Regularization,
    NoRegularization,
    RegularizationContext,
    RegularizationType,
)
from photon_tpu.game.dataset import FeatureShard, GameDataFrame
from photon_tpu.io import avro as avro_io
from photon_tpu.io.data_io import FeatureShardConfiguration
from photon_tpu.io.index_map import IndexMap
from photon_tpu.io.model_io import _vector_to_ntvs
from photon_tpu.io.schemas import BAYESIAN_LINEAR_MODEL_AVRO
from photon_tpu.ops.normalization import (
    NormalizationType,
    build_normalization_context,
    no_normalization,
)
from photon_tpu.optim.problem import GLMOptimizationConfiguration, OptimizerConfig
from photon_tpu.types import OptimizerType, TaskType
from photon_tpu.utils.timing import Timed, timing_summary

logger = logging.getLogger("photon_tpu.driver")


class DriverStage(enum.Enum):
    """Reference: DriverStage.scala:20,45."""

    INIT = 0
    PREPROCESSED = 1
    TRAINED = 2
    VALIDATED = 3


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon_tpu.driver",
        description="Legacy single-GLM training driver with a lambda sweep")
    p.add_argument("--training-data-directory", required=True)
    p.add_argument("--validating-data-directory", default=None)
    p.add_argument("--output-directory", required=True)
    p.add_argument("--task", required=True, choices=[t.value for t in TaskType])
    p.add_argument("--format", default="AVRO", choices=["AVRO", "LIBSVM"])
    p.add_argument("--feature-dimension", type=int, default=None,
                   help="LIBSVM only: fixed feature dimension")
    p.add_argument("--optimizer", default="LBFGS",
                   choices=[o.value for o in OptimizerType])
    p.add_argument("--regularization-type", default="L2",
                   choices=[r.value for r in RegularizationType])
    p.add_argument("--regularization-weights", default="0.1,1,10,100")
    p.add_argument("--elastic-net-alpha", type=float, default=0.5)
    p.add_argument("--max-iterations", type=int, default=50)
    p.add_argument("--tolerance", type=float, default=1e-7)
    p.add_argument("--normalization-type", default="NONE",
                   choices=[n.value for n in NormalizationType])
    p.add_argument("--data-validation", default="VALIDATE_FULL",
                   choices=[v.value for v in DataValidationType])
    p.add_argument("--intercept", action="store_true", default=True)
    p.add_argument("--no-intercept", dest="intercept", action="store_false")
    p.add_argument("--event-listeners", nargs="*", default=[],
                   help="fully-qualified EventListener class names "
                        "(reference: Driver.scala:62-73)")
    p.add_argument("--log-level", default="INFO")
    return p


class LegacyDriver:
    """Explicit stage machine (reference: Driver.scala)."""

    def __init__(self, args: argparse.Namespace):
        self.args = args
        self.stage = DriverStage.INIT
        self.task = TaskType(args.task)
        self.index_map: Optional[IndexMap] = None
        self.models: Dict[float, object] = {}
        self.metrics: Dict[float, Dict[str, float]] = {}
        self.best_lambda: Optional[float] = None

    # -- stage INIT -> PREPROCESSED -----------------------------------------

    def preprocess(self):
        args = self.args
        with Timed("preprocess", logger):
            if args.format == "LIBSVM":
                data = read_libsvm(args.training_data_directory,
                                   dim=args.feature_dimension,
                                   add_intercept=args.intercept)
                self.train_batch = to_batch(data, dtype=np.float64)
                self.dim = data.dim
                self.index_map = IndexMap(
                    {f"f{j}": j for j in range(self.dim)})
                self.val_batch = None
                self.val_labels = None
                self.val_weights = None
                if args.validating_data_directory:
                    vdata = read_libsvm(
                        args.validating_data_directory,
                        dim=self.dim - (1 if args.intercept else 0),
                        add_intercept=args.intercept)
                    self.val_batch = to_batch(vdata, dtype=np.float64).features
                    self.val_labels = vdata.labels
            else:
                shard = {"features": FeatureShardConfiguration.of(
                    "features", intercept=args.intercept)}

                from photon_tpu.io.fast_ingest import (
                    read_frame_with_fallback,
                )

                def read(directory, imaps):
                    return read_frame_with_fallback([directory], shard,
                                                    index_maps=imaps)

                df, imaps = read(args.training_data_directory, None)
                self.index_map = imaps["features"]
                validate_dataframe(df, self.task,
                                   DataValidationType(args.data_validation))
                self.train_batch = df.fixed_effect_batch("features")
                self.dim = self.index_map.feature_dimension
                self.val_batch = None
                self.val_labels = None
                self.val_weights = None
                if args.validating_data_directory:
                    vdf, _ = read(args.validating_data_directory, imaps)
                    self.val_batch = vdf.shard_features("features")
                    self.val_labels = vdf.response
                    self.val_weights = vdf.weights

            # feature summary (reference: Driver preprocess writes summary)
            self.summary = compute_feature_stats(self.train_batch.features,
                                                 self.dim)
            self.norm = no_normalization()
            ntype = NormalizationType(args.normalization_type)
            if ntype != NormalizationType.NONE:
                icol = (self.dim - 1 if args.intercept else None)
                self.norm = build_normalization_context(
                    ntype, self.summary.mean,
                    self.summary.variance, self.summary.abs_max,
                    intercept_index=icol)
        self.stage = DriverStage.PREPROCESSED

    # -- stage PREPROCESSED -> TRAINED --------------------------------------

    def train(self):
        args = self.args
        lambdas = [float(s) for s in args.regularization_weights.split(",")]
        reg = {
            "NONE": NoRegularization,
            "L1": L1Regularization,
            "L2": L2Regularization,
            "ELASTIC_NET": RegularizationContext(
                RegularizationType.ELASTIC_NET, args.elastic_net_alpha),
        }[args.regularization_type]
        config = GLMOptimizationConfiguration(
            optimizer=OptimizerConfig(
                optimizer_type=OptimizerType(args.optimizer),
                max_iterations=args.max_iterations,
                tolerance=args.tolerance),
            regularization=reg)
        with Timed(f"train {len(lambdas)} lambdas", logger):
            models, stats = train_generalized_linear_model(
                self.task, self.train_batch, self.dim, config,
                regularization_weights=lambdas, norm=self.norm,
                dtype=self.train_batch.labels.dtype,
                intercept_index=(self.dim - 1 if args.intercept else None))
        self.models = models
        self.solver_stats = stats
        self.stage = DriverStage.TRAINED

    # -- stage TRAINED -> VALIDATED -----------------------------------------

    def validate(self):
        if self.val_batch is None:
            return
        from photon_tpu.evaluation.evaluators import default_evaluator_for_task
        primary = default_evaluator_for_task(self.task)
        suite = EvaluationSuite([primary], np.asarray(self.val_labels),
                                weights=self.val_weights)
        with Timed("validate", logger):
            for lam, model in self.models.items():
                scores = model.compute_score(self.val_batch)
                self.metrics[lam] = suite.evaluate(scores).evaluations
        # best-model selection (reference: ModelSelection.scala:26)
        name = primary.value
        better = (max if primary.bigger_is_better else min)
        self.best_lambda = better(self.metrics,
                                  key=lambda lam: self.metrics[lam][name])
        self.stage = DriverStage.VALIDATED

    # -- persist -------------------------------------------------------------

    def save(self):
        args = self.args
        out = args.output_directory
        os.makedirs(out, exist_ok=True)
        recs = []
        for lam, model in self.models.items():
            recs.append({
                "modelId": str(lam),
                "modelClass": None,
                "means": _vector_to_ntvs(
                    np.asarray(model.coefficients.means), self.index_map,
                    sparsity_threshold=0.0),
                "variances": None,
                "lossFunction": "",
            })
        avro_io.write_avro(os.path.join(out, "models.avro"),
                           BAYESIAN_LINEAR_MODEL_AVRO, recs)
        summary = {
            "task": self.task.value,
            "lambdas": sorted(self.models.keys()),
            "metrics": {str(k): v for k, v in self.metrics.items()},
            "best_lambda": self.best_lambda,
        }
        with open(os.path.join(out, "summary.json"), "w") as f:
            json.dump(summary, f, indent=2)
        logger.info("saved %d models to %s", len(recs), out)

    def run(self):
        """Stage sequence with lifecycle events (reference: Driver.scala
        sendEvent(PhotonSetupEvent) at init :73, TrainingStart/Finish and
        PhotonOptimizationLogEvent around train :150-170)."""
        from photon_tpu.utils import events

        with events.driver_listeners(
                getattr(self.args, "event_listeners", [])):
            events.emitter.emit(events.setup_event(driver="legacy",
                                                   params=vars(self.args)))
            self.preprocess()
            events.emitter.emit(events.training_start_event(
                task=self.task.value, dim=self.dim))
            self.train()
            events.emitter.emit(events.optimization_log_event(**{
                f"lambda/{lam}": str(stats.reason)
                for lam, stats in self.solver_stats.items()}))
            self.validate()
            events.emitter.emit(events.training_finish_event(
                best_lambda=self.best_lambda,
                metrics={str(k): v for k, v in self.metrics.items()}))
            self.save()
            logger.info(timing_summary())
            return self


def main(argv: Optional[List[str]] = None) -> LegacyDriver:
    from photon_tpu.utils.compile_cache import maybe_enable
    maybe_enable()
    args = build_arg_parser().parse_args(argv)
    logging.basicConfig(level=args.log_level,
                        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    return LegacyDriver(args).run()


if __name__ == "__main__":
    main()
