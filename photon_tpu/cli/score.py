"""GAME scoring driver: load model -> score data -> write results.

Reference: photon-client cli/game/scoring/GameScoringDriver.scala:39
(run :136 — read data, load GAME model, GameTransformer.transform,
optional evaluation, saveScoresToHDFS :187 as ScoringResultAvro).
"""

from __future__ import annotations

import argparse
import json
import logging
import os
from typing import List, Optional

import numpy as np
import jax.numpy as jnp

from photon_tpu.cli.config import parse_feature_shard_config
from photon_tpu.evaluation.multi import EvaluationSuite
from photon_tpu.game.random_effect import RandomEffectDataConfiguration
from photon_tpu.game.scoring import GameScorer
from photon_tpu.io.data_io import write_scores
from photon_tpu.io.model_io import load_game_model
from photon_tpu.game.model import RandomEffectModel
from photon_tpu.utils.timing import Timed

logger = logging.getLogger("photon_tpu.score")


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon_tpu.score",
        description="Score data under a trained GAME model")
    p.add_argument("--input-data-directories", nargs="+", required=True)
    p.add_argument("--model-input-directory", required=True)
    p.add_argument("--root-output-directory", required=True)
    p.add_argument("--feature-shard-configuration", action="append",
                   required=True, dest="feature_shards")
    p.add_argument("--evaluators", nargs="*", default=[],
                   help='e.g. AUC "AUC:userId"')
    p.add_argument("--id-tag-columns", nargs="*", default=[])
    p.add_argument("--model-id", default="photon_tpu")
    p.add_argument("--event-listeners", nargs="*", default=[],
                   help="fully-qualified EventListener class names "
                        "(reference: Driver.scala:62-73)")
    p.add_argument("--telemetry", action="store_true",
                   help="enable the unified telemetry subsystem (same as "
                        "PHOTON_TPU_TELEMETRY=1); writes runreport.json + "
                        "trace.json under --root-output-directory")
    p.add_argument("--log-level", default="INFO")
    return p


def run(args: argparse.Namespace) -> np.ndarray:
    logging.basicConfig(level=args.log_level,
                        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    from photon_tpu.utils import events

    with events.driver_listeners(args.event_listeners):
        events.emitter.emit(events.setup_event(driver="game-score",
                                               params=vars(args)))
        return _run(args)


def _run(args: argparse.Namespace) -> np.ndarray:
    from photon_tpu import obs
    from photon_tpu.utils import events

    if getattr(args, "telemetry", False):
        obs.configure(True)
    _root_span = obs.span("score", driver="game-score")
    _root_span.__enter__()

    out_dir = args.root_output_directory
    os.makedirs(out_dir, exist_ok=True)

    shard_configs = dict(parse_feature_shard_config(s)
                         for s in args.feature_shards)

    with Timed("read scoring data", logger):
        from photon_tpu.io.fast_ingest import read_frame_with_fallback
        df, index_maps, records = read_frame_with_fallback(
            args.input_data_directories, shard_configs, return_records=True)

    with Timed("load model", logger):
        loaded = load_game_model(args.model_input_directory, index_maps)

    id_tags = set(args.id_tag_columns)
    for m in loaded.model.models.values():
        if isinstance(m, RandomEffectModel):
            id_tags.add(m.random_effect_type)
    for ev in args.evaluators:
        _, _, tag = str(ev).partition(":")
        if tag:
            id_tags.add(tag)
    # id-tag columns become known only after the model loads; extract them
    # from the (bag-free on the fast path) records with the single
    # None-handling rule shared by every ingest path
    from photon_tpu.io.data_io import extract_id_tags
    df.id_tags.update(extract_id_tags(records, sorted(id_tags)))

    with Timed("score", logger):
        scorer = GameScorer(df.num_samples)
        for cid, m in loaded.model.models.items():
            if isinstance(m, RandomEffectModel):
                scorer.add_random_effect(
                    cid, df,
                    RandomEffectDataConfiguration(m.random_effect_type,
                                                  m.feature_shard_id),
                    loaded.vocab, loaded.projections[cid])
            else:
                scorer.add_fixed_effect(cid, df, m.feature_shard_id)
        offsets = None if df.offsets is None else jnp.asarray(df.offsets)
        scores = np.asarray(scorer.score(loaded.model, offsets=offsets))

    with Timed("write scores", logger):
        uids = [r.get("uid") for r in records]
        write_scores(os.path.join(out_dir, "scores", "part-00000.avro"),
                     scores,
                     labels=df.response,
                     weights=None if df.weights is None else df.weights,
                     uids=uids if any(u is not None for u in uids) else None,
                     model_id=args.model_id)

    evaluations = None
    if args.evaluators:
        suite = EvaluationSuite(args.evaluators, df.response,
                                weights=df.weights, id_tags=df.id_tags)
        results = suite.evaluate(jnp.asarray(scores))
        evaluations = results.evaluations
        with open(os.path.join(out_dir, "evaluation.json"), "w") as f:
            json.dump(evaluations, f, indent=2)
        logger.info("evaluation: %s", evaluations)
    events.emitter.emit(events.Event(
        "ScoringFinishEvent",
        payload={"num_scored": int(len(scores)),
                 "evaluation": evaluations}))
    _root_span.__exit__(None, None, None)
    if obs.enabled():
        try:
            obs.write_run_report(
                os.path.join(out_dir, "runreport.json"), driver="game-score",
                extra={"num_scored": int(len(scores))}, aggregate=True)
            obs.write_trace(os.path.join(out_dir, "trace.json"))
        except Exception as e:  # noqa: BLE001 — telemetry must never fail a run
            logger.warning("failed to write telemetry artifacts: %r", e)
    return scores


def main(argv: Optional[List[str]] = None) -> None:
    from photon_tpu.utils.compile_cache import maybe_enable
    maybe_enable()
    run(build_arg_parser().parse_args(argv))


if __name__ == "__main__":
    main()
