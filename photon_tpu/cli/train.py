"""GAME training driver: ingest -> validate -> fit -> select -> persist.

Reference: photon-client cli/game/training/GameTrainingDriver.scala
(params :67-155, run :346, main :833): read Avro training/validation
data, prepare feature maps, sanity-check, compute stats + normalization,
fit one model per optimization configuration (cartesian sweep), optional
hyperparameter tuning, select + save models per ModelOutputMode
(io/ModelOutputMode.scala:20-46).

Usage:
  python -m photon_tpu.cli.train \\
    --input-data-directories data/train \\
    --validation-data-directories data/val \\
    --root-output-directory out \\
    --training-task LOGISTIC_REGRESSION \\
    --feature-shard-configuration name=global,feature.bags=features \\
    --coordinate-configuration name=fixed,feature.shard=global,\\
optimizer=LBFGS,tolerance=1e-7,max.iter=50,regularization=L2,reg.weights=1|10 \\
    --coordinate-update-sequence fixed
"""

from __future__ import annotations

import argparse
import enum
import json
import logging
import os
import sys
from typing import Dict, List, Optional

import numpy as np

from photon_tpu.cli.config import (
    ParsedCoordinate,
    expand_sweep,
    parse_coordinate_config,
    parse_feature_shard_config,
)
from photon_tpu.data.validators import DataValidationType, validate_dataframe
from photon_tpu.estimators.game_estimator import GameEstimator, GameResult
from photon_tpu.hyperparameter.tuner import (
    HyperparameterTuningMode,
    run_hyperparameter_tuning,
)
from photon_tpu.io.fast_ingest import read_frame_with_fallback
from photon_tpu.io.model_io import save_game_model
from photon_tpu.ops.normalization import NormalizationType
from photon_tpu.types import TaskType, VarianceComputationType
from photon_tpu.utils.timing import Timed

logger = logging.getLogger("photon_tpu.train")


class ModelOutputMode(enum.Enum):
    """Reference: io/ModelOutputMode.scala:20-46."""

    NONE = "NONE"          # save nothing
    BEST = "BEST"          # only the best model by validation metric
    EXPLICIT = "EXPLICIT"  # all explicitly-configured models
    TUNED = "TUNED"        # only tuned models
    ALL = "ALL"            # explicit + tuned


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon_tpu.train",
        description="Train a GAME model (fixed + random effects) on TPU")
    p.add_argument("--input-data-directories", nargs="+", required=True)
    p.add_argument("--input-data-date-range", default=None,
                   help="yyyymmdd-yyyymmdd: expand each input dir to its "
                        "daily/yyyy/mm/dd partitions in range (reference: "
                        "DateRange.scala:107)")
    p.add_argument("--input-data-days-range", default=None,
                   help="START-END days ago, e.g. 90-1 (DaysRange.scala)")
    p.add_argument("--validation-data-directories", nargs="*", default=[])
    p.add_argument("--validation-data-date-range", default=None)
    p.add_argument("--validation-data-days-range", default=None)
    p.add_argument("--root-output-directory", required=True)
    p.add_argument("--training-task", required=True,
                   choices=[t.value for t in TaskType])
    p.add_argument("--feature-shard-configuration", action="append",
                   required=True, dest="feature_shards")
    p.add_argument("--coordinate-configuration", action="append",
                   required=True, dest="coordinates")
    p.add_argument("--coordinate-update-sequence", required=True,
                   help="comma-separated coordinate names")
    p.add_argument("--coordinate-descent-iterations", type=int, default=1)
    p.add_argument("--validation-evaluators", nargs="*", default=None,
                   help='e.g. AUC RMSE "AUC:userId" "PRECISION@5:userId"')
    p.add_argument("--id-tag-columns", nargs="*", default=[],
                   help="record columns carrying entity ids")
    p.add_argument("--model-input-directory", default=None,
                   help="warm-start GAME model directory")
    p.add_argument("--checkpoint-directory", default=None,
                   help="publish a per-sweep mid-training checkpoint here "
                        "(params, PRNG counters, best-model bookkeeping); "
                        "SURVEY §5.3's Spark-lineage replacement")
    p.add_argument("--resume-from", default=None,
                   help="resume coordinate descent from the latest sweep "
                        "checkpoint in this directory (bitwise-equal "
                        "continuation); implies checkpointing there")
    p.add_argument("--partial-retrain-locked-coordinates", nargs="*",
                   default=[])
    p.add_argument("--output-mode", default="BEST",
                   choices=[m.value for m in ModelOutputMode])
    p.add_argument("--variance-computation-type", default="NONE",
                   choices=[v.value for v in VarianceComputationType])
    p.add_argument("--data-validation", default="VALIDATE_FULL",
                   choices=[v.value for v in DataValidationType])
    p.add_argument("--data-validation-drop-invalid", action="store_true",
                   help="drop rows with non-finite/invalid fields instead "
                        "of failing the run (counts are logged and reported "
                        "via telemetry)")
    p.add_argument("--hyper-parameter-tuning", default="NONE",
                   choices=[m.value for m in HyperparameterTuningMode])
    p.add_argument("--hyper-parameter-tuning-iter", type=int, default=0)
    p.add_argument("--hyper-parameter-shrink-radius", type=float, default=None,
                   help="narrow search ranges around the prior best before "
                        "tuning; radius in rescaled [0,1] space (reference: "
                        "ShrinkSearchRange.scala:28)")
    p.add_argument("--hyper-parameter-prior-json", default=None,
                   help="path to serialized prior observations "
                        '{"records": [{<coord>: weight, "evaluationValue": '
                        "v}]} (reference: GameHyperparameterDefaults + "
                        "HyperparameterSerialization)")
    p.add_argument("--sweep-l2", default=None,
                   help="comma-separated l2 grid, e.g. 0.1,1,10: fitted as "
                        "ONE lane-batched solve for single fixed-effect "
                        "models (optim/batched), sequential configurations "
                        "otherwise; grid values are validated typed before "
                        "any training starts")
    p.add_argument("--tune", type=int, default=0,
                   help="run N rounds of lane-batched GP tuning "
                        "(GameEstimator.tune): each round's ask-batch of "
                        "candidates is fitted as one batched solve, rounds "
                        "warm-start from the previous best lane")
    p.add_argument("--tune-ask-batch", type=int, default=4,
                   help="candidates per tuning round (= lanes per batched "
                        "solve) for --tune")
    p.add_argument("--model-sparsity-threshold", type=float, default=1e-4)
    p.add_argument("--num-devices", type=int, default=0,
                   help="shard training over this many devices (0 = single)")
    p.add_argument("--normalization-type", default="NONE",
                   choices=[t.value for t in NormalizationType],
                   help="feature normalization, built from training-data "
                        "statistics per feature shard (reference: "
                        "GameTrainingDriver.scala:556)")
    p.add_argument("--data-summary-directory", default=None,
                   help="write per-shard FeatureSummarizationResultAvro here "
                        "(reference: ModelProcessingUtils.scala:393)")
    p.add_argument("--event-listeners", nargs="*", default=[],
                   help="fully-qualified EventListener class names "
                        "(reference: Driver.scala:62-73)")
    p.add_argument("--profile-dir", default=None,
                   help="capture a jax.profiler trace of the fit into this "
                        "directory (SURVEY §5.1: the TPU-native analog of "
                        "the reference's Timed blocks + Spark UI)")
    p.add_argument("--telemetry", action="store_true",
                   help="enable the unified telemetry subsystem (same as "
                        "PHOTON_TPU_TELEMETRY=1): phase spans, solver "
                        "trajectories, compile/memory metrics; writes "
                        "runreport.json + trace.json (Perfetto-loadable) "
                        "under --root-output-directory")
    p.add_argument("--log-level", default="INFO")
    return p


def _emit_optimization_logs(estimator, results) -> None:
    """One PhotonOptimizationLogEvent per trained configuration with the
    per-coordinate convergence summaries snapshotted per configuration
    (reference: Driver.scala PhotonOptimizationLogEvent with the
    lambda-model trackers)."""
    from photon_tpu.utils import events

    for i, result in enumerate(results):
        payload = {"configuration": i,
                   "regularization": {
                       cid: c.optimization.regularization_weight
                       for cid, c in result.config.items()}}
        for cid, summary in result.tracker_summaries.items():
            payload[f"tracker/{cid}"] = summary
        if result.evaluation is not None:
            payload["evaluation"] = dict(result.evaluation)
        events.emitter.emit(events.optimization_log_event(**payload))


def compute_shard_statistics(df, shard_ids):
    """Per-shard FeatureDataStatistics over the training frame
    (reference: GameTrainingDriver.prepareFeatureMapsAndStats)."""
    from photon_tpu.data.stats import compute_feature_stats

    out = {}
    for sid in shard_ids:
        feats = df.shard_features(sid)
        out[sid] = compute_feature_stats(feats, df.feature_shards[sid].dim)
    return out


def build_normalization(args, df, index_maps, shard_ids):
    """(contexts, intercept_indices, stats) for the estimator + summary
    output. Stats are computed when either normalization or a summary
    directory asks for them."""
    from photon_tpu.io.index_map import INTERCEPT_KEY
    from photon_tpu.ops.normalization import build_normalization_context

    ntype = NormalizationType(args.normalization_type)
    want_stats = ntype != NormalizationType.NONE or args.data_summary_directory
    if not want_stats:
        return {}, {}, {}
    stats = compute_shard_statistics(df, shard_ids)
    intercepts = {
        sid: idx for sid, idx in
        ((sid, index_maps[sid].get_index(INTERCEPT_KEY)) for sid in shard_ids)
        if idx >= 0
    }
    contexts = {}
    if ntype != NormalizationType.NONE:
        for sid in shard_ids:
            s = stats[sid]
            contexts[sid] = build_normalization_context(
                ntype, s.mean, s.variance, s.abs_max,
                intercept_index=intercepts.get(sid))
    return contexts, intercepts, stats


def write_feature_summaries(summary_dir, stats, index_maps) -> None:
    """One Avro file per shard with per-feature summary metrics
    (reference: ModelProcessingUtils.writeBasicStatistics :393)."""
    from photon_tpu.io.avro import write_avro
    from photon_tpu.io.index_map import split_feature_key
    from photon_tpu.io.schemas import FEATURE_SUMMARIZATION_RESULT_AVRO

    for sid, s in stats.items():
        imap = index_maps[sid]
        mean = np.asarray(s.mean)
        var = np.asarray(s.variance)
        mn = np.asarray(s.min)
        mx = np.asarray(s.max)
        nnz = np.asarray(s.num_nonzeros)
        records = []
        for j in range(len(mean)):
            key = imap.get_feature_name(j)
            name, term = split_feature_key(key) if key else (str(j), "")
            records.append({
                "featureName": name,
                "featureTerm": term,
                "metrics": {"mean": float(mean[j]), "variance": float(var[j]),
                            "min": float(mn[j]), "max": float(mx[j]),
                            "numNonzeros": float(nnz[j]),
                            "count": float(s.count)},
            })
        d = os.path.join(summary_dir, sid)
        os.makedirs(d, exist_ok=True)
        write_avro(os.path.join(d, "part-00000.avro"),
                   FEATURE_SUMMARIZATION_RESULT_AVRO, records)
        logger.info("wrote %d feature summaries for shard %s under %s",
                    len(records), sid, d)


def _id_tags_needed(args, parsed: List[ParsedCoordinate]) -> List[str]:
    tags = set(args.id_tag_columns)
    for p in parsed:
        re_type = getattr(p.configuration.data, "random_effect_type", None)
        if re_type:
            tags.add(re_type)
    for ev in args.validation_evaluators or []:
        _, _, tag = str(ev).partition(":")
        if tag:
            tags.add(tag)
    return sorted(tags)


def run(args: argparse.Namespace) -> List:
    logging.basicConfig(level=args.log_level,
                        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    from photon_tpu.utils import events

    with events.driver_listeners(args.event_listeners):
        events.emitter.emit(events.setup_event(driver="game-train",
                                               params=vars(args)))
        return _run(args)


def _run(args: argparse.Namespace) -> List:
    from photon_tpu import obs
    from photon_tpu.utils import events

    if getattr(args, "telemetry", False):
        obs.configure(True)
    _root_span = obs.span("train", driver="game-train")
    _root_span.__enter__()

    task = TaskType(args.training_task)
    out_dir = args.root_output_directory
    os.makedirs(out_dir, exist_ok=True)

    sweep_l2 = None
    if args.sweep_l2:
        # typed refusal of a bad grid BEFORE any data is read or compiled
        from photon_tpu.optim.batched import validate_lane_weights
        sweep_l2 = validate_lane_weights(
            [s.strip() for s in args.sweep_l2.split(",")], name="--sweep-l2")

    shard_configs = dict(parse_feature_shard_config(s)
                         for s in args.feature_shards)
    parsed = [parse_coordinate_config(c) for c in args.coordinates]
    coordinate_configs = {p.name: p.configuration for p in parsed}
    if obs.enabled():
        # device-resident solver telemetry needs the per-iteration ring
        # buffer in the while-loop carry; honor an explicit size if the
        # config set one, otherwise use the reference's 100-state window
        import dataclasses as _dc
        for name, cfg in list(coordinate_configs.items()):
            opt = cfg.optimization.optimizer
            if opt.track_states == 0:
                coordinate_configs[name] = _dc.replace(
                    cfg, optimization=_dc.replace(
                        cfg.optimization,
                        optimizer=_dc.replace(opt, track_states=100)))
    update_sequence = [s.strip() for s in
                       args.coordinate_update_sequence.split(",")]
    unknown = set(update_sequence) - set(coordinate_configs)
    if unknown:
        raise ValueError(f"update sequence references unknown coordinates: {unknown}")
    id_tags = _id_tags_needed(args, parsed)

    from photon_tpu.utils.date_range import (
        DateRange,
        DaysRange,
        resolve_input_dirs,
    )

    def date_range_of(range_text, days_text):
        if range_text and days_text:
            raise ValueError(
                "--*-date-range and --*-days-range are mutually exclusive "
                "(reference: GameDriver treats them so)")
        if range_text:
            return DateRange.from_string(range_text)
        if days_text:
            return DaysRange.from_string(days_text).to_date_range()
        return None

    def read_frame(dirs, imaps):
        """Columnar native ingest when the schema shape and C toolchain
        allow it, generic record path otherwise (io/fast_ingest.py)."""
        return read_frame_with_fallback(dirs, shard_configs,
                                        index_maps=imaps,
                                        id_tag_columns=id_tags)

    with Timed("read training data", logger):
        input_dirs = resolve_input_dirs(
            args.input_data_directories,
            date_range_of(args.input_data_date_range,
                          args.input_data_days_range))
        df, index_maps = read_frame(input_dirs, None)
    validation_df = None
    if args.validation_data_directories:
        with Timed("read validation data", logger):
            val_dirs = resolve_input_dirs(
                args.validation_data_directories,
                date_range_of(args.validation_data_date_range,
                              args.validation_data_days_range))
            validation_df, _ = read_frame(val_dirs, index_maps)

    with Timed("data validation", logger):
        df = validate_dataframe(
            df, task, DataValidationType(args.data_validation),
            drop_invalid_rows=getattr(args, "data_validation_drop_invalid",
                                      False))

    shard_ids = sorted({p.configuration.data.feature_shard_id for p in parsed})
    with Timed("feature stats + normalization", logger):
        norm_contexts, intercepts, stats = build_normalization(
            args, df, index_maps, shard_ids)
    if args.data_summary_directory and stats:
        with Timed("write feature summaries", logger):
            write_feature_summaries(args.data_summary_directory, stats,
                                    index_maps)

    mesh = None
    if args.num_devices:
        from photon_tpu.parallel import mesh as M
        mesh = M.create_mesh(args.num_devices)

    initial_model = None
    if args.model_input_directory:
        from photon_tpu.io.model_io import load_game_model
        # pass the LoadedGameModel through — the estimator re-aligns its
        # random-effect blocks to the fresh ingest's entity/slot layout
        initial_model = load_game_model(args.model_input_directory, index_maps)
        logger.info("warm-starting from %s", args.model_input_directory)

    estimator = GameEstimator(
        task=task,
        coordinate_configs=coordinate_configs,
        update_sequence=update_sequence,
        num_iterations=args.coordinate_descent_iterations,
        validation_evaluators=args.validation_evaluators,
        locked_coordinates=args.partial_retrain_locked_coordinates,
        mesh=mesh,
        variance_computation_type=VarianceComputationType(
            args.variance_computation_type),
        normalization_contexts=norm_contexts,
        intercept_indices=intercepts,
    )

    sweeps = expand_sweep(parsed)
    events.emitter.emit(events.training_start_event(
        task=task.value, configurations=len(sweeps),
        coordinates=list(update_sequence), num_samples=df.num_samples))
    ckpt_dir = args.resume_from or args.checkpoint_directory
    import contextlib
    profile_cm = contextlib.nullcontext()
    if args.profile_dir:
        import jax
        profile_cm = jax.profiler.trace(args.profile_dir)
    from photon_tpu.resilience.failures import (
        CoordinateFailureError,
        PreemptionRequested,
    )
    try:
        with profile_cm, Timed(f"train {len(sweeps)} configuration(s)",
                               logger):
            results = estimator.fit(df, validation_df=validation_df,
                                    configurations=sweeps,
                                    initial_model=initial_model,
                                    checkpoint_dir=ckpt_dir,
                                    resume=bool(args.resume_from))
    except (PreemptionRequested, CoordinateFailureError) as e:
        # the exception carries the emergency checkpoint path published at
        # the abort boundary; flush telemetry so the RunReport records the
        # failure trail, then let main() map it to a distinct exit code
        logger.warning("training interrupted: %s", e)
        _root_span.__exit__(None, None, None)
        _write_telemetry_artifacts(out_dir, mesh, len(sweeps),
                                   update_sequence)
        raise
    if sweep_l2 is not None:
        with Timed(f"lane-batched l2 sweep over {len(sweep_l2)} weights",
                   logger):
            results = results + estimator.fit_swept(
                df, validation_df=validation_df, weights=sweep_l2)
    _emit_optimization_logs(estimator, results)

    tuned = []
    if args.tune > 0:
        if validation_df is None:
            logger.warning("--tune %d requested but no "
                           "--validation-data-directories given: skipping "
                           "tuning", args.tune)
        else:
            with Timed(f"lane-batched tuning ({args.tune} rounds)", logger):
                mode = HyperparameterTuningMode(args.hyper_parameter_tuning)
                tune_res = estimator.tune(
                    df, validation_df,
                    n_rounds=args.tune, ask_batch=args.tune_ask_batch,
                    mode=None if mode == HyperparameterTuningMode.NONE
                    else mode)
            from photon_tpu.game.descent import CoordinateDescentResult
            primary = estimator.evaluators[0]
            gm = tune_res.best_model
            tuned.append(GameResult(
                model=gm,
                config={cid: estimator.coordinate_configs[cid]
                        .with_regularization_weight(w)
                        for cid, w in tune_res.best_config.items()},
                evaluation={primary.name: tune_res.best_metric},
                descent=CoordinateDescentResult(
                    model=gm, best_model=gm,
                    validation_history=[{primary.name:
                                         tune_res.best_metric}]),
            ))
            logger.info("tuned best config %s -> %s=%s",
                        tune_res.best_config, primary.name,
                        tune_res.best_metric)
    mode = HyperparameterTuningMode(args.hyper_parameter_tuning)
    if mode != HyperparameterTuningMode.NONE:
        if args.hyper_parameter_tuning_iter <= 0:
            logger.warning("--hyper-parameter-tuning %s requested but "
                           "--hyper-parameter-tuning-iter is %d: skipping "
                           "tuning", mode.value, args.hyper_parameter_tuning_iter)
        if validation_df is None:
            logger.warning("--hyper-parameter-tuning %s requested but no "
                           "--validation-data-directories given: skipping "
                           "tuning", mode.value)
    if (mode != HyperparameterTuningMode.NONE
            and args.hyper_parameter_tuning_iter > 0
            and validation_df is not None):
        with Timed("hyperparameter tuning", logger):
            prior_json = None
            if args.hyper_parameter_prior_json:
                with open(args.hyper_parameter_prior_json) as f:
                    prior_json = f.read()
            tuned = run_hyperparameter_tuning(
                estimator, df, validation_df,
                n_iterations=args.hyper_parameter_tuning_iter,
                mode=mode, prior_results=results,
                prior_json=prior_json,
                shrink_radius=args.hyper_parameter_shrink_radius)

    best = _best_result(estimator, results + tuned)
    events.emitter.emit(events.training_finish_event(
        models_trained=len(results) + len(tuned),
        best_evaluation=None if best.evaluation is None
        else dict(best.evaluation)))
    save_models(args, estimator, results, tuned, index_maps, out_dir)
    _root_span.__exit__(None, None, None)
    _write_telemetry_artifacts(out_dir, mesh, len(sweeps), update_sequence)
    return results + tuned


def _write_telemetry_artifacts(out_dir, mesh, n_configurations,
                               update_sequence) -> None:
    """RunReport + trace flush — shared by the normal exit path and the
    preemption/failure emergency path."""
    from photon_tpu import obs

    if not obs.enabled():
        return
    try:
        report_path = os.path.join(out_dir, "runreport.json")
        obs.write_run_report(
            report_path, driver="game-train",
            mesh=mesh,
            extra={"configurations": n_configurations,
                   "coordinates": list(update_sequence)},
            aggregate=True)
        trace_path = os.path.join(out_dir, "trace.json")
        obs.write_trace(trace_path)
        logger.info("telemetry: run report at %s, trace at %s",
                    report_path, trace_path)
    except Exception as e:  # noqa: BLE001 — telemetry must never fail a run
        logger.warning("failed to write telemetry artifacts: %r", e)


def _best_result(estimator: GameEstimator, results: List):
    primary = estimator.evaluators[0]
    scored = [r for r in results if r.evaluation is not None]
    if not scored:
        return results[-1]
    return (max if primary.bigger_is_better else min)(
        scored, key=lambda r: r.evaluation[primary.name])


def save_models(args, estimator, results, tuned, index_maps, out_dir) -> None:
    mode = ModelOutputMode(args.output_mode)
    if mode == ModelOutputMode.NONE:
        return
    to_save: Dict[str, object] = {}
    if mode == ModelOutputMode.BEST:
        to_save["best"] = _best_result(estimator, results + tuned)
    else:
        if mode in (ModelOutputMode.EXPLICIT, ModelOutputMode.ALL):
            for i, r in enumerate(results):
                to_save[f"models/{i}"] = r
        if mode in (ModelOutputMode.TUNED, ModelOutputMode.ALL):
            for i, r in enumerate(tuned):
                to_save[f"tuned/{i}"] = r
        to_save["best"] = _best_result(estimator, results + tuned)

    from photon_tpu.estimators.game_estimator import persistable_artifacts
    base_projections = {cid: np.asarray(ds.projection)
                        for cid, ds in estimator._re_datasets.items()}
    for rel, result in to_save.items():
        d = os.path.join(out_dir, rel)
        with Timed(f"save model {rel}", logger):
            # RANDOM-projected coordinates are back-projected into the
            # original feature space before hitting disk (reference:
            # Projector.projectCoefficients); INDEX_MAP/IDENTITY pass through
            model, projections = persistable_artifacts(
                estimator, result.model, base_projections=base_projections)
            save_game_model(
                d, model, index_maps,
                vocab=estimator._vocab, projections=projections,
                coordinate_configs=result.config,
                sparsity_threshold=args.model_sparsity_threshold)
        if result.evaluation is not None:
            with open(os.path.join(d, "evaluation.json"), "w") as f:
                json.dump(result.evaluation, f, indent=2)
    logger.info("saved %d model(s) under %s", len(to_save), out_dir)


def main(argv: Optional[List[str]] = None) -> None:
    from photon_tpu.resilience import shutdown as _shutdown
    from photon_tpu.resilience.failures import (
        EXIT_COORDINATE_FAILURE,
        EXIT_PREEMPTED,
        CoordinateFailureError,
        PreemptionRequested,
    )
    from photon_tpu.utils.compile_cache import maybe_enable
    maybe_enable()
    # SIGTERM/SIGINT -> graceful stop at the next coordinate boundary with
    # an emergency checkpoint (resilience/shutdown.py); a second SIGINT
    # still kills immediately
    _shutdown.install()
    try:
        run(build_arg_parser().parse_args(argv))
    except PreemptionRequested as e:
        logger.warning("preempted (%s); emergency checkpoint: %s",
                       _shutdown.reason(), e.checkpoint_path)
        sys.exit(EXIT_PREEMPTED)
    except CoordinateFailureError as e:
        logger.error("training aborted: %s (resume from checkpoint: %s)",
                     e, e.checkpoint_path)
        sys.exit(EXIT_COORDINATE_FAILURE)
    finally:
        _shutdown.uninstall()


if __name__ == "__main__":
    main()
