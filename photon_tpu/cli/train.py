"""GAME training driver: ingest -> validate -> fit -> select -> persist.

Reference: photon-client cli/game/training/GameTrainingDriver.scala
(params :67-155, run :346, main :833): read Avro training/validation
data, prepare feature maps, sanity-check, compute stats + normalization,
fit one model per optimization configuration (cartesian sweep), optional
hyperparameter tuning, select + save models per ModelOutputMode
(io/ModelOutputMode.scala:20-46).

Usage:
  python -m photon_tpu.cli.train \\
    --input-data-directories data/train \\
    --validation-data-directories data/val \\
    --root-output-directory out \\
    --training-task LOGISTIC_REGRESSION \\
    --feature-shard-configuration name=global,feature.bags=features \\
    --coordinate-configuration name=fixed,feature.shard=global,\\
optimizer=LBFGS,tolerance=1e-7,max.iter=50,regularization=L2,reg.weights=1|10 \\
    --coordinate-update-sequence fixed
"""

from __future__ import annotations

import argparse
import enum
import json
import logging
import os
import sys
from typing import Dict, List, Optional

import numpy as np

from photon_tpu.cli.config import (
    ParsedCoordinate,
    expand_sweep,
    parse_coordinate_config,
    parse_feature_shard_config,
)
from photon_tpu.data.validators import DataValidationType, validate_dataframe
from photon_tpu.estimators.game_estimator import GameEstimator
from photon_tpu.hyperparameter.tuner import (
    HyperparameterTuningMode,
    run_hyperparameter_tuning,
)
from photon_tpu.io.data_io import (
    build_index_maps,
    read_records,
    records_to_game_dataframe,
)
from photon_tpu.io.model_io import save_game_model
from photon_tpu.types import TaskType, VarianceComputationType
from photon_tpu.utils.timing import Timed

logger = logging.getLogger("photon_tpu.train")


class ModelOutputMode(enum.Enum):
    """Reference: io/ModelOutputMode.scala:20-46."""

    NONE = "NONE"          # save nothing
    BEST = "BEST"          # only the best model by validation metric
    EXPLICIT = "EXPLICIT"  # all explicitly-configured models
    TUNED = "TUNED"        # only tuned models
    ALL = "ALL"            # explicit + tuned


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon_tpu.train",
        description="Train a GAME model (fixed + random effects) on TPU")
    p.add_argument("--input-data-directories", nargs="+", required=True)
    p.add_argument("--validation-data-directories", nargs="*", default=[])
    p.add_argument("--root-output-directory", required=True)
    p.add_argument("--training-task", required=True,
                   choices=[t.value for t in TaskType])
    p.add_argument("--feature-shard-configuration", action="append",
                   required=True, dest="feature_shards")
    p.add_argument("--coordinate-configuration", action="append",
                   required=True, dest="coordinates")
    p.add_argument("--coordinate-update-sequence", required=True,
                   help="comma-separated coordinate names")
    p.add_argument("--coordinate-descent-iterations", type=int, default=1)
    p.add_argument("--validation-evaluators", nargs="*", default=None,
                   help='e.g. AUC RMSE "AUC:userId" "PRECISION@5:userId"')
    p.add_argument("--id-tag-columns", nargs="*", default=[],
                   help="record columns carrying entity ids")
    p.add_argument("--model-input-directory", default=None,
                   help="warm-start GAME model directory")
    p.add_argument("--partial-retrain-locked-coordinates", nargs="*",
                   default=[])
    p.add_argument("--output-mode", default="BEST",
                   choices=[m.value for m in ModelOutputMode])
    p.add_argument("--variance-computation-type", default="NONE",
                   choices=[v.value for v in VarianceComputationType])
    p.add_argument("--data-validation", default="VALIDATE_FULL",
                   choices=[v.value for v in DataValidationType])
    p.add_argument("--hyper-parameter-tuning", default="NONE",
                   choices=[m.value for m in HyperparameterTuningMode])
    p.add_argument("--hyper-parameter-tuning-iter", type=int, default=0)
    p.add_argument("--model-sparsity-threshold", type=float, default=1e-4)
    p.add_argument("--num-devices", type=int, default=0,
                   help="shard training over this many devices (0 = single)")
    p.add_argument("--log-level", default="INFO")
    return p


def _id_tags_needed(args, parsed: List[ParsedCoordinate]) -> List[str]:
    tags = set(args.id_tag_columns)
    for p in parsed:
        re_type = getattr(p.configuration.data, "random_effect_type", None)
        if re_type:
            tags.add(re_type)
    for ev in args.validation_evaluators or []:
        _, _, tag = str(ev).partition(":")
        if tag:
            tags.add(tag)
    return sorted(tags)


def run(args: argparse.Namespace) -> List:
    logging.basicConfig(level=args.log_level,
                        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    task = TaskType(args.training_task)
    out_dir = args.root_output_directory
    os.makedirs(out_dir, exist_ok=True)

    shard_configs = dict(parse_feature_shard_config(s)
                         for s in args.feature_shards)
    parsed = [parse_coordinate_config(c) for c in args.coordinates]
    coordinate_configs = {p.name: p.configuration for p in parsed}
    update_sequence = [s.strip() for s in
                       args.coordinate_update_sequence.split(",")]
    unknown = set(update_sequence) - set(coordinate_configs)
    if unknown:
        raise ValueError(f"update sequence references unknown coordinates: {unknown}")
    id_tags = _id_tags_needed(args, parsed)

    with Timed("read training data", logger):
        records = read_records(args.input_data_directories)
        index_maps = build_index_maps(records, shard_configs)
        df = records_to_game_dataframe(records, shard_configs, index_maps,
                                       id_tag_columns=id_tags)
    validation_df = None
    if args.validation_data_directories:
        with Timed("read validation data", logger):
            vrecords = read_records(args.validation_data_directories)
            validation_df = records_to_game_dataframe(
                vrecords, shard_configs, index_maps, id_tag_columns=id_tags)

    with Timed("data validation", logger):
        validate_dataframe(df, task, DataValidationType(args.data_validation))

    mesh = None
    if args.num_devices:
        from photon_tpu.parallel import mesh as M
        mesh = M.create_mesh(args.num_devices)

    initial_model = None
    if args.model_input_directory:
        from photon_tpu.io.model_io import load_game_model
        # pass the LoadedGameModel through — the estimator re-aligns its
        # random-effect blocks to the fresh ingest's entity/slot layout
        initial_model = load_game_model(args.model_input_directory, index_maps)
        logger.info("warm-starting from %s", args.model_input_directory)

    estimator = GameEstimator(
        task=task,
        coordinate_configs=coordinate_configs,
        update_sequence=update_sequence,
        num_iterations=args.coordinate_descent_iterations,
        validation_evaluators=args.validation_evaluators,
        locked_coordinates=args.partial_retrain_locked_coordinates,
        mesh=mesh,
        variance_computation_type=VarianceComputationType(
            args.variance_computation_type),
    )

    sweeps = expand_sweep(parsed)
    with Timed(f"train {len(sweeps)} configuration(s)", logger):
        results = estimator.fit(df, validation_df=validation_df,
                                configurations=sweeps,
                                initial_model=initial_model)

    tuned = []
    mode = HyperparameterTuningMode(args.hyper_parameter_tuning)
    if mode != HyperparameterTuningMode.NONE:
        if args.hyper_parameter_tuning_iter <= 0:
            logger.warning("--hyper-parameter-tuning %s requested but "
                           "--hyper-parameter-tuning-iter is %d: skipping "
                           "tuning", mode.value, args.hyper_parameter_tuning_iter)
        if validation_df is None:
            logger.warning("--hyper-parameter-tuning %s requested but no "
                           "--validation-data-directories given: skipping "
                           "tuning", mode.value)
    if (mode != HyperparameterTuningMode.NONE
            and args.hyper_parameter_tuning_iter > 0
            and validation_df is not None):
        with Timed("hyperparameter tuning", logger):
            tuned = run_hyperparameter_tuning(
                estimator, df, validation_df,
                n_iterations=args.hyper_parameter_tuning_iter,
                mode=mode, prior_results=results)

    save_models(args, estimator, results, tuned, index_maps, out_dir)
    return results + tuned


def _best_result(estimator: GameEstimator, results: List):
    primary = estimator.evaluators[0]
    scored = [r for r in results if r.evaluation is not None]
    if not scored:
        return results[-1]
    return (max if primary.bigger_is_better else min)(
        scored, key=lambda r: r.evaluation[primary.name])


def save_models(args, estimator, results, tuned, index_maps, out_dir) -> None:
    mode = ModelOutputMode(args.output_mode)
    if mode == ModelOutputMode.NONE:
        return
    to_save: Dict[str, object] = {}
    if mode == ModelOutputMode.BEST:
        to_save["best"] = _best_result(estimator, results + tuned)
    else:
        if mode in (ModelOutputMode.EXPLICIT, ModelOutputMode.ALL):
            for i, r in enumerate(results):
                to_save[f"models/{i}"] = r
        if mode in (ModelOutputMode.TUNED, ModelOutputMode.ALL):
            for i, r in enumerate(tuned):
                to_save[f"tuned/{i}"] = r
        to_save["best"] = _best_result(estimator, results + tuned)

    projections = {cid: np.asarray(ds.projection)
                   for cid, ds in estimator._re_datasets.items()}
    for rel, result in to_save.items():
        d = os.path.join(out_dir, rel)
        with Timed(f"save model {rel}", logger):
            save_game_model(
                d, result.model, index_maps,
                vocab=estimator._vocab, projections=projections,
                coordinate_configs=result.config,
                sparsity_threshold=args.model_sparsity_threshold)
        if result.evaluation is not None:
            with open(os.path.join(d, "evaluation.json"), "w") as f:
                json.dump(result.evaluation, f, indent=2)
    logger.info("saved %d model(s) under %s", len(to_save), out_dir)


def main(argv: Optional[List[str]] = None) -> None:
    run(build_arg_parser().parse_args(argv))


if __name__ == "__main__":
    main()
