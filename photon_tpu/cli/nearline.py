"""Nearline delta-training driver: tail an event log into a live engine.

One process = one serving engine (the same build path as ``cli/serve``)
plus one :class:`~photon_tpu.nearline.pipeline.NearlinePipeline` looping
poll -> delta-train -> row-publish -> checkpoint against it.  The engine
here serves no external traffic — this driver exists to keep a model
directory's coefficient tables (hot AND cold tier) fresh while a
separate serving process reads them, or to run the whole closed loop in
one process for tests and benchmarks.

Event line schema (JSONL shards in ``--event-log``, one JSON object per
line; Avro shards with the same payload also work)::

    {"seq": 17,                   # assigned by the writer, monotone
     "ts": 1754400000.0,          # unix seconds; drives freshness lag
     "response": 1.0,
     "offset": 0.0,
     "weight": 1.0,
     "features": {"shardA": [["name", "term", 1.5], ...]},
     "entities": {"userId": "u17"}}

Lifecycle: SIGTERM/SIGINT (the shared resilience shutdown flag) finishes
the in-flight round, lands the final watermark checkpoint, writes the
stats / RunReport artifacts, and exits 0.  Restart resumes from the
durable watermark; a crash between publish and checkpoint is reconciled
from the versioned delta manifest (exactly-once per publish).

Usage::

    python -m photon_tpu.cli.nearline \
        --model-input-directory /path/to/model --event-log /path/to/log \
        [--poll-interval-s 1.0] [--max-rounds 0] [--stats-output s.json]
"""

from __future__ import annotations

import argparse
import json
import logging
import sys
from typing import Optional

logger = logging.getLogger("photon_tpu.nearline")


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon_tpu.nearline",
        description="Tail an event log into live serving tables via "
                    "delta training and row-level publish")
    p.add_argument("--model-input-directory", required=True)
    p.add_argument("--event-log", required=True,
                   help="directory of append-only JSONL/Avro event shards")
    p.add_argument("--coordinates", nargs="*", default=None,
                   help="subset of coordinate ids to load (default: all)")
    p.add_argument("--poll-interval-s", type=float, default=1.0,
                   help="idle sleep between empty polls")
    p.add_argument("--max-rounds", type=int, default=0,
                   help="stop after N non-empty rounds (0 = until SIGTERM)")
    p.add_argument("--max-events-per-round", type=int, default=None)
    p.add_argument("--state-dir", default=None,
                   help="checkpoint/manifest directory "
                        "(default: <model_dir>/nearline)")
    p.add_argument("--max-entity-buckets", type=int, default=4,
                   help="size-bucketed solve programs per delta round")
    p.add_argument("--fixed-refresh-every", type=int, default=0,
                   help="full fixed-effect refresh cadence in rounds "
                        "(0 = never; runs through the validated swap)")
    p.add_argument("--max-row-deviation", type=float, default=None,
                   help="reject delta rows deviating more than this from "
                        "the live row (default: finite-only)")
    p.add_argument("--parity-tol", type=float, default=1e-4,
                   help="shadow-score parity tolerance on touched entities")
    p.add_argument("--publish-probation-s", type=float, default=0.0,
                   help="auto-rollback window watching the serving breaker")
    p.add_argument("--max-batch", type=int, default=64,
                   help="top of the engine's bucket ladder")
    p.add_argument("--feature-pad", type=int, default=None)
    p.add_argument("--append-reserve", type=int, default=None,
                   help="zero rows reserved per full-resident coordinate "
                        "for new-entity appends (default: engine default)")
    p.add_argument("--no-warmup", action="store_true",
                   help="skip ladder pre-compilation (debugging only)")
    p.add_argument("--stats-output", default=None,
                   help="write the pipeline summary JSON here at exit")
    p.add_argument("--runreport-output", default=None,
                   help="write a RunReport (with nearline section) here")
    p.add_argument("--log-level", default="INFO")
    return p


def build_pipeline(args: argparse.Namespace):
    from photon_tpu.nearline import (
        DeltaTrainConfig,
        NearlineConfig,
        NearlinePipeline,
        NearlinePublishConfig,
    )
    from photon_tpu.serving import ServingConfig, ServingEngine
    from photon_tpu.utils import compile_cache

    compile_cache.maybe_enable()
    serving_kwargs = dict(max_batch=args.max_batch,
                          feature_pad=args.feature_pad)
    if args.append_reserve is not None:
        serving_kwargs["append_reserve"] = args.append_reserve
    engine = ServingEngine.from_model_dir(
        args.model_input_directory, config=ServingConfig(**serving_kwargs),
        coordinates_to_load=args.coordinates)
    if not args.no_warmup:
        info = engine.warmup()
        logger.info("warmed %d programs over buckets %s in %.2fs",
                    info["programs"], info["buckets"], info["seconds"])
    config = NearlineConfig(
        poll_interval_s=args.poll_interval_s,
        max_rounds=args.max_rounds,
        max_events_per_round=args.max_events_per_round,
        state_dir=args.state_dir,
        train=DeltaTrainConfig(
            max_entity_buckets=args.max_entity_buckets,
            fixed_refresh_every=args.fixed_refresh_every),
        publish=NearlinePublishConfig(
            max_row_deviation=(args.max_row_deviation
                               if args.max_row_deviation is not None
                               else float("inf")),
            parity_tol=args.parity_tol,
            probation_s=args.publish_probation_s))
    return NearlinePipeline(engine, args.event_log,
                            model_dir=args.model_input_directory,
                            config=config)


def run(args: argparse.Namespace) -> int:
    logging.basicConfig(
        level=args.log_level, stream=sys.stderr,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    import photon_tpu.serving as serving_pkg
    from photon_tpu.resilience import shutdown

    pipeline = build_pipeline(args)
    serving_pkg.set_active_engine(pipeline.engine)
    shutdown.install()
    try:
        summary = pipeline.run()
    finally:
        shutdown.uninstall()
        pipeline.engine.shutdown(0.0, reason="nearline loop exit")
    logger.info("nearline loop done: %d rounds, %d rows published",
                summary["rounds"], summary["totals"]["rows_updated"]
                + summary["totals"]["rows_appended"])
    if args.stats_output:
        with open(args.stats_output, "w") as f:
            json.dump(summary, f, indent=1)
            f.write("\n")
    if args.runreport_output:
        from photon_tpu.obs.report import write_run_report
        write_run_report(args.runreport_output, driver="nearline")
    return 0


def main(argv: Optional[list] = None) -> int:
    return run(build_arg_parser().parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
