"""Offline feature-index build + name-term feature bag extraction.

Reference: photon-client index/FeatureIndexingDriver.scala:41 (run :167,
main :297 — extract NameAndTerm per feature bag, partition by hash,
build one PalDB store per partition) and data/avro/
NameAndTermFeatureBagsDriver.scala:32 (run :143 — distinct feature
name-terms per bag written as text).

The PalDB stores become mmap-able binary index partitions
(io/index_store.py) readable by Python and the native C++ reader.
"""

from __future__ import annotations

import argparse
import logging
import os
from typing import Dict, List, Optional, Set

from photon_tpu.cli.config import parse_feature_shard_config
from photon_tpu.io import avro as avro_io
from photon_tpu.io.data_io import (
    FeatureShardConfiguration,
    _record_keys,
    read_records,
)
from photon_tpu.io.index_map import INTERCEPT_KEY
from photon_tpu.io.index_store import PartitionedIndexMap, write_partitioned_index
from photon_tpu.utils.timing import Timed

logger = logging.getLogger("photon_tpu.index")


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon_tpu.feature_index",
        description="Build partitioned feature index stores from Avro data")
    p.add_argument("--input-data-directories", nargs="+", required=True)
    p.add_argument("--root-output-directory", required=True)
    p.add_argument("--feature-shard-configuration", action="append",
                   required=True, dest="feature_shards")
    p.add_argument("--num-partitions", type=int, default=1)
    p.add_argument("--log-level", default="INFO")
    return p


def collect_shard_keys(records, shard_configs: Dict[str, FeatureShardConfiguration]
                       ) -> Dict[str, Set[str]]:
    keys: Dict[str, Set[str]] = {sid: set() for sid in shard_configs}
    for rec in records:
        for sid, cfg in shard_configs.items():
            for k, _ in _record_keys(rec, cfg.feature_bags):
                keys[sid].add(k)
    for sid, cfg in shard_configs.items():
        if cfg.has_intercept:
            keys[sid].add(INTERCEPT_KEY)
    return keys


def run(args: argparse.Namespace) -> Dict[str, int]:
    logging.basicConfig(level=args.log_level)
    shard_configs = dict(parse_feature_shard_config(s)
                         for s in args.feature_shards)
    with Timed("read data", logger):
        records = read_records(args.input_data_directories)
    with Timed("collect feature keys", logger):
        keys = collect_shard_keys(records, shard_configs)
    dims: Dict[str, int] = {}
    for sid, shard_keys in keys.items():
        with Timed(f"write index partitions [{sid}]", logger):
            dims[sid] = write_partitioned_index(
                args.root_output_directory, sid, shard_keys,
                num_partitions=args.num_partitions)
        logger.info("shard %s: %d features, %d partitions", sid, dims[sid],
                    args.num_partitions)
    return dims


def load_index_maps(directory: str, shard_ids) -> Dict[str, "IndexMap"]:
    """Load built partitions back as plain IndexMaps (the per-executor
    PalDBIndexMapLoader role)."""
    out = {}
    for sid in shard_ids:
        pim = PartitionedIndexMap(directory, sid)
        try:
            out[sid] = pim.to_index_map()
        finally:
            pim.close()
    return out


def main(argv: Optional[List[str]] = None) -> None:
    run(build_arg_parser().parse_args(argv))


# ---------------------------------------------------------------------------
# name-term feature bags (reference: NameAndTermFeatureBagsDriver)
# ---------------------------------------------------------------------------


def build_bags_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon_tpu.name_term_bags",
        description="Extract distinct (name, term) pairs per feature bag")
    p.add_argument("--input-data-directories", nargs="+", required=True)
    p.add_argument("--root-output-directory", required=True)
    p.add_argument("--feature-bag-keys", nargs="+", required=True)
    p.add_argument("--log-level", default="INFO")
    return p


def run_bags(args: argparse.Namespace) -> Dict[str, int]:
    logging.basicConfig(level=args.log_level)
    records: List[dict] = []
    for d in args.input_data_directories:
        records.extend(avro_io.iter_avro_dir(d))
    os.makedirs(args.root_output_directory, exist_ok=True)
    counts = {}
    for bag in args.feature_bag_keys:
        pairs = set()
        for rec in records:
            for f in rec.get(bag) or ():
                pairs.add((str(f["name"]), str(f["term"])))
        out = os.path.join(args.root_output_directory, bag)
        with open(out, "w") as fh:
            for name, term in sorted(pairs):
                fh.write(f"{name}\t{term}\n")
        counts[bag] = len(pairs)
        logger.info("bag %s: %d distinct name-terms -> %s", bag, len(pairs), out)
    return counts


def bags_main(argv: Optional[List[str]] = None) -> None:
    run_bags(build_bags_arg_parser().parse_args(argv))


if __name__ == "__main__":
    main()
