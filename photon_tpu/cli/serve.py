"""Online serving driver: JSONL requests on stdin -> JSONL scores on stdout.

The online counterpart of ``cli/score`` (batch). One process = one
device-resident model + one serving engine; requests stream through the
micro-batcher and responses stream out in completion order (batch pops
are FIFO, so completion order is submission order except for immediate
typed rejections).

Request line schema::

    {"uid": "r1",
     "features": {"shardA": [["name", "term", 1.5], ...]},
     "ids": {"userId": "u17"},
     "offset": 0.0,
     "timeout_ms": 25}           # optional per-request deadline

Control lines (operator plane, same stream)::

    {"control": "swap", "model_dir": "/path/to/candidate", "label": "v2"}
    {"control": "drain"}
    {"control": "stats"}     # live stats + metrics snapshot (fleet merge)

A control line emits one ``{"control": ..., ...}`` response line instead
of a score. Response line schema otherwise: ``ScoreResponse.to_json()``
— ``{"uid", "score", "degraded", "fallbacks": [{"reason", ...}]}``.

Lifecycle: stdin is consumed by a reader thread so the main loop keeps
pumping batches (and noticing SIGTERM) while the pipe is quiet — a
blocking ``readline`` would otherwise pin the process through a whole
coalescing window and, worse, never observe a shutdown request (PEP 475
retries the read after the handler returns). SIGTERM/SIGINT flips the
engine to draining via the resilience shutdown flag: queued work is
flushed within ``--drain-budget-s``, later lines get typed
SHUTTING_DOWN refusals, stats/RunReport are written, and the process
exits 0.

Usage::

    python -m photon_tpu.cli.serve --model-input-directory /path/to/model \
        [--max-batch 64] [--max-wait-ms 2] [--stats-output stats.json] \
        < requests.jsonl > scores.jsonl

Fleet shard mode: with ``--fleet-manifest FLEET_DIR --shard-id K`` the
process instead serves ONE shard of an entity-sharded fleet
(``io/fleet_store``): a random-effects-only engine over the shard's
split cold stores, fixed effects left to the router
(``cli/fleet_serve``), which fans requests out to these processes.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import logging
import os
import queue
import sys
import threading
from typing import Optional

logger = logging.getLogger("photon_tpu.serve")

#: main-loop tick while the stdin queue is quiet: long enough to idle
#: cheaply, short enough that coalescing deadlines and drain flags are
#: noticed promptly
_TICK_S = 0.05


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon_tpu.serve",
        description="Serve a trained GAME model over JSONL stdin/stdout")
    p.add_argument("--model-input-directory", default=None,
                   help="trained model dir (required unless serving a "
                        "fleet shard via --fleet-manifest)")
    p.add_argument("--coordinates", nargs="*", default=None,
                   help="subset of coordinate ids to load (default: all)")
    p.add_argument("--fleet-manifest", default=None, metavar="FLEET_DIR",
                   help="entity-sharded fleet dir (io/fleet_store); with "
                        "--shard-id, serve ONE shard's random-effect "
                        "rows (the unit a fleet router fans out to)")
    p.add_argument("--shard-id", type=int, default=None,
                   help="which fleet shard this process owns")
    p.add_argument("--hot-capacity", type=int, default=None,
                   help="two-tier hot rows per coordinate (fleet shard "
                        "mode; default: whole shard store resident)")
    p.add_argument("--max-batch", type=int, default=64,
                   help="top of the power-of-two bucket ladder")
    p.add_argument("--max-wait-ms", type=float, default=2.0,
                   help="coalescing deadline for a partial batch")
    p.add_argument("--feature-pad", type=int, default=None,
                   help="per-shard padded feature width (default: auto)")
    p.add_argument("--shed-queue-depth", type=int, default=512)
    p.add_argument("--reject-queue-depth", type=int, default=4096)
    p.add_argument("--default-timeout-ms", type=float, default=None,
                   help="deadline for requests that carry no timeout_ms "
                        "(default: no deadline)")
    p.add_argument("--min-service-ms", type=float, default=0.0,
                   help="refuse budgets below this at admission")
    p.add_argument("--score-headroom-ms", type=float, default=0.0,
                   help="assemble+score time reserved when expiring "
                        "queued requests")
    p.add_argument("--breaker-latency-p99-ms", type=float, default=None,
                   help="scorer-stage p99 trip threshold "
                        "(default: latency trip disabled)")
    p.add_argument("--breaker-failure-rate", type=float, default=0.5)
    p.add_argument("--breaker-cooldown-s", type=float, default=1.0)
    p.add_argument("--drain-budget-s", type=float,
                   default=float(os.environ.get(
                       "PHOTON_TPU_DRAIN_BUDGET_S", "5.0")),
                   help="max seconds spent flushing queued work after "
                        "SIGTERM (env PHOTON_TPU_DRAIN_BUDGET_S)")
    p.add_argument("--swap-max-deviation", type=float, default=None,
                   help="reject a swap candidate whose shadow scores "
                        "deviate more than this (default: finite-only)")
    p.add_argument("--swap-require-manifest", action="store_true",
                   help="refuse swap candidates without swap-manifest.json")
    p.add_argument("--tenant", action="append", default=None,
                   metavar="NAME=MODEL_DIR",
                   help="host NAME's model from MODEL_DIR as one tenant "
                        "behind a shared compiled ladder (repeatable; "
                        "requests route by their \"tenant\" field; the "
                        "first tenant is the default route)")
    p.add_argument("--tenant-admission-budget", type=int, default=None,
                   help="per-tenant queued-depth cap; beyond it a tenant "
                        "gets typed TENANT_BUDGET_EXCEEDED refusals "
                        "while its neighbors are unaffected")
    p.add_argument("--program-cache", default=None, metavar="DIR",
                   help="AOT program-bundle directory (serving/programs): "
                        "load before warmup for a zero-trace zero-compile "
                        "cold start; export after warmup when nothing "
                        "loadable was found")
    p.add_argument("--no-warmup", action="store_true",
                   help="skip ladder pre-compilation (debugging only; "
                        "steady-state requests will compile)")
    p.add_argument("--capture", default=None, metavar="PATH",
                   help="record every admitted request into a crc32-framed "
                        "JSONL traffic capture (serving/replay) with "
                        "engine-clock offsets, for deterministic replay")
    p.add_argument("--stats-output", default=None,
                   help="write engine stats() JSON here at stream end")
    p.add_argument("--runreport-output", default=None,
                   help="write a RunReport (with serving section) here")
    p.add_argument("--log-level", default="INFO")
    return p


def build_engine(args: argparse.Namespace):
    from photon_tpu.serving import (
        BreakerConfig,
        DeadlineConfig,
        ServingConfig,
        ServingEngine,
        SLOConfig,
        SwapConfig,
    )
    from photon_tpu.utils import compile_cache

    compile_cache.maybe_enable()
    config = ServingConfig(
        max_batch=args.max_batch,
        max_wait_s=args.max_wait_ms / 1000.0,
        feature_pad=args.feature_pad,
        slo=SLOConfig(shed_queue_depth=args.shed_queue_depth,
                      reject_queue_depth=args.reject_queue_depth),
        deadline=DeadlineConfig(
            default_timeout_s=(args.default_timeout_ms / 1000.0
                               if args.default_timeout_ms is not None
                               else None),
            min_service_s=args.min_service_ms / 1000.0,
            score_headroom_s=args.score_headroom_ms / 1000.0),
        breaker=BreakerConfig(
            latency_p99_s=(args.breaker_latency_p99_ms / 1000.0
                           if args.breaker_latency_p99_ms is not None
                           else float("inf")),
            failure_rate=args.breaker_failure_rate,
            cooldown_s=args.breaker_cooldown_s),
        swap=SwapConfig(
            max_shadow_deviation=(args.swap_max_deviation
                                  if args.swap_max_deviation is not None
                                  else float("inf")),
            require_manifest=args.swap_require_manifest),
        drain_budget_s=args.drain_budget_s)
    if args.tenant:
        if args.fleet_manifest is not None:
            raise SystemExit("--tenant and --fleet-manifest are exclusive")
        return _build_multi_tenant(args, config)
    if args.fleet_manifest is not None:
        if args.shard_id is None:
            raise SystemExit("--fleet-manifest requires --shard-id")
        from photon_tpu.serving import CoeffStoreConfig
        from photon_tpu.serving.fleet import build_shard_engine
        if args.hot_capacity is not None:
            config = dataclasses.replace(config, coeff_store=CoeffStoreConfig(
                hot_capacity=args.hot_capacity))
        engine = build_shard_engine(args.fleet_manifest, args.shard_id,
                                    serving=config,
                                    model_dir=args.model_input_directory)
    elif args.model_input_directory is None:
        raise SystemExit("--model-input-directory is required "
                         "(or --fleet-manifest with --shard-id)")
    else:
        engine = ServingEngine.from_model_dir(
            args.model_input_directory, config=config,
            coordinates_to_load=args.coordinates)
    loaded = 0
    if args.program_cache:
        from photon_tpu.serving import load_program_bundle
        from photon_tpu.serving.programs import bundle_dir_for
        bdir = bundle_dir_for(args.program_cache, engine.model)
        got = load_program_bundle(engine.model, engine.ladder.buckets, bdir)
        loaded = got["loaded"]
        logger.info("program cache: %s",
                    f"seeded {loaded} programs from {bdir}" if loaded
                    else f"refused ({got['refused']}) — tracing warmup")
    if not args.no_warmup:
        info = engine.warmup()
        logger.info("warmed %d programs over buckets %s in %.2fs",
                    info["programs"], info["buckets"], info["seconds"])
        if args.program_cache and not loaded:
            from photon_tpu.serving import export_program_bundle
            out = export_program_bundle(engine.model, engine.ladder.buckets,
                                        bdir)
            logger.info("program cache: exported %d programs to %s",
                        out["exported"], out["dir"])
    return engine


def _build_multi_tenant(args: argparse.Namespace, config):
    """``--tenant NAME=DIR`` (repeated) -> a MultiTenantEngine: N models,
    one compiled bucket ladder, per-tenant isolation. With
    ``--program-cache`` the shared ladder loads from (or seeds) the AOT
    bundle, so a restarted replica warms N tenants with zero compiles."""
    from photon_tpu.serving import MultiTenantEngine

    engine = MultiTenantEngine(config=config)
    for spec in args.tenant:
        name, sep, model_dir = spec.partition("=")
        if not sep or not name or not model_dir:
            raise SystemExit(f"--tenant expects NAME=MODEL_DIR, got {spec!r}")
        engine.add_tenant_from_dir(
            name, model_dir, admission_budget=args.tenant_admission_budget,
            warm=False)
    loads = {}
    if args.program_cache:
        loads = engine.load_program_bundles(args.program_cache)
        for name, got in loads.items():
            logger.info("program cache [%s]: %s", name,
                        f"seeded {got['loaded']}" if got["loaded"]
                        else f"refused ({got['refused']})")
    if not args.no_warmup:
        info = engine.warmup()
        logger.info("warmed %d tenants: %d programs, compile counts %s",
                    len(info["tenants"]), info["programs"],
                    info["compile_counts"])
        if args.program_cache and not any(
                got.get("loaded", 0) for got in loads.values()):
            out = engine.export_program_bundles(args.program_cache)
            logger.info("program cache: exported %s",
                        {k: v["exported"] for k, v in out.items()})
    return engine


def _start_reader(stdin) -> "queue.Queue":
    """Feed stdin lines into a queue from a daemon thread; None = EOF.
    The main loop never blocks on the pipe, so signals and coalescing
    deadlines are handled even when no requests arrive."""
    lines: "queue.Queue" = queue.Queue()

    def _read():
        try:
            for line in stdin:
                lines.put(line)
        except ValueError:
            pass  # hygiene-ok: stdin closed mid-read during interpreter exit
        lines.put(None)

    threading.Thread(target=_read, name="serve-stdin-reader",
                     daemon=True).start()
    return lines


def _handle_control(engine, obj: dict) -> dict:
    """Operator control line -> one response dict. With a multi-tenant
    engine, ``swap`` takes an optional ``tenant`` (default tenant
    otherwise) and the canary verbs manage a tenant's A/B arm."""
    from photon_tpu.serving import swap_from_dir

    cmd = obj.get("control")
    tenants = getattr(engine, "tenants", None)  # MultiTenantEngine?

    def _named_tenant():
        name = obj.get("tenant") or engine.default_tenant
        return name, tenants.get(name)

    if cmd == "swap":
        model_dir = obj.get("model_dir")
        if not model_dir:
            return {"control": "swap", "ok": False,
                    "error": "missing model_dir"}
        target = engine
        if tenants is not None:
            name, st = _named_tenant()
            if st is None:
                return {"control": "swap", "ok": False,
                        "error": f"unknown tenant {name!r}"}
            target = st.engine
        result = swap_from_dir(target, str(model_dir),
                               label=obj.get("label"))
        out = {"control": "swap", "ok": result.accepted}
        out.update(result.to_json())
        return out
    if cmd == "canary":
        if tenants is None:
            return {"control": "canary", "ok": False,
                    "error": "canary requires multi-tenant mode (--tenant)"}
        model_dir = obj.get("model_dir")
        if not model_dir:
            return {"control": "canary", "ok": False,
                    "error": "missing model_dir"}
        name, st = _named_tenant()
        if st is None:
            return {"control": "canary", "ok": False,
                    "error": f"unknown tenant {name!r}"}
        from photon_tpu.io.model_io import load_for_serving
        try:
            result = engine.start_canary(
                name, load_for_serving(str(model_dir)),
                obj.get("label") or "canary",
                float(obj.get("fraction", 0.05)))
        except (ValueError, RuntimeError, OSError) as e:
            return {"control": "canary", "ok": False, "error": repr(e)}
        out = {"control": "canary", "ok": result.accepted, "tenant": name}
        out.update(result.to_json())
        return out
    if cmd in ("promote_canary", "abort_canary"):
        if tenants is None:
            return {"control": cmd, "ok": False,
                    "error": f"{cmd} requires multi-tenant mode (--tenant)"}
        name, st = _named_tenant()
        if st is None:
            return {"control": cmd, "ok": False,
                    "error": f"unknown tenant {name!r}"}
        try:
            info = (engine.promote_canary(name) if cmd == "promote_canary"
                    else engine.abort_canary(name))
        except RuntimeError as e:
            return {"control": cmd, "ok": False, "error": repr(e)}
        return {"control": cmd, "ok": True, "tenant": name, **info}
    if cmd == "drain":
        engine.begin_drain("operator drain control line")
        return {"control": "drain", "ok": True}
    if cmd == "stats":
        # live stats + metrics snapshot — the shape a fleet router
        # merges across shard processes via obs.metrics.merge_snapshots
        from photon_tpu.obs.metrics import registry
        return {"control": "stats", "ok": True,
                "stats": engine.stats(), "metrics": registry.snapshot()}
    return {"control": cmd, "ok": False, "error": f"unknown control {cmd!r}"}


def run(args: argparse.Namespace,
        stdin=None, stdout=None) -> int:
    logging.basicConfig(
        level=args.log_level, stream=sys.stderr,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    import photon_tpu.serving as serving_pkg
    from photon_tpu.resilience import shutdown

    stdin = stdin if stdin is not None else sys.stdin
    stdout = stdout if stdout is not None else sys.stdout
    engine = build_engine(args)
    serving_pkg.set_active_engine(engine)
    shutdown.install()

    capture = None
    capture_t0 = 0.0
    if args.capture:
        from photon_tpu.serving.replay import CaptureWriter
        capture = CaptureWriter(args.capture)
        capture_t0 = engine.clock()

    def _on_shutdown(reason: str) -> None:
        engine.begin_drain(reason)

    shutdown.add_callback(_on_shutdown)

    def emit(resp):
        stdout.write(json.dumps(resp.to_json()) + "\n")

    lines = _start_reader(stdin)
    bad_lines = 0
    eof = False
    try:
        while not eof and not engine.draining:
            try:
                line = lines.get(timeout=_TICK_S)
            except queue.Empty:
                # idle tick: coalescing deadlines still fire without new
                # input, so partially-filled buckets never starve
                for resp in engine.pump():
                    emit(resp)
                stdout.flush()
                continue
            if line is None:
                eof = True
                break
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except ValueError as e:
                bad_lines += 1
                logger.warning("bad request line skipped: %r", e)
                continue
            if isinstance(obj, dict) and "control" in obj:
                stdout.write(json.dumps(_handle_control(engine, obj)) + "\n")
                stdout.flush()
                continue
            try:
                req = serving_pkg.ScoreRequest.from_json(obj)
            except (ValueError, KeyError, TypeError) as e:
                bad_lines += 1
                logger.warning("bad request line skipped: %r", e)
                continue
            rejected = engine.submit(req)
            if rejected is not None:
                emit(rejected)
            elif capture is not None:
                # admitted: one capture record at the engine-clock offset
                capture.append(engine.clock() - capture_t0, req)
            for resp in engine.pump():
                emit(resp)

        if engine.draining:
            # drain: flush in-flight work within the budget, then refuse
            # the remainder AND any lines still buffered — every request
            # gets a typed SHUTTING_DOWN response, never a dropped line
            for resp in engine.shutdown(args.drain_budget_s):
                emit(resp)
            while True:
                try:
                    line = lines.get_nowait()
                except queue.Empty:
                    break
                if line is None or not line.strip():
                    continue
                try:
                    obj = json.loads(line)
                    if isinstance(obj, dict) and "control" in obj:
                        continue
                    req = serving_pkg.ScoreRequest.from_json(obj)
                except (ValueError, KeyError, TypeError):
                    bad_lines += 1
                    continue
                refused = engine.submit(req)   # draining: typed refusal
                if refused is not None:
                    emit(refused)
            logger.info("drained: %s", engine.stats().get("drain"))
        else:
            # stream end: flush the remainder (padded partial batches)
            for resp in engine.drain():
                emit(resp)
    finally:
        stdout.flush()
        if capture is not None:
            capture.close()
        shutdown.remove_callback(_on_shutdown)
        shutdown.uninstall()

    if args.stats_output:
        with open(args.stats_output, "w") as f:
            json.dump(engine.stats(), f, indent=1)
            f.write("\n")
    if args.runreport_output:
        from photon_tpu.obs.report import write_run_report
        write_run_report(args.runreport_output, driver="serve")
    if bad_lines:
        logger.warning("%d malformed request lines skipped", bad_lines)
    return 0


def main(argv: Optional[list] = None) -> int:
    return run(build_arg_parser().parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
