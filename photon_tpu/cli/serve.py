"""Online serving driver: JSONL requests on stdin -> JSONL scores on stdout.

The online counterpart of ``cli/score`` (batch). One process = one
device-resident model + one serving engine; requests stream through the
micro-batcher and responses stream out in completion order (batch pops
are FIFO, so completion order is submission order except for immediate
typed rejections).

Request line schema::

    {"uid": "r1",
     "features": {"shardA": [["name", "term", 1.5], ...]},
     "ids": {"userId": "u17"},
     "offset": 0.0}

Response line schema: ``ScoreResponse.to_json()`` —
``{"uid", "score", "degraded", "fallbacks": [{"reason", ...}]}``.

Usage::

    python -m photon_tpu.cli.serve --model-input-directory /path/to/model \
        [--max-batch 64] [--max-wait-ms 2] [--stats-output stats.json] \
        < requests.jsonl > scores.jsonl
"""

from __future__ import annotations

import argparse
import json
import logging
import sys
from typing import Optional

logger = logging.getLogger("photon_tpu.serve")


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon_tpu.serve",
        description="Serve a trained GAME model over JSONL stdin/stdout")
    p.add_argument("--model-input-directory", required=True)
    p.add_argument("--coordinates", nargs="*", default=None,
                   help="subset of coordinate ids to load (default: all)")
    p.add_argument("--max-batch", type=int, default=64,
                   help="top of the power-of-two bucket ladder")
    p.add_argument("--max-wait-ms", type=float, default=2.0,
                   help="coalescing deadline for a partial batch")
    p.add_argument("--feature-pad", type=int, default=None,
                   help="per-shard padded feature width (default: auto)")
    p.add_argument("--shed-queue-depth", type=int, default=512)
    p.add_argument("--reject-queue-depth", type=int, default=4096)
    p.add_argument("--no-warmup", action="store_true",
                   help="skip ladder pre-compilation (debugging only; "
                        "steady-state requests will compile)")
    p.add_argument("--stats-output", default=None,
                   help="write engine stats() JSON here at stream end")
    p.add_argument("--runreport-output", default=None,
                   help="write a RunReport (with serving section) here")
    p.add_argument("--log-level", default="INFO")
    return p


def build_engine(args: argparse.Namespace):
    from photon_tpu.serving import ServingConfig, ServingEngine, SLOConfig
    from photon_tpu.utils import compile_cache

    compile_cache.maybe_enable()
    config = ServingConfig(
        max_batch=args.max_batch,
        max_wait_s=args.max_wait_ms / 1000.0,
        feature_pad=args.feature_pad,
        slo=SLOConfig(shed_queue_depth=args.shed_queue_depth,
                      reject_queue_depth=args.reject_queue_depth))
    engine = ServingEngine.from_model_dir(
        args.model_input_directory, config=config,
        coordinates_to_load=args.coordinates)
    if not args.no_warmup:
        info = engine.warmup()
        logger.info("warmed %d programs over buckets %s in %.2fs",
                    info["programs"], info["buckets"], info["seconds"])
    return engine


def run(args: argparse.Namespace,
        stdin=None, stdout=None) -> int:
    logging.basicConfig(
        level=args.log_level, stream=sys.stderr,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    import photon_tpu.serving as serving_pkg

    stdin = stdin if stdin is not None else sys.stdin
    stdout = stdout if stdout is not None else sys.stdout
    engine = build_engine(args)
    serving_pkg.set_active_engine(engine)

    def emit(resp):
        stdout.write(json.dumps(resp.to_json()) + "\n")

    bad_lines = 0
    for line in stdin:
        line = line.strip()
        if not line:
            continue
        try:
            req = serving_pkg.ScoreRequest.from_json(json.loads(line))
        except (ValueError, KeyError, TypeError) as e:
            bad_lines += 1
            logger.warning("bad request line skipped: %r", e)
            continue
        rejected = engine.submit(req)
        if rejected is not None:
            emit(rejected)
        for resp in engine.pump():
            emit(resp)
    # stream end: flush the remainder (padded partial batches)
    for resp in engine.drain():
        emit(resp)
    stdout.flush()

    if args.stats_output:
        with open(args.stats_output, "w") as f:
            json.dump(engine.stats(), f, indent=1)
            f.write("\n")
    if args.runreport_output:
        from photon_tpu.obs.report import write_run_report
        write_run_report(args.runreport_output, driver="serve")
    if bad_lines:
        logger.warning("%d malformed request lines skipped", bad_lines)
    return 0


def main(argv: Optional[list] = None) -> int:
    return run(build_arg_parser().parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
