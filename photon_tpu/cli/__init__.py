"""Driver / CLI layer: the photon-client replacement.

Entry points:
  python -m photon_tpu.cli.train          GAME training (GameTrainingDriver)
  python -m photon_tpu.cli.score          GAME batch scoring (GameScoringDriver)
  python -m photon_tpu.cli.legacy         legacy single-GLM driver (Driver)
  python -m photon_tpu.cli.feature_index  feature index build (FeatureIndexingDriver)
  python -m photon_tpu.cli.serve          online serving (JSONL stdin -> stdout)
  python -m photon_tpu.cli.fleet_serve    entity-sharded fleet router (JSONL -> routed shards)
  python -m photon_tpu.cli.nearline       nearline delta training (event log -> live tables)
  python -m photon_tpu.cli.convert_data   LibSVM/Avro -> mmap columnar chunk store
"""

from photon_tpu.cli.config import (
    expand_sweep,
    parse_coordinate_config,
    parse_feature_shard_config,
    parse_kv_args,
)

__all__ = ["expand_sweep", "parse_coordinate_config",
           "parse_feature_shard_config", "parse_kv_args"]
