"""One-time training-data conversion: LibSVM text / Avro feature bags
-> the mmap columnar chunk store (``io/data_store.py``).

The parse is paid once, here; every subsequent fit opens the store with
``data/streaming.MmapChunkSource`` and streams zero-copy mmap slices
through the chunk pipeline — bitwise identical to the in-RAM sources,
with host RAM bounded by the page-cache window instead of the dataset.

Usage:
  python -m photon_tpu.cli.convert_data \\
    --format libsvm --input data/a1a --output stores/a1a \\
    --chunk-rows 8192 --num-shards 4

  python -m photon_tpu.cli.convert_data \\
    --format avro --input data/train data/train2 --output stores/train \\
    --feature-bags features --chunk-rows 8192

A killed conversion resumes with ``--resume`` (default on): the writer's
crc-framed cursor skips completed input units and the finished store is
byte-identical to an uninterrupted run.
"""

from __future__ import annotations

import argparse
import json
import logging
import sys
from typing import List, Optional

import numpy as np

logger = logging.getLogger("photon_tpu.convert_data")


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon_tpu.convert_data",
        description="Convert LibSVM/Avro training data into the "
                    "mmap columnar chunk store")
    p.add_argument("--format", choices=("libsvm", "avro"), required=True)
    p.add_argument("--input", nargs="+", required=True,
                   help="LibSVM file/dir (one) or Avro input dirs")
    p.add_argument("--output", required=True, help="store directory")
    p.add_argument("--chunk-rows", type=int, default=8192,
                   help="rows per chunk (multiple of 8; chunk boundaries "
                        "stay 64-byte aligned for the zero-copy path)")
    p.add_argument("--num-shards", type=int, default=1,
                   help="mesh shards the manifest assigns chunks to "
                        "(crc32 partitioner, parallel/partition)")
    p.add_argument("--dtype", default="float64",
                   choices=("float32", "float64"))
    p.add_argument("--dim", type=int, default=None,
                   help="override feature dimension (libsvm)")
    p.add_argument("--max-nnz", type=int, default=None,
                   help="override ELL width (rows wider than it refuse)")
    p.add_argument("--no-intercept", action="store_true")
    p.add_argument("--zero-based", action="store_true",
                   help="libsvm feature ids start at 0, not 1")
    p.add_argument("--feature-bags", nargs="+", default=["features"],
                   help="avro feature-bag fields merged into the store")
    p.add_argument("--no-resume", action="store_true",
                   help="ignore an existing conversion cursor")
    p.add_argument("--log-level", default="INFO")
    return p


def run(args: argparse.Namespace) -> dict:
    logging.basicConfig(level=args.log_level)
    from photon_tpu.io import data_store

    dtype = np.dtype(args.dtype)
    resume = not args.no_resume
    if args.format == "libsvm":
        if len(args.input) != 1:
            raise ValueError("--format libsvm takes exactly one --input "
                             "file or directory")
        manifest = data_store.convert_libsvm(
            args.input[0], args.output, dim=args.dim,
            add_intercept=not args.no_intercept,
            zero_based=args.zero_based, dtype=dtype,
            chunk_rows=args.chunk_rows, num_shards=args.num_shards,
            max_nnz=args.max_nnz, resume=resume)
    else:
        manifest = data_store.convert_avro(
            args.input, args.output, feature_bags=tuple(args.feature_bags),
            intercept=not args.no_intercept, dtype=dtype,
            chunk_rows=args.chunk_rows, num_shards=args.num_shards,
            max_nnz=args.max_nnz, resume=resume)
    desc = data_store.DataStore(args.output, verify=False).describe()
    logger.info("converted %d rows (dim %d) into %s: %d chunks x %d rows, "
                "%d shards, %.1f MiB",
                manifest["n_rows"], manifest["dim"], args.output,
                manifest["num_chunks"], manifest["chunk_rows"],
                manifest["num_shards"], desc["bytes"] / 2**20)
    return desc


def main(argv: Optional[List[str]] = None) -> None:
    desc = run(build_arg_parser().parse_args(argv))
    json.dump(desc, sys.stdout)
    sys.stdout.write("\n")


if __name__ == "__main__":
    main()
