"""Fleet router driver: JSONL requests -> entity-sharded serving fleet.

The front-end of the entity-sharded fleet (``serving/fleet.py``): scores
fixed effects locally and routes each request's random-effect lookups to
the shard that owns the entity under the canonical partitioner
(``parallel/partition.entity_shard`` — the same hash that split the cold
stores). Line protocol matches ``cli/serve`` (``ScoreRequest.from_json``
in, ``ScoreResponse.to_json`` out), so a router drops in where a
single-host serve process ran.

Two shard attachments:

* default — in-process shards: one ``ServingEngine`` per shard inside
  this process (`LocalShardClient`), each over its own per-shard cold
  store and hot tier. One process, N isolated serving stacks: the
  single-host deployment of the fleet code path.
* ``--spawn-shards`` — one child ``cli/serve --fleet-manifest
  --shard-id K`` process per shard, attached over JSONL pipes
  (`PipeShardClient`). Process-level isolation: a shard crash is a
  routed ``SHARD_UNAVAILABLE`` degradation at the router, never an
  exception; per-shard metrics snapshots are pulled over the pipe
  (``{"control": "stats"}``) and merged via
  ``obs/metrics.merge_snapshots``.

Control lines::

    {"control": "stats"}   -> fleet stats (per-shard + merged)
    {"control": "drain"}   -> drain and exit

Usage::

    python -m photon_tpu.cli.fleet_serve --fleet-manifest /path/to/fleet \
        [--spawn-shards] [--hedge-timeout-ms 5] [--stats-output stats.json] \
        < requests.jsonl > scores.jsonl
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import queue
import subprocess
import sys
import threading
import time
from typing import List, Optional, Sequence

logger = logging.getLogger("photon_tpu.fleet_serve")

_TICK_S = 0.05


class PipeShardClient:
    """A fleet shard behind a child ``cli/serve`` process and two JSONL
    pipes. Implements the same client surface as `LocalShardClient`:
    ``serve`` returns None (never raises) when the child is dead or the
    response does not arrive in time — the router's typed-degradation
    signal."""

    def __init__(self, shard_id: int, fleet_dir: str,
                 serve_args: Sequence[str] = (),
                 response_timeout_s: float = 30.0):
        self.shard_id = int(shard_id)
        self.alive = True
        self.response_timeout_s = response_timeout_s
        self._lock = threading.Lock()
        self._proc = subprocess.Popen(
            [sys.executable, "-m", "photon_tpu.cli.serve",
             "--fleet-manifest", fleet_dir, "--shard-id", str(shard_id),
             *serve_args],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True,
            env={**os.environ, "JAX_PLATFORMS":
                 os.environ.get("JAX_PLATFORMS", "cpu")})
        self._lines: "queue.Queue" = queue.Queue()
        threading.Thread(target=self._read, daemon=True,
                         name=f"shard{shard_id}-reader").start()

    def _read(self):
        try:
            for line in self._proc.stdout:
                self._lines.put(line)
        except ValueError:
            pass  # hygiene-ok: pipe closed during shutdown
        self._lines.put(None)

    def _roundtrip(self, lines: List[str], want: int,
                   deadline: float) -> Optional[List[dict]]:
        """Write lines, collect ``want`` response objects (None on child
        death / timeout). Caller holds the lock, so responses can only
        belong to this call."""
        try:
            self._proc.stdin.write("".join(lines))
            self._proc.stdin.flush()
        except (OSError, ValueError):
            return None
        out: List[dict] = []
        while len(out) < want:
            try:
                line = self._lines.get(timeout=max(
                    deadline - time.monotonic(), 0.001))
            except queue.Empty:
                return None
            if line is None:
                return None
            try:
                out.append(json.loads(line))
            except ValueError:
                continue
        return out

    def serve(self, requests) -> Optional[list]:
        from photon_tpu.serving.types import (Fallback, FallbackReason,
                                              ScoreResponse)
        if not self.alive or self._proc.poll() is not None:
            return None
        with self._lock:
            if not self.alive:
                return None
            objs = self._roundtrip(
                [json.dumps(r.to_json() if hasattr(r, "to_json")
                            else _req_json(r)) + "\n" for r in requests],
                len(requests),
                time.monotonic() + self.response_timeout_s)
        if objs is None:
            return None
        by_uid = {o.get("uid"): o for o in objs}
        resps = []
        for r in requests:
            o = by_uid.get(r.uid)
            if o is None:
                return None
            resps.append(ScoreResponse(
                r.uid, o.get("score"), bool(o.get("degraded")),
                tuple(Fallback(FallbackReason(f["reason"]),
                               f.get("coordinate"), f.get("detail", ""))
                      for f in o.get("fallbacks", ()))))
        return resps

    def warmup(self) -> dict:
        # the child warms its own ladder at boot; confirm it is up by
        # round-tripping a stats control line
        s = self.stats_snapshot()
        return {"programs": 0, "seconds": 0.0,
                "child_ready": s is not None}

    def stats_snapshot(self) -> Optional[dict]:
        if not self.alive or self._proc.poll() is not None:
            return None
        with self._lock:
            objs = self._roundtrip([json.dumps({"control": "stats"}) + "\n"],
                                   1, time.monotonic() + self.response_timeout_s)
        return objs[0] if objs else None

    def kill(self) -> None:
        self.alive = False
        self._proc.kill()

    def revive(self) -> None:
        raise NotImplementedError("a killed shard process cannot revive; "
                                  "start a replacement client")

    def breaker_state(self) -> str:
        s = self.stats_snapshot()
        if not s:
            return "unreachable"
        return str(((s.get("stats") or {}).get("breaker") or {})
                   .get("state", "unknown"))

    def hot_hit_rate(self) -> Optional[float]:
        return None  # lives in the child's own stats snapshot

    def shutdown(self) -> None:
        self.alive = False
        try:
            self._proc.stdin.close()
        except (OSError, ValueError):
            pass  # hygiene-ok: child already gone
        try:
            self._proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            self._proc.kill()


def _req_json(r) -> dict:
    out = {"uid": r.uid, "features": {
        sid: [[n, t, v] for n, t, v in rows]
        for sid, rows in r.features.items()},
        "ids": dict(r.entity_ids), "offset": r.offset}
    if r.timeout_s is not None:
        out["timeout_ms"] = r.timeout_s * 1000.0
    return out


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="photon_tpu.fleet_serve",
        description="Route JSONL requests over an entity-sharded "
                    "serving fleet")
    p.add_argument("--fleet-manifest", required=True, metavar="FLEET_DIR",
                   help="fleet dir holding fleet-manifest.json + "
                        "per-shard cold stores (io/fleet_store)")
    p.add_argument("--model-input-directory", default=None,
                   help="override the manifest's model_dir (fixed "
                        "effects + index maps)")
    p.add_argument("--spawn-shards", action="store_true",
                   help="one child serve process per shard over JSONL "
                        "pipes (default: in-process shard engines)")
    p.add_argument("--max-batch", type=int, default=64)
    p.add_argument("--max-wait-ms", type=float, default=2.0)
    p.add_argument("--hot-capacity", type=int, default=None,
                   help="two-tier hot rows per shard coordinate "
                        "(default: shard stores fully resident)")
    p.add_argument("--hedge-timeout-ms", type=float, default=None,
                   help="resubmit a shard hop not answered within this "
                        "(default: hedging off)")
    p.add_argument("--shard-timeout-ms", type=float, default=None,
                   help="per-hop ceiling for requests without their own "
                        "deadline (default: none)")
    p.add_argument("--no-warmup", action="store_true")
    p.add_argument("--stats-output", default=None,
                   help="write fleet stats() JSON here at stream end")
    p.add_argument("--log-level", default="INFO")
    return p


def build_fleet(args: argparse.Namespace):
    from photon_tpu.io.fleet_store import read_fleet_manifest
    from photon_tpu.serving import (CoeffStoreConfig, FleetConfig,
                                    ServingConfig, ShardedServingFleet)
    from photon_tpu.serving.fleet import build_front_engine
    from photon_tpu.utils import compile_cache

    compile_cache.maybe_enable()
    serving = ServingConfig(
        max_batch=args.max_batch, max_wait_s=args.max_wait_ms / 1000.0,
        coeff_store=(CoeffStoreConfig(hot_capacity=args.hot_capacity)
                     if args.hot_capacity is not None else None))
    config = FleetConfig(
        serving=serving,
        shard_timeout_s=(args.shard_timeout_ms / 1000.0
                         if args.shard_timeout_ms is not None else None),
        hedge_timeout_s=(args.hedge_timeout_ms / 1000.0
                         if args.hedge_timeout_ms is not None else None))
    if not args.spawn_shards:
        return ShardedServingFleet.from_fleet_dir(
            args.fleet_manifest, config,
            model_dir=args.model_input_directory)
    manifest = read_fleet_manifest(args.fleet_manifest)
    from photon_tpu.serving.fleet import _load_base
    base, ordered = _load_base(manifest, args.model_input_directory)
    front = build_front_engine(manifest, config, base=base)
    serve_args = ["--max-batch", str(args.max_batch),
                  "--max-wait-ms", str(args.max_wait_ms)]
    if args.hot_capacity is not None:
        serve_args += ["--hot-capacity", str(args.hot_capacity)]
    if args.model_input_directory:
        serve_args += ["--model-input-directory",
                       args.model_input_directory]
    clients = [PipeShardClient(sh["shard_id"], args.fleet_manifest,
                               serve_args)
               for sh in manifest["shards"]]
    coords = [(re.coordinate_id, re.random_effect_type) for re in ordered]
    return ShardedServingFleet(front, clients, coords, config)


def run(args: argparse.Namespace, stdin=None, stdout=None) -> int:
    logging.basicConfig(
        level=args.log_level, stream=sys.stderr,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    from photon_tpu.resilience import shutdown
    from photon_tpu.serving import ScoreRequest

    stdin = stdin if stdin is not None else sys.stdin
    stdout = stdout if stdout is not None else sys.stdout
    fleet = build_fleet(args)
    if not args.no_warmup:
        info = fleet.warmup()
        logger.info("fleet warmed: %s", info)
    shutdown.install()
    draining = threading.Event()
    shutdown.add_callback(lambda reason: draining.set())

    lines: "queue.Queue" = queue.Queue()

    def _read():
        try:
            for line in stdin:
                lines.put(line)
        except ValueError:
            pass  # hygiene-ok: stdin closed during interpreter exit
        lines.put(None)

    threading.Thread(target=_read, daemon=True,
                     name="fleet-stdin-reader").start()

    bad_lines = 0
    try:
        while not draining.is_set():
            try:
                line = lines.get(timeout=_TICK_S)
            except queue.Empty:
                continue
            if line is None:
                break
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except ValueError as e:
                bad_lines += 1
                logger.warning("bad request line skipped: %r", e)
                continue
            if isinstance(obj, dict) and "control" in obj:
                cmd = obj.get("control")
                if cmd == "stats":
                    stdout.write(json.dumps(
                        {"control": "stats", "ok": True,
                         "stats": fleet.stats()}) + "\n")
                elif cmd == "drain":
                    stdout.write(json.dumps(
                        {"control": "drain", "ok": True}) + "\n")
                    stdout.flush()
                    break
                else:
                    stdout.write(json.dumps(
                        {"control": cmd, "ok": False,
                         "error": f"unknown control {cmd!r}"}) + "\n")
                stdout.flush()
                continue
            # router batch: this line plus whatever is already queued
            batch = []
            try:
                batch.append(ScoreRequest.from_json(obj))
            except (ValueError, KeyError, TypeError) as e:
                bad_lines += 1
                logger.warning("bad request line skipped: %r", e)
                continue
            while len(batch) < args.max_batch:
                try:
                    nxt = lines.get_nowait()
                except queue.Empty:
                    break
                if nxt is None:
                    lines.put(None)
                    break
                nxt = nxt.strip()
                if not nxt:
                    continue
                try:
                    nobj = json.loads(nxt)
                except ValueError:
                    bad_lines += 1
                    continue
                if isinstance(nobj, dict) and "control" in nobj:
                    lines.put(nxt + "\n")   # controls between batches
                    break
                try:
                    batch.append(ScoreRequest.from_json(nobj))
                except (ValueError, KeyError, TypeError):
                    bad_lines += 1
            for resp in fleet.serve(batch):
                stdout.write(json.dumps(resp.to_json()) + "\n")
            stdout.flush()
    finally:
        stdout.flush()
        if args.stats_output:
            with open(args.stats_output, "w") as f:
                json.dump(fleet.stats(), f, indent=1)
                f.write("\n")
        fleet.shutdown()
        shutdown.uninstall()
    if bad_lines:
        logger.warning("%d malformed request lines skipped", bad_lines)
    return 0


def main(argv: Optional[list] = None) -> int:
    return run(build_arg_parser().parse_args(argv))


if __name__ == "__main__":
    sys.exit(main())
