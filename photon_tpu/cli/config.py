"""CLI configuration mini-DSL: feature shards + coordinates.

Reference: photon-client io/scopt/ScoptParserHelpers.scala:33 — key=value
lists with ',' between pairs, '|' for secondary lists, '-' for ranges:

  --feature-shard-configuration name=global,feature.bags=features|userF,intercept=true
  --coordinate-configuration name=user,random.effect.type=userId,\
      feature.shard=userShard,optimizer=LBFGS,tolerance=1e-6,max.iter=50,\
      regularization=L2,reg.weights=0.1|1|10,active.data.lower.bound=5

plus io/CoordinateConfiguration.scala:57-139 (a reg-weight list expands
into one GameOptimizationConfiguration per weight) and
io/FeatureShardConfiguration.scala:23.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from photon_tpu.estimators.game_estimator import (
    CoordinateConfiguration,
    FixedEffectDataConfiguration,
)
from photon_tpu.function.objective import (
    L1Regularization,
    L2Regularization,
    NoRegularization,
    RegularizationContext,
    RegularizationType,
)
from photon_tpu.game.random_effect import RandomEffectDataConfiguration
from photon_tpu.io.data_io import FeatureShardConfiguration
from photon_tpu.optim.problem import (
    GLMOptimizationConfiguration,
    OptimizerConfig,
)
from photon_tpu.types import OptimizerType

KV_DELIMITER = "="
LIST_DELIMITER = ","
SECONDARY_LIST_DELIMITER = "|"


def parse_kv_args(text: str) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for pair in text.split(LIST_DELIMITER):
        pair = pair.strip()
        if not pair:
            continue
        k, sep, v = pair.partition(KV_DELIMITER)
        if not sep:
            raise ValueError(f"expected key{KV_DELIMITER}value, got {pair!r}")
        out[k.strip()] = v.strip()
    return out


def parse_feature_shard_config(text: str) -> Tuple[str, FeatureShardConfiguration]:
    """'name=global,feature.bags=a|b,intercept=true' -> (name, config)."""
    args = parse_kv_args(text)
    name = args.pop("name")
    bags = tuple(args.pop("feature.bags").split(SECONDARY_LIST_DELIMITER))
    intercept = args.pop("intercept", "true").lower() in ("true", "1", "yes")
    if args:
        raise ValueError(f"unknown feature-shard args: {sorted(args)}")
    return name, FeatureShardConfiguration(bags, intercept)


@dataclasses.dataclass(frozen=True)
class ParsedCoordinate:
    """One coordinate plus its reg-weight sweep (reference:
    CoordinateConfiguration.expandOptimizationConfigurations)."""

    name: str
    configuration: CoordinateConfiguration
    reg_weights: Tuple[float, ...]  # sweep; first weight is in configuration


def _regularization(args: Dict[str, str]) -> RegularizationContext:
    reg = args.pop("regularization", "NONE").upper()
    if reg == "NONE":
        return NoRegularization
    if reg == "L1":
        return L1Regularization
    if reg == "L2":
        return L2Regularization
    if reg == "ELASTIC_NET":
        alpha = float(args.pop("reg.alpha", 0.5))
        return RegularizationContext(RegularizationType.ELASTIC_NET, alpha)
    raise ValueError(f"unknown regularization {reg!r}")


def _projector_type(text: str) -> str:
    """Accept both enum spellings and the compact grammar; validate at
    parse time so a typo fails here, not mid-ingest."""
    from photon_tpu.game.projector import ProjectorType

    canon = {"INDEXMAP": "INDEX_MAP"}.get(text.upper(), text.upper())
    return ProjectorType(canon).value


def parse_coordinate_config(text: str) -> ParsedCoordinate:
    args = parse_kv_args(text)
    name = args.pop("name")
    shard = args.pop("feature.shard")
    args.pop("min.partitions", None)  # Spark partitioning knob: no analog

    re_type = args.pop("random.effect.type", None)
    if re_type is not None:
        def popi(key):
            v = args.pop(key, None)
            return None if v is None else int(float(v))
        extra = {}
        if "max.entity.buckets" in args:  # else: dataclass default rules
            extra["max_entity_buckets"] = popi("max.entity.buckets")
        data = RandomEffectDataConfiguration(
            random_effect_type=re_type,
            feature_shard_id=shard,
            active_data_lower_bound=popi("active.data.lower.bound"),
            active_data_upper_bound=popi("active.data.upper.bound"),
            features_to_samples_ratio=(
                None if "features.to.samples.ratio" not in args
                else float(args.pop("features.to.samples.ratio"))),
            # reference: ProjectorType via RandomEffectDataConfiguration
            # ("indexmap"/"random"/"identity" in its compact grammar)
            projector_type=_projector_type(args.pop("projector", "INDEX_MAP")),
            projected_dimension=popi("projected.dimension"),
            projection_seed=popi("projection.seed") or 0,
            **extra,
        )
        args.pop("passive.data.bound", None)
    else:
        data = FixedEffectDataConfiguration(shard)

    opt_type = OptimizerType(args.pop("optimizer").upper())
    # optional, as in the reference's scopt grammar — OptimizerConfig's
    # dataclass defaults stay the single source of truth (DIRECT has no
    # meaningful iteration/tolerance knobs at all)
    opt_kwargs = {}
    if "max.iter" in args:
        opt_kwargs["max_iterations"] = int(args.pop("max.iter"))
    if "tolerance" in args:
        opt_kwargs["tolerance"] = float(args.pop("tolerance"))
    reg_context = _regularization(args)
    weights_text = args.pop("reg.weights", None)
    reg_weights = tuple(float(w) for w in
                        weights_text.split(SECONDARY_LIST_DELIMITER)) \
        if weights_text else (0.0,)
    down_sampling = float(args.pop("down.sampling.rate", 1.0))
    if args:
        raise ValueError(f"unknown coordinate args for {name!r}: {sorted(args)}")

    opt = GLMOptimizationConfiguration(
        optimizer=OptimizerConfig(optimizer_type=opt_type, **opt_kwargs),
        regularization=reg_context,
        regularization_weight=reg_weights[0],
        down_sampling_rate=down_sampling,
    )
    return ParsedCoordinate(name, CoordinateConfiguration(data, opt), reg_weights)


def expand_sweep(parsed: Sequence[ParsedCoordinate]) -> List[Dict[str, float]]:
    """All permutations of per-coordinate reg weights — one model trains
    per combination (reference: GameTrainingDriver.prepareGameOptConfigs
    cartesian product)."""
    sweeps: List[Dict[str, float]] = [{}]
    for p in parsed:
        sweeps = [{**s, p.name: w} for s in sweeps for w in p.reg_weights]
    return sweeps
