"""Axon-relay liveness probe, shared by bench.py and __graft_entry__.

The TPU chip in this environment is fronted by a local relay process
(the "axon tunnel", ports 8082+ on the first PALLAS_AXON_POOL_IPS
host). When the relay dies, PJRT init blocks forever on a refused
socket, so callers TCP-preflight it before letting jax initialize the
axon backend. One copy of the port list / probe policy lives here.
"""

from __future__ import annotations

import os
import socket

RELAY_PORTS = (8082, 8083, 8087)
PROBE_TIMEOUT_S = 2.0


def relay_host() -> str | None:
    """First pool IP, or None when no axon relay is configured."""
    pool = os.environ.get("PALLAS_AXON_POOL_IPS", "")
    return pool.split(",")[0].strip() if pool else None


def probe_relay(stop_on_accept: bool = False) -> dict[int, str]:
    """{port: "accepted" | exception name} for each relay port.
    Empty dict when no relay is configured. ``stop_on_accept`` returns at
    the first live port (liveness checks); the default probes every port
    (diagnostics)."""
    host = relay_host()
    if host is None:
        return {}
    checks: dict[int, str] = {}
    for port in RELAY_PORTS:
        try:
            with socket.create_connection((host, port),
                                          timeout=PROBE_TIMEOUT_S):
                checks[port] = "accepted"
                if stop_on_accept:
                    break
        except Exception as e:  # noqa: BLE001 — any failure = not alive
            checks[port] = type(e).__name__
    return checks


def relay_alive() -> bool | None:
    """True/False for a configured relay; None when none is configured
    (nothing to preflight — backend selection proceeds normally)."""
    checks = probe_relay(stop_on_accept=True)
    if not checks:
        return None
    return any(v == "accepted" for v in checks.values())
