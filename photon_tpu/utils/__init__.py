"""Utilities: timing, jit compile cache, events, durable logging.

Replaces the reference's photon-lib util layer (Timed, PhotonLogger) and
photon-client event system.
"""

from photon_tpu.utils.events import (
    CollectingListener,
    Event,
    EventEmitter,
    EventListener,
    emitter,
    optimization_log_event,
    setup_event,
    training_finish_event,
    training_start_event,
)
from photon_tpu.utils.photon_logger import PhotonLogger, parse_level
from photon_tpu.utils.timing import Timed, timed, timing_records, timing_summary

__all__ = [
    "Event", "EventEmitter", "EventListener", "CollectingListener", "emitter",
    "setup_event", "training_start_event", "training_finish_event",
    "optimization_log_event",
    "PhotonLogger", "parse_level",
    "Timed", "timed", "timing_records", "timing_summary",
]
