"""Persistent XLA compilation cache across processes.

The in-process jitcache (utils/jitcache.py) removes re-traces within one
run; this module removes re-COMPILES across runs. A GAME fit's cold start
is compile-dominated (the CD loop jits one solve per coordinate x config
shape), so the first run of a driver on a fresh host pays tens of seconds
that every later run can skip by loading serialized XLA executables from
disk.

The reference has no analog (JVM/Spark JITs incrementally); on TPU this is
the standard deployment answer: ``jax.config.jax_compilation_cache_dir``.
"""

from __future__ import annotations

import logging
import os

from photon_tpu.obs.metrics import registry as _metrics

_logger = logging.getLogger("photon_tpu.compile_cache")

_DEFAULT_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "photon_tpu", "xla_cache")

_enabled = False


def _host_fingerprint() -> str:
    """Short token for (machine, CPU features): XLA's AOT loader will load
    an executable compiled for a different feature set with only a warning
    ('could lead to ... SIGILL'), so the cache directory itself must be
    host-specific."""
    import hashlib
    import platform

    bits = [platform.machine(), platform.processor() or ""]
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    bits.append(" ".join(sorted(line.split()[2:])))
                    break
    except OSError:
        pass
    return hashlib.sha256("|".join(bits).encode()).hexdigest()[:12]


def enable_persistent_cache(cache_dir: str | None = None) -> str:
    """Enable JAX's on-disk compilation cache (idempotent).

    Returns the cache directory in use. Call before the first jit
    compilation for maximum effect; later calls still help future jits.
    """
    global _enabled
    import jax

    base = cache_dir or os.environ.get("PHOTON_TPU_XLA_CACHE", _DEFAULT_DIR)
    path = os.path.join(base, _host_fingerprint())
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # cache aggressively: GAME programs are many medium-sized executables
    # (one solve per coordinate x block-shape set); tracing/lowering is
    # NOT covered by this cache, so skipping even fast compiles just adds
    # to the uncacheable floor
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.2)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    _enabled = True
    # activation is observable: the gauge says whether the persistent cache
    # is on, and the log line says where it lives (debuggability contract —
    # "was the cache even active for this run?")
    _metrics.gauge("compile_cache.enabled").set(1)
    _metrics.counter("compile_cache.activations").inc()
    _logger.info("persistent XLA compilation cache enabled at %s", path)
    return path


def maybe_enable() -> str | None:
    """Entry-point hook: enable the cache unless the user opted out via
    ``PHOTON_TPU_NO_XLA_CACHE``. One opt-out semantic for every driver.
    The cache is a pure optimization — any failure (unwritable HOME,
    missing jax config flags) is logged, never fatal."""
    if os.environ.get("PHOTON_TPU_NO_XLA_CACHE"):
        _metrics.counter("compile_cache.disabled", reason="env_opt_out").inc()
        _metrics.gauge("compile_cache.enabled").set(0)
        _logger.info("persistent XLA cache disabled via PHOTON_TPU_NO_XLA_CACHE")
        return None
    try:
        return enable_persistent_cache()
    except Exception as e:  # noqa: BLE001 — optional feature must not kill a driver
        _metrics.counter("compile_cache.disabled", reason="error").inc()
        _metrics.gauge("compile_cache.enabled").set(0)
        import logging
        logging.getLogger("photon_tpu").warning(
            "persistent XLA cache unavailable: %r", e)
        return None


def is_enabled() -> bool:
    return _enabled


# ---------------------------------------------------------------------------
# warmup accounting (serving contract: zero steady-state compiles)
# ---------------------------------------------------------------------------

# compile-phase flag: builds that happen inside warmup() are expected and
# budgeted at model-load time; any build outside is a steady-state compile
# — for a serving process that is an SLO violation, and
# scripts/check_serving_no_recompile.py fails on it.
_warmup_depth = 0


def in_warmup() -> bool:
    return _warmup_depth > 0


def record_compile(what: str = "program") -> None:
    """Count one program build under the current phase. Called by the
    jitcache on every build; serving asserts
    ``compiles{phase="steady_state"}`` stays zero after warmup."""
    phase = "warmup" if in_warmup() else "steady_state"
    _metrics.counter("compile_cache.compiles", phase=phase, what=what).inc()


def compile_counts() -> dict:
    """{"warmup": n, "steady_state": m} across all ``what`` labels."""
    out = {"warmup": 0.0, "steady_state": 0.0}
    for key, val in _metrics.snapshot()["counters"].items():
        if key.startswith("compile_cache.compiles{"):
            for phase in out:
                if f'phase="{phase}"' in key:
                    out[phase] += val
    return out


def warmup(buckets, compile_fn) -> int:
    """Pre-compile one program per bucket at model-load time.

    ``compile_fn(bucket)`` must actually execute the jitted program for
    that bucket (a dispatch on dummy inputs of the bucket's padded shape),
    not just lower it — only a real call populates jit's executable cache
    so steady-state traffic reuses it. Builds inside this call are counted
    as ``compile_cache.compiles{phase="warmup"}``; everything after is
    steady-state. Returns the number of buckets warmed. Reentrant (an
    engine warming several coordinates nests safely).
    """
    global _warmup_depth
    _warmup_depth += 1
    try:
        n = 0
        for b in buckets:
            compile_fn(b)
            n += 1
        return n
    finally:
        _warmup_depth -= 1
