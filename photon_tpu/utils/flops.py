"""Model-flop accounting for MFU reporting.

"Model flops" are the algorithmically-required floating point operations of
the GLM solves (the useful work), NOT hardware flops: we count the
aggregator passes the optimizer actually executed, using each solver's
reported objective-evaluation count. MFU = model_flops / wall_clock /
chip_peak_flops — a deliberate lower bound, because ancillary work
(line-search vector ops, convergence checks, scatter/gathers, Hessian-vector
products inside TRON's CG loop) is not counted.

Per objective evaluation on a batch with NNZ feature slots:
  * forward margins (matvec / gather-dot):   2 * NNZ
  * backward gradient (rmatvec / scatter):   2 * NNZ
so one value-and-gradient pass = 4 * NNZ flops
(reference hot loop being replaced: ValueAndGradientAggregator.scala:240-255).

For vmapped random-effect solves the per-entity evaluation count is not
individually tracked; we use 2 evaluations per L-BFGS iteration (one
accepted step + ~one line-search probe), again a deliberate estimate that
is labelled as such in the bench output.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from photon_tpu.ops import features as F

# bf16/native-matmul peak FLOP/s per chip, by `device_kind` substring.
# (Public figures; used only to normalize MFU in the bench report.)
_PEAK_FLOPS_BY_KIND = (
    ("v6", 918e12),           # Trillium / v6e
    ("v5p", 459e12),
    ("v5", 197e12),           # v5e / v5 lite
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)
_CPU_FALLBACK_PEAK = 1e11     # nominal; flags MFU as not-a-TPU number
_UNKNOWN_TPU_PEAK = 275e12    # v4 figure, assumed for unrecognized TPU kinds

# peak HBM bandwidth per chip (bytes/s), same device_kind matching.
# (Public figures; normalizes the bandwidth-utilization estimate.)
_PEAK_HBM_BW_BY_KIND = (
    ("v6", 1640e9),           # Trillium / v6e
    ("v5p", 2765e9),
    ("v5", 819e9),            # v5e
    ("v4", 1228e9),
    ("v3", 900e9),
    ("v2", 700e9),
)
_CPU_FALLBACK_BW = 5e10       # nominal DRAM figure; flags not-a-TPU
_UNKNOWN_TPU_BW = 1228e9      # v4 figure for unrecognized TPU kinds


def peak_flops(device) -> tuple:
    """(peak_flops, label) for a jax device; CPU gets a nominal figure."""
    kind = getattr(device, "device_kind", "") or ""
    low = kind.lower()
    for marker, peak in _PEAK_FLOPS_BY_KIND:
        if marker in low:
            return peak, kind
    if getattr(device, "platform", "") in ("tpu", "axon"):
        return _UNKNOWN_TPU_PEAK, kind or "tpu-unknown(v4 assumed)"
    return _CPU_FALLBACK_PEAK, kind or "cpu"


def peak_hbm_bw(device) -> tuple:
    """(peak HBM bytes/s, label) for a jax device; CPU gets a nominal
    figure so the utilization number is still computable (and obviously
    labelled as not a TPU measurement)."""
    kind = getattr(device, "device_kind", "") or ""
    low = kind.lower()
    for marker, bw in _PEAK_HBM_BW_BY_KIND:
        if marker in low:
            return bw, kind
    if getattr(device, "platform", "") in ("tpu", "axon"):
        return _UNKNOWN_TPU_BW, kind or "tpu-unknown(v4 assumed)"
    return _CPU_FALLBACK_BW, kind or "cpu"


def _nnz_slots(features) -> int:
    """Feature slots touched per objective pass (dense: n*d; ELL: n*K)."""
    if isinstance(features, F.SparseFeatures):
        return int(np.prod(features.values.shape))
    return int(np.prod(features.shape))


def value_grad_pass_bytes(features, dim: int, fused: bool = False) -> int:
    """HBM bytes one value+gradient evaluation must move, from shapes:
    the feature stream (dense f32 tile or ELL int32 index + f32 value
    slots), the per-sample vectors (labels, offsets, weights), and the
    coefficient/gradient vectors. The XLA two-contraction path streams
    the features TWICE (margins, then the transposed contraction);
    ``fused=True`` models the single-HBM-pass Pallas kernels
    (ops/pallas_glm.py). A deliberate lower bound — intermediates that
    XLA may spill are not counted."""
    nnz = _nnz_slots(features)
    if isinstance(features, F.SparseFeatures):
        n = int(features.values.shape[0])
        stream = nnz * (4 + 4)            # int32 index + f32 value
    else:
        n = int(features.shape[0])
        stream = nnz * int(np.dtype(features.dtype).itemsize)
    passes = 1 if fused else 2
    return passes * stream + 3 * n * 4 + 2 * int(dim) * 4


def phase_utilization(model_flops: int, bytes_moved: int, seconds: float,
                      device=None, phase: str = "solve") -> dict:
    """MFU and HBM-bandwidth-utilization estimate for one solve phase.

    Both are model-work ratios against chip peaks — deliberate lower
    bounds computed from shapes, not hardware counters. The dict lands
    in bench records, and the two gauges (``perf.mfu`` /
    ``perf.hbm_bw_util`` with a ``phase`` label) put the same numbers in
    every RunReport via the metrics-registry snapshot."""
    import jax

    from photon_tpu.obs.metrics import registry

    if device is None:
        device = jax.devices()[0]
    peak, kind = peak_flops(device)
    peak_bw, _ = peak_hbm_bw(device)
    seconds = max(float(seconds), 1e-12)
    mfu = model_flops / seconds / peak
    bw_util = bytes_moved / seconds / peak_bw
    registry.gauge("perf.mfu", phase=phase).set(mfu)
    registry.gauge("perf.hbm_bw_util", phase=phase).set(bw_util)
    return {
        "phase": phase,
        "device_kind": kind,
        "model_flops": int(model_flops),
        "bytes_moved": int(bytes_moved),
        "seconds": float(seconds),
        "mfu": float(mfu),
        "hbm_bw_utilization": float(bw_util),
        "peak_flops": float(peak),
        "peak_hbm_bw": float(peak_bw),
    }


def fixed_effect_flops(coord) -> int:
    """Model flops of a FixedEffectCoordinate's last solve."""
    result = getattr(coord, "last_result", None)
    if result is None:
        return 0
    evals = int(np.asarray(result.num_fun_evals))
    return evals * 4 * _nnz_slots(coord.batch.features)


def random_effect_flops(coord) -> int:
    """Estimated model flops of a RandomEffectCoordinate's last solve:
    sum over entities of (2 evals/iter * iters) * 4 * S_b * K_b."""
    tracker = getattr(coord, "last_tracker", None)
    if tracker is None:
        return 0
    iters = np.maximum(np.asarray(tracker.iterations), 0)
    total = 0
    for blk in coord.dataset.blocks:
        ents = np.asarray(blk.entity_rows)
        valid = ents < iters.shape[0]
        it_b = int(iters[ents[valid]].sum())
        per_eval = 4 * blk.max_samples * blk.features.values.shape[-1]
        total += 2 * it_b * per_eval
    return total


def estimator_sweep_flops(estimator) -> int:
    """Model flops of the LAST coordinate-descent sweep of a fitted
    GameEstimator (each coordinate's trackers reflect its final update)."""
    from photon_tpu.game.coordinate import (
        FixedEffectCoordinate,
        RandomEffectCoordinate,
    )

    coords = getattr(estimator, "_coordinates", None) or {}
    total = 0
    for coord in coords.values():
        if isinstance(coord, FixedEffectCoordinate):
            total += fixed_effect_flops(coord)
        elif isinstance(coord, RandomEffectCoordinate):
            total += random_effect_flops(coord)
    return total
