"""Model-flop accounting for MFU reporting.

"Model flops" are the algorithmically-required floating point operations of
the GLM solves (the useful work), NOT hardware flops: we count the
aggregator passes the optimizer actually executed, using each solver's
reported objective-evaluation count. MFU = model_flops / wall_clock /
chip_peak_flops — a deliberate lower bound, because ancillary work
(line-search vector ops, convergence checks, scatter/gathers, Hessian-vector
products inside TRON's CG loop) is not counted.

Per objective evaluation on a batch with NNZ feature slots:
  * forward margins (matvec / gather-dot):   2 * NNZ
  * backward gradient (rmatvec / scatter):   2 * NNZ
so one value-and-gradient pass = 4 * NNZ flops
(reference hot loop being replaced: ValueAndGradientAggregator.scala:240-255).

For vmapped random-effect solves the per-entity evaluation count is not
individually tracked; we use 2 evaluations per L-BFGS iteration (one
accepted step + ~one line-search probe), again a deliberate estimate that
is labelled as such in the bench output.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from photon_tpu.ops import features as F

# bf16/native-matmul peak FLOP/s per chip, by `device_kind` substring.
# (Public figures; used only to normalize MFU in the bench report.)
_PEAK_FLOPS_BY_KIND = (
    ("v6", 918e12),           # Trillium / v6e
    ("v5p", 459e12),
    ("v5", 197e12),           # v5e / v5 lite
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)
_CPU_FALLBACK_PEAK = 1e11     # nominal; flags MFU as not-a-TPU number
_UNKNOWN_TPU_PEAK = 275e12    # v4 figure, assumed for unrecognized TPU kinds

# peak HBM bandwidth per chip (bytes/s), same device_kind matching.
# (Public figures; normalizes the bandwidth-utilization estimate.)
_PEAK_HBM_BW_BY_KIND = (
    ("v6", 1640e9),           # Trillium / v6e
    ("v5p", 2765e9),
    ("v5", 819e9),            # v5e
    ("v4", 1228e9),
    ("v3", 900e9),
    ("v2", 700e9),
)
_CPU_FALLBACK_BW = 5e10       # nominal DRAM figure; flags not-a-TPU
_UNKNOWN_TPU_BW = 1228e9      # v4 figure for unrecognized TPU kinds

# peak host->device (PCIe/ICI-attached host DMA) bandwidth per chip
# (bytes/s), same matching. These normalize the streaming pipeline's
# transfer-bandwidth gauge, so what matters is the ORDER — is the
# pipeline within a small factor of the interconnect — not the digit.
_PEAK_H2D_BW_BY_KIND = (
    ("v6", 64e9),
    ("v5p", 64e9),
    ("v5", 32e9),
    ("v4", 32e9),
    ("v3", 16e9),
    ("v2", 16e9),
)
_CPU_FALLBACK_H2D = 10e9      # host memcpy figure; flags not-a-TPU
_UNKNOWN_TPU_H2D = 32e9       # v4 figure for unrecognized TPU kinds


def peak_flops(device) -> tuple:
    """(peak_flops, label) for a jax device; CPU gets a nominal figure."""
    kind = getattr(device, "device_kind", "") or ""
    low = kind.lower()
    for marker, peak in _PEAK_FLOPS_BY_KIND:
        if marker in low:
            return peak, kind
    if getattr(device, "platform", "") in ("tpu", "axon"):
        return _UNKNOWN_TPU_PEAK, kind or "tpu-unknown(v4 assumed)"
    return _CPU_FALLBACK_PEAK, kind or "cpu"


def peak_hbm_bw(device) -> tuple:
    """(peak HBM bytes/s, label) for a jax device; CPU gets a nominal
    figure so the utilization number is still computable (and obviously
    labelled as not a TPU measurement)."""
    kind = getattr(device, "device_kind", "") or ""
    low = kind.lower()
    for marker, bw in _PEAK_HBM_BW_BY_KIND:
        if marker in low:
            return bw, kind
    if getattr(device, "platform", "") in ("tpu", "axon"):
        return _UNKNOWN_TPU_BW, kind or "tpu-unknown(v4 assumed)"
    return _CPU_FALLBACK_BW, kind or "cpu"


def peak_h2d_bw(device) -> tuple:
    """(peak host->device bytes/s, label) for a jax device; CPU gets a
    nominal figure so the gauge is computable (and obviously labelled as
    not a TPU measurement)."""
    kind = getattr(device, "device_kind", "") or ""
    low = kind.lower()
    for marker, bw in _PEAK_H2D_BW_BY_KIND:
        if marker in low:
            return bw, kind
    if getattr(device, "platform", "") in ("tpu", "axon"):
        return _UNKNOWN_TPU_H2D, kind or "tpu-unknown(v4 assumed)"
    return _CPU_FALLBACK_H2D, kind or "cpu"


def stream_overlap_utilization(reader_busy_s: float, consumer_stall_s: float,
                               wall_s: float, bytes_h2d: int,
                               device=None, phase: str = "stream") -> dict:
    """Transfer-vs-compute overlap efficiency of a streamed pass.

    The double-buffered pipeline's whole point is that chunk k+1's
    read+pack+transfer happens WHILE chunk k computes. The reader thread
    was busy ``reader_busy_s``; of that, the only part the consumer ever
    saw was its own stalls waiting on the queue (``consumer_stall_s``) —
    everything else was hidden behind compute:

        hidden_s             = max(reader_busy_s - consumer_stall_s, 0)
        overlap_efficiency   = hidden_s / reader_busy_s    (1.0 = fully
                               hidden; 0.0 = fully serialized)

    ``h2d_bw_util`` is the achieved host->device byte rate over the pass
    against the chip's nominal transfer peak. Both land as gauges
    (``perf.stream_overlap`` / ``perf.h2d_bw_util``) so every RunReport
    snapshot carries them, and the returned dict goes into bench records.
    """
    import jax

    from photon_tpu.obs.metrics import registry

    if device is None:
        device = jax.devices()[0]
    peak_bw, kind = peak_h2d_bw(device)
    wall_s = max(float(wall_s), 1e-12)
    reader_busy_s = max(float(reader_busy_s), 0.0)
    hidden_s = max(reader_busy_s - max(float(consumer_stall_s), 0.0), 0.0)
    # a reader that was never meaningfully busy hid everything there was
    overlap = hidden_s / reader_busy_s if reader_busy_s > 1e-9 else 1.0
    h2d_util = bytes_h2d / wall_s / peak_bw
    registry.gauge("perf.stream_overlap", phase=phase).set(overlap)
    registry.gauge("perf.h2d_bw_util", phase=phase).set(h2d_util)
    return {
        "phase": phase,
        "device_kind": kind,
        "reader_busy_s": float(reader_busy_s),
        "consumer_stall_s": float(consumer_stall_s),
        "hidden_s": float(hidden_s),
        "wall_s": float(wall_s),
        "bytes_h2d": int(bytes_h2d),
        "overlap_efficiency": float(overlap),
        "h2d_bw_utilization": float(h2d_util),
        "peak_h2d_bw": float(peak_bw),
    }


def re_block_overlap(reader_busy_s: float, consumer_stall_s: float,
                     wall_s: float, bytes_staged: int,
                     device=None, coordinate: str = "re") -> dict:
    """Stage-vs-solve overlap efficiency of a blocked random-effect pass
    — ``stream_overlap_utilization``'s sibling for the entity-bucket
    pipeline (game/block_stream.BlockPrefetcher): the prefetch thread
    stages bucket b+1 while bucket b solves; the only staging time the
    solver ever saw was its own stalls waiting on the queue. Lands as
    ``perf.re_block_overlap{coordinate}`` / ``perf.re_h2d_bw_util
    {coordinate}`` gauges and a dict for bench records."""
    import jax

    from photon_tpu.obs.metrics import registry

    if device is None:
        device = jax.devices()[0]
    peak_bw, kind = peak_h2d_bw(device)
    wall_s = max(float(wall_s), 1e-12)
    reader_busy_s = max(float(reader_busy_s), 0.0)
    hidden_s = max(reader_busy_s - max(float(consumer_stall_s), 0.0), 0.0)
    overlap = hidden_s / reader_busy_s if reader_busy_s > 1e-9 else 1.0
    h2d_util = bytes_staged / wall_s / peak_bw
    registry.gauge("perf.re_block_overlap", coordinate=coordinate).set(overlap)
    registry.gauge("perf.re_h2d_bw_util", coordinate=coordinate).set(h2d_util)
    return {
        "coordinate": coordinate,
        "device_kind": kind,
        "reader_busy_s": float(reader_busy_s),
        "consumer_stall_s": float(consumer_stall_s),
        "hidden_s": float(hidden_s),
        "wall_s": float(wall_s),
        "bytes_staged": int(bytes_staged),
        "overlap_efficiency": float(overlap),
        "h2d_bw_utilization": float(h2d_util),
        "peak_h2d_bw": float(peak_bw),
    }


def re_peak_hbm(coordinate: str, planned_bytes: int,
                measured_bytes: int) -> dict:
    """Publish a blocked/swept random-effect pass's peak device
    footprint: the ``parallel/memory`` planner's prediction next to the
    measured peak (on CPU backends the measurement is an array-bytes /
    RSS proxy — see bench.py --mode re_sweep). Both land as
    ``perf.re_peak_hbm_bytes{coordinate, kind}`` gauges so every
    RunReport snapshot carries the planned-vs-measured pair; the
    acceptance contract is planned >= measured on every bucket."""
    from photon_tpu.obs.metrics import registry

    registry.gauge("perf.re_peak_hbm_bytes", coordinate=coordinate,
                   kind="planned").set(int(planned_bytes))
    registry.gauge("perf.re_peak_hbm_bytes", coordinate=coordinate,
                   kind="measured").set(int(measured_bytes))
    return {
        "coordinate": coordinate,
        "planned_peak_bytes": int(planned_bytes),
        "measured_peak_bytes": int(measured_bytes),
        "within_plan": bool(int(measured_bytes) <= int(planned_bytes)),
    }


def _nnz_slots(features) -> int:
    """Feature slots touched per objective pass (dense: n*d; ELL: n*K)."""
    if isinstance(features, F.SparseFeatures):
        return int(np.prod(features.values.shape))
    return int(np.prod(features.shape))


def value_grad_pass_bytes(features, dim: int, fused: bool = False) -> int:
    """HBM bytes one value+gradient evaluation must move, from shapes:
    the feature stream (dense f32 tile or ELL int32 index + f32 value
    slots), the per-sample vectors (labels, offsets, weights), and the
    coefficient/gradient vectors. The XLA two-contraction path streams
    the features TWICE (margins, then the transposed contraction);
    ``fused=True`` models the single-HBM-pass Pallas kernels
    (ops/pallas_glm.py). A deliberate lower bound — intermediates that
    XLA may spill are not counted."""
    nnz = _nnz_slots(features)
    if isinstance(features, F.SparseFeatures):
        n = int(features.values.shape[0])
        stream = nnz * (4 + 4)            # int32 index + f32 value
    else:
        n = int(features.shape[0])
        stream = nnz * int(np.dtype(features.dtype).itemsize)
    passes = 1 if fused else 2
    return passes * stream + 3 * n * 4 + 2 * int(dim) * 4


def phase_utilization(model_flops: int, bytes_moved: int, seconds: float,
                      device=None, phase: str = "solve") -> dict:
    """MFU and HBM-bandwidth-utilization estimate for one solve phase.

    Both are model-work ratios against chip peaks — deliberate lower
    bounds computed from shapes, not hardware counters. The dict lands
    in bench records, and the two gauges (``perf.mfu`` /
    ``perf.hbm_bw_util`` with a ``phase`` label) put the same numbers in
    every RunReport via the metrics-registry snapshot."""
    import jax

    from photon_tpu.obs.metrics import registry

    if device is None:
        device = jax.devices()[0]
    peak, kind = peak_flops(device)
    peak_bw, _ = peak_hbm_bw(device)
    seconds = max(float(seconds), 1e-12)
    mfu = model_flops / seconds / peak
    bw_util = bytes_moved / seconds / peak_bw
    registry.gauge("perf.mfu", phase=phase).set(mfu)
    registry.gauge("perf.hbm_bw_util", phase=phase).set(bw_util)
    return {
        "phase": phase,
        "device_kind": kind,
        "model_flops": int(model_flops),
        "bytes_moved": int(bytes_moved),
        "seconds": float(seconds),
        "mfu": float(mfu),
        "hbm_bw_utilization": float(bw_util),
        "peak_flops": float(peak),
        "peak_hbm_bw": float(peak_bw),
    }


def fixed_effect_flops(coord) -> int:
    """Model flops of a FixedEffectCoordinate's last solve."""
    result = getattr(coord, "last_result", None)
    if result is None:
        return 0
    evals = int(np.asarray(result.num_fun_evals))
    return evals * 4 * _nnz_slots(coord.batch.features)


def random_effect_flops(coord) -> int:
    """Estimated model flops of a RandomEffectCoordinate's last solve:
    sum over entities of (2 evals/iter * iters) * 4 * S_b * K_b."""
    tracker = getattr(coord, "last_tracker", None)
    if tracker is None:
        return 0
    iters = np.maximum(np.asarray(tracker.iterations), 0)
    total = 0
    for blk in coord.dataset.blocks:
        ents = np.asarray(blk.entity_rows)
        valid = ents < iters.shape[0]
        it_b = int(iters[ents[valid]].sum())
        per_eval = 4 * blk.max_samples * blk.features.values.shape[-1]
        total += 2 * it_b * per_eval
    return total


def estimator_sweep_flops(estimator) -> int:
    """Model flops of the LAST coordinate-descent sweep of a fitted
    GameEstimator (each coordinate's trackers reflect its final update)."""
    from photon_tpu.game.coordinate import (
        FixedEffectCoordinate,
        RandomEffectCoordinate,
    )

    coords = getattr(estimator, "_coordinates", None) or {}
    total = 0
    for coord in coords.values():
        if isinstance(coord, FixedEffectCoordinate):
            total += fixed_effect_flops(coord)
        elif isinstance(coord, RandomEffectCoordinate):
            total += random_effect_flops(coord)
    return total
