"""Model-flop accounting for MFU reporting.

"Model flops" are the algorithmically-required floating point operations of
the GLM solves (the useful work), NOT hardware flops: we count the
aggregator passes the optimizer actually executed, using each solver's
reported objective-evaluation count. MFU = model_flops / wall_clock /
chip_peak_flops — a deliberate lower bound, because ancillary work
(line-search vector ops, convergence checks, scatter/gathers, Hessian-vector
products inside TRON's CG loop) is not counted.

Per objective evaluation on a batch with NNZ feature slots:
  * forward margins (matvec / gather-dot):   2 * NNZ
  * backward gradient (rmatvec / scatter):   2 * NNZ
so one value-and-gradient pass = 4 * NNZ flops
(reference hot loop being replaced: ValueAndGradientAggregator.scala:240-255).

For vmapped random-effect solves the per-entity evaluation count is not
individually tracked; we use 2 evaluations per L-BFGS iteration (one
accepted step + ~one line-search probe), again a deliberate estimate that
is labelled as such in the bench output.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from photon_tpu.ops import features as F

# bf16/native-matmul peak FLOP/s per chip, by `device_kind` substring.
# (Public figures; used only to normalize MFU in the bench report.)
_PEAK_FLOPS_BY_KIND = (
    ("v6", 918e12),           # Trillium / v6e
    ("v5p", 459e12),
    ("v5", 197e12),           # v5e / v5 lite
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
)
_CPU_FALLBACK_PEAK = 1e11     # nominal; flags MFU as not-a-TPU number
_UNKNOWN_TPU_PEAK = 275e12    # v4 figure, assumed for unrecognized TPU kinds


def peak_flops(device) -> tuple:
    """(peak_flops, label) for a jax device; CPU gets a nominal figure."""
    kind = getattr(device, "device_kind", "") or ""
    low = kind.lower()
    for marker, peak in _PEAK_FLOPS_BY_KIND:
        if marker in low:
            return peak, kind
    if getattr(device, "platform", "") in ("tpu", "axon"):
        return _UNKNOWN_TPU_PEAK, kind or "tpu-unknown(v4 assumed)"
    return _CPU_FALLBACK_PEAK, kind or "cpu"


def _nnz_slots(features) -> int:
    """Feature slots touched per objective pass (dense: n*d; ELL: n*K)."""
    if isinstance(features, F.SparseFeatures):
        return int(np.prod(features.values.shape))
    return int(np.prod(features.shape))


def fixed_effect_flops(coord) -> int:
    """Model flops of a FixedEffectCoordinate's last solve."""
    result = getattr(coord, "last_result", None)
    if result is None:
        return 0
    evals = int(np.asarray(result.num_fun_evals))
    return evals * 4 * _nnz_slots(coord.batch.features)


def random_effect_flops(coord) -> int:
    """Estimated model flops of a RandomEffectCoordinate's last solve:
    sum over entities of (2 evals/iter * iters) * 4 * S_b * K_b."""
    tracker = getattr(coord, "last_tracker", None)
    if tracker is None:
        return 0
    iters = np.maximum(np.asarray(tracker.iterations), 0)
    total = 0
    for blk in coord.dataset.blocks:
        ents = np.asarray(blk.entity_rows)
        valid = ents < iters.shape[0]
        it_b = int(iters[ents[valid]].sum())
        per_eval = 4 * blk.max_samples * blk.features.values.shape[-1]
        total += 2 * it_b * per_eval
    return total


def estimator_sweep_flops(estimator) -> int:
    """Model flops of the LAST coordinate-descent sweep of a fitted
    GameEstimator (each coordinate's trackers reflect its final update)."""
    from photon_tpu.game.coordinate import (
        FixedEffectCoordinate,
        RandomEffectCoordinate,
    )

    coords = getattr(estimator, "_coordinates", None) or {}
    total = 0
    for coord in coords.values():
        if isinstance(coord, FixedEffectCoordinate):
            total += fixed_effect_flops(coord)
        elif isinstance(coord, RandomEffectCoordinate):
            total += random_effect_flops(coord)
    return total
