"""Cross-instance sharing of jit-compiled solver programs.

`jax.jit` caches compiled executables per *function object*. Estimator /
coordinate / problem instances build their jitted solves as closures, so
every new instance (a re-fit, a hyperparameter-sweep candidate, a fresh
estimator on new data of the same shape) would re-trace and re-compile
programs that are byte-identical. The reference has the same concern in
Spark clothing — closures shipped per job, re-broadcast per iteration —
and the TPU answer is: key the compiled program by everything that shapes
its trace (task, solver constants, identity of any arrays baked in via
closure), and share it process-wide.

Array-valued key parts are keyed by ``id``; the cached closure keeps the
array alive, so an id cannot be re-used while its cache entry exists.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

_CACHE: Dict[tuple, Callable] = {}


def array_token(a) -> Optional[Tuple[str, int]]:
    """Stable hashable stand-in for an (optional) array closure capture."""
    return None if a is None else ("arr", id(a))


def get_or_build(key: tuple, builder: Callable[[], Callable]) -> Callable:
    fn = _CACHE.get(key)
    if fn is None:
        fn = _CACHE[key] = builder()
    return fn


def cache_size() -> int:
    return len(_CACHE)


def clear() -> None:
    _CACHE.clear()
