"""Cross-instance sharing of jit-compiled solver programs.

`jax.jit` caches compiled executables per *function object*. Estimator /
coordinate / problem instances build their jitted solves as closures, so
every new instance (a re-fit, a hyperparameter-sweep candidate, a fresh
estimator on new data of the same shape) would re-trace and re-compile
programs that are byte-identical. The reference has the same concern in
Spark clothing — closures shipped per job, re-broadcast per iteration —
and the TPU answer is: key the compiled program by everything that shapes
its trace (task, solver constants, identity of any arrays baked in via
closure), and share it process-wide.

Array-valued key parts are keyed by ``id``; the cached closure keeps the
array alive, so an id cannot be re-used while its cache entry exists.

Compile observability: every lookup lands in the telemetry metrics
registry (``jitcache.hits`` / ``jitcache.misses`` — a miss is a fresh
trace — ``jitcache.build_seconds``, ``jitcache.size``), and when the
SAME logical program (the key with array identities erased) is built
more than once, a recompile warning is logged and
``jitcache.recompiles`` counts it: that is compile time a stable array
identity would have saved. With telemetry enabled, the first call of
each built program is additionally timed into the
``jitcache.compile_seconds`` histogram — for a jitted builder product,
first call = trace + XLA compile wall time.
"""

from __future__ import annotations

import functools
import logging
import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

from photon_tpu.obs import _config as _obs_config
from photon_tpu.obs.metrics import registry as _metrics

_logger = logging.getLogger("photon_tpu.jitcache")

_LOCK = threading.Lock()
_CACHE: Dict[tuple, Callable] = {}
# logical key (array ids erased) -> build count, for recompile detection
_LOGICAL_BUILDS: Dict[tuple, int] = {}


def array_token(a) -> Optional[Tuple[str, int]]:
    """Stable hashable stand-in for an (optional) array closure capture."""
    return None if a is None else ("arr", id(a))


def _logical_key(part: Any) -> Any:
    """Erase array identities from a cache key, recursively: two keys that
    differ only in ``("arr", id)`` tokens describe the same logical
    program, so a second build of the same logical key is a recompile."""
    if isinstance(part, tuple):
        if len(part) == 2 and part[0] == "arr":
            return "arr"
        return tuple(_logical_key(p) for p in part)
    return part


def _timed_first_call(fn: Callable, key: tuple) -> Callable:
    """Wrap a freshly-built program so its FIRST invocation (trace + XLA
    compile for jitted builders) lands in ``jitcache.compile_seconds``.
    Steady-state overhead after the first call is one flag check."""
    done = [False]

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        if done[0]:
            return fn(*args, **kwargs)
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        dt = time.perf_counter() - t0
        done[0] = True
        _metrics.histogram("jitcache.compile_seconds").observe(dt)
        _logger.debug("first call of %r: %.3fs (trace + compile)",
                      key[0] if key else key, dt)
        return out

    return wrapped


def get_or_build(key: tuple, builder: Callable[[], Callable]) -> Callable:
    with _LOCK:
        fn = _CACHE.get(key)
    if fn is not None:
        _metrics.counter("jitcache.hits").inc()
        return fn
    _metrics.counter("jitcache.misses").inc()
    # phase attribution: builds during serving warmup are budgeted, builds
    # after it are steady-state compiles (a serving SLO violation)
    from photon_tpu.utils import compile_cache as _cc
    _cc.record_compile(what=str(key[0]) if key else "program")
    t0 = time.perf_counter()
    built = builder()
    dt = time.perf_counter() - t0
    _metrics.counter("jitcache.build_seconds").inc(dt)
    if _obs_config.enabled():
        built = _timed_first_call(built, key)
    lk = _logical_key(key)
    with _LOCK:
        fn = _CACHE.get(key)
        if fn is None:  # first build wins under concurrency
            fn = _CACHE[key] = built
            n = _LOGICAL_BUILDS[lk] = _LOGICAL_BUILDS.get(lk, 0) + 1
            _metrics.gauge("jitcache.size").set(len(_CACHE))
        else:
            n = 1
    if n > 1:
        _metrics.counter("jitcache.recompiles").inc()
        _logger.warning(
            "recompile: logical program %r built %d times (array identities "
            "changed); reuse the captured arrays to share the compilation",
            lk[0] if isinstance(lk, tuple) and lk else lk, n)
    return fn


def seed(key: tuple, fn: Callable) -> bool:
    """Insert an externally-built program (an AOT-deserialized executable
    from a serving program bundle) WITHOUT counting a miss or a build —
    the whole point of seeding is that no trace and no compile happened
    in this process. Returns False (and leaves the cache untouched) when
    the key is already populated; ``get_or_build`` then serves the
    existing program. Seeded entries are plain jitcache hits from the
    caller's perspective, so the three serving compile monitors
    (phase counters, ``jitcache.misses``, per-program retrace counts)
    all read zero on a warm-start."""
    with _LOCK:
        if key in _CACHE:
            return False
        _CACHE[key] = fn
        _LOGICAL_BUILDS.setdefault(_logical_key(key), 1)
        _metrics.gauge("jitcache.size").set(len(_CACHE))
    _metrics.counter("jitcache.seeded").inc()
    return True


def cache_size() -> int:
    with _LOCK:
        return len(_CACHE)


def clear() -> None:
    with _LOCK:
        _CACHE.clear()
        _LOGICAL_BUILDS.clear()
