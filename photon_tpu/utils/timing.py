"""Wall-clock phase timing.

Reference: photon-lib util/Timed.scala:33-69 — every pipeline phase runs
inside a `Timed("msg") { ... }` block that logs "msg (duration)"; the
reference uses it pervasively (GameTrainingDriver.run,
CoordinateDescent.scala:178-185).

Used as either a context manager or a decorator; durations are also
recorded in a process-wide registry so drivers can dump a timing summary
(the Spark-UI stage-view stand-in).
"""

from __future__ import annotations

import contextlib
import functools
import logging
import time
from typing import Callable, Dict, List, Optional, Tuple

_default_logger = logging.getLogger("photon_tpu.timing")

# (label, seconds) in completion order
_TIMINGS: List[Tuple[str, float]] = []


def timing_records() -> List[Tuple[str, float]]:
    return list(_TIMINGS)


def clear_timings() -> None:
    _TIMINGS.clear()


def timing_summary() -> str:
    lines = [f"  {label}: {secs:.3f}s" for label, secs in _TIMINGS]
    return "timing summary:\n" + "\n".join(lines) if lines else "no timings"


class Timed(contextlib.AbstractContextManager):
    """``with Timed("phase", logger): ...`` logs 'phase (1.234 s)'."""

    def __init__(self, label: str, logger: Optional[logging.Logger] = None,
                 level: int = logging.INFO):
        self.label = label
        self.logger = logger or _default_logger
        self.level = level
        self.seconds: Optional[float] = None

    def __enter__(self) -> "Timed":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.seconds = time.perf_counter() - self._t0
        _TIMINGS.append((self.label, self.seconds))
        status = "" if exc_type is None else " [FAILED]"
        self.logger.log(self.level, "%s (%.3f s)%s", self.label,
                        self.seconds, status)


def timed(label: Optional[str] = None,
          logger: Optional[logging.Logger] = None) -> Callable:
    """Decorator form: ``@timed("phase")``."""

    def wrap(fn: Callable) -> Callable:
        @functools.wraps(fn)
        def inner(*args, **kwargs):
            with Timed(label or fn.__qualname__, logger):
                return fn(*args, **kwargs)

        return inner

    return wrap
