"""Wall-clock phase timing.

Reference: photon-lib util/Timed.scala:33-69 — every pipeline phase runs
inside a `Timed("msg") { ... }` block that logs "msg (duration)"; the
reference uses it pervasively (GameTrainingDriver.run,
CoordinateDescent.scala:178-185).

Used as either a context manager or a decorator; durations are also
recorded in a process-wide registry so drivers can dump a timing summary
(the Spark-UI stage-view stand-in).

``Timed`` is now a shim over the telemetry span system (photon_tpu/obs/
spans.py): when telemetry is enabled, every Timed block additionally
records a nested trace span (Perfetto-exportable, aligned with device
traces via jax.profiler.TraceAnnotation) and lands in the RunReport's
phase list. The legacy ``_TIMINGS`` registry keeps its exact behavior —
and is now thread-safe, so concurrent RE solves and the bench harness
can't corrupt or interleave the summary.
"""

from __future__ import annotations

import contextlib
import functools
import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from photon_tpu.obs.spans import span as _obs_span

_default_logger = logging.getLogger("photon_tpu.timing")

# (label, seconds) in completion order; guarded by _TIMINGS_LOCK
_TIMINGS: List[Tuple[str, float]] = []
_TIMINGS_LOCK = threading.Lock()


def timing_records() -> List[Tuple[str, float]]:
    with _TIMINGS_LOCK:
        return list(_TIMINGS)


def clear_timings() -> None:
    with _TIMINGS_LOCK:
        _TIMINGS.clear()


def timing_summary() -> str:
    records = timing_records()
    lines = [f"  {label}: {secs:.3f}s" for label, secs in records]
    return "timing summary:\n" + "\n".join(lines) if lines else "no timings"


class Timed(contextlib.AbstractContextManager):
    """``with Timed("phase", logger): ...`` logs 'phase (1.234 s)'."""

    def __init__(self, label: str, logger: Optional[logging.Logger] = None,
                 level: int = logging.INFO):
        self.label = label
        self.logger = logger or _default_logger
        self.level = level
        self.seconds: Optional[float] = None

    def __enter__(self) -> "Timed":
        # span shim: no-op (two attribute writes) when telemetry is off
        self._span = _obs_span(self.label)
        self._span.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.seconds = time.perf_counter() - self._t0
        self._span.__exit__(exc_type, exc, tb)
        with _TIMINGS_LOCK:
            _TIMINGS.append((self.label, self.seconds))
        status = "" if exc_type is None else " [FAILED]"
        self.logger.log(self.level, "%s (%.3f s)%s", self.label,
                        self.seconds, status)


def timed(label: Optional[str] = None,
          logger: Optional[logging.Logger] = None) -> Callable:
    """Decorator form: ``@timed("phase")``."""

    def wrap(fn: Callable) -> Callable:
        @functools.wraps(fn)
        def inner(*args, **kwargs):
            with Timed(label or fn.__qualname__, logger):
                return fn(*args, **kwargs)

        return inner

    return wrap
