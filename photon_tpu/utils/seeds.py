"""Counter-derived deterministic seed streams — the ONE implementation.

Every bitwise-determinism contract in the repo that needs randomness
derives it from here: the replay traffic generators
(``serving/replay.py``), the Thompson-sampling scorer's per-request
seeds (``serving/scorer.py``), and any future consumer. There is no RNG
object state anywhere — a draw is a pure function of
``(seed, stream name, counter)``, so two runs (or a capture and its
replay) can never drift apart.

The kernel is splitmix64 (Steele et al.'s SplittableRandom finalizer):
platform-independent pure-integer arithmetic, full 64-bit avalanche.
Stream separation folds the crc32 of the stream name in with two odd
multiplicative constants, exactly the construction serving/replay.py
shipped in PR 18 — the functions here are bit-for-bit that code, moved,
and the pinned forever-vectors in tests/test_seeds.py freeze them so
the stream identity can never drift.

``request_key`` is the Thompson-serving entry point: a stable 64-bit
key per ``(seed, request uid)`` — derived from the request's *identity*
(its uid string), never from arrival order, so asynchronous completion
reordering between a capture and a replay cannot change any sample.
``split32`` halves a key for jitted programs that must stay in uint32
(serving runs without x64).
"""

from __future__ import annotations

import zlib
from typing import Tuple

U64 = (1 << 64) - 1

#: odd 64-bit mixing constants (golden-ratio increment + a Mersenne-ish
#: companion) — part of the frozen stream identity, never change them
GOLDEN = 0x9E3779B97F4A7C15
STREAM_MIX = 0xD1342543DE82EF95


def splitmix64(x: int) -> int:
    """Pure-integer splitmix64 finalizer — platform-independent, no RNG
    object state."""
    x = (x + GOLDEN) & U64
    z = x
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & U64
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & U64
    return (z ^ (z >> 31)) & U64


def stream_key(seed: int, stream: str, i: int) -> int:
    """The 64-bit key of draw ``i`` of named stream ``stream`` under
    ``seed`` — the pre-finalizer combination ``_u`` has always used."""
    return (seed * GOLDEN + zlib.crc32(stream.encode()) * STREAM_MIX
            + i) & U64


def stream_u(seed: int, stream: str, i: int) -> float:
    """Uniform in (0, 1): splitmix64 over (seed, named stream, counter).
    Never exactly 0 (log-safe) or 1."""
    return (splitmix64(stream_key(seed, stream, i)) + 1) / (2.0 ** 64 + 2)


def request_key(seed: int, uid: str) -> int:
    """Stable finalized 64-bit key for one scoring request: a function
    of the request's uid string (its identity), not its arrival slot —
    replays sample identically however completions interleave."""
    return splitmix64(stream_key(seed, uid, 0))


def split32(key: int) -> Tuple[int, int]:
    """(hi, lo) uint32 halves of a 64-bit key, for programs that must
    stay in 32-bit integer arithmetic (serving runs without x64)."""
    key &= U64
    return (key >> 32) & 0xFFFFFFFF, key & 0xFFFFFFFF
