"""Durable driver logging: a leveled logger that also writes to a file.

Reference: photon-lib util/PhotonLogger.scala:28 — an SLF4J-style logger
buffering to a local temp file and flushing to an HDFS path, so the
driver log survives the cluster; log level settable from the CLI
(GameDriver.scala:106).

Here: a standard-library logger wired with a file handler under the
job's output directory (the durable store), plus helpers to set levels
by name and flush handlers.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

_LEVELS = {
    "TRACE": logging.DEBUG,
    "DEBUG": logging.DEBUG,
    "INFO": logging.INFO,
    "WARN": logging.WARNING,
    "WARNING": logging.WARNING,
    "ERROR": logging.ERROR,
}

_FORMAT = "%(asctime)s %(name)s %(levelname)s %(message)s"


def parse_level(name: str) -> int:
    try:
        return _LEVELS[name.upper()]
    except KeyError:
        raise ValueError(f"unknown log level {name!r} "
                         f"(one of {sorted(_LEVELS)})") from None


class PhotonLogger:
    """File + console logger for one driver run."""

    def __init__(self, output_dir: str, name: str = "photon_tpu",
                 level: str = "INFO", filename: str = "driver.log"):
        os.makedirs(output_dir, exist_ok=True)
        self.path = os.path.join(output_dir, filename)
        self.logger = logging.getLogger(name)
        self.logger.setLevel(parse_level(level))
        # de-duplicate: ``logging.getLogger(name)`` is shared process-wide,
        # so a second PhotonLogger with the same name would stack another
        # FileHandler onto it and every line would be written twice. Evict
        # any handler WE previously attached for the same target file
        # (foreign handlers and different-path sinks are left alone).
        target = os.path.abspath(self.path)
        for h in list(self.logger.handlers):
            if (getattr(h, "_photon_tpu_owned", False)
                    and os.path.abspath(getattr(h, "baseFilename", ""))
                    == target):
                self.logger.removeHandler(h)
                h.close()
        self._handler = logging.FileHandler(self.path)
        self._handler._photon_tpu_owned = True
        self._handler.setFormatter(logging.Formatter(_FORMAT))
        self.logger.addHandler(self._handler)

    def set_level(self, level: str) -> None:
        self.logger.setLevel(parse_level(level))

    # pass-throughs
    def debug(self, *a, **k):
        self.logger.debug(*a, **k)

    def info(self, *a, **k):
        self.logger.info(*a, **k)

    def warning(self, *a, **k):
        self.logger.warning(*a, **k)

    def error(self, *a, **k):
        self.logger.error(*a, **k)

    def flush(self) -> None:
        self._handler.flush()

    def close(self) -> None:
        self.flush()
        self.logger.removeHandler(self._handler)
        self._handler.close()

    def __enter__(self) -> "PhotonLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
