"""Structured training events for external monitoring.

Reference: photon-client event/Event.scala:27-60 (PhotonSetupEvent,
TrainingStartEvent/FinishEvent, PhotonOptimizationLogEvent),
event/EventEmitter.scala:9 (listener registry guarded by a lock,
registration by class name from the CLI — Driver.scala:62-73).
"""

from __future__ import annotations

import dataclasses
import importlib
import threading
import time
from typing import Any, Callable, Dict, List, Optional


@dataclasses.dataclass(frozen=True)
class Event:
    """Base event: name + timestamp + payload."""

    name: str
    timestamp: float = dataclasses.field(default_factory=time.time)
    payload: Dict[str, Any] = dataclasses.field(default_factory=dict)


def setup_event(**payload) -> Event:
    return Event("PhotonSetupEvent", payload=payload)


def training_start_event(**payload) -> Event:
    return Event("TrainingStartEvent", payload=payload)


def training_finish_event(**payload) -> Event:
    return Event("TrainingFinishEvent", payload=payload)


def optimization_log_event(**payload) -> Event:
    return Event("PhotonOptimizationLogEvent", payload=payload)


class EventListener:
    """Override ``on_event``; ``close`` runs at emitter shutdown."""

    def on_event(self, event: Event) -> None:  # pragma: no cover - interface
        raise NotImplementedError

    def close(self) -> None:
        pass


class EventEmitter:
    """Thread-safe listener registry + dispatch (EventEmitter.scala:14-37)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._listeners: List[EventListener] = []

    def register(self, listener: EventListener) -> None:
        with self._lock:
            self._listeners.append(listener)

    def register_by_class_name(self, class_name: str) -> EventListener:
        """Reference: listeners registered by fully-qualified class name
        from the CLI (Driver.scala:62-73). Returns the instance so callers
        can unregister exactly what they added."""
        module, _, cls = class_name.rpartition(".")
        listener_cls = getattr(importlib.import_module(module), cls)
        listener = listener_cls()
        self.register(listener)
        return listener

    def unregister(self, listener: EventListener) -> None:
        with self._lock:
            try:
                self._listeners.remove(listener)
            except ValueError:
                pass

    def emit(self, event: Event) -> None:
        with self._lock:
            listeners = list(self._listeners)
        for l in listeners:
            l.on_event(event)

    def close(self) -> None:
        with self._lock:
            listeners = list(self._listeners)
            self._listeners.clear()
        for l in listeners:
            l.close()


class CollectingListener(EventListener):
    """Test/debug listener that records every event."""

    def __init__(self):
        self.events: List[Event] = []

    def on_event(self, event: Event) -> None:
        self.events.append(event)


# default process-wide emitter (drivers emit here)
emitter = EventEmitter()


class driver_listeners:
    """Scope a driver run's CLI-registered listeners on the process-wide
    emitter: register on enter, unregister + close on exit — WITHOUT
    touching listeners other code registered (an embedding application's
    listeners survive a driver run). Registration failures roll back the
    partial set before re-raising."""

    def __init__(self, class_names):
        self._names = list(class_names or [])
        self._mine = []

    def __enter__(self):
        try:
            for name in self._names:
                self._mine.append(emitter.register_by_class_name(name))
        except Exception:
            self._cleanup()
            raise
        return self

    def __exit__(self, *exc):
        self._cleanup()
        return False

    def _cleanup(self):
        for listener in self._mine:
            emitter.unregister(listener)
            listener.close()
        self._mine.clear()
