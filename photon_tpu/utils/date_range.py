"""Date-range input-directory resolution.

Reference: photon-client util/DateRange.scala:107 (parse
"yyyyMMdd-yyyyMMdd"), util/DaysRange.scala ("start-end" days ago,
converted to a DateRange), util/IOUtils.getInputPathsWithinDateRange
(expand base/daily/yyyy/MM/dd directories inside the range, erroring
when a base dir yields nothing).
"""

from __future__ import annotations

import dataclasses
import datetime
import os
import re
from typing import List, Optional, Sequence

_DATE_FMT = "%Y%m%d"
_SPLIT = re.compile(r"\s*-\s*")


@dataclasses.dataclass(frozen=True)
class DateRange:
    """Inclusive [start, end] calendar range (DateRange.scala:20)."""

    start: datetime.date
    end: datetime.date

    def __post_init__(self):
        if self.start > self.end:
            raise ValueError(
                f"invalid date range: {self.start} is after {self.end}")

    @staticmethod
    def from_string(text: str) -> "DateRange":
        """Parse "yyyymmdd-yyyymmdd" (DateRange.scala:107)."""
        parts = _SPLIT.split(text.strip())
        if len(parts) != 2:
            raise ValueError(f"date range must be yyyymmdd-yyyymmdd: {text!r}")
        start, end = (datetime.datetime.strptime(p, _DATE_FMT).date()
                      for p in parts)
        return DateRange(start, end)

    def dates(self) -> List[datetime.date]:
        n = (self.end - self.start).days
        return [self.start + datetime.timedelta(days=i) for i in range(n + 1)]


@dataclasses.dataclass(frozen=True)
class DaysRange:
    """"start-end" DAYS AGO, start >= end (DaysRange.scala:24): e.g.
    "90-1" = from 90 days ago through yesterday."""

    start_days_ago: int
    end_days_ago: int

    def __post_init__(self):
        if self.start_days_ago < self.end_days_ago:
            raise ValueError(
                f"days range start {self.start_days_ago} must be >= end "
                f"{self.end_days_ago} (both are days ago)")

    @staticmethod
    def from_string(text: str) -> "DaysRange":
        parts = _SPLIT.split(text.strip())
        if len(parts) != 2:
            raise ValueError(f"days range must be start-end: {text!r}")
        return DaysRange(int(parts[0]), int(parts[1]))

    def to_date_range(self, today: Optional[datetime.date] = None) -> DateRange:
        today = today or datetime.date.today()
        return DateRange(today - datetime.timedelta(days=self.start_days_ago),
                         today - datetime.timedelta(days=self.end_days_ago))


def daily_path(base: str, day: datetime.date) -> str:
    """base/daily/yyyy/MM/dd (IOUtils.getInputPathsWithinDateRange)."""
    return os.path.join(base, "daily", f"{day.year:04d}", f"{day.month:02d}",
                        f"{day.day:02d}")


def resolve_input_dirs(
    base_dirs: Sequence[str],
    date_range: Optional[DateRange],
) -> List[str]:
    """With no range, pass the dirs through; with one, expand each base to
    its existing daily partitions inside the range, erroring when a base
    contributes nothing (reference: IOUtils errors on empty ranges)."""
    if date_range is None:
        return list(base_dirs)
    out: List[str] = []
    for base in base_dirs:
        found = [p for d in date_range.dates()
                 if os.path.isdir(p := daily_path(base, d))]
        if not found:
            raise ValueError(
                f"no daily input under {base} within "
                f"{date_range.start:%Y%m%d}-{date_range.end:%Y%m%d}")
        out.extend(found)
    return out
