"""Shard-aware cold-store layout for the entity-sharded serving fleet.

One model's per-coordinate cold-tier files (`io/cold_store.py`) split
into N per-shard stores by the canonical entity partitioner
(`parallel/partition.entity_shard` — the same hash training placement
and request routing use), under a crc32-protected, versioned fleet
manifest:

    fleet_dir/
      fleet-manifest.json          (schema + version + crc32, below)
      shard_00000/per_user.coldstore
      shard_00001/per_user.coldstore
      ...

Fleet manifest format (versioned like ``swap-manifest.json``, crc'd like
``nearline-manifest.json``):

    {
      "schema": "photon_tpu.fleet.manifest.v1",
      "version": 1,                      # bumped on re-split / re-publish
      "num_shards": 16,
      "partitioner": "crc32-utf8-mod",   # parallel/partition.entity_shard
      "model_dir": "/abs/path",          # fixed effects + index maps live
      "coordinates": {cid: {"random_effect_type", "feature_shard_id",
                            "slot_width", "total_entities", "updatable"}},
      "shards": [{"shard_id": 0,
                  "stores": {cid: {"path": "shard_00000/cid.coldstore",
                                   "entities": 6250000,
                                   "bytes_at_split": 52428800}}}, ...],
      "crc": 1234567890                  # crc32 of the sorted-json doc
    }

The manifest's ``crc`` covers the manifest document itself (a torn or
tampered manifest fails ``read_fleet_manifest`` with a typed error —
the ``manifest_torn_write`` chaos injector drives that path). Store
payload integrity is the store's own embedded checksum (v1 footer / v2
chunk table, ``ColdStore.verify``): per-store bytes here are recorded
at split time and go stale by design once nearline row publishes mutate
an updatable shard store in place.
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Dict, List, Optional, Sequence

import numpy as np

from photon_tpu.io.cold_store import COLD_STORE_SUFFIX, ColdStore, \
    write_cold_store
from photon_tpu.parallel.partition import BucketMap, entity_shards, \
    validate_num_buckets, validate_num_shards
from photon_tpu.resilience import io as rio

FLEET_MANIFEST_FILE = "fleet-manifest.json"
FLEET_MANIFEST_SCHEMA = "photon_tpu.fleet.manifest.v1"
#: v2 adds the two-level partition: a ``bucket_map``
#: ({"num_buckets", "assignment"}) routes entity -> virtual bucket ->
#: shard, and live resharding bumps ``version`` with a new assignment.
#: v1 manifests keep reading as the degenerate identity map (one bucket
#: per shard), so routing is bitwise-unchanged for existing fleet dirs.
FLEET_MANIFEST_SCHEMA_V2 = "photon_tpu.fleet.manifest.v2"
#: the one partitioner this layout is defined over; a manifest naming
#: anything else is refused (routing would disagree with file layout)
PARTITIONER = "crc32-utf8-mod"

__all__ = [
    "FLEET_MANIFEST_FILE", "FLEET_MANIFEST_SCHEMA",
    "FLEET_MANIFEST_SCHEMA_V2", "PARTITIONER",
    "FleetManifestError", "shard_dir", "shard_store_path",
    "split_cold_store", "build_fleet_dir",
    "write_fleet_manifest", "read_fleet_manifest",
]


class FleetManifestError(RuntimeError):
    """Fleet manifest missing, torn, schema-mismatched, or crc-corrupt."""


def shard_dir(fleet_dir: str, shard_id: int) -> str:
    return os.path.join(fleet_dir, f"shard_{shard_id:05d}")


def shard_store_path(fleet_dir: str, shard_id: int,
                     coordinate_id: str) -> str:
    return os.path.join(shard_dir(fleet_dir, shard_id),
                        coordinate_id + COLD_STORE_SUFFIX)


def split_cold_store(src_path: str, fleet_dir: str, num_shards: int, *,
                     updatable: bool = True,
                     chunk_rows: int = 262144,
                     bucket_map: Optional[BucketMap] = None
                     ) -> List[Dict[str, object]]:
    """Split one coordinate's cold store into ``num_shards`` per-shard
    stores under ``fleet_dir`` by the canonical entity hash. Returns one
    ``{"shard_id", "path", "entities", "bytes_at_split"}`` record per
    shard (empty shards still get a valid zero-row store, so every shard
    process can open its file unconditionally).

    ``updatable=True`` writes v2 stores so the nearline publisher can
    row-update and append in place per shard. ``bucket_map`` routes
    ownership through the two-level v2 partition instead of the direct
    crc32-mod-N hash (the map's ``num_shards`` must not exceed ``n``)."""
    n = validate_num_shards(num_shards)
    src = ColdStore(src_path)
    ids = src.entity_ids_array()
    if bucket_map is not None and bucket_map.num_shards > n:
        raise ValueError(
            f"bucket map assigns shard {bucket_map.num_shards - 1} but "
            f"splitting into {n} shards")
    if not src.num_entities:
        owners = np.zeros(0, np.int32)
    elif bucket_map is not None:
        owners = bucket_map.shards_for_ids(ids)
    else:
        owners = entity_shards(ids, n)
    records: List[Dict[str, object]] = []
    for s in range(n):
        sel = np.nonzero(owners == s)[0]
        out = shard_store_path(fleet_dir, s, src.coordinate_id)
        # fancy-index straight off the source mmap in bounded chunks so a
        # 100M-row split never holds two full copies
        coef = np.empty((len(sel), src.slot_width), np.float32)
        proj = np.empty((len(sel), src.slot_width), np.int32)
        for lo in range(0, len(sel), chunk_rows):
            rows = sel[lo:lo + chunk_rows]
            coef[lo:lo + len(rows)] = src.coef[rows]
            proj[lo:lo + len(rows)] = src.proj[rows]
        write_cold_store(out, src.coordinate_id, src.random_effect_type,
                         src.feature_shard_id, coef, proj, ids[sel],
                         chunk_rows=chunk_rows, updatable=updatable)
        records.append({
            "shard_id": s,
            "path": os.path.relpath(out, fleet_dir),
            "entities": int(len(sel)),
            "bytes_at_split": int(os.path.getsize(out)),
        })
    return records


def build_fleet_dir(model_dir: str, fleet_dir: str, num_shards: int, *,
                    coordinates: Optional[Sequence[str]] = None,
                    updatable: bool = True,
                    version: int = 1,
                    num_buckets: Optional[int] = None) -> dict:
    """Split every cold-backed random-effect coordinate of ``model_dir``
    into ``num_shards`` per-shard stores under ``fleet_dir`` and write
    the fleet manifest. Returns the manifest document.

    Only coordinates with a cold-store file are split (100M-entity
    serving implies cold-backed coordinates); pass ``coordinates`` to
    restrict the set.

    ``num_buckets=None`` (the default) writes the v1 single-level layout
    byte-for-byte as before. An explicit power-of-two ``num_buckets``
    writes a v2 manifest carrying ``BucketMap.initial(num_buckets, n)``
    — the elastic layout whose shard count changes by migrating whole
    buckets instead of re-splitting offline."""
    from photon_tpu.io.cold_store import COLD_STORE_DIR, cold_store_path
    n = validate_num_shards(num_shards)
    bucket_map: Optional[BucketMap] = None
    if num_buckets is not None:
        bucket_map = BucketMap.initial(validate_num_buckets(num_buckets), n)
    cold_root = os.path.join(model_dir, COLD_STORE_DIR)
    if coordinates is None:
        coordinates = sorted(
            name[:-len(COLD_STORE_SUFFIX)]
            for name in (os.listdir(cold_root)
                         if os.path.isdir(cold_root) else ())
            if name.endswith(COLD_STORE_SUFFIX))
    if not coordinates:
        raise ValueError(f"no cold-backed coordinates under {model_dir!r} "
                         "to split")
    coord_meta: Dict[str, dict] = {}
    shard_stores: List[Dict[str, dict]] = [dict() for _ in range(n)]
    for cid in coordinates:
        src_path = cold_store_path(model_dir, cid)
        src = ColdStore(src_path)
        coord_meta[cid] = {
            "random_effect_type": src.random_effect_type,
            "feature_shard_id": src.feature_shard_id,
            "slot_width": src.slot_width,
            "total_entities": src.num_entities,
            "updatable": bool(updatable),
        }
        for rec in split_cold_store(src_path, fleet_dir, n,
                                    updatable=updatable,
                                    bucket_map=bucket_map):
            shard_stores[rec["shard_id"]][cid] = {
                "path": rec["path"],
                "entities": rec["entities"],
                "bytes_at_split": rec["bytes_at_split"],
            }
    doc = {
        "schema": (FLEET_MANIFEST_SCHEMA if bucket_map is None
                   else FLEET_MANIFEST_SCHEMA_V2),
        "version": int(version),
        "num_shards": n,
        "partitioner": PARTITIONER,
        "model_dir": os.path.abspath(model_dir),
        "coordinates": coord_meta,
        "shards": [{"shard_id": s, "stores": shard_stores[s]}
                   for s in range(n)],
    }
    if bucket_map is not None:
        doc["bucket_map"] = bucket_map.to_json()
    write_fleet_manifest(fleet_dir, doc)
    return doc


def write_fleet_manifest(fleet_dir: str, doc: dict) -> str:
    """Atomically publish ``fleet_dir/fleet-manifest.json`` with the
    nearline-manifest crc discipline: ``crc`` = crc32 of the sorted-json
    document without the crc field."""
    path = os.path.join(fleet_dir, FLEET_MANIFEST_FILE)
    body = {k: v for k, v in doc.items() if k != "crc"}
    blob = json.dumps(body, sort_keys=True).encode("utf-8")
    out = dict(body)
    out["crc"] = zlib.crc32(blob) & 0xFFFFFFFF
    rio.atomic_write_bytes(path,
                           json.dumps(out, sort_keys=True).encode("utf-8"),
                           op="fleet_manifest")
    return path


def read_fleet_manifest(fleet_dir: str) -> dict:
    """Read + verify the fleet manifest; raises ``FleetManifestError``
    on a missing, torn, schema-unknown, crc-mismatched, or
    wrong-partitioner document (a router must never fall back to
    guessing shard ownership)."""
    path = os.path.join(fleet_dir, FLEET_MANIFEST_FILE)
    if not os.path.exists(path):
        raise FleetManifestError(f"no fleet manifest at {path!r}")
    try:
        doc = json.loads(rio.read_bytes(path, op="fleet_manifest"))
    except (OSError, ValueError) as e:
        raise FleetManifestError(
            f"unreadable fleet manifest {path!r}: {e}") from e
    schema = doc.get("schema")
    if schema not in (FLEET_MANIFEST_SCHEMA, FLEET_MANIFEST_SCHEMA_V2):
        raise FleetManifestError(
            f"fleet manifest {path!r}: unknown schema {doc.get('schema')!r}")
    crc = doc.pop("crc", None)
    blob = json.dumps(doc, sort_keys=True).encode("utf-8")
    if crc != zlib.crc32(blob) & 0xFFFFFFFF:
        raise FleetManifestError(f"fleet manifest {path!r}: crc mismatch")
    if doc.get("partitioner") != PARTITIONER:
        raise FleetManifestError(
            f"fleet manifest {path!r}: partitioner "
            f"{doc.get('partitioner')!r} != {PARTITIONER!r} — routing "
            "would disagree with file layout")
    if not isinstance(doc.get("num_shards"), int) or doc["num_shards"] < 1:
        raise FleetManifestError(
            f"fleet manifest {path!r}: bad num_shards "
            f"{doc.get('num_shards')!r}")
    shard_ids = {s.get("shard_id") for s in doc.get("shards", ())}
    if schema == FLEET_MANIFEST_SCHEMA:
        # v1 IS the degenerate identity map (bucket b -> shard b): the
        # two-level route composes to crc32 % num_shards bitwise, so
        # pre-bucket fleet dirs keep serving unchanged.
        if "bucket_map" in doc:
            raise FleetManifestError(
                f"fleet manifest {path!r}: v1 schema carries a "
                "bucket_map — torn upgrade?")
        doc["bucket_map"] = BucketMap.identity(doc["num_shards"]).to_json()
    else:
        try:
            bmap = BucketMap.from_json(doc.get("bucket_map"))
        except ValueError as e:
            raise FleetManifestError(
                f"fleet manifest {path!r}: bad bucket_map: {e}") from e
        missing = set(bmap.assignment) - shard_ids
        if missing:
            raise FleetManifestError(
                f"fleet manifest {path!r}: bucket_map assigns buckets to "
                f"shards {sorted(missing)} absent from the manifest")
    return doc
