"""Feature index maps: (name, term) string keys <-> dense column indices.

Reference: photon-api index/IndexMap.scala:22 (Map[String,Int] +
getFeatureName), DefaultIndexMap.scala:27 (in-heap), PalDBIndexMap.scala:43
(partitioned off-heap stores with offset arithmetic),
PalDBIndexMapBuilder.scala:27, loaders (DefaultIndexMapLoader,
PalDBIndexMapLoader); key construction photon-client util/Utils.scala:58,
Constants.scala:31-42.

TPU re-design: the index map is a host-side concern — device code only
ever sees dense int32 columns. The PalDB off-heap store (a JVM workaround
for executor heap pressure) is replaced by a flat binary store
(index_store.py) that memory-maps for O(1)-ish lookups without
deserializing the whole vocabulary, plus this in-memory map for
driver-side building.
"""

from __future__ import annotations

from typing import Dict, Iterable, Iterator, List, Optional, Tuple

# Reference: Constants.scala:31-42
DELIMITER = "\u0001"
INTERCEPT_NAME = "(INTERCEPT)"
INTERCEPT_TERM = ""
INTERCEPT_KEY = INTERCEPT_NAME + DELIMITER + INTERCEPT_TERM


def feature_key(name: str, term: str = "") -> str:
    """Reference: Utils.getFeatureKey (util/Utils.scala:58)."""
    return name + DELIMITER + term


def split_feature_key(key: str) -> Tuple[str, str]:
    """Reference: Utils.getFeatureNameFromKey/getFeatureTermFromKey."""
    name, _, term = key.partition(DELIMITER)
    return name, term


class IndexMap:
    """Bidirectional feature-key <-> index map (reference: IndexMap.scala:22)."""

    def __init__(self, key_to_idx: Optional[Dict[str, int]] = None):
        self._map: Dict[str, int] = dict(key_to_idx or {})
        self._names: Optional[List[str]] = None
        self._dim: Optional[int] = None

    # -- Map behavior --------------------------------------------------------

    def __len__(self) -> int:
        return len(self._map)

    def __contains__(self, key: str) -> bool:
        return key in self._map

    def __iter__(self) -> Iterator[str]:
        return iter(self._map)

    def items(self):
        return self._map.items()

    def get_index(self, key: str) -> int:
        """Index for a feature key, -1 if absent (reference convention:
        IndexMap.NULL_KEY = -1)."""
        return self._map.get(key, -1)

    def index_of(self, name: str, term: str = "") -> int:
        return self.get_index(feature_key(name, term))

    def get_feature_name(self, idx: int) -> Optional[str]:
        """Feature key for an index (reference: IndexMap.getFeatureName)."""
        if self._names is None:
            names: List[Optional[str]] = [None] * self.feature_dimension
            for k, i in self._map.items():
                names[i] = k
            self._names = names  # type: ignore[assignment]
        if 0 <= idx < len(self._names):
            return self._names[idx]
        return None

    @property
    def feature_dimension(self) -> int:
        """Number of columns = max index + 1. Cached: the map is frozen
        after construction, and the model-load path reads this once per
        coordinate (each read was a full value scan)."""
        if self._dim is None:
            self._dim = (max(self._map.values()) + 1) if self._map else 0
        return self._dim

    @property
    def has_intercept(self) -> bool:
        return INTERCEPT_KEY in self._map

    # -- building ------------------------------------------------------------

    @staticmethod
    def from_keys(keys: Iterable[str], add_intercept: bool = False) -> "IndexMap":
        """Deterministic map: sorted unique keys -> 0..d-1, intercept last
        (the reference appends the intercept too —
        DefaultIndexMapLoader via AvroDataReader.generateIndexMapLoaders)."""
        key_set = set(keys)
        if add_intercept:
            key_set.discard(INTERCEPT_KEY)
        uniq = sorted(key_set)
        m = {k: i for i, k in enumerate(uniq)}
        if add_intercept:
            m[INTERCEPT_KEY] = len(uniq)
        return IndexMap(m)

    @staticmethod
    def from_name_terms(name_terms: Iterable[Tuple[str, str]],
                        add_intercept: bool = False) -> "IndexMap":
        return IndexMap.from_keys(
            (feature_key(n, t) for n, t in name_terms), add_intercept)


class IndexMapBuilder:
    """Incremental builder (reference: PalDBIndexMapBuilder.scala:27):
    feeds observed keys, assigns stable first-seen indices."""

    def __init__(self):
        self._map: Dict[str, int] = {}

    def put(self, key: str) -> int:
        idx = self._map.get(key)
        if idx is None:
            idx = len(self._map)
            self._map[key] = idx
        return idx

    def put_all(self, keys: Iterable[str]) -> "IndexMapBuilder":
        for k in keys:
            self.put(k)
        return self

    def build(self) -> IndexMap:
        return IndexMap(self._map)
