"""Pure-Python Avro object-container codec.

The reference's layer-0 data contract is Avro (photon-avro-schemas/
src/main/avro/*.avsc; files written/read via Spark + avro-mapred,
reference: photon-client data/avro/AvroUtils.scala:47). The TPU build has
no JVM, so this module implements the Avro 1.x binary encoding and the
object-container file format from the specification directly: enough to
read the reference's training data and write/read models the reference
can consume byte-for-byte.

Supported: null/boolean/int/long/float/double/bytes/string, records,
enums, arrays, maps, unions, fixed; container codecs ``null`` and
``deflate``. Each file decodes under its writer schema; ``read_merged``
then resolves a cross-file reader schema (top-level field union, numeric
precedence INT < LONG < FLOAT < DOUBLE, absent -> nullable) the way the
reference's AvroDataReader.readMerged does (:246).
"""

from __future__ import annotations

import io as _io
import json
import os
import struct
import zlib
from typing import Any, BinaryIO, Dict, Iterable, Iterator, List, Optional, Tuple

MAGIC = b"Obj\x01"
SYNC_SIZE = 16

# ---------------------------------------------------------------------------
# Schema handling: schemas are plain parsed-JSON values (dict/list/str).
# Named types may be referenced by full name after first definition.
# ---------------------------------------------------------------------------

_PRIMITIVES = {"null", "boolean", "int", "long", "float", "double", "bytes", "string"}


class SchemaError(ValueError):
    pass


def _full_name(schema: dict, enclosing_ns: Optional[str]) -> str:
    name = schema["name"]
    if "." in name:
        return name
    ns = schema.get("namespace", enclosing_ns)
    return f"{ns}.{name}" if ns else name


class _Names:
    """Registry of named types seen while walking a schema."""

    def __init__(self):
        self.types: Dict[str, dict] = {}

    def register_all(self, schema: Any, enclosing_ns: Optional[str] = None) -> None:
        """Eagerly register every named type in a schema tree, so by-name
        references resolve even when the defining field's data is empty."""
        if isinstance(schema, str):
            return
        if isinstance(schema, list):
            for s in schema:
                self.register_all(s, enclosing_ns)
            return
        t = schema.get("type")
        if t in ("record", "enum", "fixed"):
            self.types[_full_name(schema, enclosing_ns)] = schema
        if t == "record":
            ns = schema.get("namespace", enclosing_ns)
            for f in schema["fields"]:
                self.register_all(f["type"], ns)
        elif t == "array":
            self.register_all(schema["items"], enclosing_ns)
        elif t == "map":
            self.register_all(schema["values"], enclosing_ns)

    def resolve(self, schema: Any, enclosing_ns: Optional[str] = None) -> Any:
        """Return the concrete schema for ``schema``, registering named types."""
        if isinstance(schema, str):
            if schema in _PRIMITIVES:
                return schema
            for cand in (schema, f"{enclosing_ns}.{schema}" if enclosing_ns else None):
                if cand and cand in self.types:
                    return self.types[cand]
            raise SchemaError(f"unknown type reference: {schema!r}")
        if isinstance(schema, list):
            return schema
        t = schema.get("type")
        if t in ("record", "enum", "fixed"):
            self.types[_full_name(schema, enclosing_ns)] = schema
        return schema


# ---------------------------------------------------------------------------
# Binary decoder
# ---------------------------------------------------------------------------


class BinaryDecoder:
    def __init__(self, buf: bytes):
        self._buf = buf
        self._pos = 0

    def eof(self) -> bool:
        return self._pos >= len(self._buf)

    def read(self, n: int) -> bytes:
        b = self._buf[self._pos:self._pos + n]
        if len(b) != n:
            raise EOFError("truncated avro data")
        self._pos += n
        return b

    def read_long(self) -> int:
        shift = 0
        acc = 0
        while True:
            b = self._buf[self._pos]
            self._pos += 1
            acc |= (b & 0x7F) << shift
            if not (b & 0x80):
                break
            shift += 7
        return (acc >> 1) ^ -(acc & 1)  # zigzag

    read_int = read_long

    def read_boolean(self) -> bool:
        return self.read(1) != b"\x00"

    def read_float(self) -> float:
        return struct.unpack("<f", self.read(4))[0]

    def read_double(self) -> float:
        return struct.unpack("<d", self.read(8))[0]

    def read_bytes(self) -> bytes:
        return self.read(self.read_long())

    def read_string(self) -> str:
        return self.read_bytes().decode("utf-8")


class BinaryEncoder:
    def __init__(self):
        self._out = _io.BytesIO()

    def getvalue(self) -> bytes:
        return self._out.getvalue()

    def write(self, b: bytes):
        self._out.write(b)

    def write_long(self, v: int):
        v = (v << 1) ^ (v >> 63) if v >= 0 else (((-v - 1) << 1) | 1)
        out = bytearray()
        while True:
            b = v & 0x7F
            v >>= 7
            if v:
                out.append(b | 0x80)
            else:
                out.append(b)
                break
        self._out.write(bytes(out))

    write_int = write_long

    def write_boolean(self, v: bool):
        self._out.write(b"\x01" if v else b"\x00")

    def write_float(self, v: float):
        self._out.write(struct.pack("<f", v))

    def write_double(self, v: float):
        self._out.write(struct.pack("<d", v))

    def write_bytes(self, v: bytes):
        self.write_long(len(v))
        self._out.write(v)

    def write_string(self, v: str):
        self.write_bytes(v.encode("utf-8"))


# ---------------------------------------------------------------------------
# Datum reader / writer (schema-driven)
# ---------------------------------------------------------------------------


def _read_datum(dec: BinaryDecoder, schema: Any, names: _Names,
                ns: Optional[str] = None) -> Any:
    schema = names.resolve(schema, ns)
    if isinstance(schema, list):  # union: long index then value
        idx = dec.read_long()
        return _read_datum(dec, schema[idx], names, ns)
    if isinstance(schema, str):
        if schema == "null":
            return None
        if schema == "boolean":
            return dec.read_boolean()
        if schema in ("int", "long"):
            return dec.read_long()
        if schema == "float":
            return dec.read_float()
        if schema == "double":
            return dec.read_double()
        if schema == "bytes":
            return dec.read_bytes()
        if schema == "string":
            return dec.read_string()
        raise SchemaError(f"bad primitive {schema!r}")
    t = schema["type"]
    if t in _PRIMITIVES:
        return _read_datum(dec, t, names, ns)
    if t == "record":
        rec_ns = schema.get("namespace", ns)
        return {f["name"]: _read_datum(dec, f["type"], names, rec_ns)
                for f in schema["fields"]}
    if t == "enum":
        return schema["symbols"][dec.read_long()]
    if t == "fixed":
        return dec.read(schema["size"])
    if t == "array":
        out: List[Any] = []
        while True:
            count = dec.read_long()
            if count == 0:
                break
            if count < 0:  # block with byte size
                dec.read_long()
                count = -count
            for _ in range(count):
                out.append(_read_datum(dec, schema["items"], names, ns))
        return out
    if t == "map":
        m: Dict[str, Any] = {}
        while True:
            count = dec.read_long()
            if count == 0:
                break
            if count < 0:
                dec.read_long()
                count = -count
            for _ in range(count):
                k = dec.read_string()
                m[k] = _read_datum(dec, schema["values"], names, ns)
        return m
    raise SchemaError(f"unsupported schema {schema!r}")


def _union_branch(schema_list: list, datum: Any, names: _Names, ns) -> int:
    """Pick the union branch for a Python datum (null/record/primitive)."""
    for i, branch in enumerate(schema_list):
        b = names.resolve(branch, ns)
        bt = b if isinstance(b, str) else b.get("type")
        if datum is None and bt == "null":
            return i
        if datum is not None and bt != "null":
            if isinstance(datum, bool) and bt == "boolean":
                return i
            if isinstance(datum, int) and not isinstance(datum, bool) \
                    and bt in ("int", "long"):
                return i
            # ints promote to float/double (Avro numeric promotion) when no
            # integral branch exists
            if isinstance(datum, (int, float)) and not isinstance(datum, bool) \
                    and bt in ("float", "double"):
                return i
            if isinstance(datum, str) and bt in ("string", "enum"):
                return i
            if isinstance(datum, bytes) and bt in ("bytes", "fixed"):
                return i
            if isinstance(datum, dict) and bt in ("record", "map"):
                return i
            if isinstance(datum, (list, tuple)) and bt == "array":
                return i
    raise SchemaError(f"no union branch for {type(datum)} in {schema_list}")


def _write_datum(enc: BinaryEncoder, schema: Any, datum: Any, names: _Names,
                 ns: Optional[str] = None):
    schema = names.resolve(schema, ns)
    if isinstance(schema, list):
        idx = _union_branch(schema, datum, names, ns)
        enc.write_long(idx)
        _write_datum(enc, schema[idx], datum, names, ns)
        return
    if isinstance(schema, str):
        if schema == "null":
            return
        if schema == "boolean":
            enc.write_boolean(bool(datum))
        elif schema in ("int", "long"):
            enc.write_long(int(datum))
        elif schema == "float":
            enc.write_float(float(datum))
        elif schema == "double":
            enc.write_double(float(datum))
        elif schema == "bytes":
            enc.write_bytes(datum)
        elif schema == "string":
            enc.write_string(datum)
        else:
            raise SchemaError(f"bad primitive {schema!r}")
        return
    t = schema["type"]
    if t in _PRIMITIVES:
        _write_datum(enc, t, datum, names, ns)
        return
    if t == "record":
        rec_ns = schema.get("namespace", ns)
        for f in schema["fields"]:
            name = f["name"]
            if name in datum:
                val = datum[name]
            elif "default" in f:
                val = f["default"]
            else:
                raise SchemaError(f"missing field {name} for {schema['name']}")
            _write_datum(enc, f["type"], val, names, rec_ns)
        return
    if t == "enum":
        enc.write_long(schema["symbols"].index(datum))
        return
    if t == "fixed":
        enc.write(datum)
        return
    if t == "array":
        if datum:
            enc.write_long(len(datum))
            for item in datum:
                _write_datum(enc, schema["items"], item, names, ns)
        enc.write_long(0)
        return
    if t == "map":
        if datum:
            enc.write_long(len(datum))
            for k, v in datum.items():
                enc.write_string(k)
                _write_datum(enc, schema["values"], v, names, ns)
        enc.write_long(0)
        return
    raise SchemaError(f"unsupported schema {schema!r}")


# ---------------------------------------------------------------------------
# Object container files
# ---------------------------------------------------------------------------


class AvroFileReader:
    """Iterate records of one Avro object-container file."""

    def __init__(self, fileobj: BinaryIO):
        self._f = fileobj
        header = fileobj.read(4)
        if header != MAGIC:
            raise SchemaError(f"not an avro container file (magic={header!r})")
        meta_dec = BinaryDecoder(fileobj.read())  # rest of file
        self._meta = _read_datum(meta_dec, {"type": "map", "values": "bytes"},
                                 _Names())
        self._sync = meta_dec.read(SYNC_SIZE)
        self._body = meta_dec  # positioned at first block
        self.schema = json.loads(self._meta[b"avro.schema"]
                                 if b"avro.schema" in self._meta
                                 else self._meta["avro.schema"])
        codec = self._meta.get(b"avro.codec", self._meta.get("avro.codec", b"null"))
        self.codec = codec.decode() if isinstance(codec, bytes) else codec
        self._names = _Names()
        self._names.register_all(self.schema)
        # native block decoder (photon_tpu/native): same objects as
        # _read_datum at ~2 orders of magnitude higher throughput; falsy
        # (-> pure-Python fallback) when the compiler or schema shape is
        # unavailable
        from photon_tpu import native as _native
        self._native = _native.BlockDecoder(self.schema, self._names)

    def __iter__(self) -> Iterator[Any]:
        dec = self._body
        while not dec.eof():
            count = dec.read_long()
            nbytes = dec.read_long()
            raw = dec.read(nbytes)
            if self.codec == "deflate":
                raw = zlib.decompress(raw, -15)
            elif self.codec != "null":
                raise SchemaError(f"unsupported codec {self.codec}")
            if self._native:
                yield from self._native.decode_block(raw, count)
            else:
                block = BinaryDecoder(raw)
                for _ in range(count):
                    yield _read_datum(block, self.schema, self._names)
            sync = dec.read(SYNC_SIZE)
            if sync != self._sync:
                raise SchemaError("sync marker mismatch")


def read_avro(path: str) -> Tuple[Any, List[Any]]:
    """Read one container file -> (writer schema, list of records).

    The bytes are fetched through the retrying reader (resilience/retry.py)
    in one shot — a transient storage error costs a backoff, never the
    run — and decoded from memory."""
    from photon_tpu.resilience import io as rio

    r = AvroFileReader(_io.BytesIO(rio.read_bytes(path, op="avro_read")))
    return r.schema, list(r)


def list_avro_files(path: str) -> List[str]:
    """``*.avro`` files under a directory (or the file itself), name
    order — the reference reads part-files the same way (AvroUtils:47)."""
    if os.path.isfile(path):
        return [path]
    return sorted(
        os.path.join(path, n) for n in os.listdir(path)
        if n.endswith(".avro") and not n.startswith("."))


def iter_avro_dir(path: str) -> Iterator[Any]:
    """Iterate records across all ``*.avro`` files in a directory (or a
    single file) in name order."""
    from photon_tpu.resilience import io as rio

    for fp in list_avro_files(path):
        yield from AvroFileReader(
            _io.BytesIO(rio.read_bytes(fp, op="avro_read")))


# -- cross-file reader-schema resolution -------------------------------------

_NUMERIC_WIDTH = {"int": 0, "long": 1, "float": 2, "double": 3}


def _field_core_type(t) -> Tuple[Any, bool]:
    """(non-null branch, nullable) of a field type; a multi-branch union
    stays as-is."""
    if isinstance(t, list):
        non_null = [x for x in t if x != "null"]
        return (non_null[0] if len(non_null) == 1 else non_null,
                "null" in t)
    return t, False


def _merge_field_types(a, b, name: str):
    """Widest numeric type wins (INT < LONG < FLOAT < DOUBLE); identical
    types pass through; anything else is a schema conflict (reference:
    AvroDataReader.checkAndConvertTypes / numeric precedence :246)."""
    if a == b:
        return a
    if isinstance(a, str) and isinstance(b, str) \
            and a in _NUMERIC_WIDTH and b in _NUMERIC_WIDTH:
        return a if _NUMERIC_WIDTH[a] >= _NUMERIC_WIDTH[b] else b
    raise ValueError(
        f"incompatible Avro schemas across files for field {name!r}: "
        f"{a!r} vs {b!r}")


def merge_schemas(schemas: List[Any]) -> Any:
    """Reader-schema resolution across container files: the union of all
    top-level fields, numeric types widened by precedence, a field
    nullable when any writer declares it nullable OR omits it entirely
    (reference: AvroDataReader.readMerged field merge :246)."""
    merged: Dict[str, list] = {}     # name -> [type, nullable, seen_count]
    order: List[str] = []
    for s in schemas:
        for f in s["fields"]:
            t, nullable = _field_core_type(f["type"])
            slot = merged.get(f["name"])
            if slot is None:
                merged[f["name"]] = [t, nullable, 1]
                order.append(f["name"])
            else:
                slot[0] = _merge_field_types(slot[0], t, f["name"])
                slot[1] = slot[1] or nullable
                slot[2] += 1
    fields = []
    for name in order:
        t, nullable, seen = merged[name]
        if nullable or seen < len(schemas):
            t = ["null", t] if not isinstance(t, list) else ["null"] + t
        fields.append({"name": name, "type": t})
    base = schemas[0]
    return {"type": "record", "name": base.get("name", "Merged"),
            "namespace": base.get("namespace", ""), "fields": fields}


def read_merged(paths: List[str]) -> Tuple[Any, List[Any]]:
    """Read many files/directories under ONE resolved reader schema:
    records gain None for fields their writer omitted, and integer values
    of numerically-widened fields are coerced to the merged float type
    (the reference's readMerged contract)."""
    per_file: List[Tuple[Any, List[Any]]] = []
    for p in paths:
        for fp in list_avro_files(p):
            per_file.append(read_avro(fp))
    if not per_file:
        return None, []
    schemas = [s for s, _ in per_file]
    first = json.dumps(schemas[0], sort_keys=True)
    if all(json.dumps(s, sort_keys=True) == first for s in schemas[1:]):
        return schemas[0], [r for _, recs in per_file for r in recs]

    merged = merge_schemas(schemas)
    float_fields = set()
    all_names = []
    for f in merged["fields"]:
        t, _ = _field_core_type(f["type"])
        all_names.append(f["name"])
        if t in ("float", "double"):
            float_fields.add(f["name"])
    out: List[Any] = []
    for schema, recs in per_file:
        local = {f["name"] for f in schema["fields"]}
        missing = [n for n in all_names if n not in local]
        coerce = [n for n in float_fields if n in local]
        for r in recs:
            for n in missing:
                r[n] = None
            for n in coerce:
                v = r[n]
                if isinstance(v, int) and not isinstance(v, bool):
                    r[n] = float(v)
            out.append(r)
    return merged, out


def write_avro(path: str, schema: Any, records: Iterable[Any],
               codec: str = "deflate", sync_interval: int = 4000) -> None:
    """Write records to one Avro object-container file.

    The container is encoded into memory once, then published with the
    retrying atomic writer (fsync + tmp-rename). Encoding first matters
    beyond atomicity: callers pass ``records`` as generators, which can
    only be consumed once — a retry loop around a streaming write would
    silently produce an empty file on the second attempt."""
    names = _Names()
    names.register_all(schema)
    sync = os.urandom(SYNC_SIZE)
    with _io.BytesIO() as f:
        f.write(MAGIC)
        meta_enc = BinaryEncoder()
        meta = {"avro.schema": json.dumps(schema).encode(),
                "avro.codec": codec.encode()}
        _write_datum(meta_enc, {"type": "map", "values": "bytes"}, meta, names)
        f.write(meta_enc.getvalue())
        f.write(sync)

        buf = BinaryEncoder()
        count = 0

        def flush():
            nonlocal buf, count
            if count == 0:
                return
            raw = buf.getvalue()
            if codec == "deflate":
                comp = zlib.compressobj(6, zlib.DEFLATED, -15)
                raw = comp.compress(raw) + comp.flush()
            head = BinaryEncoder()
            head.write_long(count)
            head.write_long(len(raw))
            f.write(head.getvalue())
            f.write(raw)
            f.write(sync)
            buf = BinaryEncoder()
            count = 0

        for rec in records:
            _write_datum(buf, schema, rec, names)
            count += 1
            if count >= sync_interval:
                flush()
        flush()
        payload = f.getvalue()

    from photon_tpu.resilience import io as rio

    rio.atomic_write_bytes(path, payload, op="avro_write")
