"""Avro training data -> GameDataFrame; score/data writers.

Reference: photon-client data/avro/AvroDataReader.scala (readMerged :34 —
one sparse vector column per feature shard, shards merge feature bags,
optional intercept; readFeaturesFromRecord :246), data/DataReader.scala,
data/avro/AvroDataWriter.scala, GameScoringDriver.saveScoresToHDFS :187,
data/InputColumnsNames.scala:25 (reserved columns uid/response/offset/
weight), util/Utils.getFeatureKey (key = name + \\u0001 + term).

TPU re-design: no DataFrame middleman — Avro records stream straight into
the host-side columnar GameDataFrame (sparse rows per shard) from which
static-shape device blocks are built.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from photon_tpu.game.dataset import FeatureShard, GameDataFrame
from photon_tpu.io import avro as avro_io
from photon_tpu.io.index_map import (
    INTERCEPT_KEY,
    IndexMap,
    feature_key,
)
from photon_tpu.io.schemas import SCORING_RESULT_AVRO, TRAINING_EXAMPLE_AVRO

# Reference: InputColumnsNames.scala:25 — reserved columns, remappable.
RESPONSE_COLUMNS = ("response", "label")
OFFSET_COLUMN = "offset"
WEIGHT_COLUMN = "weight"
UID_COLUMN = "uid"
METADATA_COLUMN = "metadataMap"


@dataclasses.dataclass(frozen=True)
class FeatureShardConfiguration:
    """Reference: io/FeatureShardConfiguration.scala:23 — a shard merges
    one or more feature bags (record fields holding FeatureAvro arrays),
    optionally with an intercept column."""

    feature_bags: Tuple[str, ...]
    has_intercept: bool = True

    @staticmethod
    def of(*bags: str, intercept: bool = True) -> "FeatureShardConfiguration":
        return FeatureShardConfiguration(tuple(bags), intercept)


def _record_keys(record: dict, bags: Sequence[str]) -> Iterable[Tuple[str, float]]:
    for bag in bags:
        arr = record.get(bag)
        if not arr:
            continue
        for f in arr:
            yield feature_key(str(f["name"]), str(f["term"])), float(f["value"])


def build_index_maps(
    records: Iterable[dict],
    shard_configs: Dict[str, FeatureShardConfiguration],
) -> Dict[str, IndexMap]:
    """Scan data once, build one IndexMap per shard (reference:
    DefaultIndexMapLoader via GameDriver.prepareFeatureMapsDefault :155)."""
    keys: Dict[str, set] = {sid: set() for sid in shard_configs}
    for rec in records:
        for sid, cfg in shard_configs.items():
            for k, _ in _record_keys(rec, cfg.feature_bags):
                keys[sid].add(k)
    return {
        sid: IndexMap.from_keys(keys[sid], add_intercept=cfg.has_intercept)
        for sid, cfg in shard_configs.items()
    }


def extract_id_tags(records: Sequence[dict],
                    id_tag_columns: Sequence[str]) -> Dict[str, List[str]]:
    """Entity-id columns from record dicts: top-level column first, then
    metadataMap (reference: GameConverters.getGameDatumFromRow idTag
    handling). A present-but-null top-level value does NOT fall through —
    the single None-handling rule for every ingest path."""
    out: Dict[str, List[str]] = {c: [None] * len(records)
                                 for c in id_tag_columns}
    for i, rec in enumerate(records):
        meta = rec.get(METADATA_COLUMN) or {}
        for col in id_tag_columns:
            v = rec.get(col, meta.get(col))
            if v is None:
                raise KeyError(f"record {i} missing id tag column {col!r}")
            out[col][i] = str(v)
    return out


def records_to_game_dataframe(
    records: Sequence[dict],
    shard_configs: Dict[str, FeatureShardConfiguration],
    index_maps: Dict[str, IndexMap],
    id_tag_columns: Sequence[str] = (),
    response_columns: Sequence[str] = RESPONSE_COLUMNS,
) -> GameDataFrame:
    """Assemble the columnar frame: response/offset/weight + one sparse
    row set per shard + id tags (reference: AvroDataReader.readMerged +
    GameConverters.getGameDatumFromRow)."""
    n = len(records)
    response = np.zeros(n)
    offsets = np.zeros(n)
    weights = np.ones(n)
    any_offset = any_weight = False
    id_tags: Dict[str, List[str]] = {c: [None] * n for c in id_tag_columns}
    rows: Dict[str, List[Tuple[np.ndarray, np.ndarray]]] = {
        sid: [None] * n for sid in shard_configs}

    for i, rec in enumerate(records):
        for col in response_columns:
            if rec.get(col) is not None:
                response[i] = float(rec[col])
                break
        else:
            raise KeyError(f"record {i} has none of {response_columns}")
        if rec.get(OFFSET_COLUMN) is not None:
            offsets[i] = float(rec[OFFSET_COLUMN])
            any_offset = True
        if rec.get(WEIGHT_COLUMN) is not None:
            weights[i] = float(rec[WEIGHT_COLUMN])
            any_weight = True
        meta = rec.get(METADATA_COLUMN) or {}
        for col in id_tag_columns:
            v = rec.get(col, meta.get(col))  # same rule as extract_id_tags
            if v is None:
                raise KeyError(f"record {i} missing id tag column {col!r}")
            id_tags[col][i] = str(v)
        for sid, cfg in shard_configs.items():
            imap = index_maps[sid]
            idx: List[int] = []
            val: List[float] = []
            seen = {}
            for k, v in _record_keys(rec, cfg.feature_bags):
                j = imap.get_index(k)
                if j < 0:
                    continue  # unseen at index-build time -> dropped
                if j in seen:  # duplicate (name, term): last wins (ref:
                    idx[seen[j]] = j  # undefined behavior; we pick last)
                    val[seen[j]] = v
                    continue
                seen[j] = len(idx)
                idx.append(j)
                val.append(v)
            if cfg.has_intercept:
                j = imap.get_index(INTERCEPT_KEY)
                if j >= 0 and j not in seen:  # data may carry its own intercept
                    idx.append(j)
                    val.append(1.0)
            rows[sid][i] = (np.asarray(idx, np.int32), np.asarray(val))

    return GameDataFrame(
        num_samples=n,
        response=response,
        feature_shards={
            sid: FeatureShard(rows[sid], index_maps[sid].feature_dimension)
            for sid in shard_configs},
        offsets=offsets if any_offset else None,
        weights=weights if any_weight else None,
        id_tags=id_tags,
    )


def read_records(directories: Sequence[str]) -> List[dict]:
    """Read all Avro records under the given files/directories under one
    resolved reader schema — cross-file field union + numeric precedence
    (reference: AvroDataReader.readMerged :246) — erroring clearly when
    nothing is found (shared by every driver)."""
    _, records = avro_io.read_merged(list(directories))
    if not records:
        raise ValueError(f"no Avro records under {list(directories)}")
    return records


def read_game_dataframe(
    path: str,
    shard_configs: Dict[str, FeatureShardConfiguration],
    index_maps: Optional[Dict[str, IndexMap]] = None,
    id_tag_columns: Sequence[str] = (),
) -> Tuple[GameDataFrame, Dict[str, IndexMap]]:
    """Read a file or directory of Avro training records into a frame,
    building index maps from the data when not supplied."""
    records = list(avro_io.iter_avro_dir(path))
    if index_maps is None:
        index_maps = build_index_maps(records, shard_configs)
    df = records_to_game_dataframe(records, shard_configs, index_maps,
                                   id_tag_columns)
    return df, index_maps


# ---------------------------------------------------------------------------
# writers
# ---------------------------------------------------------------------------


def write_training_examples(
    path: str,
    response: np.ndarray,
    rows: Sequence[Tuple[np.ndarray, np.ndarray]],
    index_map: IndexMap,
    offsets: Optional[np.ndarray] = None,
    weights: Optional[np.ndarray] = None,
    uids: Optional[Sequence[str]] = None,
) -> None:
    """Write TrainingExampleAvro records (reference: AvroDataWriter)."""
    from photon_tpu.io.index_map import split_feature_key

    def gen():
        for i in range(len(response)):
            idx, val = rows[i]
            feats = []
            for j, v in zip(idx, val):
                key = index_map.get_feature_name(int(j))
                if key is None:
                    continue
                name, term = split_feature_key(key)
                feats.append({"name": name, "term": term, "value": float(v)})
            yield {
                "uid": None if uids is None else str(uids[i]),
                "label": float(response[i]),
                "features": feats,
                "metadataMap": None,
                "weight": None if weights is None else float(weights[i]),
                "offset": None if offsets is None else float(offsets[i]),
            }

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    avro_io.write_avro(path, TRAINING_EXAMPLE_AVRO, gen())


def write_scores(
    path: str,
    scores: np.ndarray,
    labels: Optional[np.ndarray] = None,
    weights: Optional[np.ndarray] = None,
    uids: Optional[Sequence[str]] = None,
    model_id: str = "photon_tpu",
) -> None:
    """Write ScoringResultAvro records (reference:
    GameScoringDriver.saveScoresToHDFS :187)."""

    def gen():
        for i, s in enumerate(scores):
            yield {
                "uid": None if uids is None else str(uids[i]),
                "label": None if labels is None else float(labels[i]),
                "modelId": model_id,
                "predictionScore": float(s),
                "weight": None if weights is None else float(weights[i]),
                "metadataMap": None,
            }

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    avro_io.write_avro(path, SCORING_RESULT_AVRO, gen())
