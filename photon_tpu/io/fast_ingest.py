"""Columnar native ingest: Avro feature bags -> CSR arrays, no per-feature
Python objects.

The generic path (io/avro.py + io/data_io.py) builds a dict per record and
a (indices, values) pair per row — fine for fixtures, too slow to feed
chips (SURVEY §7 risk (e)). This path decodes feature bags INSIDE the C
extension (photon_tpu/native) straight into growable id/value buffers with
an interned name-term vocabulary, then assembles the same ``GameDataFrame``
with ``CsrRows`` shards. Everything non-bag still decodes generically, and
any unsupported schema shape falls back to the generic path.

Semantics mirror records_to_game_dataframe exactly: duplicate (name, term)
within a record keep the LAST value; keys unseen by a supplied index map
are dropped; an intercept slot is appended to every row unless the data
already carries one.
"""

from __future__ import annotations

import io
import logging
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from photon_tpu.game.dataset import CsrRows, FeatureShard, GameDataFrame
from photon_tpu.io.avro import AvroFileReader, SchemaError, list_avro_files
from photon_tpu.io.data_io import (
    METADATA_COLUMN,
    OFFSET_COLUMN,
    RESPONSE_COLUMNS,
    WEIGHT_COLUMN,
    FeatureShardConfiguration,
)
from photon_tpu.io.index_map import DELIMITER, INTERCEPT_KEY, IndexMap

logger = logging.getLogger(__name__)


def _bag_spec(root_program, schema, bag_name: str) -> Optional[Tuple]:
    """(field_index, role_name, role_term, role_value, union_branch) for a
    top-level field holding array<record{name, term, value}> (optionally
    behind ["null", array]); None when the shape doesn't match."""
    fields = schema.get("fields", [])
    for fi, f in enumerate(fields):
        if f["name"] != bag_name:
            continue
        t = f["type"]
        branch = -1
        if isinstance(t, list):
            arr = [i for i, b in enumerate(t)
                   if isinstance(b, dict) and b.get("type") == "array"]
            nulls = [i for i, b in enumerate(t) if b == "null"]
            if len(arr) != 1 or len(nulls) + len(arr) != len(t):
                return None
            branch = arr[0]
            t = t[branch]
        if not isinstance(t, dict) or t.get("type") != "array":
            return None
        item = t["items"]
        if not isinstance(item, dict) or item.get("type") != "record":
            return None
        ifields = item.get("fields", [])
        if len(ifields) != 3:
            return None
        roles = {}
        for pos, itf in enumerate(ifields):
            ft = itf["type"]
            if itf["name"] == "name" and ft == "string":
                roles["name"] = pos
            elif itf["name"] == "term" and ft == "string":
                roles["term"] = pos
            elif itf["name"] == "value" and ft == "double":
                roles["value"] = pos
        if set(roles) != {"name", "term", "value"}:
            return None
        total = 1 if branch < 0 else len(f["type"])
        return (fi, roles["name"], roles["term"], roles["value"], branch,
                total)
    return None


class _BagAccumulator:
    """Merges per-block columnar outputs; block-local ids -> global ids."""

    def __init__(self):
        self.vocab: Dict[str, int] = {}
        self.ids: List[np.ndarray] = []
        self.vals: List[np.ndarray] = []
        self.row_nnz: List[np.ndarray] = []

    def add_block(self, rowptr_b: bytes, ids_b: bytes, vals_b: bytes,
                  keys: List[str]) -> None:
        lut = np.empty(len(keys), np.int32)
        vocab = self.vocab
        for i, k in enumerate(keys):
            g = vocab.get(k)
            if g is None:
                g = len(vocab)
                vocab[k] = g
            lut[i] = g
        ids = np.frombuffer(ids_b, "<i4")
        rowptr = np.frombuffer(rowptr_b, "<i8")
        self.ids.append(lut[ids] if len(keys) else ids)
        self.vals.append(np.frombuffer(vals_b, "<f8"))
        self.row_nnz.append(np.diff(rowptr))

    def csr(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        nnz = (np.concatenate(self.row_nnz) if self.row_nnz
               else np.zeros(0, np.int64))
        indptr = np.concatenate([[0], np.cumsum(nnz)]).astype(np.int64)
        cols = (np.concatenate(self.ids) if self.ids
                else np.zeros(0, np.int32))
        vals = (np.concatenate(self.vals) if self.vals else np.zeros(0))
        return indptr, cols, vals


def _dedup_last_wins(indptr, cols, vals, dim):
    """Within each row keep the LAST value per column id (the generic
    path's duplicate semantics; order within a row is irrelevant to every
    consumer — margins are sums)."""
    n = len(indptr) - 1
    nnz = np.diff(indptr)
    if nnz.sum() == 0:
        return indptr, cols, vals
    row_of = np.repeat(np.arange(n, dtype=np.int64), nnz)
    key = row_of * np.int64(dim) + cols.astype(np.int64)
    order = np.arange(len(key))
    # stable sort by key; within a key, original order ascends -> take last
    perm = np.lexsort((order, key))
    k_sorted = key[perm]
    is_last = np.concatenate([k_sorted[1:] != k_sorted[:-1], [True]])
    keep = perm[is_last]
    keep.sort()
    new_cols = cols[keep]
    new_vals = vals[keep]
    new_row = row_of[keep]
    new_nnz = np.bincount(new_row, minlength=n).astype(np.int64)
    new_indptr = np.concatenate([[0], np.cumsum(new_nnz)])
    return new_indptr, new_cols, new_vals


def read_game_frame(
    input_dirs: Sequence[str],
    shard_configs: Dict[str, FeatureShardConfiguration],
    index_maps: Optional[Dict[str, IndexMap]] = None,
    id_tag_columns: Sequence[str] = (),
    response_columns: Sequence[str] = RESPONSE_COLUMNS,
    return_records: bool = False,
) -> Optional[Tuple]:
    """Columnar read of Avro dirs -> (GameDataFrame, index maps), or None
    when the native decoder / schema shape is unavailable (caller falls
    back to read_records + records_to_game_dataframe). With
    ``return_records`` the (bag-free) record dicts ride along as a third
    element — drivers use them for uid passthrough and late id-tag
    discovery."""
    from photon_tpu import native

    if native._load() is None:
        return None
    # v1 scope: single-bag shards (multi-bag merges fall back)
    for cfg in shard_configs.values():
        if len(cfg.feature_bags) != 1:
            return None

    bag_names = sorted({cfg.feature_bags[0]
                        for cfg in shard_configs.values()})
    accs = {b: _BagAccumulator() for b in bag_names}
    records: List[dict] = []

    paths = [p for d in input_dirs for p in list_avro_files(d)]
    if not paths:
        raise FileNotFoundError(f"no avro files under {list(input_dirs)}")
    from photon_tpu.resilience import io as rio

    for path in paths:
        with io.BytesIO(rio.read_bytes(path, op="ingest_read")) as f:
            reader = AvroFileReader(f)
            specs = tuple(_bag_spec(None, reader.schema, b)
                          for b in bag_names)
            if any(s is None for s in specs):
                logger.info("fast ingest: bag shape unsupported in %s — "
                            "falling back", path)
                return None
            prog = reader._native   # compiled once by AvroFileReader
            if not prog:
                return None
            mod = native._load()
            import zlib
            dec = reader._body
            while not dec.eof():
                count = dec.read_long()
                nbytes = dec.read_long()
                raw = dec.read(nbytes)
                if reader.codec == "deflate":
                    raw = zlib.decompress(raw, -15)
                elif reader.codec != "null":
                    raise SchemaError(f"unsupported codec {reader.codec}")
                recs, bags_out = mod.decode_columnar(
                    prog._program, raw, count, specs, DELIMITER)
                records.extend(recs)
                for b, out in zip(bag_names, bags_out):
                    accs[b].add_block(*out)
                sync = dec.read(16)
                if sync != reader._sync:
                    raise SchemaError("sync marker mismatch")

    n = len(records)
    if n == 0:
        # match read_records' contract: empty partitions error clearly
        # instead of yielding a degenerate 0-sample frame
        raise ValueError(f"no Avro records under {list(input_dirs)}")
    # scalar columns (cheap Python loop: one dict access per column)
    response = np.zeros(n)
    offsets = np.zeros(n)
    weights = np.ones(n)
    any_offset = any_weight = False
    id_tags: Dict[str, List[str]] = {c: [None] * n for c in id_tag_columns}
    for i, rec in enumerate(records):
        for col in response_columns:
            if rec.get(col) is not None:
                response[i] = float(rec[col])
                break
        else:
            raise KeyError(f"record {i} has none of {response_columns}")
        if rec.get(OFFSET_COLUMN) is not None:
            offsets[i] = float(rec[OFFSET_COLUMN])
            any_offset = True
        if rec.get(WEIGHT_COLUMN) is not None:
            weights[i] = float(rec[WEIGHT_COLUMN])
            any_weight = True
        if id_tag_columns:
            meta = rec.get(METADATA_COLUMN) or {}
            for col in id_tag_columns:
                v = rec.get(col, meta.get(col))
                if v is None:
                    raise KeyError(f"record {i} missing id tag column {col!r}")
                id_tags[col][i] = str(v)

    # index maps + per-shard CSR in final index space
    built_maps: Dict[str, IndexMap] = {}
    shards: Dict[str, FeatureShard] = {}
    for sid, cfg in shard_configs.items():
        bag = cfg.feature_bags[0]
        acc = accs[bag]
        indptr, cols, vals = acc.csr()
        if index_maps is None:
            imap = IndexMap.from_keys(acc.vocab.keys(),
                                      add_intercept=cfg.has_intercept)
        else:
            imap = index_maps[sid]
        built_maps[sid] = imap
        # vocabulary id -> final index (-1 drops, matching the generic path)
        lut = np.full(max(len(acc.vocab), 1), -1, np.int32)
        for k, gid in acc.vocab.items():
            lut[gid] = imap.get_index(k)
        mapped = lut[cols] if len(cols) else cols.astype(np.int32)
        keep = mapped >= 0
        if not keep.all():
            row_of = np.repeat(np.arange(n, dtype=np.int64),
                               np.diff(indptr))[keep]
            new_nnz = np.bincount(row_of, minlength=n).astype(np.int64)
            indptr = np.concatenate([[0], np.cumsum(new_nnz)])
            mapped = mapped[keep]
            vals = vals[keep]
        dim = imap.feature_dimension
        if cfg.has_intercept:
            j = imap.get_index(INTERCEPT_KEY)
            if j >= 0:
                # PREPEND one intercept slot per row; rows that carry an
                # explicit intercept keep the data value (last wins)
                nnz0 = np.diff(indptr)
                new_indptr = np.concatenate(
                    [[0], np.cumsum(nnz0 + 1)]).astype(np.int64)
                total = int(new_indptr[-1])
                new_cols = np.empty(total, mapped.dtype if len(mapped)
                                    else np.int32)
                new_vals = np.empty(total, vals.dtype if len(vals)
                                    else np.float64)
                head = new_indptr[:-1]
                new_cols[head] = j
                new_vals[head] = 1.0
                is_data = np.ones(total, bool)
                is_data[head] = False
                new_cols[is_data] = mapped
                new_vals[is_data] = vals
                indptr, mapped, vals = new_indptr, new_cols, new_vals
        indptr, mapped, vals = _dedup_last_wins(indptr, mapped, vals, dim)
        shards[sid] = FeatureShard(
            CsrRows(indptr, mapped.astype(np.int32), vals), dim)

    frame = GameDataFrame(
        num_samples=n,
        response=response,
        feature_shards=shards,
        offsets=offsets if any_offset else None,
        weights=weights if any_weight else None,
        id_tags=id_tags,
    )
    if return_records:
        return frame, built_maps, records
    return frame, built_maps


def read_frame_with_fallback(
    input_dirs: Sequence[str],
    shard_configs: Dict[str, FeatureShardConfiguration],
    index_maps: Optional[Dict[str, IndexMap]] = None,
    id_tag_columns: Sequence[str] = (),
    return_records: bool = False,
):
    """The drivers' shared ingest ladder: columnar native path first,
    generic record path on any unsupported shape or non-fatal failure.
    Genuine data errors (missing files, empty partitions, corruption)
    raise identically on BOTH arms — behavior must never depend on
    whether the C extension compiled."""
    from photon_tpu.io.data_io import (
        build_index_maps,
        read_records,
        records_to_game_dataframe,
    )

    out = None
    try:
        out = read_game_frame(input_dirs, shard_configs,
                              index_maps=index_maps,
                              id_tag_columns=id_tag_columns,
                              return_records=return_records)
    except (OSError, KeyError, ValueError):
        raise
    except Exception as e:  # noqa: BLE001 — the fast path must never be fatal
        logger.warning("fast ingest failed (%r), using generic path", e)
    if out is not None:
        return out
    records = read_records(list(input_dirs))  # raises on empty, both arms
    maps = index_maps if index_maps is not None else build_index_maps(
        records, shard_configs)
    frame = records_to_game_dataframe(records, shard_configs, maps,
                                      id_tag_columns=id_tag_columns)
    if return_records:
        return frame, maps, records
    return frame, maps
