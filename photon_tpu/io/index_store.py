"""Binary feature-index store: the PalDB replacement.

Reference: photon-api index/PalDBIndexMap.scala:43 + PalDBIndexMapBuilder
.scala:27 + PalDBIndexMapLoader — partitioned off-heap key-value stores
holding feature-name -> index (and index -> name) maps, built offline by
FeatureIndexingDriver and loaded per-executor without heap pressure.

TPU re-design: one flat memory-mappable file per partition with a sorted
(hash, key-offset, index) table — lookups are an mmap binary search over
the hash column, no deserialization of the vocabulary. Partitioning is by
``hash(key) % num_partitions`` with global indices offset per partition
(the reference's offset arithmetic, PalDBIndexMap.scala:30-62). The file
layout is fixed-width little-endian so a native (C++) reader can mmap the
same files; photon_tpu/native/index_reader.cpp does exactly that, and
``IndexStore`` uses it via ctypes when built.

Layout:
  magic  8s   b"PHIXMAP1"
  n      u64  number of keys
  table  n * (hash u64, key_off u64, key_len u32, index u32)  sorted by hash
  blob   concatenated utf-8 key bytes
"""

from __future__ import annotations

import mmap
import os
import struct
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from photon_tpu.io.index_map import IndexMap

MAGIC = b"PHIXMAP1"
_HEADER = struct.Struct("<8sQ")
_ROW_DTYPE = np.dtype([("hash", "<u8"), ("off", "<u8"),
                       ("len", "<u4"), ("idx", "<u4")])


def _key_hash(key: str) -> int:
    """FNV-1a 64-bit — trivial to reimplement in the native reader."""
    h = 0xCBF29CE484222325
    for b in key.encode("utf-8"):
        h = ((h ^ b) * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h


def write_index_store(path: str, index_map: IndexMap) -> None:
    """Write one partition file."""
    hashed = sorted(((_key_hash(k), k, idx) for k, idx in index_map.items()))
    key_bytes = [k.encode("utf-8") for _, k, _ in hashed]
    rows = np.empty(len(hashed), _ROW_DTYPE)
    off = 0
    for i, ((h, _, idx), kb) in enumerate(zip(hashed, key_bytes)):
        rows[i] = (h, off, len(kb), idx)
        off += len(kb)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    from photon_tpu.resilience import io as rio
    rio.atomic_write_bytes(
        path,
        _HEADER.pack(MAGIC, len(hashed)) + rows.tobytes()
        + b"".join(key_bytes),
        op="index_write")


class IndexStore:
    """mmap-backed read view of one partition file: O(log n) lookups
    without loading the vocabulary (the PalDB read path)."""

    def __init__(self, path: str):
        self.path = path
        self._f = open(path, "rb")
        self._mm = mmap.mmap(self._f.fileno(), 0, access=mmap.ACCESS_READ)
        magic, n = _HEADER.unpack_from(self._mm, 0)
        if magic != MAGIC:
            raise ValueError(f"{path}: not an index store (magic={magic!r})")
        self.num_keys = n
        table_off = _HEADER.size
        table_bytes = n * _ROW_DTYPE.itemsize
        self._rows = np.frombuffer(self._mm, _ROW_DTYPE, n, table_off)
        self._blob_off = table_off + table_bytes

    def get_index(self, key: str) -> int:
        kb = key.encode("utf-8")
        h = _key_hash(key)
        lo = int(np.searchsorted(self._rows["hash"], np.uint64(h), side="left"))
        while lo < self.num_keys and int(self._rows["hash"][lo]) == h:
            off = self._blob_off + int(self._rows["off"][lo])
            ln = int(self._rows["len"][lo])
            if self._mm[off:off + ln] == kb:
                return int(self._rows["idx"][lo])
            lo += 1
        return -1

    def items(self) -> Iterable[Tuple[str, int]]:
        for i in range(self.num_keys):
            off = self._blob_off + int(self._rows["off"][i])
            ln = int(self._rows["len"][i])
            yield self._mm[off:off + ln].decode("utf-8"), int(self._rows["idx"][i])

    def to_index_map(self) -> IndexMap:
        return IndexMap(dict(self.items()))

    @property
    def max_index(self) -> int:
        """Largest stored index, -1 when empty — straight off the mmap'd
        table, no key decoding."""
        return int(self._rows["idx"].max()) if self.num_keys else -1

    def close(self):
        self._rows = None  # release the numpy view over the mmap buffer
        self._mm.close()
        self._f.close()


# ---------------------------------------------------------------------------
# partitioned stores (the PalDB partition-shard layout)
# ---------------------------------------------------------------------------

PARTITION_FILE = "index-partition-{shard}-{part:05d}.bin"


def write_partitioned_index(out_dir: str, shard_id: str, keys: Iterable[str],
                            num_partitions: int = 1) -> int:
    """Build a partitioned index for one feature shard: key -> partition by
    hash, global index = local rank * num_partitions + partition (stable
    under partition-parallel builds, like the reference's offset scheme).
    Returns the feature dimension."""
    parts: List[List[str]] = [[] for _ in range(num_partitions)]
    for k in sorted(set(keys)):
        parts[_key_hash(k) % num_partitions].append(k)
    dim = 0
    for p, part_keys in enumerate(parts):
        m = {k: i * num_partitions + p for i, k in enumerate(part_keys)}
        write_index_store(
            os.path.join(out_dir, PARTITION_FILE.format(shard=shard_id, part=p)),
            IndexMap(m))
        dim = max(dim, max(m.values()) + 1 if m else 0)
    return dim


class PartitionedIndexMap:
    """Reader over all partitions of one shard (reference:
    PalDBIndexMap offset arithmetic across partitions)."""

    def __init__(self, directory: str, shard_id: str):
        self.stores: List[IndexStore] = []
        p = 0
        while True:
            path = os.path.join(directory,
                                PARTITION_FILE.format(shard=shard_id, part=p))
            if not os.path.exists(path):
                break
            self.stores.append(IndexStore(path))
            p += 1
        if not self.stores:
            raise FileNotFoundError(
                f"no index partitions for shard {shard_id!r} in {directory}")

    @property
    def num_partitions(self) -> int:
        return len(self.stores)

    def get_index(self, key: str) -> int:
        p = _key_hash(key) % self.num_partitions
        return self.stores[p].get_index(key)

    def to_index_map(self) -> IndexMap:
        merged: Dict[str, int] = {}
        for s in self.stores:
            merged.update(s.items())
        return IndexMap(merged)

    @property
    def feature_dimension(self) -> int:
        # index column only — decoding every key blob just to take a max
        # was a second full read of each partition on the load path
        return max((s.max_index for s in self.stores), default=-1) + 1

    def close(self):
        for s in self.stores:
            s.close()
