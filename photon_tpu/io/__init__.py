"""IO layer: Avro codec, data contracts, index maps, model persistence.

Replaces the reference's photon-avro-schemas module + photon-client Avro
IO stack (AvroDataReader/AvroUtils/ModelProcessingUtils) without a JVM.
"""

from photon_tpu.io.avro import AvroFileReader, iter_avro_dir, read_avro, write_avro
from photon_tpu.io.data_io import (
    FeatureShardConfiguration,
    build_index_maps,
    read_game_dataframe,
    records_to_game_dataframe,
    write_scores,
    write_training_examples,
)
from photon_tpu.io.index_map import (
    DELIMITER,
    INTERCEPT_KEY,
    INTERCEPT_NAME,
    INTERCEPT_TERM,
    IndexMap,
    IndexMapBuilder,
    feature_key,
    split_feature_key,
)
from photon_tpu.io.model_io import (
    DEFAULT_SPARSITY_THRESHOLD,
    LoadedGameModel,
    load_game_model,
    load_model_metadata,
    save_game_model,
    save_model_metadata,
)

__all__ = [
    "AvroFileReader", "read_avro", "write_avro", "iter_avro_dir",
    "FeatureShardConfiguration", "build_index_maps", "read_game_dataframe",
    "records_to_game_dataframe", "write_scores", "write_training_examples",
    "IndexMap", "IndexMapBuilder", "feature_key", "split_feature_key",
    "DELIMITER", "INTERCEPT_KEY", "INTERCEPT_NAME", "INTERCEPT_TERM",
    "LoadedGameModel", "load_game_model", "save_game_model",
    "load_model_metadata", "save_model_metadata", "DEFAULT_SPARSITY_THRESHOLD",
]
