"""GAME model persistence in the reference's on-disk layout.

Reference: photon-client data/avro/ModelProcessingUtils.scala
(saveGameModelToHDFS :40 — layout ``<out>/fixed-effect/<coord>/
coefficients/part-*.avro`` + ``id-info``, ``random-effect/<coord>/...``,
``model-metadata.json`` of optimization configs :314-372;
loadGameModelFromHDFS :96), data/avro/AvroUtils.scala:344
(GLM <-> BayesianLinearModelAvro with sparsity threshold).

A model saved here is byte-level readable by the reference (same Avro
records, same directory layout, same metadata JSON) and vice versa. The
TPU twist is only on load of random effects: per-entity (name, term,
value) records are re-packed into the dense [E, K] coefficient block +
projection gather table that the TPU scorer consumes.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from photon_tpu.game.dataset import EntityVocabulary
from photon_tpu.game.model import FixedEffectModel, GameModel, RandomEffectModel
from photon_tpu.io import avro as avro_io
from photon_tpu.io.index_map import (
    IndexMap,
    IndexMapBuilder,
    feature_key,
    split_feature_key,
)
from photon_tpu.io.schemas import BAYESIAN_LINEAR_MODEL_AVRO
from photon_tpu.models.glm import Coefficients, GeneralizedLinearModel
from photon_tpu.resilience import io as rio
from photon_tpu.types import TaskType

import jax.numpy as jnp

FIXED_EFFECT = "fixed-effect"
RANDOM_EFFECT = "random-effect"
COEFFICIENTS = "coefficients"
ID_INFO = "id-info"
METADATA_FILE = "model-metadata.json"
#: per-shard serving column space sidecar, written alongside cold-store
#: files: without it a lazy (avro-skipping) load could not reproduce the
#: column numbering the cold store's projection table was written in
FEATURE_INDEX_DIR = "feature-index"
FEATURE_INDEX_SCHEMA = "photon_tpu.featureindex.v1"

# Reference: VectorUtils.DEFAULT_SPARSITY_THRESHOLD
DEFAULT_SPARSITY_THRESHOLD = 1e-4

# modelClass strings the reference writes (AvroUtils.scala:359) and
# dispatches on at load (:405) — kept verbatim for interchange.
_MODEL_CLASS = {
    TaskType.LOGISTIC_REGRESSION:
        "com.linkedin.photon.ml.supervised.classification.LogisticRegressionModel",
    TaskType.LINEAR_REGRESSION:
        "com.linkedin.photon.ml.supervised.regression.LinearRegressionModel",
    TaskType.POISSON_REGRESSION:
        "com.linkedin.photon.ml.supervised.regression.PoissonRegressionModel",
    TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM:
        "com.linkedin.photon.ml.supervised.classification.SmoothedHingeLossLinearSVMModel",
}
_TASK_FOR_CLASS = {v: k for k, v in _MODEL_CLASS.items()}


# ---------------------------------------------------------------------------
# vector <-> NameTermValue record lists
# ---------------------------------------------------------------------------


def _vector_to_ntvs(vec: np.ndarray, index_map: IndexMap,
                    indices: Optional[np.ndarray] = None,
                    sparsity_threshold: float = DEFAULT_SPARSITY_THRESHOLD,
                    ) -> List[dict]:
    """Nonzero (name, term, value) records for a coefficient vector.
    ``indices``: optional global column per vector slot (projected models);
    None = slot i IS global column i."""
    out = []
    for slot, v in enumerate(vec):
        v = float(v)
        if abs(v) <= sparsity_threshold:
            continue
        g = int(indices[slot]) if indices is not None else slot
        if g < 0:
            continue
        key = index_map.get_feature_name(g)
        if key is None:
            raise KeyError(f"no feature name for column {g}")
        name, term = split_feature_key(key)
        out.append({"name": name, "term": term, "value": v})
    return out


def _ntvs_to_vector(ntvs: Sequence[dict], index_map: IndexMap,
                    dim: int) -> np.ndarray:
    vec = np.zeros(dim)
    for r in ntvs:
        idx = index_map.index_of(str(r["name"]), str(r["term"]))
        if idx >= 0:
            vec[idx] = r["value"]
    return vec


# ---------------------------------------------------------------------------
# metadata JSON (reference: ModelProcessingUtils.gameOptConfigToJson :314)
# ---------------------------------------------------------------------------


def _opt_config_json(cfg) -> dict:
    """GLMOptimizationConfiguration -> the reference's JSON shape."""
    reg = cfg.regularization
    reg_type = getattr(getattr(reg, "reg_type", None), "value", "NONE")
    alpha = getattr(reg, "elastic_net_alpha", None)
    return {
        "optimizerConfig": {
            "optimizerType": cfg.optimizer.optimizer_type.value,
            "maximumIterations": cfg.optimizer.max_iterations,
            "tolerance": cfg.optimizer.tolerance,
        },
        "regularizationContext": {
            "regularizationType": reg_type,
            "elasticNetParam": alpha,
        },
        "regularizationWeight": cfg.regularization_weight,
        "downSamplingRate": cfg.down_sampling_rate,
    }


def save_model_metadata(output_dir: str, task: TaskType,
                        coordinate_configs: Optional[dict] = None,
                        model_name: str = "photon_tpu GAME model") -> None:
    fixed_vals, random_vals = [], []
    for cid, ccfg in (coordinate_configs or {}).items():
        entry = {"name": cid, "configuration": _opt_config_json(ccfg.optimization)}
        (random_vals if ccfg.is_random_effect else fixed_vals).append(entry)
    meta = {
        "modelType": task.value,
        "modelName": model_name,
        "fixedEffectOptimizationConfigurations": {
            "configurations": FIXED_EFFECT, "values": fixed_vals},
        "randomEffectOptimizationConfigurations": {
            "configurations": RANDOM_EFFECT, "values": random_vals},
    }
    rio.atomic_write_bytes(os.path.join(output_dir, METADATA_FILE),
                           json.dumps(meta, indent=2).encode("utf-8"),
                           op="model_write")


def load_model_metadata(model_dir: str) -> dict:
    with open(os.path.join(model_dir, METADATA_FILE)) as f:
        return json.load(f)


# ---------------------------------------------------------------------------
# save
# ---------------------------------------------------------------------------


def save_feature_index(output_dir: str, shard_id: str,
                       index_map: IndexMap) -> str:
    """Persist one shard's column space (feature key per column) so lazy
    loads can reproduce it without replaying every Avro record."""
    fdir = os.path.join(output_dir, FEATURE_INDEX_DIR)
    os.makedirs(fdir, exist_ok=True)
    keys = [index_map.get_feature_name(i)
            for i in range(index_map.feature_dimension)]
    path = os.path.join(fdir, shard_id + ".json")
    doc = {"schema": FEATURE_INDEX_SCHEMA, "feature_shard_id": shard_id,
           "features": keys}
    rio.atomic_write_bytes(path, json.dumps(doc).encode("utf-8"),
                           op="model_write")
    return path


def load_feature_indexes(model_dir: str) -> Dict[str, IndexMap]:
    """Read every feature-index sidecar in ``model_dir``; {} when the
    model predates them (pure Avro layout)."""
    fdir = os.path.join(model_dir, FEATURE_INDEX_DIR)
    out: Dict[str, IndexMap] = {}
    if not os.path.isdir(fdir):
        return out
    for name in sorted(os.listdir(fdir)):
        if not name.endswith(".json"):
            continue
        with open(os.path.join(fdir, name)) as f:
            doc = json.load(f)
        if doc.get("schema") != FEATURE_INDEX_SCHEMA:
            raise ValueError(f"unknown feature-index schema "
                             f"{doc.get('schema')!r} in {name}")
        out[doc["feature_shard_id"]] = IndexMap(
            {k: i for i, k in enumerate(doc["features"]) if k is not None})
    return out


def save_game_model(
    output_dir: str,
    model: GameModel,
    index_maps: Dict[str, IndexMap],
    vocab: Optional[EntityVocabulary] = None,
    projections: Optional[Dict[str, np.ndarray]] = None,
    coordinate_configs: Optional[dict] = None,
    sparsity_threshold: float = DEFAULT_SPARSITY_THRESHOLD,
    records_per_file: Optional[int] = None,
    write_cold_stores: bool = True,
) -> None:
    """Write a GAME model in the reference layout.

    ``index_maps``: feature shard id -> IndexMap (global columns).
    ``vocab`` + ``projections``: required when the model has random
    effects (entity row -> REId string; local slot -> global column).
    ``records_per_file``: max per-entity records per part file (the
    reference's randomEffectModelFileLimit).
    ``write_cold_stores``: also write each random-effect coordinate's
    cold-tier columnar file (io/cold_store.py) plus the per-shard
    feature-index sidecars — the pair the two-tier serving store and
    lazy ``load_for_serving`` consume. The Avro layout stays byte-level
    reference-compatible either way; the extra files are additive.
    """
    os.makedirs(output_dir, exist_ok=True)
    save_model_metadata(output_dir, model.task, coordinate_configs)
    has_random = any(isinstance(model[cid], RandomEffectModel)
                     for cid in model.coordinate_ids)
    if write_cold_stores and has_random:
        from photon_tpu.io.cold_store import cold_store_path, write_cold_store
        for sid, imap in index_maps.items():
            save_feature_index(output_dir, sid, imap)

    for cid in model.coordinate_ids:
        m = model[cid]
        if isinstance(m, FixedEffectModel):
            cdir = os.path.join(output_dir, FIXED_EFFECT, cid)
            os.makedirs(os.path.join(cdir, COEFFICIENTS), exist_ok=True)
            rio.atomic_write_bytes(os.path.join(cdir, ID_INFO),
                                   (m.feature_shard_id + "\n").encode("utf-8"),
                                   op="model_write")
            imap = index_maps[m.feature_shard_id]
            coefs = m.model.coefficients
            rec = {
                "modelId": FIXED_EFFECT,
                "modelClass": _MODEL_CLASS[m.task],
                "means": _vector_to_ntvs(
                    np.asarray(coefs.means), imap,
                    sparsity_threshold=sparsity_threshold),
                "variances": None if coefs.variances is None else
                    _vector_to_ntvs(np.asarray(coefs.variances), imap,
                                    sparsity_threshold=0.0),
                "lossFunction": "",
            }
            avro_io.write_avro(
                os.path.join(cdir, COEFFICIENTS, "part-00000.avro"),
                BAYESIAN_LINEAR_MODEL_AVRO, [rec])
        elif isinstance(m, RandomEffectModel):
            if vocab is None or projections is None or cid not in projections:
                raise ValueError(
                    f"random-effect coordinate {cid} needs vocab + projection")
            cdir = os.path.join(output_dir, RANDOM_EFFECT, cid)
            os.makedirs(os.path.join(cdir, COEFFICIENTS), exist_ok=True)
            rio.atomic_write_bytes(
                os.path.join(cdir, ID_INFO),
                (m.random_effect_type + "\n"
                 + m.feature_shard_id + "\n").encode("utf-8"),
                op="model_write")
            imap = index_maps[m.feature_shard_id]
            names = vocab.names(m.random_effect_type)
            proj = np.asarray(projections[cid])
            coef = np.asarray(m.coefficients)
            var = None if m.variances is None else np.asarray(m.variances)

            def entity_records():
                for e, re_id in enumerate(names):
                    yield {
                        "modelId": re_id,
                        "modelClass": _MODEL_CLASS[m.task],
                        "means": _vector_to_ntvs(
                            coef[e], imap, indices=proj[e],
                            sparsity_threshold=sparsity_threshold),
                        "variances": None if var is None else
                            _vector_to_ntvs(var[e], imap, indices=proj[e],
                                            sparsity_threshold=0.0),
                        "lossFunction": "",
                    }

            recs = list(entity_records())
            per_file = records_per_file or max(len(recs), 1)
            nfiles = max((len(recs) + per_file - 1) // per_file, 1)
            for p in range(nfiles):
                avro_io.write_avro(
                    os.path.join(cdir, COEFFICIENTS, f"part-{p:05d}.avro"),
                    BAYESIAN_LINEAR_MODEL_AVRO,
                    recs[p * per_file:(p + 1) * per_file])
            if write_cold_stores:
                write_cold_store(
                    cold_store_path(output_dir, cid), cid,
                    m.random_effect_type, m.feature_shard_id,
                    coef, proj.astype(np.int32, copy=False),
                    np.asarray(list(names)),
                    variances=var)
        else:
            raise TypeError(f"unknown model type for coordinate {cid}: {type(m)}")


# ---------------------------------------------------------------------------
# load
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class LoadedGameModel:
    """A GAME model plus the host-side artifacts scoring needs."""

    model: GameModel
    vocab: EntityVocabulary                  # entity row <-> REId per type
    projections: Dict[str, np.ndarray]       # cid -> [E, K] local->global
    metadata: dict

    @property
    def task(self) -> TaskType:
        return self.model.task

    def aligned_to(self, target_vocab: EntityVocabulary,
                   target_projections: Dict[str, np.ndarray]) -> GameModel:
        """Re-pack every random-effect block into a target dataset's entity
        order and slot layout — required before using a loaded model as a
        coordinate-descent warm start (the loaded slot order is the saved
        support, not the new ingest's projection)."""
        models: Dict[str, object] = {}
        for cid, m in self.model.models.items():
            if isinstance(m, RandomEffectModel) and cid not in target_projections:
                # the new fit does not configure this coordinate; carrying it
                # verbatim would poison the final model (its block layout has
                # no dataset, and saving would fail for lack of a projection)
                continue
            if not isinstance(m, RandomEffectModel):
                models[cid] = m
                continue
            tgt_proj = np.asarray(target_projections[cid])
            E_t, K_t = tgt_proj.shape
            src_proj = self.projections[cid]
            src_names = self.vocab.names(m.random_effect_type)
            row_of = {s: i for i, s in enumerate(src_names)}
            coef_src = np.asarray(m.coefficients)
            var_src = None if m.variances is None else np.asarray(m.variances)
            coef = np.zeros((E_t, K_t), coef_src.dtype)
            var = None if var_src is None else np.zeros((E_t, K_t), var_src.dtype)
            for e_t, name in enumerate(target_vocab.names(m.random_effect_type)):
                e_s = row_of.get(name)
                if e_s is None:
                    continue
                by_col = {int(src_proj[e_s, k]): k
                          for k in range(src_proj.shape[1])
                          if src_proj[e_s, k] >= 0}
                for k_t in range(K_t):
                    g = int(tgt_proj[e_t, k_t])
                    if g < 0:
                        continue
                    k_s = by_col.get(g)
                    if k_s is None:
                        continue
                    coef[e_t, k_t] = coef_src[e_s, k_s]
                    if var is not None:
                        var[e_t, k_t] = var_src[e_s, k_s]
            models[cid] = RandomEffectModel(
                coefficients=jnp.asarray(coef),
                random_effect_type=m.random_effect_type,
                feature_shard_id=m.feature_shard_id,
                task=m.task,
                variances=None if var is None else jnp.asarray(var),
            )
        return GameModel(models)


# ---------------------------------------------------------------------------
# serving fast path
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ServingFixedEffect:
    """One fixed-effect coordinate as a flat coefficient vector (plus the
    optional posterior-variance vector a Bayesian save carries)."""

    coordinate_id: str
    feature_shard_id: str
    coefficients: np.ndarray          # [D_shard] in the serving index space
    variances: Optional[np.ndarray] = None   # [D_shard] or None (mean-only)


class ServingRandomEffect:
    """One random-effect coordinate as a gather table + entity lookup.

    Two residency flavors behind one interface:

    * eager — ``coefficients`` [E, K] float32, ``projection`` [E, K]
      int32 (-1 pad), ``entity_rows`` {REId -> row} passed at
      construction (the classic fully-resident load).
    * cold-backed — only ``cold_store_path`` is set; the dense arrays
      materialize from the mmap-backed cold store on FIRST attribute
      access. The two-tier serving path reads rows straight off the
      ColdStore and never touches these properties, so loading a
      10M-entity model for two-tier serving costs one header read; the
      full-resident fallback (no CoeffStoreConfig) still works, paying
      the materialization exactly when it asks for the arrays.
    """

    def __init__(self, coordinate_id: str, random_effect_type: str,
                 feature_shard_id: str,
                 coefficients: Optional[np.ndarray] = None,
                 projection: Optional[np.ndarray] = None,
                 entity_rows: Optional[Dict[str, int]] = None,
                 cold_store_path: Optional[str] = None,
                 variances: Optional[np.ndarray] = None):
        if coefficients is None and cold_store_path is None:
            raise ValueError(
                f"random effect {coordinate_id!r} needs either eager "
                f"arrays or a cold_store_path")
        self.coordinate_id = coordinate_id
        self.random_effect_type = random_effect_type
        self.feature_shard_id = feature_shard_id
        self.cold_store_path = cold_store_path
        self._coefficients = coefficients
        self._projection = projection
        self._entity_rows = entity_rows
        self._variances = variances
        # eager loads know up front; cold-backed answers from the header
        # on first ask (one header read, no array materialization)
        self._has_var: Optional[bool] = (
            None if coefficients is None else variances is not None)
        self._num_entities: Optional[int] = (
            None if coefficients is None else int(coefficients.shape[0]))

    def _materialize(self) -> None:
        from photon_tpu.io.cold_store import ColdStore

        cs = ColdStore(self.cold_store_path)
        self._coefficients = np.asarray(cs.coef, dtype=np.float32)
        self._projection = np.asarray(cs.proj, dtype=np.int32)
        self._entity_rows = {cs.entity_id(r): r
                             for r in range(cs.num_entities)}
        if cs.has_variances:
            self._variances = np.asarray(cs.var, dtype=np.float32)
        self._has_var = cs.has_variances
        self._num_entities = cs.num_entities

    @property
    def coefficients(self) -> np.ndarray:
        if self._coefficients is None:
            self._materialize()
        return self._coefficients

    @property
    def projection(self) -> np.ndarray:
        if self._projection is None:
            self._materialize()
        return self._projection

    @property
    def entity_rows(self) -> Dict[str, int]:
        if self._entity_rows is None:
            self._materialize()
        return self._entity_rows

    @property
    def has_variances(self) -> bool:
        if self._has_var is None:
            from photon_tpu.io.cold_store import ColdStore

            self._has_var = ColdStore(self.cold_store_path).has_variances
        return self._has_var

    @property
    def variances(self) -> Optional[np.ndarray]:
        """Per-entity posterior variances [E, K] in the same slot layout
        as ``coefficients``, or None for a mean-only model."""
        if not self.has_variances:
            return None
        if self._variances is None:
            self._materialize()
        return self._variances

    @property
    def num_entities(self) -> int:
        if self._num_entities is None:
            from photon_tpu.io.cold_store import ColdStore

            self._num_entities = ColdStore(self.cold_store_path).num_entities
        return self._num_entities


@dataclasses.dataclass
class ServingGameModel:
    """Serving-shaped GAME model: flat arrays + lookup dicts only.

    Unlike :class:`LoadedGameModel` this carries none of the training-time
    containers (no GameModel/EntityVocabulary, no variances, no
    ``aligned_to`` re-packing machinery) — it is exactly what the online
    scorer consumes, produced in one pass over the on-disk records.
    """

    task: TaskType
    fixed: List[ServingFixedEffect]
    random: List[ServingRandomEffect]
    index_maps: Dict[str, IndexMap]   # serving column space, per shard
    metadata: dict

    @property
    def shard_dims(self) -> Dict[str, int]:
        return {sid: m.feature_dimension for sid, m in self.index_maps.items()}


def load_for_serving(
    model_dir: str,
    index_maps: Optional[Dict[str, IndexMap]] = None,
    coordinates_to_load: Optional[Sequence[str]] = None,
    dtype=np.float32,
) -> ServingGameModel:
    """Load a GAME model for online scoring: one pass over every record.

    Without ``index_maps`` the serving column space is built from the
    model's own support (a feature the model never weights scores zero
    either way, so dropping out-of-support request features preserves
    scores exactly) — unless the model dir carries feature-index
    sidecars, in which case those fix the column space up front (the
    numbering the cold-store projection tables were written in).

    Random-effect coordinates with a cold-store file are opened LAZILY:
    their per-entity Avro records are never read, and the returned
    :class:`ServingRandomEffect` materializes dense arrays from the cold
    file only if something asks for them. Posterior variances ride along
    when the model has them (Avro ``variances`` fields, or the cold
    store's v3/v4 variance column) — the Thompson-sampling serving mode's
    input; mean-only models load exactly as before with ``variances``
    absent.
    """
    from photon_tpu.io.cold_store import cold_store_path

    metadata = load_model_metadata(model_dir)
    task = TaskType(metadata["modelType"])
    wanted = set(coordinates_to_load) if coordinates_to_load else None
    external = index_maps is not None
    sidecars = {} if external else load_feature_indexes(model_dir)
    builders: Dict[str, IndexMapBuilder] = {}

    def col_of(shard_id: str, name: str, term: str) -> int:
        if external:
            return index_maps[shard_id].index_of(name, term)
        if shard_id in sidecars:
            return sidecars[shard_id].index_of(name, term)
        return builders.setdefault(shard_id, IndexMapBuilder()).put(
            feature_key(name, term))

    # pass 1 (and only): records -> {global column: value} slot dicts;
    # dense packing waits until every coordinate has grown the builders
    fixed_raw: List[Tuple[str, str, Dict[int, float],
                          Optional[Dict[int, float]]]] = []
    random_raw: List[Tuple[str, str, str, List[str], List[Dict[int, float]],
                           Optional[List[Dict[int, float]]]]] = []
    cold_raw: List[Tuple[str, str, str, str]] = []  # cid, type, shard, path

    fixed_dir = os.path.join(model_dir, FIXED_EFFECT)
    if os.path.isdir(fixed_dir):
        for cid in sorted(os.listdir(fixed_dir)):
            if wanted is not None and cid not in wanted:
                continue
            cdir = os.path.join(fixed_dir, cid)
            with open(os.path.join(cdir, ID_INFO)) as f:
                shard_id = f.read().split()[0]
            if external and shard_id not in index_maps:
                raise KeyError(f"no index map for feature shard {shard_id!r}")
            recs = list(avro_io.iter_avro_dir(os.path.join(cdir, COEFFICIENTS)))
            if len(recs) != 1:
                raise ValueError(
                    f"expected 1 fixed-effect record, got {len(recs)}")
            slots: Dict[int, float] = {}
            for r in recs[0]["means"]:
                g = col_of(shard_id, str(r["name"]), str(r["term"]))
                if g >= 0:
                    slots[g] = float(r["value"])
            var_recs = recs[0].get("variances")
            var_slots: Optional[Dict[int, float]] = None
            if var_recs is not None:
                var_slots = {}
                for r in var_recs:
                    g = col_of(shard_id, str(r["name"]), str(r["term"]))
                    if g >= 0:
                        var_slots[g] = float(r["value"])
            fixed_raw.append((cid, shard_id, slots, var_slots))

    random_dir = os.path.join(model_dir, RANDOM_EFFECT)
    if os.path.isdir(random_dir):
        for cid in sorted(os.listdir(random_dir)):
            if wanted is not None and cid not in wanted:
                continue
            cdir = os.path.join(random_dir, cid)
            with open(os.path.join(cdir, ID_INFO)) as f:
                re_type, shard_id = f.read().split()[:2]
            if external and shard_id not in index_maps:
                raise KeyError(f"no index map for feature shard {shard_id!r}")
            cold_path = cold_store_path(model_dir, cid)
            if not external and os.path.exists(cold_path):
                # lazy: the cold file IS the coefficient table (its
                # projection columns are the sidecar column space), so
                # the per-entity Avro records never get read
                cold_raw.append((cid, re_type, shard_id, cold_path))
                continue
            names: List[str] = []
            per_entity: List[Dict[int, float]] = []
            per_entity_var: List[Dict[int, float]] = []
            have_var = False
            for rec in avro_io.iter_avro_dir(os.path.join(cdir, COEFFICIENTS)):
                slots = {}
                for r in rec["means"]:
                    g = col_of(shard_id, str(r["name"]), str(r["term"]))
                    if g >= 0:
                        slots[g] = float(r["value"])
                vslots: Dict[int, float] = {}
                for r in (rec.get("variances") or ()):
                    have_var = True
                    g = col_of(shard_id, str(r["name"]), str(r["term"]))
                    if g >= 0:
                        vslots[g] = float(r["value"])
                names.append(str(rec["modelId"]))
                per_entity.append(slots)
                per_entity_var.append(vslots)
            random_raw.append((cid, re_type, shard_id, names, per_entity,
                               per_entity_var if have_var else None))

    maps = dict(index_maps) if external else {
        **{sid: b.build() for sid, b in builders.items()},
        **sidecars}

    fixed = []
    for cid, shard_id, slots, var_slots in fixed_raw:
        dim = maps[shard_id].feature_dimension if shard_id in maps else 0
        vec = np.zeros(max(dim, 1), dtype)
        for g, v in slots.items():
            vec[g] = v
        var_vec = None
        if var_slots is not None:
            var_vec = np.zeros(max(dim, 1), dtype)
            for g, v in var_slots.items():
                var_vec[g] = v
        fixed.append(ServingFixedEffect(cid, shard_id, vec, var_vec))

    random_ = []
    for cid, re_type, shard_id, names, per_entity, per_entity_var \
            in random_raw:
        E = len(per_entity)
        # slot space per entity = union of the means + variances supports
        # (independent vectors on disk, same treatment as load_game_model)
        unions = [sorted(set(s) | set(per_entity_var[e]
                                      if per_entity_var else ()))
                  for e, s in enumerate(per_entity)]
        K = max((len(u) for u in unions), default=1) or 1
        coef = np.zeros((E, K), dtype)
        proj = np.full((E, K), -1, np.int32)
        var = None if per_entity_var is None else np.zeros((E, K), dtype)
        for e, cols in enumerate(unions):
            for s, g in enumerate(cols):
                proj[e, s] = g
                coef[e, s] = per_entity[e].get(g, 0.0)
                if var is not None:
                    var[e, s] = per_entity_var[e].get(g, 0.0)
        random_.append(ServingRandomEffect(
            cid, re_type, shard_id, coef, proj,
            {name: i for i, name in enumerate(names)}, variances=var))
    for cid, re_type, shard_id, cold_path in cold_raw:
        random_.append(ServingRandomEffect(
            cid, re_type, shard_id, cold_store_path=cold_path))
    random_.sort(key=lambda r: r.coordinate_id)

    return ServingGameModel(task, fixed, random_, maps, metadata)


def load_game_model(
    model_dir: str,
    index_maps: Dict[str, IndexMap],
    coordinates_to_load: Optional[Sequence[str]] = None,
    dtype=np.float32,
) -> LoadedGameModel:
    """Reference: ModelProcessingUtils.loadGameModelFromHDFS :96."""
    metadata = load_model_metadata(model_dir)
    task = TaskType(metadata["modelType"])
    wanted = set(coordinates_to_load) if coordinates_to_load else None

    models: Dict[str, object] = {}
    vocab = EntityVocabulary()
    projections: Dict[str, np.ndarray] = {}

    fixed_dir = os.path.join(model_dir, FIXED_EFFECT)
    if os.path.isdir(fixed_dir):
        for cid in sorted(os.listdir(fixed_dir)):
            if wanted is not None and cid not in wanted:
                continue
            cdir = os.path.join(fixed_dir, cid)
            with open(os.path.join(cdir, ID_INFO)) as f:
                shard_id = f.read().split()[0]
            if shard_id not in index_maps:
                if wanted is not None:
                    raise KeyError(f"no index map for feature shard {shard_id!r}")
                continue
            imap = index_maps[shard_id]
            dim = imap.feature_dimension
            recs = list(avro_io.iter_avro_dir(os.path.join(cdir, COEFFICIENTS)))
            if len(recs) != 1:
                raise ValueError(f"expected 1 fixed-effect record, got {len(recs)}")
            rec = recs[0]
            rec_task = _TASK_FOR_CLASS.get(rec.get("modelClass") or "", task)
            means = jnp.asarray(_ntvs_to_vector(rec["means"], imap, dim), dtype)
            variances = rec.get("variances")
            var = None if variances is None else jnp.asarray(
                _ntvs_to_vector(variances, imap, dim), dtype)
            models[cid] = FixedEffectModel(
                GeneralizedLinearModel(Coefficients(means, var), rec_task),
                shard_id)

    random_dir = os.path.join(model_dir, RANDOM_EFFECT)
    if os.path.isdir(random_dir):
        for cid in sorted(os.listdir(random_dir)):
            if wanted is not None and cid not in wanted:
                continue
            cdir = os.path.join(random_dir, cid)
            with open(os.path.join(cdir, ID_INFO)) as f:
                re_type, shard_id = f.read().split()[:2]
            if shard_id not in index_maps:
                if wanted is not None:
                    raise KeyError(f"no index map for feature shard {shard_id!r}")
                continue
            imap = index_maps[shard_id]
            entities: List[Tuple[str, List[dict], Optional[List[dict]], str]] = []
            for rec in avro_io.iter_avro_dir(os.path.join(cdir, COEFFICIENTS)):
                entities.append((str(rec["modelId"]), rec["means"],
                                 rec.get("variances"),
                                 rec.get("modelClass") or ""))
            # dense block: entity row per record order, local slots = the
            # union of the means + variances supports (the IndexMapProjector
            # role; means and variances are independent vectors on disk)
            vocab.build(re_type, [e[0] for e in entities])
            E = len(entities)
            per_entity: List[Dict[int, Tuple[float, float]]] = []
            have_var = False
            rec_task = task
            for re_id, means, variances, cls in entities:
                rec_task = _TASK_FOR_CLASS.get(cls, task)
                slots: Dict[int, Tuple[float, float]] = {}
                for r in means:
                    g = imap.index_of(str(r["name"]), str(r["term"]))
                    if g >= 0:
                        slots[g] = (float(r["value"]), 0.0)
                if variances:
                    have_var = True
                    for r in variances:
                        g = imap.index_of(str(r["name"]), str(r["term"]))
                        if g >= 0:
                            mean_v = slots.get(g, (0.0, 0.0))[0]
                            slots[g] = (mean_v, float(r["value"]))
                per_entity.append(slots)
            k_max = max((len(s) for s in per_entity), default=1) or 1
            coef = np.zeros((E, k_max), dtype)
            var_block = np.zeros((E, k_max), dtype)
            proj = np.full((E, k_max), -1, np.int32)
            for e, slots in enumerate(per_entity):
                for s, (g, (m, v)) in enumerate(sorted(slots.items())):
                    proj[e, s] = g
                    coef[e, s] = m
                    var_block[e, s] = v
            models[cid] = RandomEffectModel(
                coefficients=jnp.asarray(coef),
                random_effect_type=re_type,
                feature_shard_id=shard_id,
                task=rec_task,
                variances=jnp.asarray(var_block) if have_var else None,
            )
            projections[cid] = proj

    return LoadedGameModel(GameModel(models), vocab, projections, metadata)
