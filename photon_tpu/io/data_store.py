"""Disk-native training data: a crc32-verified, mmap-backed columnar
chunk store — the data-side twin of ``io/cold_store.py``.

Every in-RAM ``ChunkSource`` (``data/streaming.py``) bounds dataset size
by host memory and re-pays the LibSVM/Avro parse on every run. This
module moves the parse to a ONE-TIME conversion into a fixed-layout
binary store that ``data/streaming.MmapChunkSource`` then maps straight
into the training pipeline: ``read_block`` is a pure mmap slice — no
parse, no row assembly — flowing through the loader's 64-byte-aligned
zero-copy alias fast path, so a streamed fit is bitwise identical to the
in-RAM sources while the dataset never materializes in host RAM.

Layout — a directory of section files plus a manifest-written-LAST:

    store/
      labels.sec              raw little-endian C-order array bytes [n]
      weights.sec, offsets.sec        (optional per-row columns)
      x.sec                   dense [n, dim]
      idx.sec, val.sec        sparse padded-ELL [n, ell_width] — stored
                              PRE-ASSEMBLED, bitwise identical to what
                              ``CsrSource.read_block`` would materialize,
                              so disk chunks equal in-RAM chunks byte for
                              byte and read time does zero row assembly
      nnz.sec                 int32 per-row nonzero counts (sparse)
      manifest.json           crc32-wrapped JSON: geometry, per-section
                              byte lengths + crc32s, per-chunk nnz
                              headers, chunk -> mesh-shard assignment

Invariants (mirroring the cold store's):

- **Manifest last, atomically.** Section files are staged as ``.part``
  files and renamed into place before the manifest is published via the
  fsync-audited atomic write (``resilience/io``). A store without a
  valid manifest does not exist; a kill at any point leaves either the
  previous store or recognizable debris, never a half-store a reader
  could silently truncate.
- **Typed refusal, never a silent short read.** Missing or size-skewed
  section files, torn or crc-skewed manifests, and bit-flipped section
  bytes (``verify=True`` scans every section) all raise
  ``DataStoreCorruptError``.
- **64-byte alignment.** Each section starts at file (= mmap) offset 0,
  page-aligned and therefore aligned to the ChunkLoader's ``_ALIGN=64``
  staging granularity; any chunk boundary at a multiple of 8 rows stays
  64-byte aligned for every section dtype, keeping the dlpack alias
  path live. (Page-backed sections are also exactly what a future
  pinned-host-allocation path wants to register for real DMA.)
- **Resumable conversion.** The writer persists a crc-framed cursor
  (per-section byte lengths + running crc32s + completed input units)
  after every unit; a killed conversion resumes by truncating to the
  cursor and re-converting deterministically from the next unit, landing
  on a byte-identical store. Chaos hook: ``chaos.should_kill_convert``.
- **Shard-aware.** ``chunk_shards[c] = partition.entity_shard(f"chunk-{c}",
  num_shards)`` — the same crc32 partitioner that places entities —
  so multi-host meshes read disjoint chunk ranges from one store.
"""

from __future__ import annotations

import json
import mmap
import os
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from photon_tpu.resilience import chaos
from photon_tpu.resilience import io as rio

MAGIC = "PHOTDSTR"
SCHEMA = 1
_ALIGN = 64           # ChunkLoader staging-pool granularity
MANIFEST = "manifest.json"
CURSOR = "_convert_cursor.json"
_SCAN_BUF = 4 << 20   # buffered crc scan: keeps verify RSS at 4MB


class DataStoreCorruptError(RuntimeError):
    """The on-disk training-data store failed an integrity gate; loading
    anyway could train on silently truncated or bit-flipped rows."""

    def __init__(self, path: str, detail: str):
        super().__init__(f"data store {path!r} refused: {detail}")
        self.path = path
        self.detail = detail


# -- crc-wrapped JSON documents (manifest + conversion cursor) --------------

def _wrap_json(doc: dict) -> bytes:
    payload = json.dumps(doc, sort_keys=True)
    return json.dumps({"crc32": zlib.crc32(payload.encode()),
                       "payload": doc}, sort_keys=True).encode()


def _unwrap_json(blob: bytes, path: str) -> dict:
    try:
        outer = json.loads(blob.decode("utf-8"))
    except (ValueError, UnicodeDecodeError) as e:
        raise DataStoreCorruptError(path, f"torn/unparseable JSON: {e}")
    if not isinstance(outer, dict) or "payload" not in outer:
        raise DataStoreCorruptError(path, "missing crc envelope")
    payload = outer["payload"]
    want = outer.get("crc32")
    got = zlib.crc32(json.dumps(payload, sort_keys=True).encode())
    if want != got:
        raise DataStoreCorruptError(
            path, f"crc mismatch (manifest says {want}, computed {got})")
    return payload


# -- shared ELL assembly (bitwise contract with CsrSource.read_block) -------

def ell_from_csr(indptr: np.ndarray, cols: np.ndarray, vals: np.ndarray,
                 k: int, dtype) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """CSR rows -> (idx, val, row_nnz) padded-ELL block, with the EXACT
    numpy operations ``data/streaming.CsrSource.read_block`` uses — zeros
    padding, int32 indices, cast-on-assignment — so a store converted
    here is bitwise identical to what the in-RAM source materializes."""
    indptr = np.asarray(indptr, np.int64)
    r = len(indptr) - 1
    row_nnz = np.diff(indptr)
    if int(row_nnz.max(initial=0)) > k:
        raise ValueError(f"row has {int(row_nnz.max())} nonzeros > "
                         f"ell_width={k}; refusing to silently truncate")
    idx = np.zeros((r, k), np.int32)
    val = np.zeros((r, k), np.dtype(dtype))
    if r and k:
        slot = np.arange(k)[None, :]
        mask = slot < row_nnz[:, None]
        src = indptr[:-1, None] + slot
        idx[mask] = cols[src[mask]]
        val[mask] = vals[src[mask]]
    return idx, val, row_nnz.astype(np.int32)


# -- section schema ---------------------------------------------------------

def _section_schema(dim: int, ell_width: Optional[int], dtype: np.dtype,
                    has_offsets: bool, has_weights: bool) -> Dict[str, dict]:
    """name -> {dtype, cols} for every section this store carries (cols=0
    means a flat [n] column)."""
    dt = np.dtype(dtype).str
    secs = {"labels": {"dtype": dt, "cols": 0}}
    if has_weights:
        secs["weights"] = {"dtype": dt, "cols": 0}
    if has_offsets:
        secs["offsets"] = {"dtype": dt, "cols": 0}
    if ell_width is None:
        secs["x"] = {"dtype": dt, "cols": int(dim)}
    else:
        secs["idx"] = {"dtype": np.dtype(np.int32).str,
                       "cols": int(ell_width)}
        secs["val"] = {"dtype": dt, "cols": int(ell_width)}
        secs["nnz"] = {"dtype": np.dtype(np.int32).str, "cols": 0}
    return secs


def _row_bytes(spec: dict) -> int:
    return np.dtype(spec["dtype"]).itemsize * max(1, int(spec["cols"]) or 1)


# ===========================================================================
# Writer: resumable, cursor-checkpointed section appender
# ===========================================================================

class DataStoreWriter:
    """Append-only store builder with a resumable conversion cursor.

    The converter appends row batches, calls ``mark_unit`` after each
    completed input unit (a file, a directory), and ``finalize`` once.
    A kill between ``mark_unit`` calls loses at most one unit of work:
    ``resume=True`` truncates the ``.part`` sections back to the cursor
    and the converter re-runs only the units the cursor does not list —
    deterministically, so the finished store is byte-identical to an
    uninterrupted conversion.
    """

    def __init__(self, path: str, *, dim: int, dtype=np.float64,
                 ell_width: Optional[int] = None, has_offsets: bool = False,
                 has_weights: bool = False, chunk_rows: int = 8192,
                 num_shards: int = 1, source: Optional[dict] = None,
                 resume: bool = False):
        if chunk_rows <= 0 or chunk_rows % 8:
            # multiples of 8 rows keep every chunk boundary 64-byte
            # aligned for all section dtypes (f32 rows: 8*4 = 32... the
            # widest flat column is 8 bytes, 8 rows * 8B = 64)
            raise ValueError(f"chunk_rows={chunk_rows} must be a positive "
                             "multiple of 8 (64-byte chunk alignment)")
        self.path = path
        self.dim = int(dim)
        self.dtype = np.dtype(dtype)
        self.ell_width = None if ell_width is None else int(ell_width)
        self.chunk_rows = int(chunk_rows)
        self.num_shards = int(num_shards)
        self.source = dict(source or {})
        self._schema = _section_schema(dim, self.ell_width, self.dtype,
                                       has_offsets, has_weights)
        self._rows = 0
        self._crcs = {name: 0 for name in self._schema}
        self._units: List[str] = []
        self._finalized = False
        os.makedirs(path, exist_ok=True)
        if resume and os.path.exists(os.path.join(path, CURSOR)):
            self._resume_from_cursor()
        mode = "r+b" if resume and self._rows else "wb"
        self._files = {name: open(self._part(name), mode)
                       for name in self._schema}
        for name, f in self._files.items():
            f.seek(self._rows * _row_bytes(self._schema[name]))
            f.truncate()

    def _part(self, name: str) -> str:
        return os.path.join(self.path, f"{name}.sec.part")

    @property
    def rows(self) -> int:
        return self._rows

    @property
    def units_done(self) -> Tuple[str, ...]:
        """Input units already durably recorded — the converter skips
        these on resume."""
        return tuple(self._units)

    # -- cursor -------------------------------------------------------------

    def _cursor_doc(self) -> dict:
        return {
            "magic": MAGIC, "schema": SCHEMA, "rows": self._rows,
            "dim": self.dim, "dtype": self.dtype.str,
            "ell_width": self.ell_width, "chunk_rows": self.chunk_rows,
            "num_shards": self.num_shards,
            "sections": {n: {"bytes": self._rows * _row_bytes(s),
                             "crc32": self._crcs[n]}
                         for n, s in self._schema.items()},
            "units": list(self._units),
        }

    def _resume_from_cursor(self) -> None:
        cpath = os.path.join(self.path, CURSOR)
        cur = _unwrap_json(rio.read_bytes(cpath, op="data_store.cursor"),
                           cpath)
        for key, want in (("magic", MAGIC), ("schema", SCHEMA),
                          ("dim", self.dim), ("dtype", self.dtype.str),
                          ("ell_width", self.ell_width),
                          ("chunk_rows", self.chunk_rows),
                          ("num_shards", self.num_shards)):
            if cur.get(key) != want:
                raise DataStoreCorruptError(
                    cpath, f"cursor {key}={cur.get(key)!r} does not match "
                           f"this conversion's {want!r}")
        if set(cur["sections"]) != set(self._schema):
            raise DataStoreCorruptError(cpath, "cursor section set skew")
        self._rows = int(cur["rows"])
        for name, rec in cur["sections"].items():
            want = self._rows * _row_bytes(self._schema[name])
            if int(rec["bytes"]) != want:
                raise DataStoreCorruptError(
                    cpath, f"cursor bytes for {name!r} != rows * row_bytes")
            part = self._part(name)
            have = os.path.getsize(part) if os.path.exists(part) else -1
            if have < want:
                raise DataStoreCorruptError(
                    cpath, f"section {name}.sec.part is {have} bytes, "
                           f"shorter than the cursor's {want} — the store "
                           "lost data the cursor says was durable")
            self._crcs[name] = int(rec["crc32"])
        self._units = [str(u) for u in cur["units"]]

    # -- appending ----------------------------------------------------------

    def _append_one(self, name: str, arr: Optional[np.ndarray],
                    rows: int) -> None:
        spec = self._schema[name]
        want_dt = np.dtype(spec["dtype"])
        cols = int(spec["cols"])
        shape = (rows, cols) if cols else (rows,)
        if arr is None:
            raise ValueError(f"store schema includes section {name!r} but "
                             "append() received None for it")
        arr = np.ascontiguousarray(arr)
        if arr.dtype != want_dt:
            raise ValueError(f"section {name!r} expects dtype {want_dt}, "
                             f"got {arr.dtype} (cast explicitly — silent "
                             "casts would break bitwise parity)")
        if arr.shape != shape:
            raise ValueError(f"section {name!r} expects shape {shape}, "
                             f"got {arr.shape}")
        data = arr.tobytes()
        self._crcs[name] = zlib.crc32(data, self._crcs[name])
        self._files[name].write(data)

    def append(self, labels: np.ndarray, *, x: Optional[np.ndarray] = None,
               idx: Optional[np.ndarray] = None,
               val: Optional[np.ndarray] = None,
               nnz: Optional[np.ndarray] = None,
               offsets: Optional[np.ndarray] = None,
               weights: Optional[np.ndarray] = None) -> None:
        if self._finalized:
            raise RuntimeError("writer already finalized")
        rows = int(np.shape(labels)[0])
        by_name = {"labels": labels, "x": x, "idx": idx, "val": val,
                   "nnz": nnz, "offsets": offsets, "weights": weights}
        for name in self._schema:
            self._append_one(name, by_name[name], rows)
        self._rows += rows

    def append_csr(self, labels: np.ndarray, indptr: np.ndarray,
                   cols: np.ndarray, vals: np.ndarray, *,
                   offsets: Optional[np.ndarray] = None,
                   weights: Optional[np.ndarray] = None) -> None:
        """Append CSR rows, assembling the stored padded-ELL block with
        the CsrSource-bitwise ``ell_from_csr``."""
        if self.ell_width is None:
            raise ValueError("append_csr on a dense store")
        idx, val, nnz = ell_from_csr(indptr, cols, vals, self.ell_width,
                                     self.dtype)
        self.append(np.asarray(labels, self.dtype), idx=idx, val=val,
                    nnz=nnz, offsets=offsets, weights=weights)

    def mark_unit(self, unit_id: str) -> None:
        """Durably record one completed input unit: flush + fsync the
        section data, then publish the cursor. The chaos kill point sits
        between the two — data durable, cursor stale — the harshest spot
        for resume correctness (the unit is re-converted and must land
        byte-identically)."""
        for f in self._files.values():
            f.flush()
            os.fsync(f.fileno())
        if chaos.should_kill_convert(len(self._units)):
            raise chaos.SimulatedKill(
                f"chaos: killed conversion after unit {unit_id!r} data "
                "write, before its cursor advance")
        self._units.append(str(unit_id))
        rio.atomic_write_bytes(os.path.join(self.path, CURSOR),
                               _wrap_json(self._cursor_doc()),
                               op="data_store.cursor")

    # -- finalize -----------------------------------------------------------

    def _chunk_nnz(self) -> Optional[List[int]]:
        """Per-chunk nnz headers from the nnz section (read back buffered,
        resume-proof — the writer's in-memory state never has to carry
        partial chunk sums across a kill)."""
        if self.ell_width is None:
            return None
        nnz = np.fromfile(self._part("nnz"), np.int32)
        starts = np.arange(0, self._rows, self.chunk_rows)
        return [int(v) for v in np.add.reduceat(nnz.astype(np.int64),
                                                starts)] if self._rows \
            else []

    def finalize(self) -> dict:
        """Rename sections into place and publish the manifest LAST.
        Returns the manifest payload."""
        if self._finalized:
            raise RuntimeError("writer already finalized")
        chunk_nnz = self._chunk_nnz()
        for f in self._files.values():
            f.flush()
            os.fsync(f.fileno())
            f.close()
        num_chunks = max(1, -(-self._rows // self.chunk_rows)) \
            if self._rows else 0
        from photon_tpu.parallel.partition import entity_shard
        chunk_shards = [entity_shard(f"chunk-{c}", self.num_shards)
                        for c in range(num_chunks)]
        sections = {}
        for name, spec in self._schema.items():
            final = os.path.join(self.path, f"{name}.sec")
            os.replace(self._part(name), final)
            sections[name] = {
                "dtype": spec["dtype"], "cols": spec["cols"],
                "bytes": self._rows * _row_bytes(spec),
                "crc32": self._crcs[name],
            }
        rio.fsync_dir(self.path)
        manifest = {
            "magic": MAGIC, "schema": SCHEMA,
            "dtype": self.dtype.str, "n_rows": self._rows,
            "dim": self.dim, "ell_width": self.ell_width,
            "has_offsets": "offsets" in self._schema,
            "has_weights": "weights" in self._schema,
            "chunk_rows": self.chunk_rows, "num_chunks": num_chunks,
            "num_shards": self.num_shards, "chunk_shards": chunk_shards,
            "chunk_nnz": chunk_nnz,
            "sections": sections,
            "source": self.source,
        }
        rio.atomic_write_bytes(os.path.join(self.path, MANIFEST),
                               _wrap_json(manifest),
                               op="data_store.manifest")
        cursor = os.path.join(self.path, CURSOR)
        if os.path.exists(cursor):
            os.remove(cursor)
        self._finalized = True
        return manifest

    def abort(self) -> None:
        """Close part files without publishing (error-path cleanup; the
        cursor and parts stay for a later resume)."""
        for f in self._files.values():
            if not f.closed:
                f.close()


# ===========================================================================
# Reader: typed-refusal manifest gate + mmap section views
# ===========================================================================

class DataStore:
    """Read side of the store: validates the manifest envelope and every
    section's size up front (and, with ``verify=True`` — the default —
    crc-scans all section bytes with bounded 4MB buffers), then serves
    zero-copy mmap array views per section.

    Sections are mapped ``ACCESS_COPY`` (private, copy-on-write): the
    arrays are writable as far as the buffer protocol is concerned — so
    dlpack export, and with it the ChunkLoader's zero-copy alias path,
    works — but no write can ever reach the store. Consumers treat the
    views as immutable training data; ``advise_dontneed`` relies on that
    to drop clean resident pages behind a streaming cursor.
    """

    def __init__(self, path: str, *, verify: bool = True):
        self.path = path
        mpath = os.path.join(path, MANIFEST)
        if not os.path.exists(mpath):
            raise DataStoreCorruptError(
                path, "no manifest.json — not a data store (or its "
                      "conversion never finalized)")
        man = _unwrap_json(rio.read_bytes(mpath, op="data_store.manifest"),
                           mpath)
        if man.get("magic") != MAGIC or man.get("schema") != SCHEMA:
            raise DataStoreCorruptError(
                path, f"bad magic/schema {man.get('magic')!r}/"
                      f"{man.get('schema')!r} (want {MAGIC!r}/{SCHEMA})")
        for key in ("dtype", "n_rows", "dim", "ell_width", "chunk_rows",
                    "num_chunks", "num_shards", "chunk_shards", "sections"):
            if key not in man:
                raise DataStoreCorruptError(path,
                                            f"manifest missing {key!r}")
        if len(man["chunk_shards"]) != man["num_chunks"]:
            raise DataStoreCorruptError(
                path, "chunk_shards length != num_chunks")
        want_secs = _section_schema(
            man["dim"], man["ell_width"], np.dtype(man["dtype"]),
            man["has_offsets"], man["has_weights"])
        if set(man["sections"]) != set(want_secs):
            raise DataStoreCorruptError(
                path, f"section set {sorted(man['sections'])} does not "
                      f"match schema {sorted(want_secs)}")
        for name, rec in man["sections"].items():
            spath = os.path.join(path, f"{name}.sec")
            if not os.path.exists(spath):
                raise DataStoreCorruptError(path,
                                            f"missing section {name}.sec")
            size = os.path.getsize(spath)
            want = int(man["n_rows"]) * _row_bytes(rec)
            if size != want or int(rec["bytes"]) != want:
                raise DataStoreCorruptError(
                    path, f"section {name}.sec is {size} bytes, manifest "
                          f"rows demand {want} — refusing the short/long "
                          "read")
        self.manifest = man
        self._maps: Dict[str, Tuple[object, np.ndarray]] = {}
        self._page = mmap.ALLOCATIONGRANULARITY
        if verify:
            self.verify()

    def verify(self) -> None:
        """Buffered crc32 scan of every section against the manifest —
        bounded host memory (one 4MB buffer), typed refusal on any flip."""
        for name, rec in self.manifest["sections"].items():
            spath = os.path.join(self.path, f"{name}.sec")
            crc = 0
            with open(spath, "rb") as f:
                while True:
                    buf = f.read(_SCAN_BUF)
                    if not buf:
                        break
                    crc = zlib.crc32(buf, crc)
            if crc != int(rec["crc32"]):
                raise DataStoreCorruptError(
                    self.path, f"section {name}.sec crc mismatch "
                               f"(manifest {rec['crc32']}, scanned {crc}) "
                               "— bit flip or torn write")

    # -- mmap views ---------------------------------------------------------

    def section(self, name: str) -> np.ndarray:
        """Zero-copy array view of one section (cached mmap)."""
        if name in self._maps:
            return self._maps[name][1]
        rec = self.manifest["sections"][name]
        spath = os.path.join(self.path, f"{name}.sec")
        n = int(self.manifest["n_rows"])
        cols = int(rec["cols"])
        with open(spath, "rb") as f:
            if n == 0:
                arr = np.zeros((n, cols) if cols else (n,),
                               np.dtype(rec["dtype"]))
                self._maps[name] = (None, arr)
                return arr
            mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_COPY)
        arr = np.frombuffer(mm, dtype=np.dtype(rec["dtype"]))
        if cols:
            arr = arr.reshape(n, cols)
        self._maps[name] = (mm, arr)
        return arr

    def advise_dontneed(self, row_lo: int, row_hi: int) -> None:
        """Release resident pages of rows [row_lo, row_hi) across every
        mapped section — the streaming source calls this behind its read
        cursor so a full pass's resident-set high-water stays a small
        window instead of the whole dataset. Purely an RSS hint: the
        pages are clean and file-backed, so a racing reader simply
        re-faults the same bytes."""
        if row_hi <= row_lo or not hasattr(mmap.mmap, "madvise"):
            return
        for name, (mm, _arr) in self._maps.items():
            if mm is None:
                continue
            rb = _row_bytes(self.manifest["sections"][name])
            lo = -(-(row_lo * rb) // self._page) * self._page  # round up
            hi = (row_hi * rb) // self._page * self._page      # round down
            if hi > lo:
                mm.madvise(mmap.MADV_DONTNEED, lo, hi - lo)

    def close(self) -> None:
        maps, self._maps = self._maps, {}
        for mm, _arr in maps.values():
            if mm is not None:
                # the array still references the buffer; drop our handle
                # and let refcounting unmap when consumers are done
                del _arr

    def describe(self) -> dict:
        m = self.manifest
        return {
            "path": self.path, "rows": m["n_rows"], "dim": m["dim"],
            "ell_width": m["ell_width"], "dtype": m["dtype"],
            "chunk_rows": m["chunk_rows"], "num_chunks": m["num_chunks"],
            "num_shards": m["num_shards"],
            "bytes": sum(int(s["bytes"]) for s in m["sections"].values()),
            "source": m.get("source", {}),
        }


# ===========================================================================
# One-shot array writer (tests / in-memory conversion)
# ===========================================================================

def write_data_store(path: str, labels: np.ndarray, *,
                     x: Optional[np.ndarray] = None,
                     indptr: Optional[np.ndarray] = None,
                     cols: Optional[np.ndarray] = None,
                     vals: Optional[np.ndarray] = None,
                     dim: Optional[int] = None,
                     ell_width: Optional[int] = None,
                     offsets: Optional[np.ndarray] = None,
                     weights: Optional[np.ndarray] = None,
                     dtype=np.float64, chunk_rows: int = 8192,
                     num_shards: int = 1,
                     source: Optional[dict] = None) -> dict:
    """Build a store from in-memory arrays: dense ``x`` [n, dim] or CSR
    ``(indptr, cols, vals)``. Returns the manifest payload."""
    dt = np.dtype(dtype)
    labels = np.asarray(labels, dt)
    if x is not None:
        dim = int(x.shape[1]) if dim is None else int(dim)
        w = DataStoreWriter(path, dim=dim, dtype=dt, ell_width=None,
                            has_offsets=offsets is not None,
                            has_weights=weights is not None,
                            chunk_rows=chunk_rows, num_shards=num_shards,
                            source=source)
        w.append(labels, x=np.asarray(x, dt), offsets=offsets,
                 weights=weights)
    else:
        if indptr is None or cols is None or vals is None or dim is None:
            raise ValueError("sparse store needs indptr/cols/vals/dim")
        indptr = np.asarray(indptr, np.int64)
        widest = int(np.diff(indptr).max(initial=0))
        k = widest if ell_width is None else int(ell_width)
        w = DataStoreWriter(path, dim=int(dim), dtype=dt, ell_width=k,
                            has_offsets=offsets is not None,
                            has_weights=weights is not None,
                            chunk_rows=chunk_rows, num_shards=num_shards,
                            source=source)
        w.append_csr(labels, indptr - indptr[0], cols, vals,
                     offsets=offsets, weights=weights)
    w.mark_unit("arrays")
    return w.finalize()


# ===========================================================================
# Converters: LibSVM text and Avro feature bags -> store
# ===========================================================================

def _parse_libsvm_file(path: str, zero_based: bool):
    """Raw columnar parse of ONE LibSVM file via the native tokenizer,
    python fallback otherwise — the same ladder ``read_libsvm`` uses, so
    converted bytes match the in-RAM ingest bit for bit."""
    from photon_tpu.data import ingest
    try:
        parsed = ingest._parse_libsvm_native([path], zero_based)
    except (MemoryError, ValueError):
        raise
    except Exception:  # noqa: BLE001 — optional fast path, never fatal
        parsed = None
    if parsed is None:
        parsed = ingest._parse_libsvm_python([path], zero_based)
    return parsed


def _libsvm_units(path: str) -> List[str]:
    if os.path.isdir(path):
        return sorted(os.path.join(path, f) for f in os.listdir(path)
                      if not f.startswith("."))
    return [path]


def _libsvm_scan(files: Sequence[str], zero_based: bool) -> dict:
    """Global pass-0 facts the per-file conversion needs to reproduce
    ``read_libsvm`` on the concatenated input: feature dimension, widest
    row, and whether the label alphabet is the {-1,+1} convention (the
    remap decision is global — one file of {0,1} labels flips it for the
    whole dataset, exactly as the in-RAM reader would see)."""
    dim = 0
    max_nnz = 0
    remap = True
    n_rows = 0
    for fp in files:
        labels, indptr, cols, vals = _parse_libsvm_file(fp, zero_based)
        if len(cols) and int(cols.min()) < 0:
            raise ValueError("negative feature index (1-based data parsed "
                             "with zero_based=True?)")
        if len(cols):
            dim = max(dim, int(cols.max()) + 1)
        if len(labels):
            max_nnz = max(max_nnz, int(np.diff(indptr).max()))
        remap = remap and set(np.unique(labels)) <= {-1.0, 1.0}
        n_rows += len(labels)
    return {"dim": dim, "max_nnz": max_nnz,
            "remap_pm1": bool(remap and n_rows > 0), "n_rows": n_rows}


def convert_libsvm(input_path: str, out_path: str, *,
                   dim: Optional[int] = None, add_intercept: bool = True,
                   zero_based: bool = False, dtype=np.float64,
                   chunk_rows: int = 8192, num_shards: int = 1,
                   max_nnz: Optional[int] = None,
                   resume: bool = False) -> dict:
    """One-time LibSVM text -> chunk store conversion, one resumable
    unit per input file. The result streams bitwise identically to
    ``chunk_source(read_libsvm(input_path, ...), dtype=...)``: same file
    order, same global label remap / intercept / dimension decisions,
    same ELL assembly. Peak host memory is one parsed file, never the
    dataset. Returns the manifest payload."""
    files = _libsvm_units(input_path)
    if not files:
        raise FileNotFoundError(f"no LibSVM files under {input_path!r}")
    scan = _libsvm_scan(files, zero_based)
    d = int(dim) if dim is not None else scan["dim"]
    k = int(max_nnz) if max_nnz is not None else scan["max_nnz"]
    if add_intercept:
        k += 1
    if scan["n_rows"] == 0:
        k = max(k, 1 if add_intercept else 0)
    writer = DataStoreWriter(
        out_path, dim=d + 1 if add_intercept else d, dtype=dtype,
        ell_width=k, chunk_rows=chunk_rows, num_shards=num_shards,
        resume=resume,
        source={"kind": "libsvm", "input": os.path.abspath(input_path),
                "files": [os.path.basename(f) for f in files],
                "add_intercept": bool(add_intercept),
                "zero_based": bool(zero_based), "scan": scan})
    try:
        done = set(writer.units_done)
        for fp in files:
            unit = os.path.basename(fp)
            if unit in done:
                continue
            labels, indptr, cols, vals = _parse_libsvm_file(fp, zero_based)
            y = labels
            if scan["remap_pm1"]:
                y = (y + 1.0) / 2.0
            if add_intercept:
                # same vectorized append read_libsvm uses: a constant-1
                # slot at index d on every row (row-local => per-file
                # application equals the global one)
                n = len(y)
                cols = np.insert(cols, indptr[1:], d).astype(np.int32)
                vals = np.insert(vals, indptr[1:], 1.0)
                indptr = indptr + np.arange(n + 1, dtype=np.int64)
            writer.append_csr(np.asarray(y, writer.dtype), indptr, cols,
                              vals)
            writer.mark_unit(unit)
        manifest = writer.finalize()
    except BaseException:
        writer.abort()
        raise
    return manifest


def convert_avro(input_dirs: Sequence[str], out_path: str, *,
                 feature_bags: Sequence[str] = ("features",),
                 intercept: bool = True, dtype=np.float64,
                 chunk_rows: int = 8192, num_shards: int = 1,
                 max_nnz: Optional[int] = None,
                 resume: bool = False) -> dict:
    """Avro feature-bag records -> chunk store, through the vectorized
    ``io/fast_ingest.read_frame_with_fallback`` ladder (native columnar
    decode when available, generic ``io/avro.py`` otherwise — identical
    output either way). One resumable unit per input directory; the
    feature index map is built over ALL inputs first so per-dir batches
    share one index space. Returns the manifest payload."""
    from photon_tpu.io.data_io import FeatureShardConfiguration
    from photon_tpu.io.fast_ingest import read_frame_with_fallback

    input_dirs = list(input_dirs)
    if not input_dirs:
        raise FileNotFoundError("no Avro input directories")
    cfg = {"store": FeatureShardConfiguration.of(*feature_bags,
                                                 intercept=intercept)}
    # pass 0: the full frame fixes the global facts every per-dir unit
    # must share — one feature index space, the widest row (the static
    # ELL width), and which optional per-row columns exist
    full, maps = read_frame_with_fallback(input_dirs, cfg)
    d = maps["store"].feature_dimension
    k = int(max_nnz) if max_nnz is not None \
        else max(1, full.feature_shards["store"].max_nnz())
    writer = DataStoreWriter(
        out_path, dim=d, dtype=dtype, ell_width=k,
        has_offsets=full.offsets is not None,
        has_weights=full.weights is not None,
        chunk_rows=chunk_rows, num_shards=num_shards, resume=resume,
        source={"kind": "avro",
                "inputs": [os.path.abspath(p) for p in input_dirs],
                "feature_bags": list(feature_bags),
                "intercept": bool(intercept)})
    try:
        for i, indir in enumerate(input_dirs):
            if str(i) in writer.units_done:
                continue
            frame = full if len(input_dirs) == 1 else \
                read_frame_with_fallback([indir], cfg,
                                         index_maps=maps)[0]
            indptr, ccols, cvals = _csr_arrays(
                frame.feature_shards["store"].rows)
            dt = writer.dtype
            writer.append_csr(
                np.asarray(frame.response, dt), indptr, ccols, cvals,
                offsets=None if frame.offsets is None
                else np.asarray(frame.offsets, dt),
                weights=None if frame.weights is None
                else np.asarray(frame.weights, dt))
            writer.mark_unit(str(i))
        manifest = writer.finalize()
    except BaseException:
        writer.abort()
        raise
    return manifest


def _csr_arrays(rows) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """FeatureShard rows (CsrRows, list-form SparseRows, or dense) ->
    (indptr, cols, vals) CSR arrays."""
    from photon_tpu.game.dataset import CsrRows
    if isinstance(rows, np.ndarray):
        rows = CsrRows.from_dense(rows)
    if isinstance(rows, CsrRows):
        return rows.indptr, rows.cols, rows.vals
    indptr = np.zeros(len(rows) + 1, np.int64)
    cols_l, vals_l = [], []
    for i, (ci, vi) in enumerate(rows):
        indptr[i + 1] = indptr[i] + len(ci)
        cols_l.append(np.asarray(ci, np.int64))
        vals_l.append(np.asarray(vi, np.float64))
    cols = (np.concatenate(cols_l) if cols_l
            else np.zeros(0, np.int64))
    vals = (np.concatenate(vals_l) if vals_l
            else np.zeros(0, np.float64))
    return indptr, cols, vals
