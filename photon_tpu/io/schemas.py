"""Avro data contracts (layer 0).

Wire-compatible re-declarations of the reference's eight schemas
(reference: photon-avro-schemas/src/main/avro/*.avsc). Field names, types
and order are the contract — a model or dataset written here reads back in
the reference and vice versa. Doc strings are dropped (they don't affect
the encoding).
"""

NS = "com.linkedin.photon.avro.generated"

FEATURE_AVRO = {
    "type": "record", "name": "FeatureAvro", "namespace": NS,
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": "string"},
        {"name": "value", "type": "double"},
    ],
}

NAME_TERM_VALUE_AVRO = {
    "type": "record", "name": "NameTermValueAvro", "namespace": NS,
    "fields": [
        {"name": "name", "type": "string"},
        {"name": "term", "type": "string"},
        {"name": "value", "type": "double"},
    ],
}

TRAINING_EXAMPLE_AVRO = {
    "type": "record", "name": "TrainingExampleAvro", "namespace": NS,
    "fields": [
        {"name": "uid", "type": ["null", "string"], "default": None},
        {"name": "label", "type": "double"},
        {"name": "features", "type": {"type": "array", "items": FEATURE_AVRO}},
        {"name": "metadataMap",
         "type": ["null", {"type": "map", "values": "string"}], "default": None},
        {"name": "weight", "type": ["null", "double"], "default": None},
        {"name": "offset", "type": ["null", "double"], "default": None},
    ],
}

BAYESIAN_LINEAR_MODEL_AVRO = {
    "type": "record", "name": "BayesianLinearModelAvro", "namespace": NS,
    "fields": [
        {"name": "modelId", "type": "string"},
        {"name": "modelClass", "type": ["null", "string"], "default": None},
        {"name": "means", "type": {"type": "array", "items": NAME_TERM_VALUE_AVRO}},
        {"name": "variances",
         "type": ["null", {"type": "array", "items": "NameTermValueAvro"}],
         "default": None},
        {"name": "lossFunction", "type": ["null", "string"], "default": None},
    ],
}

SCORING_RESULT_AVRO = {
    "type": "record", "name": "ScoringResultAvro", "namespace": NS,
    "fields": [
        {"name": "uid", "type": ["null", "string"], "default": None},
        {"name": "label", "type": ["null", "double"], "default": None},
        {"name": "modelId", "type": "string"},
        {"name": "predictionScore", "type": "double"},
        {"name": "weight", "type": ["null", "double"], "default": None},
        {"name": "metadataMap",
         "type": ["null", {"type": "map", "values": "string"}], "default": None},
    ],
}

RESPONSE_PREDICTION_AVRO = {
    "type": "record", "name": "SimplifiedResponsePrediction", "namespace": NS,
    "fields": [
        {"name": "response", "type": "double"},
        {"name": "features", "type": {"type": "array", "items": FEATURE_AVRO}},
        {"name": "weight", "type": "double", "default": 1.0},
        {"name": "offset", "type": "double", "default": 0.0},
    ],
}

LATENT_FACTOR_AVRO = {
    "type": "record", "name": "LatentFactorAvro", "namespace": NS,
    "fields": [
        {"name": "effectId", "type": "string"},
        {"name": "latentFactor", "type": {"type": "array", "items": "double"}},
    ],
}

FEATURE_SUMMARIZATION_RESULT_AVRO = {
    "type": "record", "name": "FeatureSummarizationResultAvro", "namespace": NS,
    "fields": [
        {"name": "featureName", "type": "string"},
        {"name": "featureTerm", "type": "string"},
        {"name": "metrics", "type": {"type": "map", "values": "double"}},
    ],
}
