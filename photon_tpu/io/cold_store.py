"""Host-RAM cold tier for per-entity coefficient tables.

One columnar file per random-effect coordinate, holding ALL entity rows
sorted by entity id — the photon_tpu analog of Photon ML's PalDB
off-heap coefficient index. Serving keeps only a fixed-budget hot set of
rows in device HBM (serving/coeff_store.py); everything else lives here,
loaded zero-copy via ``np.memmap`` so a 10M-entity table costs page
cache, not process heap, and training's blocked iteration mode streams
entity blocks through the per-entity solve without ever materializing
the full table on device.

On-disk layout (``photon_tpu.coldstore.v1``)::

    magic      8 bytes   b"PHOTCOLD"
    header     u32 little-endian JSON length, then the JSON header
    sections   each 64-byte aligned, offsets recorded in the header:
        coef   float32 [num_entities, slot_width]   dense coefficients
        proj   int32   [num_entities, slot_width]   global col per local
                                                    slot, -1 padded
        ids    entity-id table: fixed-width byte rows (id_width > 0) or
               u64 offsets[num_entities + 1] + utf-8 blob (id_width == 0)
    footer     u32 crc32 of every preceding byte

Rows are sorted by utf-8-encoded entity id, so lookup is one binary
search over the mmapped id table — no host dict of N entries is ever
built. The crc footer makes torn or bit-flipped files refusable at swap
validation (``verify()``); the chaos harness's ``corrupt_cold_store``
drives that gate.

The updatable layout (``photon_tpu.coldstore.v2``) is the nearline
delta-publish substrate: sections are sized to a reserved ``capacity``
(rows) and ``id_blob_len`` (id bytes) so row updates and entity appends
rewrite only the touched bytes in place, and the single whole-file crc
footer becomes a crc *table* — one entry per ``rows_per_chunk`` rows of
the coef and proj sections plus one each for the id region, the sort
region, and the header — so a delta publish recomputes only the crcs of
the chunks it touched. Storage rows are append-stable (an entity's row
index never changes once assigned — the serving hot tier caches cold row
numbers), and id-ordered lookup goes through a sort-indirection section
instead of physically sorted rows. Every byte of a v2 file is either
covered by a crc entry or is itself part of the crc table, so a torn
in-place update (killed between the data write and the crc/header
rewrite) is refusable by ``verify()`` exactly like a torn v1 write.
``apply_cold_store_delta`` / ``rollback_cold_store_delta`` are the
nearline publisher's commit and bitwise-undo primitives;
``upgrade_cold_store`` rewrites a v1 (or full v2) file with fresh
reserve space.
"""

from __future__ import annotations

import json
import os
import time
import zlib
from typing import Iterator, List, Optional, Sequence, Tuple, Union

import numpy as np

from photon_tpu.resilience import chaos as _chaos

MAGIC = b"PHOTCOLD"
SCHEMA = "photon_tpu.coldstore.v1"
SCHEMA_V2 = "photon_tpu.coldstore.v2"
# Bayesian column (PR 20): v3 = v1 + an optional [E, slot_width] f32
# posterior-variance section (between proj and ids), v4 = v2 + the same
# section with its OWN per-chunk crc entries. Files without variances
# keep writing v1/v2 BYTE-IDENTICAL to before — the variance column is
# strictly additive, and every v1/v2 reader path is unchanged.
SCHEMA_V3 = "photon_tpu.coldstore.v3"
SCHEMA_V4 = "photon_tpu.coldstore.v4"
COLD_STORE_DIR = "cold-store"
COLD_STORE_SUFFIX = ".coldstore"
_ALIGN = 64
_SENTINEL = 10 ** 14  # 15-digit placeholder reserving header field width


class ColdStoreCorruptError(RuntimeError):
    """A cold-store file failed magic/header/crc validation."""

    def __init__(self, path: str, detail: str):
        self.path = path
        super().__init__(f"corrupt cold store at {path}: {detail}")


class ColdStoreNotUpdatable(RuntimeError):
    """In-place delta applied to a file without reserved sections (v1).
    Callers upgrade first via ``upgrade_cold_store``."""

    def __init__(self, path: str, schema):
        self.path = path
        super().__init__(
            f"cold store at {path} (schema {schema!r}) is not updatable; "
            f"run upgrade_cold_store() first")


class ColdStoreCapacityError(RuntimeError):
    """A delta would overflow the file's reserved row or id-blob space.
    Typed so the publisher can turn it into a gate failure (or an
    automatic ``upgrade_cold_store``) instead of a torn write."""

    def __init__(self, path: str, detail: str):
        self.path = path
        super().__init__(f"cold store at {path} out of capacity: {detail}")


def cold_store_path(model_dir: str, coordinate_id: str) -> str:
    """Canonical location of a coordinate's cold-tier file in a model
    directory, alongside the reference per-coordinate Avro layout."""
    return os.path.join(model_dir, COLD_STORE_DIR,
                        coordinate_id + COLD_STORE_SUFFIX)


def _encode_ids(entity_ids) -> Tuple[np.ndarray, int]:
    """(bytes array [E] dtype S*, fixed width or 0). Ids are compared and
    sorted as utf-8 bytes — the same order ``ColdStore.entity_row``'s
    binary search uses."""
    arr = np.asarray(entity_ids)
    if arr.dtype.kind == "U":
        arr = np.char.encode(arr, "utf-8")
    elif arr.dtype.kind != "S":
        arr = np.asarray([str(e).encode("utf-8") for e in entity_ids],
                         dtype=bytes)
    lengths = np.char.str_len(arr)
    if arr.size and lengths.min() == lengths.max() == arr.dtype.itemsize:
        return arr, int(arr.dtype.itemsize)
    return arr, 0


def _pad(f, crc: int, pos: int) -> Tuple[int, int]:
    gap = (-pos) % _ALIGN
    if gap:
        pad = b"\x00" * gap
        f.write(pad)
        crc = zlib.crc32(pad, crc)
    return crc, pos + gap


def _aligned(pos: int) -> int:
    return pos + ((-pos) % _ALIGN)


def normalize_slot_rows(coefficients: np.ndarray, projection: np.ndarray,
                        variances: Optional[np.ndarray] = None):
    """Normalize coefficient/projection rows to the canonical on-disk and
    serving form: valid slots sorted ascending by global column, -1 pads
    last. The serving hot-tier slot replay (searchsorted over the valid
    prefix) and the bitwise delta-parity gates both depend on every row —
    whether written at model save or row-published nearline — being in
    exactly this layout. Rows already normalized pass through unchanged
    (stable sort). When ``variances`` is given it rides the SAME slot
    permutation (a variance belongs to its coefficient) and a 3-tuple is
    returned."""
    coefficients = np.asarray(coefficients, dtype=np.float32)
    projection = np.asarray(projection, dtype=np.int32)
    if variances is not None:
        variances = np.asarray(variances, dtype=np.float32)
    if coefficients.size and coefficients.shape[-1] > 1:
        key = np.where(projection < 0, np.iinfo(np.int32).max, projection)
        slot_order = np.argsort(key, axis=-1, kind="stable")
        projection = np.take_along_axis(projection, slot_order, axis=-1)
        coefficients = np.take_along_axis(coefficients, slot_order, axis=-1)
        if variances is not None:
            variances = np.take_along_axis(variances, slot_order, axis=-1)
    if variances is not None:
        return coefficients, projection, variances
    return coefficients, projection


def write_cold_store(
    path: str,
    coordinate_id: str,
    random_effect_type: str,
    feature_shard_id: str,
    coefficients: np.ndarray,
    projection: np.ndarray,
    entity_ids: Union[Sequence[str], np.ndarray],
    chunk_rows: int = 262144,
    *,
    updatable: bool = False,
    capacity: Optional[int] = None,
    id_blob_cap: Optional[int] = None,
    rows_per_chunk: int = 4096,
    variances: Optional[np.ndarray] = None,
) -> str:
    """Write one coordinate's cold-tier file; returns its path.

    Rows are re-sorted by entity id internally, so callers pass arrays in
    any order. Streams in ``chunk_rows`` chunks (a 10M-entity table never
    needs a second full copy in RAM beyond the sort permutation) and
    publishes atomically (tmp + fsync + rename).

    ``updatable=True`` writes the v2 layout with ``capacity`` reserved
    rows and ``id_blob_cap`` reserved id bytes (defaults: ~25% headroom)
    so the nearline publisher can row-update and entity-append in place;
    the crc footer becomes a per-``rows_per_chunk`` chunk table.

    ``variances`` (optional ``[E, slot_width]`` f32, same slot layout as
    ``coefficients``) adds the Bayesian posterior-variance column —
    schema bumps to v3 (plain) / v4 (updatable). Omitting it writes
    v1/v2 files byte-identical to pre-variance builds.
    """
    coefficients = np.asarray(coefficients, dtype=np.float32)
    projection = np.asarray(projection, dtype=np.int32)
    ids, id_width = _encode_ids(entity_ids)
    num_entities, slot_width = coefficients.shape
    if projection.shape != coefficients.shape:
        raise ValueError(f"projection shape {projection.shape} != "
                         f"coefficients shape {coefficients.shape}")
    if ids.shape != (num_entities,):
        raise ValueError(f"{ids.shape[0]} entity ids for "
                         f"{num_entities} rows")
    if variances is not None:
        variances = np.asarray(variances, dtype=np.float32)
        if variances.shape != coefficients.shape:
            raise ValueError(f"variances shape {variances.shape} != "
                             f"coefficients shape {coefficients.shape}")

    # normalize every row to (valid slots sorted ascending by global
    # column, -1 pads last) — the invariant the serving hot-tier slot
    # replay (searchsorted over the valid prefix) depends on; rows
    # already in that form pass through unchanged (stable sort)
    if variances is None:
        coefficients, projection = normalize_slot_rows(coefficients,
                                                       projection)
    else:
        coefficients, projection, variances = normalize_slot_rows(
            coefficients, projection, variances)

    order = np.argsort(ids, kind="stable")
    if updatable:
        return _write_cold_store_v2(
            path, coordinate_id, random_effect_type, feature_shard_id,
            coefficients, projection, ids, order,
            capacity=capacity, id_blob_cap=id_blob_cap,
            rows_per_chunk=rows_per_chunk, chunk_rows=chunk_rows,
            variances=variances)
    ids = ids[order]

    header = {
        "schema": SCHEMA if variances is None else SCHEMA_V3,
        "coordinate_id": coordinate_id,
        "random_effect_type": random_effect_type,
        "feature_shard_id": feature_shard_id,
        "num_entities": int(num_entities),
        "slot_width": int(slot_width),
        "coef_dtype": "<f4",
        "proj_dtype": "<i4",
        "id_width": id_width,
    }
    # one-pass header layout: reserve maximal-width offset fields (15
    # digits covers any sub-petabyte file), measure the serialized
    # length, then fill real offsets and pad back to the reserved length
    # — the header's byte length never depends on the offset values
    _SENTINEL = 10 ** 14
    sentinel_keys = ["coef_off", "proj_off", "id_offsets_off", "id_blob_off",
                     "id_blob_len"]
    if variances is not None:
        # the var_off key only exists in v3 headers, so v1 headers (and
        # therefore whole v1 files) stay byte-identical to pre-variance
        # builds
        sentinel_keys.append("var_off")
    for key in sentinel_keys:
        header[key] = _SENTINEL
    reserved = len(json.dumps(header).encode())
    base = len(MAGIC) + 4 + reserved

    def aligned(pos: int) -> int:
        return pos + ((-pos) % _ALIGN)

    coef_off = aligned(base)
    proj_off = aligned(coef_off + num_entities * slot_width * 4)
    after_proj = aligned(proj_off + num_entities * slot_width * 4)
    if variances is not None:
        var_off = after_proj
        id_offsets_off = aligned(var_off + num_entities * slot_width * 4)
    else:
        var_off = 0
        id_offsets_off = after_proj
    if id_width:
        id_blob_off = id_offsets_off
        id_offsets_off = 0
        id_blob_len = num_entities * id_width
    else:
        id_blob_off = aligned(id_offsets_off + (num_entities + 1) * 8)
        id_blob_len = int(np.char.str_len(ids).sum()) if num_entities else 0
    header.update(coef_off=coef_off, proj_off=proj_off,
                  id_offsets_off=id_offsets_off, id_blob_off=id_blob_off,
                  id_blob_len=id_blob_len)
    if variances is not None:
        header.update(var_off=var_off)
    header_bytes = json.dumps(header).encode()
    header_bytes += b" " * (reserved - len(header_bytes))

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    crc = 0
    with open(tmp, "wb") as f:
        pos = 0

        def put(data: bytes) -> None:
            nonlocal crc, pos
            f.write(data)
            crc = zlib.crc32(data, crc)
            pos += len(data)

        put(MAGIC)
        put(len(header_bytes).to_bytes(4, "little"))
        put(header_bytes)
        crc, pos = _pad(f, crc, pos)
        assert pos == header["coef_off"], (pos, header["coef_off"])
        for lo in range(0, num_entities, chunk_rows):
            sel = order[lo:lo + chunk_rows]
            put(np.ascontiguousarray(coefficients[sel]).tobytes())
        crc, pos = _pad(f, crc, pos)
        for lo in range(0, num_entities, chunk_rows):
            sel = order[lo:lo + chunk_rows]
            put(np.ascontiguousarray(projection[sel]).tobytes())
        crc, pos = _pad(f, crc, pos)
        if variances is not None:
            assert pos == header["var_off"], (pos, header["var_off"])
            for lo in range(0, num_entities, chunk_rows):
                sel = order[lo:lo + chunk_rows]
                put(np.ascontiguousarray(variances[sel]).tobytes())
            crc, pos = _pad(f, crc, pos)
        if id_width:
            put(ids.tobytes())
        else:
            lengths = np.char.str_len(ids).astype(np.uint64)
            offsets = np.zeros(num_entities + 1, dtype=np.uint64)
            np.cumsum(lengths, out=offsets[1:])
            put(offsets.tobytes())
            crc, pos = _pad(f, crc, pos)
            for lo in range(0, num_entities, chunk_rows):
                put(b"".join(bytes(s) for s in ids[lo:lo + chunk_rows]))
        f.write(crc.to_bytes(4, "little"))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


# -- v2 updatable layout ------------------------------------------------------


def _read_header(path: str) -> Tuple[dict, int]:
    """(header dict, header byte length) — shared by the reader and the
    in-place delta functions."""
    with open(path, "rb") as f:
        magic = f.read(len(MAGIC))
        if magic != MAGIC:
            raise ColdStoreCorruptError(path, f"bad magic {magic!r}")
        hlen = int.from_bytes(f.read(4), "little")
        if hlen <= 0 or hlen > 1 << 20:
            raise ColdStoreCorruptError(path, f"bad header length {hlen}")
        try:
            h = json.loads(f.read(hlen))
        except (ValueError, UnicodeDecodeError) as e:
            raise ColdStoreCorruptError(path, f"unparseable header: {e}")
    return h, hlen


def _rewrite_header(f, h: dict, hlen: int) -> None:
    """Re-serialize the header in place, padded to its reserved length.
    Safe because every mutable numeric field was measured at creation
    with a sentinel wider than any legal value."""
    hb = json.dumps(h).encode()
    if len(hb) > hlen:
        raise ColdStoreCorruptError(
            f.name, f"header grew past reserved length ({len(hb)} > {hlen})")
    hb += b" " * (hlen - len(hb))
    f.seek(len(MAGIC) + 4)
    f.write(hb)


def _region_crc(f, lo: int, hi: int, buf: int = 4 << 20) -> int:
    f.seek(lo)
    crc, remaining = 0, hi - lo
    while remaining > 0:
        data = f.read(min(buf, remaining))
        if not data:
            raise ColdStoreCorruptError(f.name, "short read during crc scan")
        crc = zlib.crc32(data, crc)
        remaining -= len(data)
    return crc


def _v2_sections(h: dict) -> int:
    """Number of chunked data sections in the crc table: 3 when the file
    carries the v4 variance column, else 2."""
    return 3 if h.get("var_off") else 2


def _v2_chunk_bounds(h: dict, section: str) -> List[Tuple[int, int]]:
    """Byte ranges of each crc chunk of the coef/proj/var section. The
    last chunk extends to the next section offset so alignment padding is
    always covered by exactly one crc entry."""
    if section == "coef":
        off, end = h["coef_off"], h["proj_off"]
    elif section == "proj":
        off = h["proj_off"]
        end = h.get("var_off") or h["id_offsets_off"]
    elif section == "var":
        off, end = h["var_off"], h["id_offsets_off"]
    else:
        raise ValueError(f"unknown section {section!r}")
    csz = h["rows_per_chunk"] * h["slot_width"] * 4
    n = h["n_chunks"]
    return [(off + ci * csz, end if ci == n - 1 else min(off + (ci + 1) * csz, end))
            for ci in range(n)]


def _v2_recompute_crcs(f, h: dict, *, coef_chunks=None, proj_chunks=None,
                       var_chunks=None, ids: bool = True, sort: bool = True,
                       header: bool = True) -> None:
    """Recompute and write the selected crc-table entries by reading the
    current file bytes back. ``coef_chunks``/``proj_chunks``/``var_chunks``
    are chunk indices (None = all). Table layout: [coef chunks..., proj
    chunks..., var chunks... (v4 only), ids region, sort region, header
    region]."""
    n = h["n_chunks"]
    s = _v2_sections(h)
    coef_bounds = _v2_chunk_bounds(h, "coef")
    proj_bounds = _v2_chunk_bounds(h, "proj")
    entries: List[Tuple[int, int, int]] = []  # (table idx, lo, hi)
    for ci in sorted(set(range(n) if coef_chunks is None else coef_chunks)):
        entries.append((ci,) + coef_bounds[ci])
    for ci in sorted(set(range(n) if proj_chunks is None else proj_chunks)):
        entries.append((n + ci,) + proj_bounds[ci])
    if s == 3:
        var_bounds = _v2_chunk_bounds(h, "var")
        for ci in sorted(set(range(n) if var_chunks is None
                             else var_chunks)):
            entries.append((2 * n + ci,) + var_bounds[ci])
    if ids:
        entries.append((s * n, h["id_offsets_off"], h["sort_off"]))
    if sort:
        entries.append((s * n + 1, h["sort_off"], h["crc_off"]))
    if header:
        entries.append((s * n + 2, 0, h["coef_off"]))
    for idx, lo, hi in entries:
        crc = _region_crc(f, lo, hi)
        f.seek(h["crc_off"] + 4 * idx)
        f.write(crc.to_bytes(4, "little"))


def _write_cold_store_v2(
    path: str,
    coordinate_id: str,
    random_effect_type: str,
    feature_shard_id: str,
    coefficients: np.ndarray,
    projection: np.ndarray,
    ids: np.ndarray,
    order: np.ndarray,
    *,
    capacity: Optional[int],
    id_blob_cap: Optional[int],
    rows_per_chunk: int,
    chunk_rows: int = 262144,
    variances: Optional[np.ndarray] = None,
) -> str:
    """Write the updatable layout. ``order`` maps storage row -> input
    index; ``write_cold_store`` passes an id-sort (fresh files start
    physically sorted, making the sort indirection the identity) while
    ``upgrade_cold_store`` passes arange to keep every existing storage
    row number stable — the serving hot tier caches cold row indices, so
    an upgrade must never renumber rows. ``variances`` adds the v4
    posterior-variance section (capacity-sized, its own crc chunks)."""
    num_entities, slot_width = coefficients.shape
    lengths = np.char.str_len(ids).astype(np.int64) if num_entities else \
        np.zeros(0, dtype=np.int64)
    blob_used = int(lengths[order].sum()) if num_entities else 0
    if capacity is None:
        capacity = num_entities + max(16, num_entities // 4)
    capacity = max(int(capacity), num_entities, 1)
    if id_blob_cap is None:
        id_blob_cap = blob_used + max(256, blob_used // 4)
    id_blob_cap = max(int(id_blob_cap), blob_used, 1)
    rows_per_chunk = max(1, int(rows_per_chunk))
    n_chunks = -(-capacity // rows_per_chunk)

    header = {
        "schema": SCHEMA_V2 if variances is None else SCHEMA_V4,
        "coordinate_id": coordinate_id,
        "random_effect_type": random_effect_type,
        "feature_shard_id": feature_shard_id,
        "slot_width": int(slot_width),
        "coef_dtype": "<f4",
        "proj_dtype": "<i4",
        "id_width": 0,
        "capacity": int(capacity),
        "rows_per_chunk": rows_per_chunk,
        "n_chunks": int(n_chunks),
    }
    # same one-pass trick as v1, extended to the fields a delta mutates
    # (num_entities, id_blob_used): measure with sentinels, fill real
    # values, pad — so an in-place header rewrite can never overflow
    sentinel_keys = ["num_entities", "id_blob_used", "coef_off", "proj_off",
                     "id_offsets_off", "id_blob_off", "id_blob_len",
                     "sort_off", "crc_off"]
    if variances is not None:
        sentinel_keys.append("var_off")
    for key in sentinel_keys:
        header[key] = _SENTINEL
    reserved = len(json.dumps(header).encode())
    base = len(MAGIC) + 4 + reserved
    coef_off = _aligned(base)
    proj_off = _aligned(coef_off + capacity * slot_width * 4)
    after_proj = _aligned(proj_off + capacity * slot_width * 4)
    if variances is not None:
        var_off = after_proj
        id_offsets_off = _aligned(var_off + capacity * slot_width * 4)
    else:
        var_off = 0
        id_offsets_off = after_proj
    id_blob_off = _aligned(id_offsets_off + (capacity + 1) * 8)
    sort_off = _aligned(id_blob_off + id_blob_cap)
    crc_off = _aligned(sort_off + capacity * 8)
    n_sections = 2 if variances is None else 3
    file_end = crc_off + 4 * (n_sections * n_chunks + 3)
    header.update(num_entities=int(num_entities), id_blob_used=blob_used,
                  coef_off=coef_off, proj_off=proj_off,
                  id_offsets_off=id_offsets_off, id_blob_off=id_blob_off,
                  id_blob_len=int(id_blob_cap), sort_off=sort_off,
                  crc_off=crc_off)
    if variances is not None:
        header.update(var_off=var_off)
    header_bytes = json.dumps(header).encode()
    header_bytes += b" " * (reserved - len(header_bytes))

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w+b") as f:
        # reserve the full extent up front; untouched reserve bytes read
        # back as zeros (sparse where the filesystem supports it)
        f.truncate(file_end)
        f.seek(0)
        f.write(MAGIC)
        f.write(len(header_bytes).to_bytes(4, "little"))
        f.write(header_bytes)
        f.seek(coef_off)
        for lo in range(0, num_entities, chunk_rows):
            sel = order[lo:lo + chunk_rows]
            f.write(np.ascontiguousarray(coefficients[sel]).tobytes())
        f.seek(proj_off)
        for lo in range(0, num_entities, chunk_rows):
            sel = order[lo:lo + chunk_rows]
            f.write(np.ascontiguousarray(projection[sel]).tobytes())
        if variances is not None:
            f.seek(var_off)
            for lo in range(0, num_entities, chunk_rows):
                sel = order[lo:lo + chunk_rows]
                f.write(np.ascontiguousarray(variances[sel]).tobytes())
        offsets = np.full(capacity + 1, blob_used, dtype=np.uint64)
        offsets[0] = 0
        if num_entities:
            np.cumsum(lengths[order].astype(np.uint64),
                      out=offsets[1:num_entities + 1])
        f.seek(id_offsets_off)
        f.write(offsets.tobytes())
        f.seek(id_blob_off)
        for lo in range(0, num_entities, chunk_rows):
            f.write(b"".join(bytes(s) for s in ids[order[lo:lo + chunk_rows]]))
        sort = np.full(capacity, -1, dtype=np.int64)
        if num_entities:
            sort[:num_entities] = np.argsort(ids[order], kind="stable")
        f.seek(sort_off)
        f.write(sort.tobytes())
        _v2_recompute_crcs(f, header)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


class ColdStore:
    """Zero-copy reader over one coordinate's cold-tier file.

    ``coef``/``proj`` are read-only ``np.memmap`` views — opening a
    10M-entity store touches only the header. ``entity_row`` binary
    searches the mmapped sorted-id table; ``read_rows`` fancy-indexes the
    requested rows into a fresh host array (the unit the transfer thread
    uploads). ``verify()`` streams the whole file against the crc footer
    — swap validation's cold-pair gate.
    """

    def __init__(self, path: str, *, verify: bool = False):
        self.path = path
        with open(path, "rb") as f:
            magic = f.read(len(MAGIC))
            if magic != MAGIC:
                raise ColdStoreCorruptError(path, f"bad magic {magic!r}")
            hlen = int.from_bytes(f.read(4), "little")
            if hlen <= 0 or hlen > 1 << 20:
                raise ColdStoreCorruptError(path, f"bad header length {hlen}")
            try:
                h = json.loads(f.read(hlen))
            except (ValueError, UnicodeDecodeError) as e:
                raise ColdStoreCorruptError(path, f"unparseable header: {e}")
        if h.get("schema") not in (SCHEMA, SCHEMA_V2, SCHEMA_V3, SCHEMA_V4):
            raise ColdStoreCorruptError(path, f"schema {h.get('schema')!r}")
        self.updatable: bool = h["schema"] in (SCHEMA_V2, SCHEMA_V4)
        self._h = dict(h)
        self.coordinate_id: str = h["coordinate_id"]
        self.random_effect_type: str = h["random_effect_type"]
        self.feature_shard_id: str = h["feature_shard_id"]
        self.num_entities: int = h["num_entities"]
        self.slot_width: int = h["slot_width"]
        self.capacity: int = h.get("capacity", self.num_entities)
        self._id_width: int = h["id_width"]
        self.file_bytes = os.path.getsize(path)
        if self.updatable and not (0 <= self.num_entities <= self.capacity):
            raise ColdStoreCorruptError(
                path, f"num_entities {self.num_entities} exceeds "
                      f"capacity {self.capacity}")
        shape = (self.num_entities, self.slot_width)
        self.coef = np.memmap(path, dtype=np.dtype(h["coef_dtype"]),
                              mode="r", offset=h["coef_off"], shape=shape)
        self.proj = np.memmap(path, dtype=np.dtype(h["proj_dtype"]),
                              mode="r", offset=h["proj_off"], shape=shape)
        if h.get("var_off"):
            # v3/v4 Bayesian posterior-variance column, same row/slot
            # layout as coef; None on v1/v2 files (mean-only models)
            self.var: Optional[np.memmap] = np.memmap(
                path, dtype=np.float32, mode="r", offset=h["var_off"],
                shape=shape)
        else:
            self.var = None
        if self._id_width:
            self._id_blob = np.memmap(
                path, dtype=np.uint8, mode="r", offset=h["id_blob_off"],
                shape=(self.num_entities * self._id_width,))
            self._id_offsets = None
        else:
            self._id_offsets = np.memmap(
                path, dtype=np.uint64, mode="r",
                offset=h["id_offsets_off"], shape=(self.num_entities + 1,))
            self._id_blob = np.memmap(
                path, dtype=np.uint8, mode="r", offset=h["id_blob_off"],
                shape=(h["id_blob_len"],))
        if self.updatable and self.num_entities:
            # id-order -> storage-row indirection; v2 rows are
            # append-stable, not physically sorted
            self._sort = np.memmap(path, dtype=np.int64, mode="r",
                                   offset=h["sort_off"],
                                   shape=(self.num_entities,))
        else:
            self._sort = None
        if verify:
            self.verify()

    # -- id table -----------------------------------------------------------

    def _row_at(self, pos: int) -> int:
        """Storage row of the ``pos``-th entity in ascending-id order."""
        return int(self._sort[pos]) if self._sort is not None else pos

    def _id_bytes(self, row: int) -> bytes:
        if self._id_width:
            lo = row * self._id_width
            return bytes(self._id_blob[lo:lo + self._id_width])
        lo = int(self._id_offsets[row])
        hi = int(self._id_offsets[row + 1])
        return bytes(self._id_blob[lo:hi])

    def entity_id(self, row: int) -> str:
        return self._id_bytes(row).decode("utf-8")

    def entity_ids_array(self) -> np.ndarray:
        """All entity ids as a numpy bytes array in STORAGE-row order
        (row ``i`` of ``coef``/``proj`` belongs to ``ids[i]``). Fixed-width
        id tables come back as a zero-copy ``S{width}`` view over the
        mmapped blob; variable-width tables materialize one bytes object
        per row. The fleet splitter's bulk-partition input."""
        if self._id_width:
            blob = np.asarray(
                self._id_blob[:self.num_entities * self._id_width])
            return blob.view(f"S{self._id_width}")
        return np.asarray([self._id_bytes(r)
                           for r in range(self.num_entities)], dtype=bytes)

    def entity_row(self, entity_id: str) -> Optional[int]:
        """Row index of ``entity_id`` (binary search over the sorted id
        table), or None when the entity is not in the model — the caller's
        typed ``UNKNOWN_ENTITY`` signal."""
        key = entity_id.encode("utf-8")
        if self._id_width and len(key) != self._id_width:
            return None
        lo, hi = 0, self.num_entities
        while lo < hi:
            mid = (lo + hi) // 2
            if self._id_bytes(self._row_at(mid)) < key:
                lo = mid + 1
            else:
                hi = mid
        if lo < self.num_entities:
            row = self._row_at(lo)
            if self._id_bytes(row) == key:
                return row
        return None

    # -- row access ---------------------------------------------------------

    def read_rows(self, rows: np.ndarray) -> np.ndarray:
        """Coefficient rows [len(rows), slot_width] as a fresh float32
        host array — the transfer thread's upload unit. Consults the
        chaos harness's cold-read-delay injector (this path is allowed to
        be slow; the scoring hot path must not wait on it)."""
        delay = _chaos.cold_read_delay()
        if delay > 0:
            time.sleep(delay)
        return np.asarray(self.coef[np.asarray(rows, dtype=np.int64)],
                          dtype=np.float32)

    def read_proj_rows(self, rows: np.ndarray) -> np.ndarray:
        return np.asarray(self.proj[np.asarray(rows, dtype=np.int64)],
                          dtype=np.int32)

    @property
    def has_variances(self) -> bool:
        return self.var is not None

    def read_var_rows(self, rows: np.ndarray) -> np.ndarray:
        """Posterior-variance rows [len(rows), slot_width] as a fresh
        float32 host array. Raises on mean-only (v1/v2) files — callers
        gate on ``has_variances``."""
        if self.var is None:
            raise ValueError(f"cold store at {self.path} has no variance "
                             f"column (schema {self._h.get('schema')!r})")
        return np.asarray(self.var[np.asarray(rows, dtype=np.int64)],
                          dtype=np.float32)

    def iter_blocks(self, block_rows: int,
                    start_row: int = 0
                    ) -> Iterator[Tuple[int, List[str], np.ndarray,
                                        np.ndarray]]:
        """Stream ``(start_row, entity_ids, coef_block, proj_block)`` in
        storage-row order (sorted-id for v1 files; append order for v2)
        — training's blocked iteration unit."""
        if block_rows <= 0:
            raise ValueError(f"block_rows must be positive, got {block_rows}")
        for lo in range(start_row, self.num_entities, block_rows):
            hi = min(lo + block_rows, self.num_entities)
            idx = np.arange(lo, hi)
            ids = [self.entity_id(r) for r in range(lo, hi)]
            yield lo, ids, self.read_rows(idx), self.read_proj_rows(idx)

    # -- integrity ----------------------------------------------------------

    def verify(self, chunk_bytes: int = 4 << 20) -> None:
        """Stream the file against its crc32 footer (v1) or per-section
        crc table (v2); raises ``ColdStoreCorruptError`` on mismatch or
        truncation. A v2 file torn mid-delta (data rewritten, crcs not
        yet) fails here — the publisher's torn-update refusal gate."""
        if self.updatable:
            self._verify_v2()
            return
        size = os.path.getsize(self.path)
        if size < len(MAGIC) + 4 + 4:
            raise ColdStoreCorruptError(self.path, f"truncated ({size}B)")
        crc = 0
        remaining = size - 4
        with open(self.path, "rb") as f:
            while remaining > 0:
                chunk = f.read(min(chunk_bytes, remaining))
                if not chunk:
                    raise ColdStoreCorruptError(
                        self.path, "short read during verify")
                crc = zlib.crc32(chunk, crc)
                remaining -= len(chunk)
            footer = int.from_bytes(f.read(4), "little")
        if crc != footer:
            raise ColdStoreCorruptError(
                self.path,
                f"crc mismatch: computed {crc:#010x}, footer {footer:#010x}")

    def _verify_v2(self) -> None:
        h = self._h
        n = h["n_chunks"]
        s = _v2_sections(h)
        expected_size = h["crc_off"] + 4 * (s * n + 3)
        size = os.path.getsize(self.path)
        if size != expected_size:
            raise ColdStoreCorruptError(
                self.path, f"size {size} != expected {expected_size}")
        if h["id_blob_used"] > h["id_blob_len"]:
            raise ColdStoreCorruptError(
                self.path, f"id_blob_used {h['id_blob_used']} exceeds "
                           f"reserve {h['id_blob_len']}")
        regions: List[Tuple[str, int, int, int]] = []
        for ci, (lo, hi) in enumerate(_v2_chunk_bounds(h, "coef")):
            regions.append((f"coef chunk {ci}", ci, lo, hi))
        for ci, (lo, hi) in enumerate(_v2_chunk_bounds(h, "proj")):
            regions.append((f"proj chunk {ci}", n + ci, lo, hi))
        if s == 3:
            for ci, (lo, hi) in enumerate(_v2_chunk_bounds(h, "var")):
                regions.append((f"var chunk {ci}", 2 * n + ci, lo, hi))
        regions.append(("id table", s * n, h["id_offsets_off"],
                        h["sort_off"]))
        regions.append(("sort table", s * n + 1, h["sort_off"],
                        h["crc_off"]))
        regions.append(("header", s * n + 2, 0, h["coef_off"]))
        with open(self.path, "rb") as f:
            f.seek(h["crc_off"])
            table = np.frombuffer(f.read(4 * (s * n + 3)), dtype="<u4")
            for name, idx, lo, hi in regions:
                crc = _region_crc(f, lo, hi)
                if crc != int(table[idx]):
                    raise ColdStoreCorruptError(
                        self.path,
                        f"{name} crc mismatch: computed {crc:#010x}, "
                        f"stored {int(table[idx]):#010x}")
        if self._sort is not None:
            rows = np.asarray(self._sort)
            if rows.size and ((rows < 0).any()
                              or (rows >= self.num_entities).any()):
                raise ColdStoreCorruptError(
                    self.path, "sort table references out-of-range rows")

    def describe(self) -> dict:
        return {
            "path": self.path,
            "coordinate_id": self.coordinate_id,
            "random_effect_type": self.random_effect_type,
            "feature_shard_id": self.feature_shard_id,
            "num_entities": self.num_entities,
            "slot_width": self.slot_width,
            "file_bytes": self.file_bytes,
            "updatable": self.updatable,
            "capacity": self.capacity,
            "has_variances": self.has_variances,
        }


# -- in-place deltas (v2) -----------------------------------------------------


def apply_cold_store_delta(
    path: str,
    *,
    update_rows: Optional[np.ndarray] = None,
    update_coef: Optional[np.ndarray] = None,
    update_proj: Optional[np.ndarray] = None,
    append_ids: Sequence[str] = (),
    append_coef: Optional[np.ndarray] = None,
    append_proj: Optional[np.ndarray] = None,
    update_var: Optional[np.ndarray] = None,
    append_var: Optional[np.ndarray] = None,
    normalize: bool = True,
    chaos_op: Optional[str] = "cold_delta",
) -> dict:
    """Apply a row-level delta to a v2/v4 file in place; returns the undo
    record ``rollback_cold_store_delta`` needs for a bitwise restore.

    On v4 files ``update_var``/``append_var`` carry the posterior
    variances alongside the means. A delta that omits ``update_var``
    leaves the updated rows' existing variance bytes untouched (a
    mean-only refresh never silently zeroes uncertainty); appends that
    omit ``append_var`` land zero variances (served at the mean until a
    Bayesian pass republishes them). Passing either on a v2 (var-less)
    file is a typed error — upgrade the file with variances first.

    Write order is data rows -> (chaos kill point) -> id tail -> sort
    rebuild -> header -> touched-chunk crcs -> fsync, so a crash at any
    point leaves a file that either verifies as the prior state (nothing
    written yet) or fails ``verify()`` and is refused — never a silently
    half-applied delta. Appends take storage rows ``num_entities..`` so
    existing row numbers never move (the hot tier caches them); the sort
    indirection is rebuilt in O(E) per batch, which at nearline delta
    cadence is noise next to the solves.

    The returned undo dict carries the prior bytes of every touched row
    plus the prior id/sort sections, and ``append_rows`` telling the
    caller which storage rows the new entities landed on.
    """
    h, hlen = _read_header(path)
    if h.get("schema") not in (SCHEMA_V2, SCHEMA_V4):
        raise ColdStoreNotUpdatable(path, h.get("schema"))
    has_var = bool(h.get("var_off"))
    if (update_var is not None or append_var is not None) and not has_var:
        raise ValueError(
            f"cold store at {path} (schema {h.get('schema')!r}) has no "
            f"variance column; rewrite it with variances (v4) before "
            f"publishing variance deltas")
    slot_width = h["slot_width"]
    num_entities = h["num_entities"]
    capacity = h["capacity"]
    blob_used = h["id_blob_used"]
    rowb = slot_width * 4

    update_rows = (np.zeros(0, dtype=np.int64) if update_rows is None
                   else np.asarray(update_rows, dtype=np.int64))
    n_upd = int(update_rows.shape[0])
    update_coef = (np.zeros((0, slot_width), np.float32) if update_coef is None
                   else np.asarray(update_coef, np.float32))
    update_proj = (np.full((0, slot_width), -1, np.int32) if update_proj is None
                   else np.asarray(update_proj, np.int32))
    append_ids = [str(e) for e in append_ids]
    n_app = len(append_ids)
    append_coef = (np.zeros((0, slot_width), np.float32) if append_coef is None
                   else np.asarray(append_coef, np.float32))
    append_proj = (np.full((0, slot_width), -1, np.int32) if append_proj is None
                   else np.asarray(append_proj, np.int32))
    if update_coef.shape != (n_upd, slot_width) or \
            update_proj.shape != (n_upd, slot_width):
        raise ValueError(f"update arrays must be [{n_upd}, {slot_width}], "
                         f"got {update_coef.shape} / {update_proj.shape}")
    if append_coef.shape != (n_app, slot_width) or \
            append_proj.shape != (n_app, slot_width):
        raise ValueError(f"append arrays must be [{n_app}, {slot_width}], "
                         f"got {append_coef.shape} / {append_proj.shape}")
    if update_var is not None:
        update_var = np.asarray(update_var, np.float32)
        if update_var.shape != (n_upd, slot_width):
            raise ValueError(f"update_var must be [{n_upd}, {slot_width}], "
                             f"got {update_var.shape}")
    if append_var is not None:
        append_var = np.asarray(append_var, np.float32)
        if append_var.shape != (n_app, slot_width):
            raise ValueError(f"append_var must be [{n_app}, {slot_width}], "
                             f"got {append_var.shape}")
    if n_upd and (np.unique(update_rows).size != n_upd
                  or update_rows.min() < 0
                  or update_rows.max() >= num_entities):
        raise ValueError(f"update_rows must be unique and in "
                         f"[0, {num_entities})")
    if len(set(append_ids)) != n_app:
        raise ValueError("duplicate ids in append_ids")
    if normalize:
        if update_var is not None:
            update_coef, update_proj, update_var = normalize_slot_rows(
                update_coef, update_proj, update_var)
        else:
            update_coef, update_proj = normalize_slot_rows(update_coef,
                                                           update_proj)
        if append_var is not None:
            append_coef, append_proj, append_var = normalize_slot_rows(
                append_coef, append_proj, append_var)
        else:
            append_coef, append_proj = normalize_slot_rows(append_coef,
                                                           append_proj)

    new_id_bytes = [e.encode("utf-8") for e in append_ids]
    blob_add = sum(len(b) for b in new_id_bytes)
    if num_entities + n_app > capacity:
        raise ColdStoreCapacityError(
            path, f"{num_entities} + {n_app} rows > capacity {capacity}")
    if blob_used + blob_add > h["id_blob_len"]:
        raise ColdStoreCapacityError(
            path, f"id blob {blob_used} + {blob_add}B > reserve "
                  f"{h['id_blob_len']}B")
    if n_app:
        reader = ColdStore(path)
        dup = [e for e in append_ids if reader.entity_row(e) is not None]
        del reader
        if dup:
            raise ValueError(f"append_ids already present: {dup[:5]}")

    undo = {
        "schema": h["schema"],
        "update_rows": update_rows.copy(),
        "prior_update_coef": np.zeros((n_upd, slot_width), np.float32),
        "prior_update_proj": np.zeros((n_upd, slot_width), np.int32),
        "prior_update_var": (np.zeros((n_upd, slot_width), np.float32)
                             if update_var is not None else None),
        "prior_num_entities": num_entities,
        "prior_id_blob_used": blob_used,
        "append_rows": np.arange(num_entities, num_entities + n_app,
                                 dtype=np.int64),
        "appended_ids": list(append_ids),
        "prior_id_offsets_bytes": None,
        "prior_sort_bytes": None,
    }
    with open(path, "r+b") as f:
        # capture prior bytes for bitwise rollback
        for i, r in enumerate(update_rows):
            f.seek(h["coef_off"] + int(r) * rowb)
            undo["prior_update_coef"][i] = np.frombuffer(f.read(rowb),
                                                         np.float32)
            f.seek(h["proj_off"] + int(r) * rowb)
            undo["prior_update_proj"][i] = np.frombuffer(f.read(rowb),
                                                         np.int32)
            if update_var is not None:
                f.seek(h["var_off"] + int(r) * rowb)
                undo["prior_update_var"][i] = np.frombuffer(f.read(rowb),
                                                            np.float32)
        existing_ids: List[bytes] = []
        if n_app:
            f.seek(h["id_offsets_off"])
            undo["prior_id_offsets_bytes"] = f.read((capacity + 1) * 8)
            f.seek(h["sort_off"])
            undo["prior_sort_bytes"] = f.read(capacity * 8)
            offs = np.frombuffer(undo["prior_id_offsets_bytes"], np.uint64)
            f.seek(h["id_blob_off"])
            blob = f.read(blob_used)
            existing_ids = [blob[int(offs[i]):int(offs[i + 1])]
                            for i in range(num_entities)]
        # data rows
        for i, r in enumerate(update_rows):
            f.seek(h["coef_off"] + int(r) * rowb)
            f.write(np.ascontiguousarray(update_coef[i]).tobytes())
            f.seek(h["proj_off"] + int(r) * rowb)
            f.write(np.ascontiguousarray(update_proj[i]).tobytes())
            if update_var is not None:
                f.seek(h["var_off"] + int(r) * rowb)
                f.write(np.ascontiguousarray(update_var[i]).tobytes())
        zero_var = (np.zeros(slot_width, np.float32)
                    if has_var and append_var is None else None)
        for j in range(n_app):
            r = num_entities + j
            f.seek(h["coef_off"] + r * rowb)
            f.write(np.ascontiguousarray(append_coef[j]).tobytes())
            f.seek(h["proj_off"] + r * rowb)
            f.write(np.ascontiguousarray(append_proj[j]).tobytes())
            if has_var:
                # appended entities without a variance delta get explicit
                # zeros (served at the mean) — reserve bytes there may be
                # stale from a rolled-back append
                row_var = zero_var if append_var is None else append_var[j]
                f.seek(h["var_off"] + r * rowb)
                f.write(np.ascontiguousarray(row_var).tobytes())
        # torn-update kill point: data landed, ids/header/crcs stale —
        # a kill here must leave a file verify() refuses
        if chaos_op is not None:
            _chaos.at_publish(chaos_op)
        touched = set((update_rows // h["rows_per_chunk"]).tolist())
        var_touched = set(touched) if update_var is not None else set()
        if n_app:
            offs = np.frombuffer(undo["prior_id_offsets_bytes"],
                                 np.uint64).copy()
            pos = blob_used
            for j, kb in enumerate(new_id_bytes):
                pos += len(kb)
                offs[num_entities + 1 + j] = pos
            offs[num_entities + n_app + 1:] = pos
            f.seek(h["id_offsets_off"])
            f.write(offs.tobytes())
            f.seek(h["id_blob_off"] + blob_used)
            f.write(b"".join(new_id_bytes))
            all_ids = np.asarray(existing_ids + new_id_bytes, dtype=bytes)
            sort = np.full(capacity, -1, dtype=np.int64)
            sort[:num_entities + n_app] = np.argsort(all_ids, kind="stable")
            f.seek(h["sort_off"])
            f.write(sort.tobytes())
            h2 = dict(h)
            h2["num_entities"] = num_entities + n_app
            h2["id_blob_used"] = blob_used + blob_add
            _rewrite_header(f, h2, hlen)
            app_chunks = set((undo["append_rows"]
                              // h["rows_per_chunk"]).tolist())
            touched |= app_chunks
            if has_var:
                var_touched |= app_chunks
        _v2_recompute_crcs(f, h, coef_chunks=touched, proj_chunks=touched,
                           var_chunks=var_touched,
                           ids=bool(n_app), sort=bool(n_app),
                           header=bool(n_app))
        f.flush()
        os.fsync(f.fileno())
    return undo


def rollback_cold_store_delta(path: str, undo: dict) -> None:
    """Bitwise-restore the rows a previous ``apply_cold_store_delta``
    touched. Updated rows get their exact prior bytes back; appended
    entities disappear (num_entities and the id/sort sections revert, so
    their reserve rows become unreachable garbage that the recomputed
    chunk crcs still cover). The file verifies clean afterwards."""
    h, hlen = _read_header(path)
    if h.get("schema") not in (SCHEMA_V2, SCHEMA_V4):
        raise ColdStoreNotUpdatable(path, h.get("schema"))
    has_var = bool(h.get("var_off"))
    rowb = h["slot_width"] * 4
    update_rows = np.asarray(undo["update_rows"], dtype=np.int64)
    prior_coef = np.asarray(undo["prior_update_coef"], dtype=np.float32)
    prior_proj = np.asarray(undo["prior_update_proj"], dtype=np.int32)
    prior_var = undo.get("prior_update_var")
    with open(path, "r+b") as f:
        for i, r in enumerate(update_rows):
            f.seek(h["coef_off"] + int(r) * rowb)
            f.write(np.ascontiguousarray(prior_coef[i]).tobytes())
            f.seek(h["proj_off"] + int(r) * rowb)
            f.write(np.ascontiguousarray(prior_proj[i]).tobytes())
            if prior_var is not None:
                f.seek(h["var_off"] + int(r) * rowb)
                f.write(np.ascontiguousarray(
                    np.asarray(prior_var[i], np.float32)).tobytes())
        touched = set((update_rows // h["rows_per_chunk"]).tolist())
        var_touched = set(touched) if prior_var is not None else set()
        had_appends = undo.get("prior_sort_bytes") is not None
        if had_appends:
            f.seek(h["id_offsets_off"])
            f.write(undo["prior_id_offsets_bytes"])
            f.seek(h["sort_off"])
            f.write(undo["prior_sort_bytes"])
            append_rows = np.asarray(undo["append_rows"], dtype=np.int64)
            app_chunks = set((append_rows // h["rows_per_chunk"]).tolist())
            touched |= app_chunks
            if has_var:
                var_touched |= app_chunks
            h2 = dict(h)
            h2["num_entities"] = int(undo["prior_num_entities"])
            h2["id_blob_used"] = int(undo["prior_id_blob_used"])
            _rewrite_header(f, h2, hlen)
        _v2_recompute_crcs(f, h, coef_chunks=touched, proj_chunks=touched,
                           var_chunks=var_touched,
                           ids=had_appends, sort=had_appends,
                           header=had_appends)
        f.flush()
        os.fsync(f.fileno())


def upgrade_cold_store(path: str, *, capacity: Optional[int] = None,
                       id_blob_cap: Optional[int] = None,
                       rows_per_chunk: int = 4096) -> str:
    """Rewrite a cold-store file (v1, or a full v2) as v2 with fresh
    reserve space. A full atomic rewrite (tmp + fsync + rename), NOT an
    in-place delta — but storage row numbers are preserved exactly, so
    open readers can be refreshed by reopening the path without any
    row-index remap. Callers holding a ``ColdStore`` must reopen it
    afterwards (the old mmap still sees the replaced inode)."""
    cs = ColdStore(path)
    coef = np.asarray(cs.coef, dtype=np.float32)
    proj = np.asarray(cs.proj, dtype=np.int32)
    var = (np.asarray(cs.var, dtype=np.float32)
           if cs.has_variances else None)
    ids, _ = _encode_ids([cs.entity_id(r) for r in range(cs.num_entities)])
    if ids.shape[0] == 0:
        ids = np.asarray([], dtype="S1")
    meta = (cs.coordinate_id, cs.random_effect_type, cs.feature_shard_id)
    del cs
    return _write_cold_store_v2(
        path, *meta, coef, proj, ids,
        np.arange(ids.shape[0], dtype=np.int64),
        capacity=capacity, id_blob_cap=id_blob_cap,
        rows_per_chunk=rows_per_chunk, variances=var)
