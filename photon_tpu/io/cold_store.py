"""Host-RAM cold tier for per-entity coefficient tables.

One columnar file per random-effect coordinate, holding ALL entity rows
sorted by entity id — the photon_tpu analog of Photon ML's PalDB
off-heap coefficient index. Serving keeps only a fixed-budget hot set of
rows in device HBM (serving/coeff_store.py); everything else lives here,
loaded zero-copy via ``np.memmap`` so a 10M-entity table costs page
cache, not process heap, and training's blocked iteration mode streams
entity blocks through the per-entity solve without ever materializing
the full table on device.

On-disk layout (``photon_tpu.coldstore.v1``)::

    magic      8 bytes   b"PHOTCOLD"
    header     u32 little-endian JSON length, then the JSON header
    sections   each 64-byte aligned, offsets recorded in the header:
        coef   float32 [num_entities, slot_width]   dense coefficients
        proj   int32   [num_entities, slot_width]   global col per local
                                                    slot, -1 padded
        ids    entity-id table: fixed-width byte rows (id_width > 0) or
               u64 offsets[num_entities + 1] + utf-8 blob (id_width == 0)
    footer     u32 crc32 of every preceding byte

Rows are sorted by utf-8-encoded entity id, so lookup is one binary
search over the mmapped id table — no host dict of N entries is ever
built. The crc footer makes torn or bit-flipped files refusable at swap
validation (``verify()``); the chaos harness's ``corrupt_cold_store``
drives that gate.
"""

from __future__ import annotations

import json
import os
import time
import zlib
from typing import Iterator, List, Optional, Sequence, Tuple, Union

import numpy as np

from photon_tpu.resilience import chaos as _chaos

MAGIC = b"PHOTCOLD"
SCHEMA = "photon_tpu.coldstore.v1"
COLD_STORE_DIR = "cold-store"
COLD_STORE_SUFFIX = ".coldstore"
_ALIGN = 64


class ColdStoreCorruptError(RuntimeError):
    """A cold-store file failed magic/header/crc validation."""

    def __init__(self, path: str, detail: str):
        self.path = path
        super().__init__(f"corrupt cold store at {path}: {detail}")


def cold_store_path(model_dir: str, coordinate_id: str) -> str:
    """Canonical location of a coordinate's cold-tier file in a model
    directory, alongside the reference per-coordinate Avro layout."""
    return os.path.join(model_dir, COLD_STORE_DIR,
                        coordinate_id + COLD_STORE_SUFFIX)


def _encode_ids(entity_ids) -> Tuple[np.ndarray, int]:
    """(bytes array [E] dtype S*, fixed width or 0). Ids are compared and
    sorted as utf-8 bytes — the same order ``ColdStore.entity_row``'s
    binary search uses."""
    arr = np.asarray(entity_ids)
    if arr.dtype.kind == "U":
        arr = np.char.encode(arr, "utf-8")
    elif arr.dtype.kind != "S":
        arr = np.asarray([str(e).encode("utf-8") for e in entity_ids],
                         dtype=bytes)
    lengths = np.char.str_len(arr)
    if arr.size and lengths.min() == lengths.max() == arr.dtype.itemsize:
        return arr, int(arr.dtype.itemsize)
    return arr, 0


def _pad(f, crc: int, pos: int) -> Tuple[int, int]:
    gap = (-pos) % _ALIGN
    if gap:
        pad = b"\x00" * gap
        f.write(pad)
        crc = zlib.crc32(pad, crc)
    return crc, pos + gap


def write_cold_store(
    path: str,
    coordinate_id: str,
    random_effect_type: str,
    feature_shard_id: str,
    coefficients: np.ndarray,
    projection: np.ndarray,
    entity_ids: Union[Sequence[str], np.ndarray],
    chunk_rows: int = 262144,
) -> str:
    """Write one coordinate's cold-tier file; returns its path.

    Rows are re-sorted by entity id internally, so callers pass arrays in
    any order. Streams in ``chunk_rows`` chunks (a 10M-entity table never
    needs a second full copy in RAM beyond the sort permutation) and
    publishes atomically (tmp + fsync + rename).
    """
    coefficients = np.asarray(coefficients, dtype=np.float32)
    projection = np.asarray(projection, dtype=np.int32)
    ids, id_width = _encode_ids(entity_ids)
    num_entities, slot_width = coefficients.shape
    if projection.shape != coefficients.shape:
        raise ValueError(f"projection shape {projection.shape} != "
                         f"coefficients shape {coefficients.shape}")
    if ids.shape != (num_entities,):
        raise ValueError(f"{ids.shape[0]} entity ids for "
                         f"{num_entities} rows")

    # normalize every row to (valid slots sorted ascending by global
    # column, -1 pads last) — the invariant the serving hot-tier slot
    # replay (searchsorted over the valid prefix) depends on; rows
    # already in that form pass through unchanged (stable sort)
    if num_entities and slot_width > 1:
        key = np.where(projection < 0, np.iinfo(np.int32).max, projection)
        slot_order = np.argsort(key, axis=1, kind="stable")
        projection = np.take_along_axis(projection, slot_order, axis=1)
        coefficients = np.take_along_axis(coefficients, slot_order, axis=1)

    order = np.argsort(ids, kind="stable")
    ids = ids[order]

    header = {
        "schema": SCHEMA,
        "coordinate_id": coordinate_id,
        "random_effect_type": random_effect_type,
        "feature_shard_id": feature_shard_id,
        "num_entities": int(num_entities),
        "slot_width": int(slot_width),
        "coef_dtype": "<f4",
        "proj_dtype": "<i4",
        "id_width": id_width,
    }
    # one-pass header layout: reserve maximal-width offset fields (15
    # digits covers any sub-petabyte file), measure the serialized
    # length, then fill real offsets and pad back to the reserved length
    # — the header's byte length never depends on the offset values
    _SENTINEL = 10 ** 14
    for key in ("coef_off", "proj_off", "id_offsets_off", "id_blob_off",
                "id_blob_len"):
        header[key] = _SENTINEL
    reserved = len(json.dumps(header).encode())
    base = len(MAGIC) + 4 + reserved

    def aligned(pos: int) -> int:
        return pos + ((-pos) % _ALIGN)

    coef_off = aligned(base)
    proj_off = aligned(coef_off + num_entities * slot_width * 4)
    id_offsets_off = aligned(proj_off + num_entities * slot_width * 4)
    if id_width:
        id_blob_off = id_offsets_off
        id_offsets_off = 0
        id_blob_len = num_entities * id_width
    else:
        id_blob_off = aligned(id_offsets_off + (num_entities + 1) * 8)
        id_blob_len = int(np.char.str_len(ids).sum()) if num_entities else 0
    header.update(coef_off=coef_off, proj_off=proj_off,
                  id_offsets_off=id_offsets_off, id_blob_off=id_blob_off,
                  id_blob_len=id_blob_len)
    header_bytes = json.dumps(header).encode()
    header_bytes += b" " * (reserved - len(header_bytes))

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    crc = 0
    with open(tmp, "wb") as f:
        pos = 0

        def put(data: bytes) -> None:
            nonlocal crc, pos
            f.write(data)
            crc = zlib.crc32(data, crc)
            pos += len(data)

        put(MAGIC)
        put(len(header_bytes).to_bytes(4, "little"))
        put(header_bytes)
        crc, pos = _pad(f, crc, pos)
        assert pos == header["coef_off"], (pos, header["coef_off"])
        for lo in range(0, num_entities, chunk_rows):
            sel = order[lo:lo + chunk_rows]
            put(np.ascontiguousarray(coefficients[sel]).tobytes())
        crc, pos = _pad(f, crc, pos)
        for lo in range(0, num_entities, chunk_rows):
            sel = order[lo:lo + chunk_rows]
            put(np.ascontiguousarray(projection[sel]).tobytes())
        crc, pos = _pad(f, crc, pos)
        if id_width:
            put(ids.tobytes())
        else:
            lengths = np.char.str_len(ids).astype(np.uint64)
            offsets = np.zeros(num_entities + 1, dtype=np.uint64)
            np.cumsum(lengths, out=offsets[1:])
            put(offsets.tobytes())
            crc, pos = _pad(f, crc, pos)
            for lo in range(0, num_entities, chunk_rows):
                put(b"".join(bytes(s) for s in ids[lo:lo + chunk_rows]))
        f.write(crc.to_bytes(4, "little"))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path


class ColdStore:
    """Zero-copy reader over one coordinate's cold-tier file.

    ``coef``/``proj`` are read-only ``np.memmap`` views — opening a
    10M-entity store touches only the header. ``entity_row`` binary
    searches the mmapped sorted-id table; ``read_rows`` fancy-indexes the
    requested rows into a fresh host array (the unit the transfer thread
    uploads). ``verify()`` streams the whole file against the crc footer
    — swap validation's cold-pair gate.
    """

    def __init__(self, path: str, *, verify: bool = False):
        self.path = path
        with open(path, "rb") as f:
            magic = f.read(len(MAGIC))
            if magic != MAGIC:
                raise ColdStoreCorruptError(path, f"bad magic {magic!r}")
            hlen = int.from_bytes(f.read(4), "little")
            if hlen <= 0 or hlen > 1 << 20:
                raise ColdStoreCorruptError(path, f"bad header length {hlen}")
            try:
                h = json.loads(f.read(hlen))
            except (ValueError, UnicodeDecodeError) as e:
                raise ColdStoreCorruptError(path, f"unparseable header: {e}")
        if h.get("schema") != SCHEMA:
            raise ColdStoreCorruptError(path, f"schema {h.get('schema')!r}")
        self.coordinate_id: str = h["coordinate_id"]
        self.random_effect_type: str = h["random_effect_type"]
        self.feature_shard_id: str = h["feature_shard_id"]
        self.num_entities: int = h["num_entities"]
        self.slot_width: int = h["slot_width"]
        self._id_width: int = h["id_width"]
        self.file_bytes = os.path.getsize(path)
        shape = (self.num_entities, self.slot_width)
        self.coef = np.memmap(path, dtype=np.dtype(h["coef_dtype"]),
                              mode="r", offset=h["coef_off"], shape=shape)
        self.proj = np.memmap(path, dtype=np.dtype(h["proj_dtype"]),
                              mode="r", offset=h["proj_off"], shape=shape)
        if self._id_width:
            self._id_blob = np.memmap(
                path, dtype=np.uint8, mode="r", offset=h["id_blob_off"],
                shape=(self.num_entities * self._id_width,))
            self._id_offsets = None
        else:
            self._id_offsets = np.memmap(
                path, dtype=np.uint64, mode="r",
                offset=h["id_offsets_off"], shape=(self.num_entities + 1,))
            self._id_blob = np.memmap(
                path, dtype=np.uint8, mode="r", offset=h["id_blob_off"],
                shape=(h["id_blob_len"],))
        if verify:
            self.verify()

    # -- id table -----------------------------------------------------------

    def _id_bytes(self, row: int) -> bytes:
        if self._id_width:
            lo = row * self._id_width
            return bytes(self._id_blob[lo:lo + self._id_width])
        lo = int(self._id_offsets[row])
        hi = int(self._id_offsets[row + 1])
        return bytes(self._id_blob[lo:hi])

    def entity_id(self, row: int) -> str:
        return self._id_bytes(row).decode("utf-8")

    def entity_row(self, entity_id: str) -> Optional[int]:
        """Row index of ``entity_id`` (binary search over the sorted id
        table), or None when the entity is not in the model — the caller's
        typed ``UNKNOWN_ENTITY`` signal."""
        key = entity_id.encode("utf-8")
        if self._id_width and len(key) != self._id_width:
            return None
        lo, hi = 0, self.num_entities
        while lo < hi:
            mid = (lo + hi) // 2
            if self._id_bytes(mid) < key:
                lo = mid + 1
            else:
                hi = mid
        if lo < self.num_entities and self._id_bytes(lo) == key:
            return lo
        return None

    # -- row access ---------------------------------------------------------

    def read_rows(self, rows: np.ndarray) -> np.ndarray:
        """Coefficient rows [len(rows), slot_width] as a fresh float32
        host array — the transfer thread's upload unit. Consults the
        chaos harness's cold-read-delay injector (this path is allowed to
        be slow; the scoring hot path must not wait on it)."""
        delay = _chaos.cold_read_delay()
        if delay > 0:
            time.sleep(delay)
        return np.asarray(self.coef[np.asarray(rows, dtype=np.int64)],
                          dtype=np.float32)

    def read_proj_rows(self, rows: np.ndarray) -> np.ndarray:
        return np.asarray(self.proj[np.asarray(rows, dtype=np.int64)],
                          dtype=np.int32)

    def iter_blocks(self, block_rows: int,
                    start_row: int = 0
                    ) -> Iterator[Tuple[int, List[str], np.ndarray,
                                        np.ndarray]]:
        """Stream ``(start_row, entity_ids, coef_block, proj_block)`` in
        sorted-id order — training's blocked iteration unit."""
        if block_rows <= 0:
            raise ValueError(f"block_rows must be positive, got {block_rows}")
        for lo in range(start_row, self.num_entities, block_rows):
            hi = min(lo + block_rows, self.num_entities)
            idx = np.arange(lo, hi)
            ids = [self.entity_id(r) for r in range(lo, hi)]
            yield lo, ids, self.read_rows(idx), self.read_proj_rows(idx)

    # -- integrity ----------------------------------------------------------

    def verify(self, chunk_bytes: int = 4 << 20) -> None:
        """Stream the file against its crc32 footer; raises
        ``ColdStoreCorruptError`` on mismatch or truncation."""
        size = os.path.getsize(self.path)
        if size < len(MAGIC) + 4 + 4:
            raise ColdStoreCorruptError(self.path, f"truncated ({size}B)")
        crc = 0
        remaining = size - 4
        with open(self.path, "rb") as f:
            while remaining > 0:
                chunk = f.read(min(chunk_bytes, remaining))
                if not chunk:
                    raise ColdStoreCorruptError(
                        self.path, "short read during verify")
                crc = zlib.crc32(chunk, crc)
                remaining -= len(chunk)
            footer = int.from_bytes(f.read(4), "little")
        if crc != footer:
            raise ColdStoreCorruptError(
                self.path,
                f"crc mismatch: computed {crc:#010x}, footer {footer:#010x}")

    def describe(self) -> dict:
        return {
            "path": self.path,
            "coordinate_id": self.coordinate_id,
            "random_effect_type": self.random_effect_type,
            "feature_shard_id": self.feature_shard_id,
            "num_entities": self.num_entities,
            "slot_width": self.slot_width,
            "file_bytes": self.file_bytes,
        }
