"""The nearline loop: poll -> delta-train -> row-publish -> checkpoint.

One :class:`NearlinePipeline` drives one serving engine.  Each round:

1. ``EventLogReader.poll`` pulls the new events past the watermark
   (deduplicated, re-ordered, torn tails left for the writer to finish).
2. ``DeltaTrainer.train`` re-solves ONLY the entities those events
   touch, warm-started from the live coefficients.
3. ``DeltaPublisher.publish`` pushes the changed rows into the live
   serving tables behind its gate ladder, landing a durable versioned
   manifest (which carries the watermark).
4. ``save_checkpoint`` advances the durable offset watermark.

The manifest-before-checkpoint order is the exactly-once handshake: a
crash between 3 and 4 leaves ``manifest.version > ckpt.published_version``
and recovery adopts the manifest's watermark instead of re-publishing the
same delta (re-running step 3 would double-apply nothing — publishes are
idempotent per row — but would re-consume capacity gates and re-trip
probation; adopting the watermark is both cheaper and exact).

Freshness is the pipeline's north-star metric: the histogram
``nearline.freshness_seconds`` measures event timestamp -> the moment the
entity's new row is scoreable (the publish commit), per touched entity.

Run it inline round by round (``run_round``, what the tests and bench
do), or as a long-lived loop (``run``) with the shared shutdown hook
providing graceful drain: finish the in-flight round, land the final
checkpoint, exit.  ``cli/nearline`` wraps ``run`` for operators.
"""

from __future__ import annotations

import dataclasses
import os
import sys
import time
from typing import Any, Dict, Optional

from photon_tpu.nearline.delta_trainer import DeltaTrainConfig, DeltaTrainer
from photon_tpu.nearline.events import (
    EventLogReader,
    load_checkpoint,
    save_checkpoint,
)
from photon_tpu.nearline.publisher import DeltaPublisher, NearlinePublishConfig
from photon_tpu.obs.metrics import registry as _metrics
from photon_tpu.resilience import shutdown as _shutdown

_FRESHNESS_BUCKETS = tuple(0.01 * 2.0 ** i for i in range(28))
_ROUND_BUCKETS = tuple(1e-3 * 1.8 ** i for i in range(30))


@dataclasses.dataclass(frozen=True)
class NearlineConfig:
    """Loop cadence and sub-stage configuration."""

    #: idle sleep between polls that found nothing
    poll_interval_s: float = 1.0
    #: stop after this many rounds; 0 = run until shutdown
    max_rounds: int = 0
    #: cap on events consumed per round (None = drain the log)
    max_events_per_round: Optional[int] = None
    #: durable watermark checkpoint; None derives <state_dir>/checkpoint.json
    checkpoint_path: Optional[str] = None
    #: manifest/checkpoint directory; None derives <model_dir>/nearline
    state_dir: Optional[str] = None
    train: DeltaTrainConfig = dataclasses.field(
        default_factory=DeltaTrainConfig)
    publish: NearlinePublishConfig = dataclasses.field(
        default_factory=NearlinePublishConfig)

    def __post_init__(self) -> None:
        if self.poll_interval_s < 0:
            raise ValueError("poll_interval_s must be >= 0")
        if self.max_rounds < 0:
            raise ValueError("max_rounds must be >= 0")
        if (self.max_events_per_round is not None
                and self.max_events_per_round <= 0):
            raise ValueError("max_events_per_round must be positive")


class NearlinePipeline:
    """Poll -> train -> publish -> checkpoint against one engine."""

    def __init__(self, engine, log_dir: str,
                 model_dir: Optional[str] = None,
                 config: Optional[NearlineConfig] = None):
        self.engine = engine
        self.log_dir = log_dir
        self.model_dir = model_dir
        self.config = config or NearlineConfig()
        state_dir = self.config.state_dir
        if state_dir is None and model_dir is not None:
            state_dir = os.path.join(model_dir, "nearline")
        self.state_dir = state_dir
        self.checkpoint_path = self.config.checkpoint_path
        if self.checkpoint_path is None and state_dir is not None:
            self.checkpoint_path = os.path.join(state_dir, "checkpoint.json")
        self.reader = EventLogReader(log_dir)
        self.trainer = DeltaTrainer(engine, model_dir, self.config.train)
        self.publisher = DeltaPublisher(engine, model_dir, state_dir,
                                        self.config.publish)
        self.rounds = 0
        self.recovered = False
        self.totals: Dict[str, int] = {
            "events": 0, "rows_updated": 0, "rows_appended": 0,
            "publishes": 0, "rejected": 0, "rollbacks": 0,
            "fixed_refreshes": 0}
        self.last_round: Dict[str, Any] = {}
        self._recover()
        set_active(self)

    # ---------------------------------------------------------- recovery

    def _recover(self) -> None:
        """Adopt the durable watermark; reconcile a publish that landed
        its manifest but died before the checkpoint advanced."""
        published_version = 0
        ckpt = (load_checkpoint(self.checkpoint_path)
                if self.checkpoint_path else None)
        if ckpt is not None:
            self.reader.restore(ckpt["state"])
            published_version = int(ckpt.get("published_version", 0))
        manifest = self.publisher.last_manifest
        if manifest is not None and \
                int(manifest["version"]) > published_version:
            # the exactly-once seam: rows are already live (and durable
            # in the cold tier) — adopt the manifest watermark, do NOT
            # re-train/re-publish the same events
            if manifest.get("watermark"):
                self.reader.restore(manifest["watermark"])
            self._checkpoint()
            self.recovered = True
            _metrics.counter("nearline.pipeline.recovered_publishes").inc()

    def _checkpoint(self) -> None:
        if self.checkpoint_path is None:
            return
        os.makedirs(os.path.dirname(self.checkpoint_path) or ".",
                    exist_ok=True)
        save_checkpoint(self.checkpoint_path, self.reader.state(),
                        published_version=self.publisher.version)

    # ------------------------------------------------------------ rounds

    def run_round(self) -> Dict[str, Any]:
        """One poll -> train -> publish -> checkpoint round (no sleep)."""
        t0 = time.perf_counter()
        self.publisher.check_probation()
        events = self.reader.poll(self.config.max_events_per_round)
        summary: Dict[str, Any] = {"round": self.rounds,
                                   "events": len(events)}
        if not events:
            self.last_round = summary
            return summary
        self.rounds += 1
        summary["round"] = self.rounds
        self.totals["events"] += len(events)

        delta = self.trainer.train(events)
        summary["entities"] = delta.num_rows
        summary["train_stats"] = dict(delta.stats)

        if delta.num_rows:
            label = f"nearline-r{self.rounds:05d}"
            res = self.publisher.publish(delta, label,
                                         watermark=self.reader.state())
            summary["publish"] = res.to_json()
            if res.accepted:
                self.totals["publishes"] += 1
                self.totals["rows_updated"] += res.rows_updated
                self.totals["rows_appended"] += res.rows_appended
                # event -> scoreable: the commit is the moment the new
                # rows gather into scores
                now = time.time()
                hist = _metrics.histogram("nearline.freshness_seconds",
                                          buckets=_FRESHNESS_BUCKETS)
                for cd in delta.coordinates.values():
                    for ts in cd.event_ts.values():
                        hist.observe(max(now - float(ts), 0.0))
            else:
                self.totals["rejected"] += 1
                if res.rolled_back:
                    self.totals["rollbacks"] += 1

        swap = self.trainer.maybe_refresh_fixed()
        if swap is not None:
            summary["fixed_refresh"] = swap.to_json()
            if swap.accepted:
                self.totals["fixed_refreshes"] += 1

        # watermark advances only after the publish (and its manifest)
        # landed — crash anywhere above replays this round's events
        self._checkpoint()
        dt = time.perf_counter() - t0
        summary["seconds"] = dt
        _metrics.histogram("nearline.round_seconds",
                           buckets=_ROUND_BUCKETS).observe(dt)
        _metrics.gauge("nearline.rounds").set(float(self.rounds))
        self.last_round = summary
        return summary

    def run(self) -> Dict[str, Any]:
        """Loop until shutdown (or ``max_rounds``); graceful drain lands
        a final checkpoint before returning the run summary."""
        cfg = self.config
        while not _shutdown.requested():
            if cfg.max_rounds and self.rounds >= cfg.max_rounds:
                break
            got = self.run_round()
            if got["events"] == 0:
                # idle: nap in small slices so shutdown stays responsive
                deadline = time.monotonic() + cfg.poll_interval_s
                while (time.monotonic() < deadline
                       and not _shutdown.requested()):
                    time.sleep(min(0.05, cfg.poll_interval_s or 0.05))
        self._checkpoint()
        return self.describe()

    # --------------------------------------------------------------- obs

    def describe(self) -> Dict[str, Any]:
        return {
            "log_dir": self.log_dir,
            "rounds": self.rounds,
            "recovered": self.recovered,
            "watermark": self.reader.max_seq,
            "published_version": self.publisher.version,
            "totals": dict(self.totals),
            "reader_stats": dict(self.reader.stats),
            "last_round": dict(self.last_round),
        }


# -- RunReport integration ---------------------------------------------------

_ACTIVE: Optional[NearlinePipeline] = None


def set_active(pipeline: Optional[NearlinePipeline]) -> None:
    """Register the pipeline the obs RunReport should describe."""
    global _ACTIVE
    _ACTIVE = pipeline


def report_section() -> Optional[Dict[str, Any]]:
    """The ``nearline`` RunReport section (None when no pipeline ran)."""
    if _ACTIVE is None:
        return None
    return _ACTIVE.describe()
