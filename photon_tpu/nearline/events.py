"""Append-only event-log reader/writer with a durable watermark checkpoint.

The nearline pipeline consumes *training events* from an append-only log
directory of shard files.  Two shard formats are supported:

- ``.jsonl`` — one JSON object per line, appendable in place.  The reader
  tracks a byte offset per shard and never consumes a final line that is
  missing its trailing newline (a torn tail from a crashed writer); the
  torn bytes are re-read on the next poll once the writer completes them.
- ``.avro`` — immutable container shards written whole.  Pragmatically the
  avro records are a thin envelope (``seq`` + a JSON ``payload`` string)
  so both formats share one event schema; the point of the avro arm is
  exercising offset bookkeeping for whole-file shards, not avro fidelity.

An *event* is a dict with keys:

- ``seq``       — global monotone int assigned by the writer (required).
- ``ts``        — unix timestamp (float) of the interaction, for the
  event->scoreable freshness-lag histogram.  Optional.
- ``response``  — label (float).  ``weight`` and ``offset`` optional.
- ``features``  — ``{shard_id: [[name, term, value], ...]}``.
- ``entities``  — ``{random_effect_type: entity_id}``.

Delivery hazards are handled in the reader, not pushed to callers:
duplicate shards replay events with ``seq <= max_seq`` and are dropped
(``duplicates`` counter); out-of-order records inside a poll batch are
re-sorted by ``seq`` (``out_of_order`` counter); undecodable interior
lines are skipped (``bad_records``) while an undecodable *final* line is
treated as a torn tail and retried.

The reader's position (``max_seq`` + per-shard offsets) snapshots into a
*watermark* dict.  ``save_checkpoint`` persists it with a crc32 guard via
the resilience atomic-write path (op ``"nearline_checkpoint"`` so chaos
can kill between publish and checkpoint); a corrupt or torn checkpoint
raises :class:`NearlineCheckpointError` rather than silently replaying
from zero.  Exactly-once per publish is the manifest/checkpoint handshake
documented in :mod:`photon_tpu.nearline.publisher`: the publisher durably
records the watermark in a versioned manifest *before* the checkpoint is
advanced, so a crash between the two is recovered by adopting the
manifest watermark instead of re-publishing.
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from photon_tpu.obs.metrics import registry as _metrics
from photon_tpu.resilience import io as rio

CKPT_SCHEMA = "photon_tpu.nearline.ckpt.v1"

EVENT_AVRO_SCHEMA: Dict[str, Any] = {
    "type": "record",
    "name": "NearlineEvent",
    "namespace": "photon_tpu.nearline",
    "fields": [
        {"name": "seq", "type": "long"},
        {"name": "payload", "type": "string"},
    ],
}


class NearlineCheckpointError(RuntimeError):
    """A nearline watermark checkpoint failed its integrity check."""

    def __init__(self, path: str, detail: str):
        self.path = path
        self.detail = detail
        super().__init__(f"nearline checkpoint {path}: {detail}")


def _shard_names(log_dir: str) -> List[str]:
    try:
        names = os.listdir(log_dir)
    except FileNotFoundError:
        return []
    return sorted(n for n in names if n.endswith(".jsonl") or n.endswith(".avro"))


class EventLogWriter:
    """Appends events to shard files, assigning monotone ``seq`` numbers.

    JSONL shards are appended line-at-a-time (flush + fsync per ``append``
    call) and rotate after ``shard_records`` records; avro shards are
    immutable, so each ``append`` call writes one whole container shard.
    """

    def __init__(
        self,
        log_dir: str,
        shard_records: int = 4096,
        fmt: str = "jsonl",
        start_seq: int = 0,
    ):
        if fmt not in ("jsonl", "avro"):
            raise ValueError(f"unsupported event shard format: {fmt!r}")
        self.log_dir = log_dir
        self.fmt = fmt
        self.shard_records = int(shard_records)
        self._next_seq = int(start_seq)
        os.makedirs(log_dir, exist_ok=True)
        existing = _shard_names(log_dir)
        self._shard_idx = len(existing)
        self._records_in_shard = 0

    @property
    def next_seq(self) -> int:
        return self._next_seq

    def _shard_path(self) -> str:
        return os.path.join(
            self.log_dir, f"events-{self._shard_idx:06d}.{self.fmt}"
        )

    def rotate(self) -> None:
        if self._records_in_shard:
            self._shard_idx += 1
            self._records_in_shard = 0

    def append(self, events: Sequence[Dict[str, Any]]) -> List[int]:
        """Assign seqs and durably append ``events``; returns the seqs."""
        seqs: List[int] = []
        stamped: List[Dict[str, Any]] = []
        for ev in events:
            ev = dict(ev)
            if "seq" not in ev:
                ev["seq"] = self._next_seq
            self._next_seq = max(self._next_seq, int(ev["seq"]) + 1)
            seqs.append(int(ev["seq"]))
            stamped.append(ev)
        if not stamped:
            return seqs
        if self.fmt == "avro":
            from photon_tpu.io.avro import write_avro

            path = self._shard_path()
            self._shard_idx += 1
            write_avro(
                path,
                EVENT_AVRO_SCHEMA,
                [
                    {"seq": int(ev["seq"]), "payload": json.dumps(ev)}
                    for ev in stamped
                ],
            )
            return seqs
        path = self._shard_path()
        with open(path, "ab") as f:
            for ev in stamped:
                f.write(json.dumps(ev).encode("utf-8") + b"\n")
            f.flush()
            os.fsync(f.fileno())
        self._records_in_shard += len(stamped)
        if self._records_in_shard >= self.shard_records:
            self.rotate()
        return seqs


class EventLogReader:
    """Polls an event-log directory, tracking a resumable watermark."""

    def __init__(self, log_dir: str):
        self.log_dir = log_dir
        self.max_seq = -1
        # Per-shard progress: {"bytes": int, "records": int}.  ``bytes``
        # is meaningful only for jsonl shards; avro shards use ``records``.
        self._shards: Dict[str, Dict[str, int]] = {}
        self.stats: Dict[str, int] = {
            "polled": 0,
            "duplicates": 0,
            "out_of_order": 0,
            "bad_records": 0,
            "torn_records": 0,
        }
        # (shard, offset) of the last torn tail we counted, so one torn
        # write is not re-counted on every poll while the writer is down.
        self._last_torn: Optional[Tuple[str, int]] = None

    # ----------------------------------------------------------- polling

    def _count(self, key: str, n: int = 1) -> None:
        self.stats[key] += n
        _metrics.counter(f"nearline.events.{key}").inc(n)

    def _poll_jsonl(
        self, name: str, st: Dict[str, int], budget: int
    ) -> List[Dict[str, Any]]:
        path = os.path.join(self.log_dir, name)
        try:
            with open(path, "rb") as f:
                f.seek(st["bytes"])
                data = f.read()
        except FileNotFoundError:
            return []
        if not data:
            return []
        end = data.rfind(b"\n")
        if end < 0:
            # No complete line beyond our offset: torn tail, retry later.
            if self._last_torn != (name, st["bytes"]):
                self._last_torn = (name, st["bytes"])
                self._count("torn_records")
            return []
        lines = data[: end + 1].split(b"\n")[:-1]
        if len(data) > end + 1 and self._last_torn != (name, end + 1 + st["bytes"]):
            self._last_torn = (name, end + 1 + st["bytes"])
            self._count("torn_records")
        out: List[Dict[str, Any]] = []
        consumed = 0
        for i, line in enumerate(lines):
            if len(out) >= budget:
                break
            consumed += len(line) + 1
            if not line.strip():
                continue
            try:
                ev = json.loads(line)
            except ValueError:
                # A garbled *final* complete line could be a torn write
                # that happened to contain a newline; treat interior bad
                # lines as poison (skip) but stop before a bad last line
                # only if nothing follows it in the file.
                if i == len(lines) - 1 and len(data) == end + 1:
                    consumed -= len(line) + 1
                    if self._last_torn != (name, st["bytes"] + consumed):
                        self._last_torn = (name, st["bytes"] + consumed)
                        self._count("torn_records")
                    break
                self._count("bad_records")
                continue
            if isinstance(ev, dict) and "seq" in ev:
                out.append(ev)
                st["records"] += 1
            else:
                self._count("bad_records")
        st["bytes"] += consumed
        return out

    def _poll_avro(
        self, name: str, st: Dict[str, int], budget: int
    ) -> List[Dict[str, Any]]:
        path = os.path.join(self.log_dir, name)
        try:
            size = os.path.getsize(path)
        except OSError:
            return []
        if st.get("bytes") == size and st["bytes"] > 0:
            return []  # fully consumed, container files never grow
        from photon_tpu.io.avro import read_avro

        try:
            _, records = read_avro(path)
        except Exception:
            # Truncated/torn container: retry whole-file next poll.
            if self._last_torn != (name, size):
                self._last_torn = (name, size)
                self._count("torn_records")
            return []
        out: List[Dict[str, Any]] = []
        start = st["records"]
        for rec in records[start:]:
            if len(out) >= budget:
                break
            try:
                ev = json.loads(rec["payload"])
            except (KeyError, TypeError, ValueError):
                self._count("bad_records")
                st["records"] += 1
                continue
            if isinstance(ev, dict) and "seq" in ev:
                out.append(ev)
            else:
                self._count("bad_records")
            st["records"] += 1
        if st["records"] >= len(records):
            st["bytes"] = size  # mark consumed
        return out

    def poll(self, max_events: Optional[int] = None) -> List[Dict[str, Any]]:
        """Read newly arrived events, deduped and sorted by ``seq``."""
        budget = int(max_events) if max_events is not None else (1 << 62)
        raw: List[Dict[str, Any]] = []
        for name in _shard_names(self.log_dir):
            if budget - len(raw) <= 0:
                break
            st = self._shards.setdefault(name, {"bytes": 0, "records": 0})
            if name.endswith(".jsonl"):
                raw.extend(self._poll_jsonl(name, st, budget - len(raw)))
            else:
                raw.extend(self._poll_avro(name, st, budget - len(raw)))
        fresh: List[Dict[str, Any]] = []
        seen: set = set()
        for ev in raw:
            try:
                seq = int(ev["seq"])
            except (TypeError, ValueError):
                self._count("bad_records")
                continue
            if seq <= self.max_seq or seq in seen:
                self._count("duplicates")
                continue
            seen.add(seq)
            fresh.append(ev)
        seqs = [int(ev["seq"]) for ev in fresh]
        if any(b < a for a, b in zip(seqs, seqs[1:])):
            self._count(
                "out_of_order",
                sum(1 for a, b in zip(seqs, seqs[1:]) if b < a),
            )
            fresh.sort(key=lambda ev: int(ev["seq"]))
        if fresh:
            self.max_seq = int(fresh[-1]["seq"])
        self._count("polled", len(fresh))
        _metrics.gauge("nearline.events.max_seq").set(float(self.max_seq))
        return fresh

    # -------------------------------------------------------- watermarks

    def state(self) -> Dict[str, Any]:
        """Snapshot of the reader position (the publish watermark)."""
        return {
            "max_seq": self.max_seq,
            "shards": {k: dict(v) for k, v in self._shards.items()},
        }

    def restore(self, state: Dict[str, Any]) -> None:
        self.max_seq = int(state.get("max_seq", -1))
        self._shards = {
            str(k): {"bytes": int(v.get("bytes", 0)), "records": int(v.get("records", 0))}
            for k, v in dict(state.get("shards", {})).items()
        }


# ------------------------------------------------------------ checkpoint


def _ckpt_payload(state: Dict[str, Any], published_version: int) -> Dict[str, Any]:
    return {
        "schema": CKPT_SCHEMA,
        "state": state,
        "published_version": int(published_version),
    }


def save_checkpoint(
    path: str, state: Dict[str, Any], published_version: int = 0
) -> None:
    """Durably persist a watermark checkpoint with a crc32 guard."""
    payload = _ckpt_payload(state, published_version)
    blob = json.dumps(payload, sort_keys=True).encode("utf-8")
    doc = dict(payload)
    doc["crc"] = zlib.crc32(blob) & 0xFFFFFFFF
    rio.atomic_write_bytes(
        path,
        json.dumps(doc, sort_keys=True).encode("utf-8"),
        op="nearline_checkpoint",
    )


def load_checkpoint(path: str) -> Optional[Dict[str, Any]]:
    """Load a checkpoint; ``None`` if absent, typed error if corrupt."""
    # absence is the normal first-boot case — don't spin the retry path
    if not os.path.exists(path):
        return None
    try:
        data = rio.read_bytes(path, op="nearline_checkpoint")
    except FileNotFoundError:
        return None
    try:
        doc = json.loads(data)
    except ValueError as e:
        raise NearlineCheckpointError(path, f"unparseable: {e}") from e
    if not isinstance(doc, dict) or doc.get("schema") != CKPT_SCHEMA:
        raise NearlineCheckpointError(
            path, f"unexpected schema {doc.get('schema')!r}"
        )
    crc = doc.pop("crc", None)
    blob = json.dumps(doc, sort_keys=True).encode("utf-8")
    if crc != zlib.crc32(blob) & 0xFFFFFFFF:
        raise NearlineCheckpointError(path, "crc mismatch")
    return doc
