"""Row-level delta publish into the LIVE serving tables.

A full model swap (serving/swap.py) re-stages every table to change one
row; the nearline publisher instead pushes only the changed coefficient
rows into the tables the engine is scoring from RIGHT NOW — without a
model re-stage, without a steady-state compile, and without a scoring
thread ever observing a half-published entity.

Placement-aware apply:

- **Two-tier coordinates** are updated at the source of truth first: a
  row-level in-place delta to the v2 cold-store file
  (``io/cold_store.apply_cold_store_delta`` — crc-repaired, torn-update
  refused, undo record captured), then a non-donated fixed-shape scatter
  builds a republished copy of the CURRENT hot table with the updated
  rows rewritten at their hot slots, committed with the slot-projection
  mirrors in one transfer-lock hold.  New entities append to the cold
  tier's reserve rows and become scoreable via the normal promotion path
  (their pre-publish status is a typed UNKNOWN_ENTITY, after: scored).
- **Full-resident coordinates** scatter updated rows into a copy of the
  device gather table, splice the (entity*D + col) -> slot projection
  arrays, and hand new entities the zero reserve rows baked into the
  table shape at load (``append_reserve``) — the table SHAPE (a compiled
  program shape) never changes.

Atomicity protocol (the order matters):

1. ``engine.pending_publish_rows`` is set FIRST, so the admission
   lookahead stops prefetching the touched entities.
2. Every touched store's ``publish_lock`` is acquired (sorted by
   coordinate id), pausing cold->hot transfer cycles; the scoring path
   only takes the transfer lock and keeps serving the PRIOR rows.
3. Gates run against a stable table: finite -> variance (published
   posterior rows finite and non-negative) -> deviation -> capacity ->
   staging+parity (device readback of the staged copy, bitwise) ->
   shadow (expected-vs-actual score delta on touched entities; the RE
   margin is linear in the row, so the expectation is host-computable)
   -> compiles (steady-state compile counters frozen).
4. Commit under the transfer lock: cold delta, table pointer swap, map
   updates, cold remap.  A scorer sees the OLD world or the NEW world,
   never a mix — the publish is atomic per micro-batch boundary.
5. Post-commit readback re-gathers every published row from the device
   and the cold file and compares BITWISE against the intended bytes; a
   mismatch (e.g. chaos ``publish_poison_row``) triggers an immediate
   row-level rollback.
6. A versioned manifest (watermark included) lands durably BEFORE the
   reader checkpoint advances — the exactly-once handshake
   (:mod:`photon_tpu.nearline.events`).

Rollback (immediate, or breaker-probation via ``check_probation``)
restores the exact prior bytes: cold rows via the undo record, device
rows via re-scatter of the prior values, appended entities evicted and
forgotten.  Full-resident rollback is a pointer restore of the prior
table + projection arrays (bitwise by construction).
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from photon_tpu.io.cold_store import (
    ColdStoreCapacityError,
    ColdStoreNotUpdatable,
    apply_cold_store_delta,
    normalize_slot_rows,
    rollback_cold_store_delta,
    upgrade_cold_store,
)
from photon_tpu.nearline.delta_trainer import (
    CoordinateDelta,
    DeltaTrainResult,
    _parse_features,
    _row_margin,
    current_entity_row,
)
from photon_tpu.obs.metrics import registry as _metrics
from photon_tpu.resilience import chaos as _chaos
from photon_tpu.resilience import io as rio
from photon_tpu.resilience.failures import record_failure
from photon_tpu.utils import compile_cache, jitcache

MANIFEST_FILE = "nearline-manifest.json"
MANIFEST_SCHEMA = "photon_tpu.nearline.manifest.v1"

_PUBLISH_BUCKETS = tuple(100e-6 * 1.6 ** i for i in range(32))


@dataclasses.dataclass(frozen=True)
class NearlinePublishConfig:
    """Gate thresholds and apply geometry for delta publishes."""

    #: per-row max |new - prior| over the union feature space; inf = off.
    #: Appends are exempt (there is no prior).
    max_row_deviation: float = float("inf")
    #: shadow gate: |actual score delta - host-expected delta| bound
    parity_tol: float = 1e-4
    #: shadow gate skipped below this many touched-entity requests
    min_shadow_requests: int = 0
    #: max touched-entity requests the shadow gate scores
    max_shadow_requests: int = 64
    #: fixed scatter/gather batch (a compiled-program shape)
    publish_batch: int = 64
    #: breaker watch window after an accepted publish; 0 = off
    probation_s: float = 0.0
    #: v1 / capacity-exhausted cold stores are upgraded in place
    auto_upgrade: bool = True


@dataclasses.dataclass
class DeltaPublishResult:
    """Outcome of one delta-publish round."""

    accepted: bool
    version: int
    label: str
    gates: Dict[str, str]
    reason: str = ""
    rows_updated: int = 0
    rows_appended: int = 0
    rows_truncated: int = 0
    rolled_back: bool = False
    shadow_requests: int = 0
    shadow_max_deviation: Optional[float] = None
    coordinates: Dict[str, Dict[str, int]] = dataclasses.field(
        default_factory=dict)

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


# -- fixed-shape publish programs (warmed at publisher construction) ---------


def _pub_scatter(shape: Tuple[int, int], batch: int, dtype) -> object:
    """NON-donated row scatter: builds a republished COPY of a live
    gather table, leaving the original valid for in-flight scorers."""
    import jax

    def build():
        def scatter(table, idx, rows):
            return table.at[idx].set(rows)

        return jax.jit(scatter)

    return jitcache.get_or_build(
        ("nearline_pub_scatter", shape[0], shape[1], batch,
         str(np.dtype(dtype))), build)


def _pub_gather(shape: Tuple[int, int], batch: int, dtype) -> object:
    """Row gather for parity / post-commit readback verification."""
    import jax

    def build():
        def gather(table, idx):
            return table[idx]

        return jax.jit(gather)

    return jitcache.get_or_build(
        ("nearline_row_gather", shape[0], shape[1], batch,
         str(np.dtype(dtype))), build)


def _scatter_rows(scatter, table, idx: np.ndarray, rows: np.ndarray,
                  batch: int, pad_row: int, pad_value: float = 0.0):
    """Apply [N] row writes through the fixed-shape scatter in chunks;
    padding writes ``pad_value`` rows to ``pad_row`` — zero for the
    coef tables (the zero/scratch row), the prior variance for a var
    table (whose unknown row HOLDS the prior, so the pad write must be
    idempotent, not a clobber)."""
    import jax

    for lo in range(0, len(idx), batch):
        i = np.full(batch, pad_row, np.int32)
        r = np.full((batch, rows.shape[1]), pad_value, rows.dtype)
        n = min(batch, len(idx) - lo)
        i[:n] = idx[lo:lo + n]
        r[:n] = rows[lo:lo + n]
        table = scatter(table, jax.device_put(i), jax.device_put(r))
    return table


def _gather_rows(gather, table, idx: np.ndarray, batch: int) -> np.ndarray:
    import jax

    out = []
    for lo in range(0, len(idx), batch):
        i = np.zeros(batch, np.int32)
        n = min(batch, len(idx) - lo)
        i[:n] = idx[lo:lo + n]
        out.append(np.asarray(gather(table, jax.device_put(i)))[:n])
    return (np.concatenate(out) if out
            else np.zeros((0, 1), np.float32))


def _fit_slot_width(coef: np.ndarray, proj: np.ndarray, width: int,
                    var: Optional[np.ndarray] = None,
                    ) -> Tuple[np.ndarray, np.ndarray,
                               Optional[np.ndarray], int]:
    """Normalize candidate rows into the serving slot width.  Rows whose
    valid slots exceed ``width`` keep the largest-|coef| features (count
    returned as truncated).  ``var`` rides the same drops and the same
    slot permutation — a variance belongs to its coefficient."""
    coef = np.asarray(coef, np.float32)
    proj = np.asarray(proj, np.int32)
    if var is not None:
        var = np.asarray(var, np.float32)
    truncated = 0
    nvalid = (proj >= 0).sum(axis=1)
    over = nvalid > width
    if over.any():
        coef = coef.copy()
        proj = proj.copy()
        var = var.copy() if var is not None else None
        for r in np.nonzero(over)[0]:
            valid = np.nonzero(proj[r] >= 0)[0]
            drop = valid[np.argsort(np.abs(coef[r, valid]),
                                    kind="stable")[:len(valid) - width]]
            proj[r, drop] = -1
            coef[r, drop] = 0.0
            if var is not None:
                var[r, drop] = 0.0
            truncated += len(drop)
    if var is not None:
        coef, proj, var = normalize_slot_rows(coef, proj, variances=var)
    else:
        coef, proj = normalize_slot_rows(coef, proj)
    k = coef.shape[1]
    if k < width:
        coef = np.pad(coef, [(0, 0), (0, width - k)])
        proj = np.pad(proj, [(0, 0), (0, width - k)], constant_values=-1)
        if var is not None:
            var = np.pad(var, [(0, 0), (0, width - k)])
    elif k > width:
        coef = np.ascontiguousarray(coef[:, :width])
        proj = np.ascontiguousarray(proj[:, :width])
        if var is not None:
            var = np.ascontiguousarray(var[:, :width])
    return coef, proj, var, truncated


def _union_deviation(coef_a, proj_a, coef_b, proj_b) -> float:
    """max |a - b| over the union of the two rows' feature spaces."""
    a = {int(c): float(v) for c, v in zip(proj_a, coef_a) if c >= 0}
    b = {int(c): float(v) for c, v in zip(proj_b, coef_b) if c >= 0}
    return max((abs(a.get(c, 0.0) - b.get(c, 0.0))
                for c in set(a) | set(b)), default=0.0)


@dataclasses.dataclass
class _CoordPlan:
    """One coordinate's normalized, partitioned publish plan."""

    rs: object
    cid: str
    re_type: str
    shard: str
    upd_ids: List[str]
    upd_coef: np.ndarray               # [U, K] serving layout
    upd_proj: np.ndarray
    upd_prior_coef: np.ndarray         # [U, K] live rows (rollback source)
    upd_prior_proj: np.ndarray
    app_ids: List[str]
    app_coef: np.ndarray               # [A, K]
    app_proj: np.ndarray
    truncated: int = 0
    cold_rows: Optional[np.ndarray] = None   # two-tier: storage rows
    # posterior-variance rows published WITH the means (Thompson
    # coordinates); None = mean-only round, existing variance bytes stay
    upd_var: Optional[np.ndarray] = None     # [U, K]
    app_var: Optional[np.ndarray] = None     # [A, K]


class DeltaPublisher:
    """Pushes delta-trained rows into the live tables behind gates."""

    def __init__(self, engine, model_dir: Optional[str] = None,
                 state_dir: Optional[str] = None,
                 config: Optional[NearlinePublishConfig] = None):
        self.engine = engine
        self.model_dir = model_dir
        self.config = config or NearlinePublishConfig()
        if state_dir is None and model_dir is not None:
            state_dir = os.path.join(model_dir, "nearline")
        self.state_dir = state_dir
        self.version = 0
        self.last_manifest: Optional[dict] = None
        m = self._read_manifest()
        if m is not None:
            self.version = int(m["version"])
            self.last_manifest = m
        self._lock = threading.Lock()     # one publish at a time
        self._last_undo: Optional[dict] = None
        self._probation_until: Optional[float] = None
        self._warm_programs()

    # ------------------------------------------------------------ warmup

    def _warm_programs(self) -> None:
        """Compile the publish scatter/gather for every coordinate
        geometry up front — steady-state publishes dispatch only."""
        batch = self.config.publish_batch
        model = self.engine.model

        def warm(b: int) -> None:
            import jax

            for rs in model.random:
                table = rs.store.table if rs.store is not None else rs.coef
                shape = tuple(table.shape)
                dtype = np.dtype(str(table.dtype))
                sc = _pub_scatter(shape, b, dtype)
                ga = _pub_gather(shape, b, dtype)
                pad = (rs.store._scratch_row if rs.store is not None
                       else rs.unknown_row)
                idx = jax.device_put(np.full(b, pad, np.int32))
                rows = jax.device_put(np.zeros((b, shape[1]), dtype))
                sc(table, idx, rows).block_until_ready()
                ga(table, jax.device_put(
                    np.zeros(b, np.int32))).block_until_ready()

        compile_cache.warmup((batch,), warm)

    # --------------------------------------------------------- manifests

    def _manifest_path(self) -> Optional[str]:
        if self.state_dir is None:
            return None
        return os.path.join(self.state_dir, MANIFEST_FILE)

    def _read_manifest(self) -> Optional[dict]:
        path = self._manifest_path()
        if path is None or not os.path.exists(path):
            return None
        doc = json.loads(rio.read_bytes(path, op="nearline_manifest"))
        if doc.get("schema") != MANIFEST_SCHEMA:
            return None
        crc = doc.pop("crc", None)
        blob = json.dumps(doc, sort_keys=True).encode("utf-8")
        if crc != zlib.crc32(blob) & 0xFFFFFFFF:
            raise ValueError(f"nearline manifest {path}: crc mismatch")
        return doc

    def _write_manifest(self, label: str, watermark: Optional[dict],
                        coords: Dict[str, Dict[str, Any]]) -> None:
        path = self._manifest_path()
        doc = {
            "schema": MANIFEST_SCHEMA,
            "version": self.version,
            "label": label,
            "watermark": watermark,
            "coordinates": coords,
        }
        self.last_manifest = doc
        if path is None:
            return
        os.makedirs(self.state_dir, exist_ok=True)
        blob = json.dumps(doc, sort_keys=True).encode("utf-8")
        out = dict(doc)
        out["crc"] = zlib.crc32(blob) & 0xFFFFFFFF
        rio.atomic_write_bytes(
            path, json.dumps(out, sort_keys=True).encode("utf-8"),
            op="nearline_manifest")

    # ------------------------------------------------------------- gates

    def _fail(self, gates: Dict[str, str], gate: str, reason: str,
              label: str, **kw) -> DeltaPublishResult:
        gates[gate] = "fail"
        _metrics.counter("nearline.publish.rejected", gate=gate).inc()
        record_failure("nearline_publish_rejected", label=label, gate=gate,
                       reason=reason)
        return DeltaPublishResult(False, self.version, label, dict(gates),
                                  reason=reason, **kw)

    def _plan(self, delta, stats: Dict[str, int]) -> List[_CoordPlan]:
        """Normalize candidate rows into serving layout and partition
        into updates vs appends per coordinate."""
        model = self.engine.model
        by_cid = {rs.coordinate_id: rs for rs in model.random}
        coords = (delta.coordinates if isinstance(delta, DeltaTrainResult)
                  else delta)
        plans: List[_CoordPlan] = []
        for cid, cd in sorted(coords.items()):
            rs = by_cid.get(cid)
            if rs is None or not cd.rows:
                if rs is None:
                    stats["unknown_coordinates"] = \
                        stats.get("unknown_coordinates", 0) + 1
                continue
            ids = sorted(cd.rows)
            coef = np.stack([cd.rows[e][0] for e in ids])
            proj = np.stack([cd.rows[e][1] for e in ids])
            # Variance rows ride the same slot normalization when the
            # coordinate serves variances (Thompson) and the delta
            # carries any.  Entities the trainer skipped keep their
            # LIVE variance row (updates) or land zeros (appends), so
            # the full-width variance write stays coherent with the
            # cold-store contract: a mean-only refresh never silently
            # zeroes uncertainty.
            vr = getattr(cd, "var_rows", None) or {}
            serves_var = (getattr(rs, "var_coef", None) is not None
                          or (rs.store is not None
                              and rs.store.cold.has_variances))
            disk_cold = None
            if (vr and not serves_var and rs.store is None
                    and self.model_dir is not None):
                from photon_tpu.io.cold_store import (ColdStore,
                                                      cold_store_path)

                cp = cold_store_path(self.model_dir, rs.coordinate_id)
                if os.path.exists(cp):
                    try:
                        dc = ColdStore(cp)
                        if dc.has_variances:
                            disk_cold = dc
                            serves_var = True
                    except (OSError, ValueError):
                        pass
            var = None
            have_var = None
            if serves_var and vr:
                var = np.zeros(proj.shape, np.float32)
                have_var = np.zeros(len(ids), bool)
                for i, e in enumerate(ids):
                    v = vr.get(e)
                    if v is not None:
                        var[i] = np.asarray(v, np.float32)
                        have_var[i] = True
            coef, proj, var, trunc = _fit_slot_width(coef, proj,
                                                     rs.slot_width, var)
            if var is not None and not have_var.all():
                for i in np.nonzero(~have_var)[0]:
                    lv = self._live_var_row(rs, ids[i], disk_cold)
                    if lv is not None:
                        k = min(len(lv), var.shape[1])
                        var[i, :k] = lv[:k]
            D = model.shard_dims.get(rs.feature_shard_id, 1)
            upd_i, app_i, priors, cold_rows = [], [], [], []
            for i, e in enumerate(ids):
                live = current_entity_row(rs, e, D)
                if live is None:
                    app_i.append(i)
                    continue
                upd_i.append(i)
                if rs.store is not None:
                    # the prior is the row the scorer SERVES: for a hot
                    # entity that is the hot-tier row + proj mirror, which
                    # can diverge from the cold tier after a torn publish
                    # (replay-from-watermark recovery heals cold first)
                    with rs.store.lock:
                        s = rs.store.hot_slot_locked(e)
                        if s is not None:
                            live = (np.asarray(rs.store.table[s],
                                               np.float32),
                                    np.array(rs.store.proj_row_locked(s),
                                             np.int32))
                    cold_rows.append(rs.store.cold.entity_row(e))
                priors.append(live)
            K = rs.slot_width
            plans.append(_CoordPlan(
                rs=rs, cid=cid, re_type=cd.random_effect_type,
                shard=rs.feature_shard_id,
                upd_ids=[ids[i] for i in upd_i],
                upd_coef=coef[upd_i], upd_proj=proj[upd_i],
                upd_prior_coef=(np.stack([p[0] for p in priors])
                                if priors else np.zeros((0, K), np.float32)),
                upd_prior_proj=(np.stack([p[1] for p in priors])
                                if priors else np.full((0, K), -1, np.int32)),
                app_ids=[ids[i] for i in app_i],
                app_coef=coef[app_i], app_proj=proj[app_i],
                truncated=trunc,
                cold_rows=(np.asarray(cold_rows, np.int64)
                           if rs.store is not None else None),
                upd_var=(var[upd_i] if var is not None else None),
                app_var=(var[app_i] if var is not None else None)))
        return plans

    @staticmethod
    def _live_var_row(rs, entity_id: str,
                      disk_cold) -> Optional[np.ndarray]:
        """The variance row ``entity_id`` currently serves with, in
        serving layout — the fill for delta entities whose variance the
        trainer skipped (their update must not disturb live bytes)."""
        if rs.store is not None and rs.store.cold.has_variances:
            r = rs.store.cold.entity_row(entity_id)
            if r is not None:
                return rs.store.cold.read_var_rows(
                    np.asarray([r], np.int64))[0]
            return None
        if getattr(rs, "var_coef", None) is not None:
            er = rs.entity_rows.get(entity_id)
            if er is not None:
                return np.asarray(rs.var_coef[er], np.float32)
            return None
        if disk_cold is not None:
            r = disk_cold.entity_row(entity_id)
            if r is not None:
                return disk_cold.read_var_rows(
                    np.asarray([r], np.int64))[0]
        return None

    def _expected_delta(self, request, plans: List[_CoordPlan],
                        hot_slots: Dict[str, Dict[str, int]]) -> float:
        """Host-computed score delta the staged tables should produce
        for one request.  Until the commit also lands the new slot
        projection, the assemble path maps request features to slots
        through the PRIOR projection — so the staged-table margin is the
        new coefficient bytes read through the old slot mapping:
        sum_j val(prior_proj[j]) * (new_row[j] - prior_row[j]) over the
        touched entities this request can actually SEE pre-promotion
        (hot slots for two-tier, resident rows for full-resident).  The
        RE margin is linear in the row, so this is exact, not a bound."""
        model = self.engine.model
        stats: Dict[str, int] = {}
        total = 0.0
        for p in plans:
            re_id = request.entity_ids.get(p.re_type)
            if re_id is None or re_id not in p.upd_ids:
                continue
            if p.rs.store is not None and re_id not in hot_slots[p.cid]:
                continue  # cold rows gather the zero row in both tables
            i = p.upd_ids.index(re_id)
            cols, vals = _parse_features(
                {"features": request.features}, p.shard,
                model.index_maps[p.shard], stats)
            prior_proj = p.upd_prior_proj[i]
            total += (_row_margin(cols, vals, p.upd_coef[i], prior_proj)
                      - _row_margin(cols, vals, p.upd_prior_coef[i],
                                    prior_proj))
        return total

    # ----------------------------------------------------------- publish

    def publish(self, delta, label: str,
                watermark: Optional[dict] = None) -> DeltaPublishResult:
        """One gated delta-publish round.  ``delta`` is a
        :class:`~photon_tpu.nearline.delta_trainer.DeltaTrainResult` (or
        a ``{cid: CoordinateDelta}`` mapping)."""
        with self._lock:
            return self._publish_locked(delta, label, watermark)

    def _publish_locked(self, delta, label: str,
                        watermark: Optional[dict]) -> DeltaPublishResult:
        import jax

        t0 = time.perf_counter()
        engine = self.engine
        model = engine.model
        cfg = self.config
        gates: Dict[str, str] = {}
        stats: Dict[str, int] = {}
        _metrics.counter("nearline.publish.attempts").inc()

        plans = self._plan(delta, stats)
        n_upd = sum(len(p.upd_ids) for p in plans)
        n_app = sum(len(p.app_ids) for p in plans)
        n_trunc = sum(p.truncated for p in plans)
        if n_trunc:
            _metrics.counter("nearline.publish.rows_truncated").inc(n_trunc)
        if not plans:
            return DeltaPublishResult(True, self.version, label,
                                      {"empty": "skip"})

        # finite: every candidate row, before anything is locked
        for p in plans:
            for arr in (p.upd_coef, p.app_coef):
                if arr.size and not np.isfinite(arr).all():
                    return self._fail(gates, "finite",
                                      f"non-finite candidate rows in "
                                      f"{p.cid!r}", label,
                                      rows_truncated=n_trunc)
        gates["finite"] = "pass"

        # variance: published uncertainty must be finite and
        # non-negative — a NaN or negative variance row would make the
        # Thompson sampler emit NaN scores (sqrt of the row) for every
        # request that gathers it
        if any(p.upd_var is not None or p.app_var is not None
               for p in plans):
            for p in plans:
                for arr in (p.upd_var, p.app_var):
                    if arr is not None and arr.size and not (
                            np.isfinite(arr).all() and (arr >= 0).all()):
                        return self._fail(
                            gates, "variance",
                            f"non-finite or negative variance rows in "
                            f"{p.cid!r}", label, rows_truncated=n_trunc)
            gates["variance"] = "pass"
        else:
            gates["variance"] = "skip"

        # deviation: |new - prior| over the union feature space
        if np.isfinite(cfg.max_row_deviation):
            for p in plans:
                for i, e in enumerate(p.upd_ids):
                    dev = _union_deviation(p.upd_coef[i], p.upd_proj[i],
                                           p.upd_prior_coef[i],
                                           p.upd_prior_proj[i])
                    if dev > cfg.max_row_deviation:
                        return self._fail(
                            gates, "deviation",
                            f"{p.cid!r}/{e!r} deviates {dev:.3e} > "
                            f"{cfg.max_row_deviation:.3e}", label,
                            rows_truncated=n_trunc)
        gates["deviation"] = "pass" if np.isfinite(cfg.max_row_deviation) \
            else "skip"

        # capacity: cold reserve (two-tier, auto-upgradable) / append
        # reserve rows (full-resident, a typed hard failure)
        for p in plans:
            if p.rs.store is not None:
                err = self._ensure_cold_capacity(p)
                if err:
                    return self._fail(gates, "capacity", err, label,
                                      rows_truncated=n_trunc)
            elif len(p.app_ids) > p.rs.append_reserve - p.rs.append_used:
                free = p.rs.append_reserve - p.rs.append_used
                return self._fail(
                    gates, "capacity",
                    f"{p.cid!r}: {len(p.app_ids)} appends > {free} free "
                    f"reserve rows (ServingConfig.append_reserve)", label,
                    rows_truncated=n_trunc)
        gates["capacity"] = "pass"

        touched = frozenset((p.re_type, e) for p in plans
                            for e in (p.upd_ids + p.app_ids))
        # 1) stop admission lookahead from prefetching touched entities
        engine.pending_publish_rows = touched
        # 2) pause transfer cycles on every touched two-tier store
        plocks = [p.rs.store.publish_lock for p in plans
                  if p.rs.store is not None]
        for lk in plocks:
            lk.acquire()
        committed: List[dict] = []
        try:
            steady0 = compile_cache.compile_counts().get("steady_state", 0)

            # staging: republished table copies + hot-slot resolution.
            # Transfers are paused, so store.table cannot change under us;
            # scoring keeps gathering the ORIGINAL tables untouched.
            staged: Dict[str, Any] = {}
            hot_slots: Dict[str, Dict[str, int]] = {}
            batch = cfg.publish_batch
            for p in plans:
                rs = p.rs
                if rs.store is not None:
                    with rs.store.lock:
                        hs = {e: s for e in p.upd_ids
                              if (s := rs.store.hot_slot_locked(e))
                              is not None}
                    hot_slots[p.cid] = hs
                    table = rs.store.table
                    idx = np.asarray([hs[e] for e in p.upd_ids
                                      if e in hs], np.int32)
                    rows = (p.upd_coef[[i for i, e in enumerate(p.upd_ids)
                                        if e in hs]]
                            if len(idx) else
                            np.zeros((0, rs.slot_width), np.float32))
                    pad = rs.store._scratch_row
                else:
                    hot_slots[p.cid] = {}
                    table = rs.coef
                    upd_rows = np.asarray(
                        [rs.entity_rows[e] for e in p.upd_ids], np.int32)
                    app_rows = np.arange(len(p.app_ids), dtype=np.int32) \
                        + rs.unknown_row + 1 + rs.append_used
                    idx = np.concatenate([upd_rows, app_rows])
                    rows = np.concatenate([p.upd_coef, p.app_coef]) \
                        if len(idx) else np.zeros((0, rs.slot_width),
                                                  np.float32)
                    pad = rs.unknown_row
                dtype = np.dtype(str(table.dtype))
                sc = _pub_scatter(tuple(table.shape), batch, dtype)
                ga = _pub_gather(tuple(table.shape), batch, dtype)
                new_table = (_scatter_rows(sc, table, idx,
                                           rows.astype(dtype), batch, pad)
                             if len(idx) else table)
                staged[p.cid] = (new_table, idx, rows, sc, ga, pad)
            gates["staging"] = "pass"

            # parity: gather the staged rows back — bitwise vs intended
            for p in plans:
                new_table, idx, rows, _sc, ga, _pad = staged[p.cid]
                if not len(idx):
                    continue
                got = _gather_rows(ga, new_table, idx, batch)
                if got.astype(np.float32).tobytes() != \
                        rows.astype(np.float32).tobytes():
                    return self._fail(gates, "parity",
                                      f"{p.cid!r}: staged rows differ from "
                                      f"intended rows", label,
                                      rows_truncated=n_trunc)
            gates["parity"] = "pass"

            # shadow: touched-entity requests through live vs staged
            # tables; actual score delta must match the host expectation
            sample = [r for r in engine.recent_requests()
                      if any((t, i) in touched
                             for t, i in r.entity_ids.items())]
            sample = sample[-cfg.max_shadow_requests:]
            shadow_n = len(sample)
            max_dev: Optional[float] = None
            if shadow_n >= max(cfg.min_shadow_requests, 1):
                from photon_tpu.serving.scorer import get_scorer

                cid_pos = {rs.coordinate_id: k
                           for k, rs in enumerate(model.random)}
                devs = []
                top = engine.ladder.max_batch
                for lo in range(0, shadow_n, top):
                    chunk = sample[lo:lo + top]
                    bucket = engine.ladder.bucket_for(len(chunk))
                    with model.transfer_lock:
                        args, _fb, _c = model.assemble(chunk, bucket)
                        thetas = model.current_thetas()
                        tables = list(model.current_tables())
                        live = np.asarray(get_scorer(model, "full", bucket)(
                            *args, thetas, tuple(tables)))[:len(chunk)]
                        for p in plans:
                            tables[cid_pos[p.cid]] = staged[p.cid][0]
                        cand = np.asarray(get_scorer(model, "full", bucket)(
                            *args, thetas, tuple(tables)))[:len(chunk)]
                    for j, r in enumerate(chunk):
                        want = self._expected_delta(r, plans, hot_slots)
                        devs.append(abs(float(cand[j] - live[j]) - want))
                max_dev = max(devs, default=0.0)
                if max_dev > cfg.parity_tol:
                    return self._fail(
                        gates, "shadow",
                        f"shadow delta off by {max_dev:.3e} > "
                        f"{cfg.parity_tol:.3e} over {shadow_n} requests",
                        label, shadow_requests=shadow_n,
                        shadow_max_deviation=max_dev,
                        rows_truncated=n_trunc)
                gates["shadow"] = "pass"
            else:
                gates["shadow"] = "skip"

            steady1 = compile_cache.compile_counts().get("steady_state", 0)
            if steady1 != steady0:
                return self._fail(gates, "compiles",
                                  f"{steady1 - steady0} steady-state "
                                  f"compiles during staging/shadow", label,
                                  shadow_requests=shadow_n,
                                  rows_truncated=n_trunc)
            gates["compiles"] = "pass"

            # chaos: poison the final written payload AFTER the gates —
            # the post-commit readback must catch it and roll back
            poisoned = _chaos.should_poison_publish_row()
            written: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
            for p in plans:
                wc = p.upd_coef.copy()
                wa = p.app_coef.copy()
                if poisoned:
                    if len(wc):
                        wc[0, 0] = np.nan
                    elif len(wa):
                        wa[0, 0] = np.nan
                    poisoned = False  # one row, first coordinate
                written[p.cid] = (wc, wa)

            # commit: atomic per batch boundary under the transfer lock
            with model.transfer_lock:
                for p in plans:
                    committed.append(self._commit_coord(
                        p, staged[p.cid], written[p.cid], hot_slots[p.cid],
                        batch))
                verify_err = self._verify_readback(plans, batch)
            if verify_err:
                self._rollback(committed, touched, locked=True)
                _metrics.counter("nearline.publish.rollbacks").inc()
                record_failure("nearline_publish_verify_failed",
                               label=label, detail=verify_err)
                gates["verify"] = "fail"
                return DeltaPublishResult(
                    False, self.version, label, dict(gates),
                    reason=f"post-commit readback mismatch: {verify_err}",
                    rolled_back=True, shadow_requests=shadow_n,
                    shadow_max_deviation=max_dev, rows_truncated=n_trunc)
            gates["verify"] = "pass"
        finally:
            for lk in reversed(plocks):
                lk.release()
            engine.pending_publish_rows = frozenset()

        # durable manifest BEFORE the caller advances its checkpoint —
        # the exactly-once half the events module documents
        self.version += 1
        coords_doc = {
            p.cid: {
                "updated": list(p.upd_ids),
                "appended": list(p.app_ids),
                "row_crc": zlib.crc32(
                    written[p.cid][0].tobytes()
                    + written[p.cid][1].tobytes()
                    + (p.upd_var.tobytes() if p.upd_var is not None
                       else b"")
                    + (p.app_var.tobytes() if p.app_var is not None
                       else b"")) & 0xFFFFFFFF,
            } for p in plans}
        self._write_manifest(label, watermark, coords_doc)
        self._last_undo = {"label": label, "version": self.version,
                           "touched": touched, "coords": committed}
        if cfg.probation_s > 0:
            self._probation_until = engine.clock() + cfg.probation_s

        _metrics.counter("nearline.publish.accepted").inc()
        _metrics.counter("nearline.publish.rows_updated").inc(n_upd)
        _metrics.counter("nearline.publish.rows_appended").inc(n_app)
        _metrics.histogram("nearline.publish.seconds",
                           buckets=_PUBLISH_BUCKETS).observe(
            time.perf_counter() - t0)
        return DeltaPublishResult(
            True, self.version, label, dict(gates),
            rows_updated=n_upd, rows_appended=n_app, rows_truncated=n_trunc,
            shadow_requests=shadow_n, shadow_max_deviation=max_dev,
            coordinates={p.cid: {"updated": len(p.upd_ids),
                                 "appended": len(p.app_ids)}
                         for p in plans})

    # ---------------------------------------------------------- capacity

    def _ensure_cold_capacity(self, p: _CoordPlan) -> str:
        """Make the cold file updatable with room for the appends;
        returns an error string when it cannot be."""
        rs = p.rs
        cold = rs.store.cold
        need_rows = cold.num_entities + len(p.app_ids)
        blob_need = sum(len(e.encode("utf-8")) for e in p.app_ids)
        needs_upgrade = not cold.updatable
        if cold.updatable:
            h = cold._h
            if (need_rows > cold.capacity
                    or h["id_blob_used"] + blob_need > h["id_blob_len"]):
                needs_upgrade = True
        if not needs_upgrade:
            return ""
        if not self.config.auto_upgrade:
            return (f"{p.cid!r}: cold store "
                    f"{'not updatable (v1)' if not cold.updatable else 'full'}"
                    f" and auto_upgrade is off")
        try:
            cap = max(need_rows * 2, 64)
            # the capacity gate runs before lock acquisition, so the
            # upgrade + refresh take the publish and store locks here
            with rs.store.publish_lock:
                upgrade_cold_store(
                    cold.path, capacity=cap,
                    id_blob_cap=2 * (cold._h["id_blob_used"] + blob_need)
                    + 256 if cold.updatable else None)
                with rs.store.lock:
                    rs.store.refresh_cold_locked()
            _metrics.counter("nearline.publish.cold_upgrades").inc()
            # re-resolve the plan's storage rows against the new file
            if p.cold_rows is not None and len(p.upd_ids):
                cold2 = rs.store.cold
                p.cold_rows = np.asarray(
                    [cold2.entity_row(e) for e in p.upd_ids], np.int64)
            return ""
        except (OSError, ColdStoreNotUpdatable,
                ColdStoreCapacityError) as e:
            return f"{p.cid!r}: cold upgrade failed: {e!r}"

    # ------------------------------------------------------------ commit

    def _commit_coord(self, p: _CoordPlan, staged_entry, written,
                      hs: Dict[str, int], batch: int) -> dict:
        """Apply one coordinate's rows (caller holds transfer_lock and,
        for two-tier, the store's publish_lock). Returns the undo
        record."""
        import jax

        rs = p.rs
        wc, wa = written
        new_table, idx, rows, sc, ga, pad = staged_entry
        if rs.store is not None:
            cold = rs.store.cold
            undo = apply_cold_store_delta(
                cold.path,
                update_rows=p.cold_rows if len(p.upd_ids) else None,
                update_coef=wc if len(p.upd_ids) else None,
                update_proj=p.upd_proj if len(p.upd_ids) else None,
                append_ids=p.app_ids,
                append_coef=wa if len(p.app_ids) else None,
                append_proj=p.app_proj if len(p.app_ids) else None,
                update_var=(p.upd_var if cold.has_variances
                            and p.upd_var is not None
                            and len(p.upd_ids) else None),
                append_var=(p.app_var if cold.has_variances
                            and p.app_var is not None
                            and len(p.app_ids) else None),
                normalize=False)
            # the staged table was built from the intended rows; if the
            # written payload differs (chaos poison) re-scatter so table
            # and cold agree — readback then catches both
            if wc.tobytes() != p.upd_coef.tobytes() and len(idx):
                rows2 = wc[[i for i, e in enumerate(p.upd_ids) if e in hs]]
                new_table = _scatter_rows(
                    sc, new_table, idx, rows2.astype(rows.dtype), batch, pad)
            with rs.store.lock:
                rs.store.commit_table_locked(new_table)
                for i, e in enumerate(p.upd_ids):
                    if e in hs:
                        rs.store.set_hot_proj_locked(hs[e], p.upd_proj[i])
                rs.store.refresh_cold_locked()
            return {"kind": "two_tier", "plan": p, "undo": undo,
                    "hot_slots": dict(hs)}
        # full-resident
        if wc.tobytes() != p.upd_coef.tobytes() and len(idx):
            rows2 = np.concatenate([wc, wa]) if len(idx) else rows
            new_table = _scatter_rows(
                sc, rs.coef, idx, rows2.astype(rows.dtype), batch, pad)
        prior = {"kind": "full", "plan": p, "prior_table": rs.coef,
                 "prior_pkeys": rs.pkeys_sorted,
                 "prior_pslots": rs.pslots_sorted,
                 "prior_append_used": rs.append_used,
                 "prior_coef_q": rs.coef_q, "prior_scales": rs.scales,
                 "prior_var_table": getattr(rs, "var_coef", None),
                 "cold_undo": None, "cold_path": None}
        model = self.engine.model
        D = max(model.shard_dims.get(rs.feature_shard_id, 1), 1)
        app_rows = np.arange(len(p.app_ids), dtype=np.int64) \
            + rs.unknown_row + 1 + rs.append_used
        # splice the projection lookup: drop the updated entities' keys,
        # insert the new (entity * D + col) -> slot pairs, re-sort stable
        keep = np.ones(len(rs.pkeys_sorted), bool)
        ent_of = {e: rs.entity_rows[e] for e in p.upd_ids}
        for e in p.upd_ids:
            er = ent_of[e]
            lo = np.searchsorted(rs.pkeys_sorted, er * D)
            hi = np.searchsorted(rs.pkeys_sorted, (er + 1) * D)
            keep[lo:hi] = False
        add_keys, add_slots = [], []
        for i, e in enumerate(p.upd_ids):
            valid = np.nonzero(p.upd_proj[i] >= 0)[0]
            add_keys.append(ent_of[e] * D
                            + p.upd_proj[i][valid].astype(np.int64))
            add_slots.append(valid.astype(np.int64))
        for j, e in enumerate(p.app_ids):
            valid = np.nonzero(p.app_proj[j] >= 0)[0]
            add_keys.append(int(app_rows[j]) * D
                            + p.app_proj[j][valid].astype(np.int64))
            add_slots.append(valid.astype(np.int64))
        pk = np.concatenate([rs.pkeys_sorted[keep]] + add_keys) \
            if add_keys else rs.pkeys_sorted[keep]
        psl = np.concatenate([rs.pslots_sorted[keep]] + add_slots) \
            if add_slots else rs.pslots_sorted[keep]
        order = np.argsort(pk, kind="stable")
        rs.coef = new_table
        # int8 serving arm: the quantized mirror must track every row
        # publish or the dequantizing "full_int8" programs would serve
        # stale coefficients. Quantization is row-local and deterministic
        # (model_state.quantize_rows), so requantizing ONLY the written
        # rows reproduces a from-scratch staging of the new table; the
        # prior (coef_q, scales) objects ride the undo record above.
        if rs.coef_q is not None and len(idx):
            from photon_tpu.serving.model_state import quantize_rows

            wrows = np.concatenate([wc, wa]) \
                if wc.tobytes() != p.upd_coef.tobytes() else rows
            qrows, srows = quantize_rows(np.asarray(wrows, np.float32))
            qsc = _pub_scatter(tuple(rs.coef_q.shape), batch, np.int8)
            ssc = _pub_scatter(tuple(rs.scales.shape), batch, np.float32)
            rs.coef_q = _scatter_rows(qsc, rs.coef_q, idx, qrows, batch, pad)
            rs.scales = _scatter_rows(ssc, rs.scales, idx, srows, batch, pad)
        # Thompson arm: the resident variance table tracks every row
        # publish in the same transaction, or the sampler would explore
        # a fresh mean with STALE uncertainty. Pad writes target the
        # unknown row, which holds the prior variance — so the pad value
        # is the prior, making the padding idempotent instead of a
        # cold-start-exploration clobber.
        if getattr(rs, "var_coef", None) is not None \
                and p.upd_var is not None and len(idx):
            vrows = np.concatenate([p.upd_var, p.app_var])
            vsc = _pub_scatter(tuple(rs.var_coef.shape), batch, np.float32)
            rs.var_coef = _scatter_rows(
                vsc, rs.var_coef, idx, vrows.astype(np.float32), batch,
                pad, pad_value=float(getattr(model, "prior_variance", 1.0)))
        rs.pkeys_sorted = pk[order]
        rs.pslots_sorted = psl[order]
        for j, e in enumerate(p.app_ids):
            rs.entity_rows[e] = int(app_rows[j])
        rs.append_used += len(p.app_ids)
        # keep the on-disk cold store current so delta-trainer warm
        # starts and a later fixed-refresh swap see the published rows
        if self.model_dir is not None:
            from photon_tpu.io.cold_store import ColdStore, cold_store_path

            cp = cold_store_path(self.model_dir, rs.coordinate_id)
            if os.path.exists(cp):
                try:
                    disk = ColdStore(cp)
                    if not disk.updatable and self.config.auto_upgrade:
                        upgrade_cold_store(
                            cp, capacity=max(
                                2 * (disk.num_entities
                                     + len(p.app_ids)), 64))
                        disk = ColdStore(cp)
                    if disk.updatable:
                        crs = np.asarray(
                            [disk.entity_row(e) for e in p.upd_ids],
                            np.int64) if p.upd_ids else None
                        prior["cold_undo"] = apply_cold_store_delta(
                            cp, update_rows=crs,
                            update_coef=wc if len(p.upd_ids) else None,
                            update_proj=(p.upd_proj if len(p.upd_ids)
                                         else None),
                            append_ids=p.app_ids,
                            append_coef=wa if len(p.app_ids) else None,
                            append_proj=(p.app_proj if len(p.app_ids)
                                         else None),
                            update_var=(p.upd_var
                                        if disk.has_variances
                                        and p.upd_var is not None
                                        and len(p.upd_ids) else None),
                            append_var=(p.app_var
                                        if disk.has_variances
                                        and p.app_var is not None
                                        and len(p.app_ids) else None),
                            normalize=False)
                        prior["cold_path"] = cp
                except (ColdStoreCapacityError, ColdStoreNotUpdatable,
                        OSError, ValueError) as e:
                    _metrics.counter(
                        "nearline.publish.cold_mirror_errors").inc()
                    record_failure("nearline_cold_mirror_failed",
                                   coordinate=rs.coordinate_id,
                                   error=repr(e))
        return prior

    def _verify_readback(self, plans: List[_CoordPlan],
                         batch: int) -> str:
        """Re-gather every published row (device + cold) and compare
        BITWISE against the INTENDED rows — not the written payload, or
        a corruption between the gates and the commit (chaos
        ``publish_poison_row``) would read back as consistent."""
        for p in plans:
            rs = p.rs
            wc, wa = p.upd_coef, p.app_coef
            if rs.store is not None:
                cold = rs.store.cold
                if len(p.upd_ids):
                    got = cold.read_rows(p.cold_rows)
                    if got.astype(np.float32).tobytes() != wc.tobytes():
                        return f"{p.cid}: cold updated rows mismatch"
                for j, e in enumerate(p.app_ids):
                    r = cold.entity_row(e)
                    if r is None:
                        return f"{p.cid}: appended {e!r} missing from cold"
                    if np.asarray(cold.coef[r], np.float32).tobytes() != \
                            wa[j].tobytes():
                        return f"{p.cid}: appended {e!r} bytes mismatch"
                if cold.has_variances and p.upd_var is not None:
                    if len(p.upd_ids):
                        got = cold.read_var_rows(p.cold_rows)
                        if got.astype(np.float32).tobytes() != \
                                p.upd_var.astype(np.float32).tobytes():
                            return f"{p.cid}: cold variance rows mismatch"
                    for j, e in enumerate(p.app_ids):
                        r = cold.entity_row(e)
                        if r is not None and np.asarray(
                                cold.var[r], np.float32).tobytes() != \
                                p.app_var[j].astype(np.float32).tobytes():
                            return (f"{p.cid}: appended {e!r} variance "
                                    f"bytes mismatch")
                with rs.store.lock:
                    hs = {e: s for e in p.upd_ids
                          if (s := rs.store.hot_slot_locked(e)) is not None}
                    table = rs.store.table
                if hs:
                    ga = _pub_gather(tuple(table.shape), batch,
                                     np.dtype(str(table.dtype)))
                    idx = np.asarray(list(hs.values()), np.int32)
                    rows = wc[[i for i, e in enumerate(p.upd_ids)
                               if e in hs]]
                    got = _gather_rows(ga, table, idx, batch)
                    if got.astype(np.float32).tobytes() != rows.tobytes():
                        return f"{p.cid}: hot rows mismatch"
            else:
                ga = _pub_gather(tuple(rs.coef.shape), batch,
                                 np.dtype(str(rs.coef.dtype)))
                idx = np.asarray(
                    [rs.entity_rows[e] for e in p.upd_ids + p.app_ids],
                    np.int32)
                if len(idx):
                    want = np.concatenate([wc, wa])
                    got = _gather_rows(ga, rs.coef, idx, batch)
                    if got.astype(np.float32).tobytes() != \
                            want.astype(np.float32).tobytes():
                        return f"{p.cid}: resident rows mismatch"
                if getattr(rs, "var_coef", None) is not None \
                        and p.upd_var is not None and len(idx):
                    vga = _pub_gather(tuple(rs.var_coef.shape), batch,
                                      np.float32)
                    vwant = np.concatenate([p.upd_var, p.app_var])
                    vgot = _gather_rows(vga, rs.var_coef, idx, batch)
                    if vgot.astype(np.float32).tobytes() != \
                            vwant.astype(np.float32).tobytes():
                        return f"{p.cid}: resident variance rows mismatch"
        return ""

    # ---------------------------------------------------------- rollback

    def rollback_last(self, why: str = "operator rollback") -> bool:
        """Bitwise-restore the rows of the most recent accepted publish.
        Returns False when there is nothing to roll back."""
        with self._lock:
            last = self._last_undo
            if last is None:
                return False
            self._last_undo = None
            self._probation_until = None
            engine = self.engine
            engine.pending_publish_rows = last["touched"]
            stores = {c["plan"].cid: c["plan"].rs.store
                      for c in last["coords"]
                      if c["plan"].rs.store is not None}
            locks = [stores[k].publish_lock for k in sorted(stores)]
            for lk in locks:
                lk.acquire()
            try:
                with engine.model.transfer_lock:
                    self._rollback(last["coords"], last["touched"],
                                   locked=True)
            finally:
                for lk in reversed(locks):
                    lk.release()
                engine.pending_publish_rows = frozenset()
            _metrics.counter("nearline.publish.rollbacks").inc()
            record_failure("nearline_publish_rollback", why=why,
                           label=last["label"], version=last["version"])
            return True

    def _rollback(self, committed: List[dict], touched: frozenset,
                  locked: bool) -> None:
        """Row-level restore; caller holds transfer_lock (+ publish
        locks).  Survives interim promotions: prior values re-scatter at
        the entities' CURRENT hot slots, not remembered ones."""
        batch = self.config.publish_batch
        for c in reversed(committed):
            p = c["plan"]
            rs = p.rs
            if c["kind"] == "two_tier":
                rollback_cold_store_delta(rs.store.cold.path, c["undo"])
                with rs.store.lock:
                    # appends vanish from the refreshed cold -> evicted
                    rs.store.refresh_cold_locked()
                    hs = {e: s for e in p.upd_ids
                          if (s := rs.store.hot_slot_locked(e))
                          is not None}
                    table = rs.store.table
                    if hs:
                        dtype = np.dtype(str(table.dtype))
                        sc = _pub_scatter(tuple(table.shape), batch, dtype)
                        sel = [i for i, e in enumerate(p.upd_ids)
                               if e in hs]
                        idx = np.asarray([hs[p.upd_ids[i]] for i in sel],
                                         np.int32)
                        rows = p.upd_prior_coef[sel].astype(dtype)
                        table = _scatter_rows(sc, table, idx, rows, batch,
                                              rs.store._scratch_row)
                        rs.store.commit_table_locked(table)
                        for i in sel:
                            rs.store.set_hot_proj_locked(
                                hs[p.upd_ids[i]], p.upd_prior_proj[i])
            else:
                rs.coef = c["prior_table"]
                rs.coef_q = c.get("prior_coef_q")
                rs.scales = c.get("prior_scales")
                rs.var_coef = c.get("prior_var_table")
                rs.pkeys_sorted = c["prior_pkeys"]
                rs.pslots_sorted = c["prior_pslots"]
                for e in p.app_ids:
                    rs.entity_rows.pop(e, None)
                rs.append_used = c["prior_append_used"]
                if c.get("cold_undo") is not None:
                    rollback_cold_store_delta(c["cold_path"],
                                              c["cold_undo"])

    # --------------------------------------------------------- probation

    def check_probation(self) -> bool:
        """Roll the last publish back if the breaker degraded inside the
        probation window (mirrors the engine's post-swap probation).
        Returns True when a rollback happened."""
        until = self._probation_until
        if until is None:
            return False
        engine = self.engine
        if engine.clock() > until:
            self._probation_until = None
            return False
        from photon_tpu.serving.breaker import OPEN, SHED

        if engine.breaker.state() in (SHED, OPEN):
            return self.rollback_last(
                "breaker tripped in post-publish probation")
        return False


# -- entity-sharded fleet fan-out ---------------------------------------------


@dataclasses.dataclass
class FleetPublishResult:
    """Outcome of one fleet publish round: all-or-nothing across shards."""

    accepted: bool
    label: str
    #: shard id -> that shard's DeltaPublishResult (only shards that own
    #: rows in the delta appear; untouched shards are never called)
    shards: Dict[int, DeltaPublishResult] = dataclasses.field(
        default_factory=dict)
    reason: str = ""
    #: shards whose already-committed rows were bitwise-restored because a
    #: later shard's gates rejected the round
    rolled_back_shards: List[int] = dataclasses.field(default_factory=list)
    rows_updated: int = 0
    rows_appended: int = 0

    def to_json(self) -> dict:
        return {
            "accepted": self.accepted,
            "label": self.label,
            "reason": self.reason,
            "rolled_back_shards": list(self.rolled_back_shards),
            "rows_updated": self.rows_updated,
            "rows_appended": self.rows_appended,
            "shards": {str(s): r.to_json() for s, r in self.shards.items()},
        }


class FleetDeltaPublisher:
    """Routes row publishes to owning shards of an entity-sharded fleet.

    One `DeltaPublisher` per shard engine, each with its own state dir
    (``fleet_dir/shard_XXXXX/nearline`` — per-shard versioned manifest,
    same exactly-once handshake as single-host: the SHARED watermark
    lands in every touched shard's manifest before the reader checkpoint
    may advance). Each delta row goes to exactly the shard the canonical
    partitioner (`parallel/partition.entity_shard`) owns it on — the
    same hash that split the cold stores and that routes serve traffic —
    so untouched shards are never called and their stores stay
    byte-identical.

    The fleet round is all-or-nothing: shards publish in shard-id order,
    and if any shard's gate ladder rejects, every shard that already
    committed this round is bitwise-restored via its own
    ``rollback_last`` before the rejection is returned.
    """

    def __init__(self, fleet, fleet_dir: str,
                 config: Optional[NearlinePublishConfig] = None):
        from photon_tpu.io.fleet_store import shard_dir

        self.fleet = fleet
        self.num_shards = fleet.num_shards
        self.publishers: Dict[int, DeltaPublisher] = {
            c.shard_id: DeltaPublisher(
                c.engine,
                state_dir=os.path.join(shard_dir(fleet_dir, c.shard_id),
                                       "nearline"),
                config=config)
            for c in fleet.clients}
        self._lock = threading.Lock()

    def route_rows(self, delta) -> Dict[int, Dict[str, CoordinateDelta]]:
        """Split a delta's rows by owning shard -> per-shard
        ``{cid: CoordinateDelta}`` subsets (event_ts subset to match).
        Pure partitioner application — exposed so tests can pin
        publish routing == serve routing == file layout."""
        from photon_tpu.parallel.partition import entity_shard

        coords = (delta.coordinates
                  if isinstance(delta, DeltaTrainResult) else delta)
        out: Dict[int, Dict[str, CoordinateDelta]] = {}
        for cid, cd in coords.items():
            by_shard: Dict[int, Dict[str, Tuple[np.ndarray, np.ndarray]]] = {}
            for eid, row in cd.rows.items():
                by_shard.setdefault(
                    entity_shard(eid, self.num_shards), {})[eid] = row
            vr = getattr(cd, "var_rows", None) or {}
            for s, rows in by_shard.items():
                out.setdefault(s, {})[cid] = CoordinateDelta(
                    coordinate_id=cd.coordinate_id,
                    random_effect_type=cd.random_effect_type,
                    feature_shard_id=cd.feature_shard_id,
                    rows=rows,
                    event_ts={e: cd.event_ts[e] for e in rows
                              if e in cd.event_ts},
                    num_events=cd.num_events,
                    var_rows={e: vr[e] for e in rows if e in vr})
        return out

    def publish(self, delta, label: str,
                watermark: Optional[dict] = None) -> FleetPublishResult:
        """One all-or-nothing fleet publish round. ``delta`` is a
        `DeltaTrainResult` or ``{cid: CoordinateDelta}``; ``watermark``
        is the shared reader position recorded in every touched shard's
        manifest."""
        with self._lock:
            routed = self.route_rows(delta)
            result = FleetPublishResult(accepted=True, label=label)
            committed: List[int] = []
            for s in sorted(routed):
                res = self.publishers[s].publish(routed[s], label,
                                                 watermark)
                result.shards[s] = res
                if not res.accepted:
                    result.accepted = False
                    result.reason = (f"shard {s} rejected: {res.reason}"
                                     if res.reason else f"shard {s} rejected")
                    for c in committed:
                        if self.publishers[c].rollback_last(
                                f"fleet round {label!r} aborted by "
                                f"shard {s}"):
                            result.rolled_back_shards.append(c)
                    _metrics.counter("nearline.fleet.rejected").inc()
                    return result
                committed.append(s)
                result.rows_updated += res.rows_updated
                result.rows_appended += res.rows_appended
            _metrics.counter("nearline.fleet.accepted").inc()
            return result

    def rollback_last(self, why: str = "operator rollback") -> List[int]:
        """Fan a bitwise rollback of the most recent accepted round out
        to every shard; returns the shard ids that had one to undo."""
        with self._lock:
            return [s for s, p in sorted(self.publishers.items())
                    if p.rollback_last(why)]

    def watermarks(self) -> Dict[int, Optional[dict]]:
        """Per-shard durable watermark (from each shard's manifest)."""
        return {s: (p.last_manifest or {}).get("watermark")
                for s, p in sorted(self.publishers.items())}
