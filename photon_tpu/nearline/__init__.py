"""Nearline delta training: the continuous train -> publish -> serve loop.

The GLMix production story is per-entity random-effect models that
refresh as new member events arrive. Offline training (game/) produces
whole models; online serving (serving/) scores them; this package closes
the loop:

- ``events``   — append-only event-log reader with a crc32-checked
  watermark checkpoint (exactly-once per publish, preemption-safe).
- ``delta_trainer`` — warm-started per-entity RE solves for only the
  entities with new data, plus an optional low-cadence fixed refresh.
- ``publisher`` — row-level delta publish into the LIVE serving tables
  behind a gate ladder, with versioned manifests and bitwise rollback.
- ``pipeline`` — the poll -> train -> publish -> checkpoint loop with
  freshness-lag instrumentation and graceful drain (``cli/nearline``).
"""

from photon_tpu.nearline.delta_trainer import DeltaTrainConfig, DeltaTrainer
from photon_tpu.nearline.events import (
    EventLogReader,
    EventLogWriter,
    NearlineCheckpointError,
    load_checkpoint,
    save_checkpoint,
)
from photon_tpu.nearline.pipeline import NearlineConfig, NearlinePipeline
from photon_tpu.nearline.publisher import (
    DeltaPublisher,
    DeltaPublishResult,
    FleetDeltaPublisher,
    FleetPublishResult,
    NearlinePublishConfig,
)

__all__ = [
    "DeltaPublisher",
    "DeltaPublishResult",
    "DeltaTrainConfig",
    "FleetDeltaPublisher",
    "FleetPublishResult",
    "DeltaTrainer",
    "EventLogReader",
    "EventLogWriter",
    "NearlineCheckpointError",
    "NearlineConfig",
    "NearlinePipeline",
    "NearlinePublishConfig",
    "load_checkpoint",
    "save_checkpoint",
]
