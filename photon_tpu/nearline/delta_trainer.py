"""Incremental per-entity retraining over the dirty-entity set.

A nearline round only touches the entities that actually received new
events.  For each random-effect coordinate the trainer builds a *tiny*
GAME dataset over just those events, warm-starts each entity's solve
from the live model's coefficients (the cold store when one backs the
coordinate, the resident table otherwise), and runs the exact per-entity
solve programs offline training uses (``RandomEffectCoordinate.
update_model_blocked`` — size-bucketed, jitted, warm-started, failed
entities keep their warm start).  The output is a per-coordinate set of
*candidate rows* — ``{entity_id: (coef_row, proj_row)}`` in the delta
dataset's projected space — which the publisher normalizes into the
serving layout and pushes behind its gate ladder.

Residualization follows GAME score algebra: each event's solve offset is
its logged offset plus the host-computed margins of every *other*
coordinate (fixed thetas and other coordinates' current entity rows), so
the per-entity solve sees the same residual it would in a full
coordinate-descent sweep over that data.

Fixed effects change on a much slower cadence and their thetas are
closed over by the compiled scorers, so a fixed refresh cannot be a
row-level publish — ``maybe_refresh_fixed`` re-fits the fixed coordinate
on the accumulated event buffer (warm-started from the live theta) and
routes the result through the full validated swap (``serving/swap.py``).
Two-tier coordinates survive the swap with their nearline deltas intact
because the publisher keeps the on-disk cold stores current; a
full-resident coordinate re-stages whatever ``model_dir`` holds, so pair
fixed refresh with two-tier serving when nearline deltas must persist.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from photon_tpu.obs.metrics import registry as _metrics
from photon_tpu.optim.problem import GLMOptimizationConfiguration


@dataclasses.dataclass(frozen=True)
class DeltaTrainConfig:
    """Knobs for the per-round delta solves.

    ``max_entity_buckets`` is deliberately tiny: a delta round touches
    few entities with few samples each, and every distinct bucket shape
    is an XLA compile.  ``fixed_refresh_every`` = 0 disables the fixed
    refresh; N > 0 refreshes every N rounds via a full validated swap.
    """

    max_entity_buckets: int = 4
    fixed_refresh_every: int = 0
    fixed_buffer: int = 8192           # events retained for fixed refresh
    glm: GLMOptimizationConfiguration = dataclasses.field(
        default_factory=GLMOptimizationConfiguration)


@dataclasses.dataclass
class CoordinateDelta:
    """Candidate rows for one random-effect coordinate."""

    coordinate_id: str
    random_effect_type: str
    feature_shard_id: str
    # entity_id -> (coef_row [K_ds] f32, proj_row [K_ds] i32) in the
    # delta dataset's projected space (ascending global cols, -1 pad)
    rows: Dict[str, Tuple[np.ndarray, np.ndarray]]
    event_ts: Dict[str, float]         # entity_id -> newest event ts
    num_events: int = 0
    # entity_id -> posterior-variance row [K_ds] f32 aligned with
    # ``rows`` (same projected space, same slot order).  Populated only
    # when the serving coordinate carries variances (Thompson models) —
    # a delta-trained mean must republish its uncertainty in the SAME
    # round or the scorer would explore with stale noise.
    var_rows: Dict[str, np.ndarray] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class DeltaTrainResult:
    coordinates: Dict[str, CoordinateDelta]
    num_events: int
    stats: Dict[str, int]

    @property
    def num_rows(self) -> int:
        return sum(len(c.rows) for c in self.coordinates.values())


def _parse_features(event: Dict[str, Any], sid: str, imap,
                    stats: Dict[str, int]) -> Tuple[np.ndarray, np.ndarray]:
    """(global cols int64, values f64) for one event on one shard,
    unknown (name, term) pairs dropped."""
    feats = (event.get("features") or {}).get(sid) or ()
    cols = np.fromiter((imap.index_of(f[0], f[1]) for f in feats),
                       np.int64, count=len(feats))
    vals = np.fromiter((float(f[2]) for f in feats), np.float64,
                       count=len(feats))
    keep = cols >= 0
    dropped = int(len(cols) - keep.sum())
    if dropped:
        stats["unknown_features"] += dropped
    return cols[keep], vals[keep]


def current_entity_row(rs, entity_id: str,
                       shard_dim: int) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """The LIVE (coef_row, proj_row) of ``entity_id`` in serving layout,
    host-side.  Two-tier coordinates read the authoritative cold tier
    (the hot set is a cache of it); full-resident coordinates gather the
    device row and reconstruct its projection from the load-time sorted
    (entity * D + col) -> slot table.  None = unknown entity."""
    if rs.store is not None:
        cold = rs.store.cold
        r = cold.entity_row(entity_id)
        if r is None:
            return None
        return (np.array(cold.coef[r], np.float32),
                np.array(cold.proj[r], np.int32))
    e = rs.entity_rows.get(entity_id)
    if e is None:
        return None
    coef = np.asarray(rs.coef[e], np.float32)
    D = max(int(shard_dim), 1)
    lo = int(np.searchsorted(rs.pkeys_sorted, e * D))
    hi = int(np.searchsorted(rs.pkeys_sorted, (e + 1) * D))
    proj = np.full(rs.slot_width, -1, np.int32)
    proj[rs.pslots_sorted[lo:hi]] = (rs.pkeys_sorted[lo:hi] - e * D).astype(
        np.int32)
    return coef, proj


def _row_margin(cols: np.ndarray, vals: np.ndarray,
                coef_row: np.ndarray, proj_row: np.ndarray) -> float:
    """Host replay of one entity-row margin: sum of vals over the
    features its projection covers."""
    if not len(cols):
        return 0.0
    pvalid = proj_row >= 0
    pcols = proj_row[pvalid].astype(np.int64)
    pcoef = coef_row[pvalid].astype(np.float64)
    rank = np.searchsorted(pcols, cols)
    rank = np.minimum(rank, max(len(pcols) - 1, 0))
    if not len(pcols):
        return 0.0
    hit = pcols[rank] == cols
    return float(np.dot(pcoef[rank[hit]], vals[hit]))


class DeltaTrainer:
    """Builds candidate rows for the publisher from a batch of events."""

    def __init__(self, engine, model_dir: Optional[str] = None,
                 config: Optional[DeltaTrainConfig] = None):
        self.engine = engine
        self.model_dir = model_dir
        self.config = config or DeltaTrainConfig()
        self._rounds = 0
        self._fixed_events: List[Dict[str, Any]] = []

    # ------------------------------------------------------------ helpers

    def _cold_for(self, rs):
        """The ColdStore backing a coordinate, if any (two-tier store's
        cold tier, else the model_dir cold-store file)."""
        if rs.store is not None:
            return rs.store.cold
        if self.model_dir is not None:
            import os

            from photon_tpu.io.cold_store import ColdStore, cold_store_path

            p = cold_store_path(self.model_dir, rs.coordinate_id)
            if os.path.exists(p):
                return ColdStore(p)
        return None

    def _fixed_margin(self, model, ev: Dict[str, Any],
                      thetas: Dict[str, np.ndarray],
                      stats: Dict[str, int]) -> float:
        m = 0.0
        for fs in model.fixed:
            cols, vals = _parse_features(ev, fs.feature_shard_id,
                                         model.index_maps[fs.feature_shard_id],
                                         stats)
            if len(cols):
                m += float(np.dot(thetas[fs.coordinate_id][cols], vals))
        return m

    def _re_margin(self, model, ev: Dict[str, Any], exclude: str,
                   stats: Dict[str, int]) -> float:
        """Margins of every random-effect coordinate except ``exclude``."""
        m = 0.0
        for rs in model.random:
            if rs.coordinate_id == exclude:
                continue
            re_id = (ev.get("entities") or {}).get(rs.random_effect_type)
            if re_id is None:
                continue
            row = current_entity_row(
                rs, str(re_id), model.shard_dims.get(rs.feature_shard_id, 1))
            if row is None:
                continue
            cols, vals = _parse_features(
                ev, rs.feature_shard_id,
                model.index_maps[rs.feature_shard_id], stats)
            m += _row_margin(cols, vals, row[0], row[1])
        return m

    # ------------------------------------------------------------- train

    def train(self, events: Sequence[Dict[str, Any]]) -> DeltaTrainResult:
        """One delta round: per-coordinate warm-started solves over the
        entities ``events`` touch.  Pure training — nothing is published."""
        from photon_tpu.game.coordinate import RandomEffectCoordinate
        from photon_tpu.game.dataset import (EntityVocabulary, FeatureShard,
                                             GameDataFrame)
        from photon_tpu.game.random_effect import (
            RandomEffectDataConfiguration, build_random_effect_dataset,
            warm_start_from_cold_store)

        model = self.engine.model
        stats: Dict[str, int] = {
            "events": len(events), "entities": 0,
            "unknown_features": 0, "nonfinite_rows": 0,
        }
        self._rounds += 1
        if self.config.fixed_refresh_every > 0:
            self._fixed_events.extend(events)
            if len(self._fixed_events) > self.config.fixed_buffer:
                self._fixed_events = \
                    self._fixed_events[-self.config.fixed_buffer:]
        thetas = {fs.coordinate_id: np.asarray(fs.theta, np.float64)
                  for fs in model.fixed}
        out: Dict[str, CoordinateDelta] = {}
        for rs in model.random:
            evs = [ev for ev in events
                   if (ev.get("entities") or {}).get(rs.random_effect_type)
                   is not None]
            if not evs:
                continue
            sid = rs.feature_shard_id
            imap = model.index_maps[sid]
            rows, ids = [], []
            resp = np.empty(len(evs), np.float64)
            wts = np.empty(len(evs), np.float64)
            offs = np.empty(len(evs), np.float64)
            for i, ev in enumerate(evs):
                cols, vals = _parse_features(ev, sid, imap, stats)
                rows.append((cols.astype(np.int32), vals))
                ids.append(str(ev["entities"][rs.random_effect_type]))
                resp[i] = float(ev.get("response", 0.0))
                wts[i] = float(ev.get("weight", 1.0))
                # residual offset: logged offset + every other
                # coordinate's margin on this event (GAME score algebra)
                offs[i] = (float(ev.get("offset", 0.0))
                           + self._fixed_margin(model, ev, thetas, stats)
                           + self._re_margin(model, ev, rs.coordinate_id,
                                             stats))
            df = GameDataFrame(
                num_samples=len(evs), response=resp,
                feature_shards={sid: FeatureShard(rows, imap.feature_dimension)},
                offsets=offs, weights=wts,
                id_tags={rs.random_effect_type: ids})
            vocab = EntityVocabulary()
            ds = build_random_effect_dataset(
                df,
                RandomEffectDataConfiguration(
                    rs.random_effect_type, sid,
                    max_entity_buckets=self.config.max_entity_buckets),
                vocab)
            names = vocab.names(rs.random_effect_type)
            proj = np.asarray(ds.projection)
            cold = self._cold_for(rs)
            if cold is not None:
                warm = warm_start_from_cold_store(cold, names, proj)
            else:
                warm = np.zeros(proj.shape, np.float32)
                for r, name in enumerate(names):
                    live = current_entity_row(
                        rs, name, model.shard_dims.get(sid, 1))
                    if live is None:
                        continue
                    from photon_tpu.game.random_effect import replay_cold_rows
                    warm[r] = replay_cold_rows(
                        proj[r:r + 1], live[1][None, :], live[0][None, :])[0]
            coord = RandomEffectCoordinate(
                ds, df.num_samples, rs.random_effect_type, sid, model.task,
                config=self.config.glm)
            rem = coord.update_model_blocked(None, warm_start=warm)
            coef = np.asarray(rem.coefficients, np.float32)[:len(names)]
            # Thompson coordinates republish uncertainty WITH the means:
            # a diagonal-Hessian Laplace pass at the freshly solved rows
            # (bayes/laplace), gated on the target actually serving
            # variances and the loss having a Hessian (typed skip — the
            # mean delta still publishes, existing variance bytes stay).
            var: Optional[np.ndarray] = None
            serves_var = (getattr(rs, "var_coef", None) is not None
                          or (cold is not None
                              and getattr(cold, "has_variances", False)))
            if serves_var:
                if coord.objective.loss.has_hessian:
                    from photon_tpu.bayes.laplace import \
                        entity_variances_blocked
                    var = np.asarray(
                        entity_variances_blocked(coord, rem.coefficients),
                        np.float32)[:len(names)]
                else:
                    stats["variance_skips"] = stats.get(
                        "variance_skips", 0) + 1
                    _metrics.counter(
                        "nearline.train.variance_skipped",
                        reason="no_hessian").inc()
            delta_rows: Dict[str, Tuple[np.ndarray, np.ndarray]] = {}
            var_rows: Dict[str, np.ndarray] = {}
            ev_ts: Dict[str, float] = {}
            for r, name in enumerate(names):
                if not np.isfinite(coef[r]).all():
                    stats["nonfinite_rows"] += 1
                    _metrics.counter("nearline.train.nonfinite_rows").inc()
                    continue
                delta_rows[name] = (coef[r].copy(), proj[r].astype(np.int32))
                if var is not None:
                    if np.isfinite(var[r]).all() and (var[r] >= 0).all():
                        var_rows[name] = var[r].copy()
                    else:
                        stats["nonfinite_var_rows"] = stats.get(
                            "nonfinite_var_rows", 0) + 1
                        _metrics.counter(
                            "nearline.train.nonfinite_var_rows").inc()
            for ev, name in zip(evs, ids):
                ts = ev.get("ts")
                if ts is not None and name in delta_rows:
                    ev_ts[name] = max(ev_ts.get(name, float(ts)), float(ts))
            stats["entities"] += len(delta_rows)
            out[rs.coordinate_id] = CoordinateDelta(
                rs.coordinate_id, rs.random_effect_type, sid,
                delta_rows, ev_ts, num_events=len(evs),
                var_rows=var_rows)
        _metrics.counter("nearline.train.events").inc(len(events))
        _metrics.counter("nearline.train.entities").inc(stats["entities"])
        return DeltaTrainResult(out, len(events), stats)

    # ------------------------------------------------------ fixed refresh

    def maybe_refresh_fixed(self, label: str = "nearline-fixed"):
        """Low-cadence fixed-effect re-fit through the full validated
        swap.  Returns the ``SwapResult`` when a refresh ran, else None.
        Requires ``model_dir`` (thetas are closed over by the compiled
        scorers, so this is a whole-model publish, not a row publish)."""
        cfg = self.config
        if (cfg.fixed_refresh_every <= 0 or self.model_dir is None
                or self._rounds == 0
                or self._rounds % cfg.fixed_refresh_every != 0
                or not self._fixed_events):
            return None
        import dataclasses as _dc

        import jax.numpy as jnp

        from photon_tpu.game.coordinate import FixedEffectCoordinate
        from photon_tpu.game.dataset import FeatureShard, GameDataFrame
        from photon_tpu.game.model import FixedEffectModel
        from photon_tpu.io.model_io import load_for_serving
        from photon_tpu.models.glm import Coefficients, GeneralizedLinearModel
        from photon_tpu.serving.swap import swap_staged

        engine = self.engine
        model = engine.model
        stats: Dict[str, int] = {"unknown_features": 0}
        thetas = {fs.coordinate_id: np.asarray(fs.theta, np.float64)
                  for fs in model.fixed}
        evs = self._fixed_events
        new_thetas: Dict[str, np.ndarray] = {}
        for fs in model.fixed:
            sid = fs.feature_shard_id
            imap = model.index_maps[sid]
            dim = imap.feature_dimension
            rows = []
            resp = np.empty(len(evs), np.float64)
            wts = np.empty(len(evs), np.float64)
            offs = np.empty(len(evs), np.float64)
            for i, ev in enumerate(evs):
                cols, vals = _parse_features(ev, sid, imap, stats)
                rows.append((cols.astype(np.int32), vals))
                resp[i] = float(ev.get("response", 0.0))
                wts[i] = float(ev.get("weight", 1.0))
                # residual: everything except THIS fixed coordinate
                other_fixed = sum(
                    float(np.dot(thetas[f2.coordinate_id][c2], v2))
                    for f2 in model.fixed if f2.coordinate_id
                    != fs.coordinate_id
                    for c2, v2 in [_parse_features(
                        ev, f2.feature_shard_id,
                        model.index_maps[f2.feature_shard_id], stats)]
                    if len(c2))
                offs[i] = (float(ev.get("offset", 0.0)) + other_fixed
                           + self._re_margin(model, ev, "", stats))
            df = GameDataFrame(
                num_samples=len(evs), response=resp,
                feature_shards={sid: FeatureShard(rows, dim)},
                offsets=offs, weights=wts)
            coord = FixedEffectCoordinate(
                df.fixed_effect_batch(sid), dim, sid, model.task,
                config=cfg.glm)
            theta0 = thetas[fs.coordinate_id][:dim].astype(np.float32)
            prev = FixedEffectModel(
                GeneralizedLinearModel(
                    Coefficients(jnp.asarray(theta0)), model.task), sid)
            fem = coord.update_model(prev, None)
            theta_new = np.asarray(fem.model.coefficients.means, np.float32)
            if not np.isfinite(theta_new).all():
                _metrics.counter("nearline.fixed.nonfinite_refresh").inc()
                return None
            new_thetas[fs.coordinate_id] = theta_new
        sm = load_for_serving(self.model_dir)
        sm = _dc.replace(sm, fixed=[
            _dc.replace(fe, coefficients=new_thetas.get(
                fe.coordinate_id, fe.coefficients))
            for fe in sm.fixed])
        _metrics.counter("nearline.fixed.refreshes").inc()
        return swap_staged(engine, sm, label)
