"""Supervised GLM model containers.

Reference: photon-api supervised/model/GeneralizedLinearModel.scala:12-27
(computeScore = theta.x, computeMean via link), LogisticRegressionModel
.scala:31, LinearRegressionModel.scala:29, PoissonRegressionModel.scala:29,
SmoothedHingeLossLinearSVMModel; photon-lib model/Coefficients.scala:31
(means + optional variances).

One dataclass parameterized by TaskType replaces the subclass-per-task
hierarchy — the link function comes from the task's PointwiseLoss.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from photon_tpu.ops import features as F
from photon_tpu.ops.losses import loss_for_task
from photon_tpu.types import TaskType

Array = jax.Array


class Coefficients(NamedTuple):
    """means + optional variances (reference: Coefficients.scala:31)."""

    means: Array                      # [d]
    variances: Optional[Array] = None  # [d]

    @property
    def dim(self) -> int:
        return self.means.shape[0]

    def compute_score(self, x: F.FeatureMatrix) -> Array:
        return F.matvec(x, self.means)

    @staticmethod
    def zeros(dim: int, dtype=jnp.float32) -> "Coefficients":
        return Coefficients(means=jnp.zeros((dim,), dtype))


class GeneralizedLinearModel(NamedTuple):
    """A trained GLM: coefficients + task (link)."""

    coefficients: Coefficients
    task: TaskType

    @property
    def dim(self) -> int:
        return self.coefficients.dim

    def compute_score(self, x: F.FeatureMatrix, offsets: Optional[Array] = None) -> Array:
        """Raw margin theta.x (+ offset) — what GAME score algebra sums."""
        s = self.coefficients.compute_score(x)
        return s if offsets is None else s + offsets

    def compute_mean(self, x: F.FeatureMatrix, offsets: Optional[Array] = None) -> Array:
        """Mean response via the inverse link (sigmoid / exp / identity)."""
        return loss_for_task(self.task).mean(self.compute_score(x, offsets))

    def predict_class(self, x: F.FeatureMatrix, threshold: float = 0.5,
                      offsets: Optional[Array] = None) -> Array:
        """Binary prediction (reference: BinaryClassifier threshold scoring)."""
        if not self.task.is_classification:
            raise ValueError(f"{self.task} is not a classification task")
        return (self.compute_mean(x, offsets) >= threshold).astype(jnp.int32)


def logistic_regression_model(coef: Coefficients) -> GeneralizedLinearModel:
    return GeneralizedLinearModel(coef, TaskType.LOGISTIC_REGRESSION)


def linear_regression_model(coef: Coefficients) -> GeneralizedLinearModel:
    return GeneralizedLinearModel(coef, TaskType.LINEAR_REGRESSION)


def poisson_regression_model(coef: Coefficients) -> GeneralizedLinearModel:
    return GeneralizedLinearModel(coef, TaskType.POISSON_REGRESSION)


def smoothed_hinge_svm_model(coef: Coefficients) -> GeneralizedLinearModel:
    return GeneralizedLinearModel(coef, TaskType.SMOOTHED_HINGE_LOSS_LINEAR_SVM)
