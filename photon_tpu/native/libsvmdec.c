/* Native LibSVM text parser -> columnar CSR arrays.
 *
 * Python-side tokenization of LibSVM lines (data/ingest.read_libsvm)
 * builds two Python objects per nonzero; this parser emits four flat
 * buffers (labels f64, indptr i64, cols i32, vals f64) in one pass over
 * the bytes, zero Python objects per feature. Grammar per line:
 *     <label> (<index>:<value>)*  [# comment]
 * Blank lines are skipped; a '#' truncates the line. Indices are
 * 1-based unless zero_based is nonzero (matching the Python parser).
 *
 * parse(data: bytes, zero_based: int)
 *   -> (labels: bytes, indptr: bytes, cols: bytes, vals: bytes)
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>

static PyObject *
parse(PyObject *self, PyObject *args)
{
    Py_buffer buf;
    int zero_based = 0;
    if (!PyArg_ParseTuple(args, "y*|i", &buf, &zero_based))
        return NULL;
    const char *p = (const char *)buf.buf;
    Py_ssize_t len = buf.len;
    const char *end = p + len;
    /* strtod/strtol scan until a non-numeric byte; a number token ending
     * exactly at the buffer end would let them read past it (the "y*"
     * converter accepts bytearray/memoryview/mmap, which are NOT
     * NUL-terminated). Every line that ends in '\n' is already bounded
     * inside the original buffer, so the token-parsing pass walks the
     * input as up to two segments: the buffer up to (and including) its
     * last '\n', then — only when the blob lacks a trailing newline — a
     * SMALL owned copy of just the final partial line with a '\n'
     * appended. The previous implementation duplicated the entire blob
     * for that one missing byte (2x peak RSS on a multi-GB mmap). */
    const char *last_nl = NULL;
    for (const char *t = end; t > p; ) {
        t--;
        if (*t == '\n') { last_nl = t; break; }
    }
    size_t safe_len = last_nl ? (size_t)(last_nl - p) + 1 : 0;
    size_t tail_len = (size_t)len - safe_len;
    char *owned = NULL;
    if (tail_len) {
        owned = (char *)malloc(tail_len + 1);
        if (!owned) {
            PyBuffer_Release(&buf);
            return PyErr_NoMemory();
        }
        memcpy(owned, p + safe_len, tail_len);
        owned[tail_len] = '\n';
    }
    const char *segs[2];
    const char *seg_ends[2];
    int nsegs = 0;
    if (safe_len) {
        segs[nsegs] = p;
        seg_ends[nsegs] = p + safe_len;
        nsegs++;
    }
    if (owned) {
        segs[nsegs] = owned;
        seg_ends[nsegs] = owned + tail_len + 1;
        nsegs++;
    }

    /* pass 1: count data lines and nonzeros (':' before any '#').
     * Both passes touch only raw buffers — the GIL is released so the
     * Python side can fan chunks of one file across threads. */
    size_t nrows = 0, nnz = 0;
    int in_comment = 0, has_data = 0;
    Py_BEGIN_ALLOW_THREADS
    for (const char *q = p; q < end; q++) {
        char c = *q;
        if (c == '\n') {
            if (has_data) nrows++;
            in_comment = 0;
            has_data = 0;
        } else if (!in_comment) {
            if (c == '#') in_comment = 1;
            else if (c == ':') nnz++;
            else if (c != ' ' && c != '\t' && c != '\r') has_data = 1;
        }
    }
    if (has_data) nrows++;
    Py_END_ALLOW_THREADS

    double  *labels = (double *)malloc(sizeof(double) * (nrows ? nrows : 1));
    int64_t *indptr = (int64_t *)malloc(sizeof(int64_t) * (nrows + 1));
    int32_t *cols   = (int32_t *)malloc(sizeof(int32_t) * (nnz ? nnz : 1));
    double  *vals   = (double *)malloc(sizeof(double) * (nnz ? nnz : 1));
    if (!labels || !indptr || !cols || !vals) {
        free(labels); free(indptr); free(cols); free(vals);
        free(owned);
        PyBuffer_Release(&buf);
        return PyErr_NoMemory();
    }

    size_t r = 0, k = 0;
    indptr[0] = 0;
    int bad = 0;
    Py_BEGIN_ALLOW_THREADS
    for (int s = 0; s < nsegs && !bad; s++) {
    const char *q = segs[s];
    const char *seg_end = seg_ends[s];
    while (q < seg_end && !bad) {
        /* find the line span, excluding any comment; every segment ends
         * with '\n', so the scan below never leaves the segment */
        const char *eol = memchr(q, '\n', (size_t)(seg_end - q));
        if (!eol) eol = seg_end;
        const char *stop = memchr(q, '#', (size_t)(eol - q));
        if (!stop) stop = eol;
        /* skip leading whitespace */
        while (q < stop && (*q == ' ' || *q == '\t' || *q == '\r')) q++;
        if (q >= stop) { q = eol + 1; continue; }   /* blank/comment line */
        if (r >= nrows) { bad = 1; break; }
        /* label */
        char *next;
        labels[r] = strtod(q, &next);
        if (next == q) { bad = 1; break; }
        q = next;
        /* index:value pairs */
        while (q < stop) {
            while (q < stop && (*q == ' ' || *q == '\t' || *q == '\r')) q++;
            if (q >= stop) break;
            long idx = strtol(q, &next, 10);
            if (next == q || next >= stop || *next != ':') { bad = 1; break; }
            q = next + 1;
            /* the value must start immediately: strtod skips leading
             * whitespace (even newlines past this line's end), which
             * would silently swallow the next line on "2:\n" input */
            if (q >= stop || *q == ' ' || *q == '\t' || *q == '\r'
                || *q == '\n') { bad = 1; break; }
            double v = strtod(q, &next);
            if (next == q || next > stop) { bad = 1; break; }
            q = next;
            if (k >= nnz) { bad = 1; break; }
            long j = zero_based ? idx : idx - 1;
            if (j < 0 || j > INT32_MAX) { bad = 1; break; }
            cols[k] = (int32_t)j;
            vals[k] = v;
            k++;
        }
        if (bad) break;
        r++;
        indptr[r] = (int64_t)k;
        q = eol + 1;
    }
    }
    Py_END_ALLOW_THREADS
    free(owned);
    PyBuffer_Release(&buf);
    if (bad || r != nrows) {
        free(labels); free(indptr); free(cols); free(vals);
        PyErr_SetString(PyExc_ValueError, "malformed LibSVM input");
        return NULL;
    }

    PyObject *out = Py_BuildValue(
        "(y#y#y#y#)",
        (const char *)labels, (Py_ssize_t)(sizeof(double) * nrows),
        (const char *)indptr, (Py_ssize_t)(sizeof(int64_t) * (nrows + 1)),
        (const char *)cols,   (Py_ssize_t)(sizeof(int32_t) * k),
        (const char *)vals,   (Py_ssize_t)(sizeof(double) * k));
    free(labels); free(indptr); free(cols); free(vals);
    return out;
}

static PyMethodDef Methods[] = {
    {"parse", parse, METH_VARARGS,
     "parse(data, zero_based=0) -> (labels, indptr, cols, vals) buffers"},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_libsvmdec", NULL, -1, Methods,
};

PyMODINIT_FUNC
PyInit__libsvmdec(void)
{
    return PyModule_Create(&moduledef);
}
