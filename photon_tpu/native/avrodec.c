/* Native Avro binary block decoder.
 *
 * The host-side ingest path must feed TPU chips; the pure-Python datum
 * decoder (photon_tpu/io/avro.py:_read_datum) tops out at a few MB/s,
 * two orders of magnitude short of a host pipeline. This CPython
 * extension walks a schema "program" compiled from the (already
 * reference-resolved) writer schema and decodes one decompressed block
 * of records into the exact same Python objects the fallback produces:
 * dict for records/maps, list for arrays, str/bytes/int/float/bool/None
 * primitives, enum symbols as str.
 *
 * Program encoding (built by photon_tpu/native/__init__.py):
 *   (0,) null   (1,) boolean   (2,) int/long   (3,) float   (4,) double
 *   (5,) bytes  (6,) string    (7, size) fixed (8, (sym, ...)) enum
 *   (9, item) array            (10, value) map
 *   (11, (branch, ...)) union  (12, ((name, field), ...)) record
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <limits.h>
#include <string.h>

enum {
    OP_NULL = 0, OP_BOOL = 1, OP_LONG = 2, OP_FLOAT = 3, OP_DOUBLE = 4,
    OP_BYTES = 5, OP_STRING = 6, OP_FIXED = 7, OP_ENUM = 8, OP_ARRAY = 9,
    OP_MAP = 10, OP_UNION = 11, OP_RECORD = 12,
};

typedef struct Node {
    int op;
    Py_ssize_t n;            /* children / symbols / fixed size */
    struct Node **child;     /* array/map: 1; union/record: n */
    PyObject **names;        /* record field names / enum symbols (owned) */
} Node;

static void node_free(Node *node) {
    if (node == NULL) return;
    if (node->child != NULL) {
        for (Py_ssize_t i = 0; i < node->n; i++) node_free(node->child[i]);
        PyMem_Free(node->child);
    }
    if (node->names != NULL) {
        for (Py_ssize_t i = 0; i < node->n; i++) Py_XDECREF(node->names[i]);
        PyMem_Free(node->names);
    }
    PyMem_Free(node);
}

static Node *node_build(PyObject *tree, int depth) {
    if (depth > 64) {
        PyErr_SetString(PyExc_ValueError, "schema nesting too deep");
        return NULL;
    }
    if (!PyTuple_Check(tree) || PyTuple_GET_SIZE(tree) < 1) {
        PyErr_SetString(PyExc_TypeError, "schema program node must be a tuple");
        return NULL;
    }
    long op = PyLong_AsLong(PyTuple_GET_ITEM(tree, 0));
    if (op == -1 && PyErr_Occurred()) return NULL;

    /* ops with an operand need arity 2 and (where applicable) a tuple
     * operand — a malformed program must raise, never fault */
    if (op >= OP_FIXED && op <= OP_RECORD) {
        if (PyTuple_GET_SIZE(tree) < 2) {
            PyErr_Format(PyExc_ValueError, "opcode %ld needs an operand", op);
            return NULL;
        }
        if (op != OP_FIXED && op != OP_ARRAY && op != OP_MAP
            && !PyTuple_Check(PyTuple_GET_ITEM(tree, 1))) {
            PyErr_Format(PyExc_TypeError,
                         "opcode %ld operand must be a tuple", op);
            return NULL;
        }
        if (op == OP_RECORD) {
            PyObject *fields = PyTuple_GET_ITEM(tree, 1);
            for (Py_ssize_t i = 0; i < PyTuple_GET_SIZE(fields); i++) {
                PyObject *pair = PyTuple_GET_ITEM(fields, i);
                if (!PyTuple_Check(pair) || PyTuple_GET_SIZE(pair) != 2) {
                    PyErr_SetString(PyExc_TypeError,
                                    "record fields must be (name, schema)");
                    return NULL;
                }
            }
        }
    }

    Node *node = (Node *)PyMem_Calloc(1, sizeof(Node));
    if (node == NULL) { PyErr_NoMemory(); return NULL; }
    node->op = (int)op;

    switch (op) {
    case OP_NULL: case OP_BOOL: case OP_LONG: case OP_FLOAT:
    case OP_DOUBLE: case OP_BYTES: case OP_STRING:
        return node;
    case OP_FIXED: {
        node->n = PyLong_AsSsize_t(PyTuple_GET_ITEM(tree, 1));
        if (node->n < 0) {
            if (!PyErr_Occurred())
                PyErr_SetString(PyExc_ValueError, "negative fixed size");
            goto fail;
        }
        return node;
    }
    case OP_ENUM: {
        PyObject *syms = PyTuple_GET_ITEM(tree, 1);
        node->n = PyTuple_GET_SIZE(syms);
        node->names = (PyObject **)PyMem_Calloc((size_t)node->n,
                                                sizeof(PyObject *));
        if (node->names == NULL) { PyErr_NoMemory(); goto fail; }
        for (Py_ssize_t i = 0; i < node->n; i++) {
            node->names[i] = PyTuple_GET_ITEM(syms, i);
            Py_INCREF(node->names[i]);
        }
        return node;
    }
    case OP_ARRAY: case OP_MAP: {
        node->n = 1;
        node->child = (Node **)PyMem_Calloc(1, sizeof(Node *));
        if (node->child == NULL) { PyErr_NoMemory(); goto fail; }
        node->child[0] = node_build(PyTuple_GET_ITEM(tree, 1), depth + 1);
        if (node->child[0] == NULL) goto fail;
        return node;
    }
    case OP_UNION: {
        PyObject *branches = PyTuple_GET_ITEM(tree, 1);
        node->n = PyTuple_GET_SIZE(branches);
        node->child = (Node **)PyMem_Calloc((size_t)node->n, sizeof(Node *));
        if (node->child == NULL) { PyErr_NoMemory(); goto fail; }
        for (Py_ssize_t i = 0; i < node->n; i++) {
            node->child[i] = node_build(PyTuple_GET_ITEM(branches, i),
                                        depth + 1);
            if (node->child[i] == NULL) goto fail;
        }
        return node;
    }
    case OP_RECORD: {
        PyObject *fields = PyTuple_GET_ITEM(tree, 1);
        node->n = PyTuple_GET_SIZE(fields);
        node->child = (Node **)PyMem_Calloc((size_t)node->n, sizeof(Node *));
        node->names = (PyObject **)PyMem_Calloc((size_t)node->n,
                                                sizeof(PyObject *));
        if (node->child == NULL || node->names == NULL) {
            PyErr_NoMemory(); goto fail;
        }
        for (Py_ssize_t i = 0; i < node->n; i++) {
            PyObject *pair = PyTuple_GET_ITEM(fields, i);
            node->names[i] = PyTuple_GET_ITEM(pair, 0);
            Py_INCREF(node->names[i]);
            node->child[i] = node_build(PyTuple_GET_ITEM(pair, 1), depth + 1);
            if (node->child[i] == NULL) goto fail;
        }
        return node;
    }
    default:
        PyErr_Format(PyExc_ValueError, "bad opcode %ld", op);
        goto fail;
    }
fail:
    node_free(node);
    return NULL;
}

/* ---- decoding ---------------------------------------------------------- */

typedef struct {
    const unsigned char *buf;
    Py_ssize_t pos, len;
} Dec;

static int dec_long(Dec *d, long long *out) {
    unsigned long long acc = 0;
    int shift = 0;
    while (1) {
        if (d->pos >= d->len) {
            PyErr_SetString(PyExc_EOFError, "truncated avro data");
            return -1;
        }
        unsigned char b = d->buf[d->pos++];
        acc |= ((unsigned long long)(b & 0x7F)) << shift;
        if (!(b & 0x80)) break;
        shift += 7;
        if (shift > 63) {
            PyErr_SetString(PyExc_ValueError, "varint too long");
            return -1;
        }
    }
    *out = (long long)(acc >> 1) ^ -(long long)(acc & 1);
    return 0;
}

static const unsigned char *dec_read(Dec *d, Py_ssize_t n) {
    /* n > len - pos, never pos + n: a corrupt length near SSIZE_MAX must
     * not overflow the signed addition and sail past the bounds check */
    if (n < 0 || n > d->len - d->pos) {
        PyErr_SetString(PyExc_EOFError, "truncated avro data");
        return NULL;
    }
    const unsigned char *p = d->buf + d->pos;
    d->pos += n;
    return p;
}

static PyObject *decode_node(Dec *d, const Node *node) {
    long long v;
    const unsigned char *p;
    switch (node->op) {
    case OP_NULL:
        Py_RETURN_NONE;
    case OP_BOOL:
        if ((p = dec_read(d, 1)) == NULL) return NULL;
        if (*p) Py_RETURN_TRUE;
        Py_RETURN_FALSE;
    case OP_LONG:
        if (dec_long(d, &v) < 0) return NULL;
        return PyLong_FromLongLong(v);
    case OP_FLOAT: {
        float f;
        if ((p = dec_read(d, 4)) == NULL) return NULL;
        memcpy(&f, p, 4);
        return PyFloat_FromDouble((double)f);
    }
    case OP_DOUBLE: {
        double f;
        if ((p = dec_read(d, 8)) == NULL) return NULL;
        memcpy(&f, p, 8);
        return PyFloat_FromDouble(f);
    }
    case OP_BYTES:
        if (dec_long(d, &v) < 0) return NULL;
        if ((p = dec_read(d, (Py_ssize_t)v)) == NULL) return NULL;
        return PyBytes_FromStringAndSize((const char *)p, (Py_ssize_t)v);
    case OP_STRING:
        if (dec_long(d, &v) < 0) return NULL;
        if ((p = dec_read(d, (Py_ssize_t)v)) == NULL) return NULL;
        return PyUnicode_DecodeUTF8((const char *)p, (Py_ssize_t)v, NULL);
    case OP_FIXED:
        if ((p = dec_read(d, node->n)) == NULL) return NULL;
        return PyBytes_FromStringAndSize((const char *)p, node->n);
    case OP_ENUM:
        if (dec_long(d, &v) < 0) return NULL;
        if (v < 0 || v >= node->n) {
            PyErr_SetString(PyExc_ValueError, "enum index out of range");
            return NULL;
        }
        Py_INCREF(node->names[v]);
        return node->names[v];
    case OP_UNION:
        if (dec_long(d, &v) < 0) return NULL;
        if (v < 0 || v >= node->n) {
            PyErr_SetString(PyExc_ValueError, "union index out of range");
            return NULL;
        }
        return decode_node(d, node->child[v]);
    case OP_RECORD: {
        PyObject *obj = PyDict_New();
        if (obj == NULL) return NULL;
        for (Py_ssize_t i = 0; i < node->n; i++) {
            PyObject *val = decode_node(d, node->child[i]);
            if (val == NULL || PyDict_SetItem(obj, node->names[i], val) < 0) {
                Py_XDECREF(val);
                Py_DECREF(obj);
                return NULL;
            }
            Py_DECREF(val);
        }
        return obj;
    }
    case OP_ARRAY: {
        PyObject *out = PyList_New(0);
        if (out == NULL) return NULL;
        while (1) {
            if (dec_long(d, &v) < 0) goto arr_fail;
            if (v == 0) break;
            if (v < 0) {      /* block with byte size */
                long long nb;
                if (dec_long(d, &nb) < 0) goto arr_fail;
                if (v == LLONG_MIN) {   /* -v would be signed-overflow UB */
                    PyErr_SetString(PyExc_ValueError, "bad block count");
                    goto arr_fail;
                }
                v = -v;
            }
            for (long long i = 0; i < v; i++) {
                PyObject *item = decode_node(d, node->child[0]);
                if (item == NULL || PyList_Append(out, item) < 0) {
                    Py_XDECREF(item);
                    goto arr_fail;
                }
                Py_DECREF(item);
            }
        }
        return out;
    arr_fail:
        Py_DECREF(out);
        return NULL;
    }
    case OP_MAP: {
        PyObject *out = PyDict_New();
        if (out == NULL) return NULL;
        while (1) {
            if (dec_long(d, &v) < 0) goto map_fail;
            if (v == 0) break;
            if (v < 0) {
                long long nb;
                if (dec_long(d, &nb) < 0) goto map_fail;
                if (v == LLONG_MIN) {   /* -v would be signed-overflow UB */
                    PyErr_SetString(PyExc_ValueError, "bad block count");
                    goto map_fail;
                }
                v = -v;
            }
            for (long long i = 0; i < v; i++) {
                long long klen;
                if (dec_long(d, &klen) < 0) goto map_fail;
                if ((p = dec_read(d, (Py_ssize_t)klen)) == NULL) goto map_fail;
                PyObject *key = PyUnicode_DecodeUTF8(
                    (const char *)p, (Py_ssize_t)klen, NULL);
                if (key == NULL) goto map_fail;
                PyObject *val = decode_node(d, node->child[0]);
                if (val == NULL || PyDict_SetItem(out, key, val) < 0) {
                    Py_DECREF(key);
                    Py_XDECREF(val);
                    goto map_fail;
                }
                Py_DECREF(key);
                Py_DECREF(val);
            }
        }
        return out;
    map_fail:
        Py_DECREF(out);
        return NULL;
    }
    default:
        PyErr_SetString(PyExc_ValueError, "corrupt schema program");
        return NULL;
    }
}

/* ---- module ------------------------------------------------------------ */

static void capsule_destructor(PyObject *capsule) {
    node_free((Node *)PyCapsule_GetPointer(capsule, "photon_tpu.avrodec"));
}

static PyObject *py_compile_program(PyObject *self, PyObject *args) {
    PyObject *tree;
    if (!PyArg_ParseTuple(args, "O", &tree)) return NULL;
    Node *node = node_build(tree, 0);
    if (node == NULL) return NULL;
    PyObject *cap = PyCapsule_New(node, "photon_tpu.avrodec",
                                  capsule_destructor);
    if (cap == NULL) node_free(node);
    return cap;
}

static PyObject *py_decode_block(PyObject *self, PyObject *args) {
    PyObject *cap;
    Py_buffer buf;
    Py_ssize_t count;
    if (!PyArg_ParseTuple(args, "Oy*n", &cap, &buf, &count)) return NULL;
    Node *node = (Node *)PyCapsule_GetPointer(cap, "photon_tpu.avrodec");
    if (node == NULL) { PyBuffer_Release(&buf); return NULL; }
    Dec d = { (const unsigned char *)buf.buf, 0, buf.len };
    PyObject *out = PyList_New(count);
    if (out == NULL) { PyBuffer_Release(&buf); return NULL; }
    for (Py_ssize_t i = 0; i < count; i++) {
        PyObject *rec = decode_node(&d, node);
        if (rec == NULL) {
            Py_DECREF(out);
            PyBuffer_Release(&buf);
            return NULL;
        }
        PyList_SET_ITEM(out, i, rec);
    }
    if (d.pos != d.len) {
        Py_DECREF(out);
        PyBuffer_Release(&buf);
        PyErr_Format(PyExc_ValueError,
                     "block not fully consumed (%zd of %zd bytes)",
                     d.pos, d.len);
        return NULL;
    }
    PyBuffer_Release(&buf);
    return out;
}

static PyMethodDef methods[] = {
    {"compile_program", py_compile_program, METH_VARARGS,
     "Compile a schema program tree into a decoder capsule."},
    {"decode_block", py_decode_block, METH_VARARGS,
     "Decode `count` records from a decompressed Avro block."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_avrodec",
    "Native Avro binary block decoder for photon_tpu.", -1, methods,
};

PyMODINIT_FUNC PyInit__avrodec(void) {
    return PyModule_Create(&moduledef);
}
