/* Native Avro binary block decoder.
 *
 * The host-side ingest path must feed TPU chips; the pure-Python datum
 * decoder (photon_tpu/io/avro.py:_read_datum) tops out at a few MB/s,
 * two orders of magnitude short of a host pipeline. This CPython
 * extension walks a schema "program" compiled from the (already
 * reference-resolved) writer schema and decodes one decompressed block
 * of records into the exact same Python objects the fallback produces:
 * dict for records/maps, list for arrays, str/bytes/int/float/bool/None
 * primitives, enum symbols as str.
 *
 * Program encoding (built by photon_tpu/native/__init__.py):
 *   (0,) null   (1,) boolean   (2,) int/long   (3,) float   (4,) double
 *   (5,) bytes  (6,) string    (7, size) fixed (8, (sym, ...)) enum
 *   (9, item) array            (10, value) map
 *   (11, (branch, ...)) union  (12, ((name, field), ...)) record
 */

#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <limits.h>
#include <string.h>

enum {
    OP_NULL = 0, OP_BOOL = 1, OP_LONG = 2, OP_FLOAT = 3, OP_DOUBLE = 4,
    OP_BYTES = 5, OP_STRING = 6, OP_FIXED = 7, OP_ENUM = 8, OP_ARRAY = 9,
    OP_MAP = 10, OP_UNION = 11, OP_RECORD = 12,
};

typedef struct Node {
    int op;
    Py_ssize_t n;            /* children / symbols / fixed size */
    struct Node **child;     /* array/map: 1; union/record: n */
    PyObject **names;        /* record field names / enum symbols (owned) */
} Node;

static void node_free(Node *node) {
    if (node == NULL) return;
    if (node->child != NULL) {
        for (Py_ssize_t i = 0; i < node->n; i++) node_free(node->child[i]);
        PyMem_Free(node->child);
    }
    if (node->names != NULL) {
        for (Py_ssize_t i = 0; i < node->n; i++) Py_XDECREF(node->names[i]);
        PyMem_Free(node->names);
    }
    PyMem_Free(node);
}

static Node *node_build(PyObject *tree, int depth) {
    if (depth > 64) {
        PyErr_SetString(PyExc_ValueError, "schema nesting too deep");
        return NULL;
    }
    if (!PyTuple_Check(tree) || PyTuple_GET_SIZE(tree) < 1) {
        PyErr_SetString(PyExc_TypeError, "schema program node must be a tuple");
        return NULL;
    }
    long op = PyLong_AsLong(PyTuple_GET_ITEM(tree, 0));
    if (op == -1 && PyErr_Occurred()) return NULL;

    /* ops with an operand need arity 2 and (where applicable) a tuple
     * operand — a malformed program must raise, never fault */
    if (op >= OP_FIXED && op <= OP_RECORD) {
        if (PyTuple_GET_SIZE(tree) < 2) {
            PyErr_Format(PyExc_ValueError, "opcode %ld needs an operand", op);
            return NULL;
        }
        if (op != OP_FIXED && op != OP_ARRAY && op != OP_MAP
            && !PyTuple_Check(PyTuple_GET_ITEM(tree, 1))) {
            PyErr_Format(PyExc_TypeError,
                         "opcode %ld operand must be a tuple", op);
            return NULL;
        }
        if (op == OP_RECORD) {
            PyObject *fields = PyTuple_GET_ITEM(tree, 1);
            for (Py_ssize_t i = 0; i < PyTuple_GET_SIZE(fields); i++) {
                PyObject *pair = PyTuple_GET_ITEM(fields, i);
                if (!PyTuple_Check(pair) || PyTuple_GET_SIZE(pair) != 2) {
                    PyErr_SetString(PyExc_TypeError,
                                    "record fields must be (name, schema)");
                    return NULL;
                }
            }
        }
    }

    Node *node = (Node *)PyMem_Calloc(1, sizeof(Node));
    if (node == NULL) { PyErr_NoMemory(); return NULL; }
    node->op = (int)op;

    switch (op) {
    case OP_NULL: case OP_BOOL: case OP_LONG: case OP_FLOAT:
    case OP_DOUBLE: case OP_BYTES: case OP_STRING:
        return node;
    case OP_FIXED: {
        node->n = PyLong_AsSsize_t(PyTuple_GET_ITEM(tree, 1));
        if (node->n < 0) {
            if (!PyErr_Occurred())
                PyErr_SetString(PyExc_ValueError, "negative fixed size");
            goto fail;
        }
        return node;
    }
    case OP_ENUM: {
        PyObject *syms = PyTuple_GET_ITEM(tree, 1);
        node->n = PyTuple_GET_SIZE(syms);
        node->names = (PyObject **)PyMem_Calloc((size_t)node->n,
                                                sizeof(PyObject *));
        if (node->names == NULL) { PyErr_NoMemory(); goto fail; }
        for (Py_ssize_t i = 0; i < node->n; i++) {
            node->names[i] = PyTuple_GET_ITEM(syms, i);
            Py_INCREF(node->names[i]);
        }
        return node;
    }
    case OP_ARRAY: case OP_MAP: {
        node->n = 1;
        node->child = (Node **)PyMem_Calloc(1, sizeof(Node *));
        if (node->child == NULL) { PyErr_NoMemory(); goto fail; }
        node->child[0] = node_build(PyTuple_GET_ITEM(tree, 1), depth + 1);
        if (node->child[0] == NULL) goto fail;
        return node;
    }
    case OP_UNION: {
        PyObject *branches = PyTuple_GET_ITEM(tree, 1);
        node->n = PyTuple_GET_SIZE(branches);
        node->child = (Node **)PyMem_Calloc((size_t)node->n, sizeof(Node *));
        if (node->child == NULL) { PyErr_NoMemory(); goto fail; }
        for (Py_ssize_t i = 0; i < node->n; i++) {
            node->child[i] = node_build(PyTuple_GET_ITEM(branches, i),
                                        depth + 1);
            if (node->child[i] == NULL) goto fail;
        }
        return node;
    }
    case OP_RECORD: {
        PyObject *fields = PyTuple_GET_ITEM(tree, 1);
        node->n = PyTuple_GET_SIZE(fields);
        node->child = (Node **)PyMem_Calloc((size_t)node->n, sizeof(Node *));
        node->names = (PyObject **)PyMem_Calloc((size_t)node->n,
                                                sizeof(PyObject *));
        if (node->child == NULL || node->names == NULL) {
            PyErr_NoMemory(); goto fail;
        }
        for (Py_ssize_t i = 0; i < node->n; i++) {
            PyObject *pair = PyTuple_GET_ITEM(fields, i);
            node->names[i] = PyTuple_GET_ITEM(pair, 0);
            Py_INCREF(node->names[i]);
            node->child[i] = node_build(PyTuple_GET_ITEM(pair, 1), depth + 1);
            if (node->child[i] == NULL) goto fail;
        }
        return node;
    }
    default:
        PyErr_Format(PyExc_ValueError, "bad opcode %ld", op);
        goto fail;
    }
fail:
    node_free(node);
    return NULL;
}

/* ---- decoding ---------------------------------------------------------- */

typedef struct {
    const unsigned char *buf;
    Py_ssize_t pos, len;
} Dec;

static int dec_long(Dec *d, long long *out) {
    unsigned long long acc = 0;
    int shift = 0;
    while (1) {
        if (d->pos >= d->len) {
            PyErr_SetString(PyExc_EOFError, "truncated avro data");
            return -1;
        }
        unsigned char b = d->buf[d->pos++];
        acc |= ((unsigned long long)(b & 0x7F)) << shift;
        if (!(b & 0x80)) break;
        shift += 7;
        if (shift > 63) {
            PyErr_SetString(PyExc_ValueError, "varint too long");
            return -1;
        }
    }
    *out = (long long)(acc >> 1) ^ -(long long)(acc & 1);
    return 0;
}

static const unsigned char *dec_read(Dec *d, Py_ssize_t n) {
    /* n > len - pos, never pos + n: a corrupt length near SSIZE_MAX must
     * not overflow the signed addition and sail past the bounds check */
    if (n < 0 || n > d->len - d->pos) {
        PyErr_SetString(PyExc_EOFError, "truncated avro data");
        return NULL;
    }
    const unsigned char *p = d->buf + d->pos;
    d->pos += n;
    return p;
}

static PyObject *decode_node(Dec *d, const Node *node) {
    long long v;
    const unsigned char *p;
    switch (node->op) {
    case OP_NULL:
        Py_RETURN_NONE;
    case OP_BOOL:
        if ((p = dec_read(d, 1)) == NULL) return NULL;
        if (*p) Py_RETURN_TRUE;
        Py_RETURN_FALSE;
    case OP_LONG:
        if (dec_long(d, &v) < 0) return NULL;
        return PyLong_FromLongLong(v);
    case OP_FLOAT: {
        float f;
        if ((p = dec_read(d, 4)) == NULL) return NULL;
        memcpy(&f, p, 4);
        return PyFloat_FromDouble((double)f);
    }
    case OP_DOUBLE: {
        double f;
        if ((p = dec_read(d, 8)) == NULL) return NULL;
        memcpy(&f, p, 8);
        return PyFloat_FromDouble(f);
    }
    case OP_BYTES:
        if (dec_long(d, &v) < 0) return NULL;
        if ((p = dec_read(d, (Py_ssize_t)v)) == NULL) return NULL;
        return PyBytes_FromStringAndSize((const char *)p, (Py_ssize_t)v);
    case OP_STRING:
        if (dec_long(d, &v) < 0) return NULL;
        if ((p = dec_read(d, (Py_ssize_t)v)) == NULL) return NULL;
        return PyUnicode_DecodeUTF8((const char *)p, (Py_ssize_t)v, NULL);
    case OP_FIXED:
        if ((p = dec_read(d, node->n)) == NULL) return NULL;
        return PyBytes_FromStringAndSize((const char *)p, node->n);
    case OP_ENUM:
        if (dec_long(d, &v) < 0) return NULL;
        if (v < 0 || v >= node->n) {
            PyErr_SetString(PyExc_ValueError, "enum index out of range");
            return NULL;
        }
        Py_INCREF(node->names[v]);
        return node->names[v];
    case OP_UNION:
        if (dec_long(d, &v) < 0) return NULL;
        if (v < 0 || v >= node->n) {
            PyErr_SetString(PyExc_ValueError, "union index out of range");
            return NULL;
        }
        return decode_node(d, node->child[v]);
    case OP_RECORD: {
        PyObject *obj = PyDict_New();
        if (obj == NULL) return NULL;
        for (Py_ssize_t i = 0; i < node->n; i++) {
            PyObject *val = decode_node(d, node->child[i]);
            if (val == NULL || PyDict_SetItem(obj, node->names[i], val) < 0) {
                Py_XDECREF(val);
                Py_DECREF(obj);
                return NULL;
            }
            Py_DECREF(val);
        }
        return obj;
    }
    case OP_ARRAY: {
        PyObject *out = PyList_New(0);
        if (out == NULL) return NULL;
        while (1) {
            if (dec_long(d, &v) < 0) goto arr_fail;
            if (v == 0) break;
            if (v < 0) {      /* block with byte size */
                long long nb;
                if (dec_long(d, &nb) < 0) goto arr_fail;
                if (v == LLONG_MIN) {   /* -v would be signed-overflow UB */
                    PyErr_SetString(PyExc_ValueError, "bad block count");
                    goto arr_fail;
                }
                v = -v;
            }
            for (long long i = 0; i < v; i++) {
                PyObject *item = decode_node(d, node->child[0]);
                if (item == NULL || PyList_Append(out, item) < 0) {
                    Py_XDECREF(item);
                    goto arr_fail;
                }
                Py_DECREF(item);
            }
        }
        return out;
    arr_fail:
        Py_DECREF(out);
        return NULL;
    }
    case OP_MAP: {
        PyObject *out = PyDict_New();
        if (out == NULL) return NULL;
        while (1) {
            if (dec_long(d, &v) < 0) goto map_fail;
            if (v == 0) break;
            if (v < 0) {
                long long nb;
                if (dec_long(d, &nb) < 0) goto map_fail;
                if (v == LLONG_MIN) {   /* -v would be signed-overflow UB */
                    PyErr_SetString(PyExc_ValueError, "bad block count");
                    goto map_fail;
                }
                v = -v;
            }
            for (long long i = 0; i < v; i++) {
                long long klen;
                if (dec_long(d, &klen) < 0) goto map_fail;
                if ((p = dec_read(d, (Py_ssize_t)klen)) == NULL) goto map_fail;
                PyObject *key = PyUnicode_DecodeUTF8(
                    (const char *)p, (Py_ssize_t)klen, NULL);
                if (key == NULL) goto map_fail;
                PyObject *val = decode_node(d, node->child[0]);
                if (val == NULL || PyDict_SetItem(out, key, val) < 0) {
                    Py_DECREF(key);
                    Py_XDECREF(val);
                    goto map_fail;
                }
                Py_DECREF(key);
                Py_DECREF(val);
            }
        }
        return out;
    map_fail:
        Py_DECREF(out);
        return NULL;
    }
    default:
        PyErr_SetString(PyExc_ValueError, "corrupt schema program");
        return NULL;
    }
}

/* ---- module ------------------------------------------------------------ */

static void capsule_destructor(PyObject *capsule) {
    node_free((Node *)PyCapsule_GetPointer(capsule, "photon_tpu.avrodec"));
}

static PyObject *py_compile_program(PyObject *self, PyObject *args) {
    PyObject *tree;
    if (!PyArg_ParseTuple(args, "O", &tree)) return NULL;
    Node *node = node_build(tree, 0);
    if (node == NULL) return NULL;
    PyObject *cap = PyCapsule_New(node, "photon_tpu.avrodec",
                                  capsule_destructor);
    if (cap == NULL) node_free(node);
    return cap;
}

static PyObject *py_decode_block(PyObject *self, PyObject *args) {
    PyObject *cap;
    Py_buffer buf;
    Py_ssize_t count;
    if (!PyArg_ParseTuple(args, "Oy*n", &cap, &buf, &count)) return NULL;
    Node *node = (Node *)PyCapsule_GetPointer(cap, "photon_tpu.avrodec");
    if (node == NULL) { PyBuffer_Release(&buf); return NULL; }
    Dec d = { (const unsigned char *)buf.buf, 0, buf.len };
    PyObject *out = PyList_New(count);
    if (out == NULL) { PyBuffer_Release(&buf); return NULL; }
    for (Py_ssize_t i = 0; i < count; i++) {
        PyObject *rec = decode_node(&d, node);
        if (rec == NULL) {
            Py_DECREF(out);
            PyBuffer_Release(&buf);
            return NULL;
        }
        PyList_SET_ITEM(out, i, rec);
    }
    if (d.pos != d.len) {
        Py_DECREF(out);
        PyBuffer_Release(&buf);
        PyErr_Format(PyExc_ValueError,
                     "block not fully consumed (%zd of %zd bytes)",
                     d.pos, d.len);
        return NULL;
    }
    PyBuffer_Release(&buf);
    return out;
}

/* ---- columnar feature-bag decoding -------------------------------------- */

/* Feature bags (array<record{name, term, value}>) decode straight into
 * growable id/value CSR buffers with a (ptr,len)-keyed open-addressing
 * intern table over "name<DELIM>term" byte keys — no per-feature Python
 * objects at all. Everything else in the record decodes generically. */

typedef struct {
    uint64_t hash;
    uint32_t off, len;   /* into arena */
    int32_t id;          /* first-seen id; slot empty when id < 0 */
} InternSlot;

typedef struct {
    int32_t *ids; double *vals;          /* nnz-aligned */
    int64_t *rowptr;                     /* one per record + 1 */
    size_t nnz, ids_cap, vals_cap, nrows, rows_cap;
    unsigned char *arena; size_t arena_len, arena_cap;
    uint32_t *key_off, *key_len;         /* per interned key, id order */
    size_t nkeys, key_off_cap, key_len_cap;
    InternSlot *slots; size_t nslots;    /* power of two */
} Bag;

static int bag_init(Bag *b) {
    memset(b, 0, sizeof(*b));
    b->nslots = 1u << 12;
    b->slots = (InternSlot *)PyMem_Malloc(b->nslots * sizeof(InternSlot));
    if (b->slots == NULL) { PyErr_NoMemory(); return -1; }
    for (size_t i = 0; i < b->nslots; i++) b->slots[i].id = -1;
    return 0;
}

static void bag_free(Bag *b) {
    PyMem_Free(b->ids); PyMem_Free(b->vals); PyMem_Free(b->rowptr);
    PyMem_Free(b->arena); PyMem_Free(b->key_off); PyMem_Free(b->key_len);
    PyMem_Free(b->slots);
}

static int grow(void **p, size_t *cap, size_t need, size_t elem) {
    if (need <= *cap) return 0;
    size_t ncap = *cap ? *cap : 1024;
    while (ncap < need) ncap *= 2;
    void *np_ = PyMem_Realloc(*p, ncap * elem);
    if (np_ == NULL) { PyErr_NoMemory(); return -1; }
    *p = np_; *cap = ncap;
    return 0;
}

static uint64_t fnv1a(const unsigned char *s, size_t n, uint64_t h) {
    for (size_t i = 0; i < n; i++) { h ^= s[i]; h *= 1099511628211ULL; }
    return h;
}

static int bag_rehash(Bag *b) {
    size_t nslots = b->nslots * 2;
    InternSlot *ns = (InternSlot *)PyMem_Malloc(nslots * sizeof(InternSlot));
    if (ns == NULL) { PyErr_NoMemory(); return -1; }
    for (size_t i = 0; i < nslots; i++) ns[i].id = -1;
    for (size_t i = 0; i < b->nslots; i++) {
        if (b->slots[i].id < 0) continue;
        size_t j = (size_t)b->slots[i].hash & (nslots - 1);
        while (ns[j].id >= 0) j = (j + 1) & (nslots - 1);
        ns[j] = b->slots[i];
    }
    PyMem_Free(b->slots);
    b->slots = ns; b->nslots = nslots;
    return 0;
}

/* intern name<delim>term; returns id or -1 on error */
static int32_t bag_intern(Bag *b, const unsigned char *name, size_t nlen,
                          const unsigned char *delim, size_t dlen,
                          const unsigned char *term, size_t tlen) {
    uint64_t h = 1469598103934665603ULL;
    h = fnv1a(name, nlen, h); h = fnv1a(delim, dlen, h);
    h = fnv1a(term, tlen, h);
    size_t klen = nlen + dlen + tlen;
    size_t j = (size_t)h & (b->nslots - 1);
    while (b->slots[j].id >= 0) {
        InternSlot *s = &b->slots[j];
        if (s->hash == h && s->len == klen) {
            const unsigned char *k = b->arena + s->off;
            if (memcmp(k, name, nlen) == 0
                && memcmp(k + nlen, delim, dlen) == 0
                && memcmp(k + nlen + dlen, term, tlen) == 0)
                return s->id;
        }
        j = (j + 1) & (b->nslots - 1);
    }
    /* miss: append to arena + key table, fill slot */
    if (grow((void **)&b->arena, &b->arena_cap, b->arena_len + klen, 1) < 0)
        return -1;
    memcpy(b->arena + b->arena_len, name, nlen);
    memcpy(b->arena + b->arena_len + nlen, delim, dlen);
    memcpy(b->arena + b->arena_len + nlen + dlen, term, tlen);
    if (grow((void **)&b->key_off, &b->key_off_cap, b->nkeys + 1,
             sizeof(uint32_t)) < 0)
        return -1;
    if (grow((void **)&b->key_len, &b->key_len_cap, b->nkeys + 1,
             sizeof(uint32_t)) < 0)
        return -1;
    b->key_off[b->nkeys] = (uint32_t)b->arena_len;
    b->key_len[b->nkeys] = (uint32_t)klen;
    b->arena_len += klen;
    int32_t id = (int32_t)b->nkeys++;
    b->slots[j].hash = h; b->slots[j].off = b->key_off[id];
    b->slots[j].len = (uint32_t)klen; b->slots[j].id = id;
    if (b->nkeys * 4 > b->nslots * 3 && bag_rehash(b) < 0) return -1;
    return id;
}

/* one string: varint length + bytes, returned as (ptr, len) into buf */
static int dec_str_view(Dec *d, const unsigned char **p, size_t *n) {
    long long v;
    if (dec_long(d, &v) < 0) return -1;
    if ((*p = dec_read(d, (Py_ssize_t)v)) == NULL) return -1;
    *n = (size_t)v;
    return 0;
}

/* decode one feature-bag array value; roles: position of name/term/value
 * within the 3-field item record (e.g. {0,1,2}) */
static int decode_bag_array(Dec *d, Bag *b, const int roles[3],
                            const unsigned char *delim, size_t dlen,
                            int nullable_union_branch, int n_branches) {
    long long v;
    if (nullable_union_branch >= 0) {   /* bag behind ["null", array] */
        if (dec_long(d, &v) < 0) return -1;
        if (v < 0 || v >= n_branches) {  /* match the generic decoder */
            PyErr_SetString(PyExc_ValueError, "union index out of range");
            return -1;
        }
        if (v != nullable_union_branch) return 0;  /* null -> empty row */
    }
    while (1) {
        if (dec_long(d, &v) < 0) return -1;
        if (v == 0) break;
        if (v < 0) {
            long long nb;
            if (dec_long(d, &nb) < 0) return -1;
            if (v == LLONG_MIN) {
                PyErr_SetString(PyExc_ValueError, "bad block count");
                return -1;
            }
            v = -v;
        }
        for (long long i = 0; i < v; i++) {
            const unsigned char *name = NULL, *term = NULL;
            size_t nlen = 0, tlen = 0;
            double value = 0.0;
            for (int f = 0; f < 3; f++) {
                if (f == roles[0]) {        /* name */
                    if (dec_str_view(d, &name, &nlen) < 0) return -1;
                } else if (f == roles[1]) { /* term */
                    if (dec_str_view(d, &term, &tlen) < 0) return -1;
                } else {                    /* value: double */
                    const unsigned char *p = dec_read(d, 8);
                    if (p == NULL) return -1;
                    memcpy(&value, p, 8);
                }
            }
            int32_t id = bag_intern(b, name, nlen, delim, dlen, term, tlen);
            if (id < 0) return -1;
            if (grow((void **)&b->ids, &b->ids_cap, b->nnz + 1,
                     sizeof(int32_t)) < 0)
                return -1;
            if (grow((void **)&b->vals, &b->vals_cap, b->nnz + 1,
                     sizeof(double)) < 0)
                return -1;
            b->ids[b->nnz] = id;
            b->vals[b->nnz] = value;
            b->nnz++;
        }
    }
    return 0;
}

static PyObject *py_decode_columnar(PyObject *self, PyObject *args) {
    /* (program, buf, count, bag_specs, delim) where bag_specs is a tuple
     * of (top_field_index, role_name, role_term, role_value,
     * nullable_union_branch) and the program is the TOP-LEVEL RECORD. */
    PyObject *cap, *bag_specs;
    Py_buffer buf;
    Py_ssize_t count;
    const char *delim;
    Py_ssize_t dlen;
    if (!PyArg_ParseTuple(args, "Oy*nOs#", &cap, &buf, &count, &bag_specs,
                          &delim, &dlen))
        return NULL;
    Node *root = (Node *)PyCapsule_GetPointer(cap, "photon_tpu.avrodec");
    PyObject *records = NULL, *result = NULL;
    Bag *bags = NULL;
    Py_ssize_t nbags = 0;
    int *field_mode = NULL;   /* -1 generic, else bag index */
    int (*bag_roles)[3] = NULL;  /* loop-invariant per-bag params */
    int *bag_nub = NULL, *bag_nbranch = NULL;

    if (root == NULL || root->op != OP_RECORD) {
        PyErr_SetString(PyExc_ValueError, "program root must be a record");
        goto done;
    }
    if (!PyTuple_Check(bag_specs)) {
        PyErr_SetString(PyExc_TypeError, "bag_specs must be a tuple");
        goto done;
    }
    nbags = PyTuple_GET_SIZE(bag_specs);
    bags = (Bag *)PyMem_Calloc((size_t)nbags ? (size_t)nbags : 1, sizeof(Bag));
    field_mode = (int *)PyMem_Malloc((size_t)root->n * sizeof(int));
    /* loop-invariant per-bag parameters, parsed once */
    bag_roles = (int (*)[3])PyMem_Malloc(
        ((size_t)nbags ? (size_t)nbags : 1) * sizeof(*bag_roles));
    bag_nub = (int *)PyMem_Malloc(
        ((size_t)nbags ? (size_t)nbags : 1) * sizeof(int));
    bag_nbranch = (int *)PyMem_Malloc(
        ((size_t)nbags ? (size_t)nbags : 1) * sizeof(int));
    if (bags == NULL || field_mode == NULL || bag_roles == NULL
        || bag_nub == NULL || bag_nbranch == NULL) {
        PyErr_NoMemory();
        goto done;
    }
    for (Py_ssize_t i = 0; i < root->n; i++) field_mode[i] = -1;
    for (Py_ssize_t bi = 0; bi < nbags; bi++) {
        if (bag_init(&bags[bi]) < 0) goto done;
        PyObject *spec = PyTuple_GET_ITEM(bag_specs, bi);
        if (!PyTuple_Check(spec) || PyTuple_GET_SIZE(spec) < 6) {
            PyErr_SetString(PyExc_ValueError,
                            "bag spec must be a 6-tuple");
            goto done;
        }
        long fidx = PyLong_AsLong(PyTuple_GET_ITEM(spec, 0));
        if (fidx < 0 || fidx >= root->n) {
            PyErr_SetString(PyExc_ValueError, "bag field index out of range");
            goto done;
        }
        field_mode[fidx] = (int)bi;
        bag_roles[bi][0] = (int)PyLong_AsLong(PyTuple_GET_ITEM(spec, 1));
        bag_roles[bi][1] = (int)PyLong_AsLong(PyTuple_GET_ITEM(spec, 2));
        bag_roles[bi][2] = (int)PyLong_AsLong(PyTuple_GET_ITEM(spec, 3));
        bag_nub[bi] = (int)PyLong_AsLong(PyTuple_GET_ITEM(spec, 4));
        bag_nbranch[bi] = (int)PyLong_AsLong(PyTuple_GET_ITEM(spec, 5));
        if (PyErr_Occurred()) goto done;
    }

    records = PyList_New(count);
    if (records == NULL) goto done;
    Dec d = { (const unsigned char *)buf.buf, 0, buf.len };

    for (Py_ssize_t r = 0; r < count; r++) {
        PyObject *rec = PyDict_New();
        if (rec == NULL) goto done;
        PyList_SET_ITEM(records, r, rec);
        for (Py_ssize_t f = 0; f < root->n; f++) {
            if (field_mode[f] >= 0) {
                int bi2 = field_mode[f];
                Bag *b = &bags[bi2];
                if (grow((void **)&b->rowptr, &b->rows_cap, b->nrows + 2,
                         sizeof(int64_t)) < 0)
                    goto done;
                if (b->nrows == 0) b->rowptr[0] = 0;
                if (decode_bag_array(&d, b, bag_roles[bi2],
                                     (const unsigned char *)delim,
                                     (size_t)dlen, bag_nub[bi2],
                                     bag_nbranch[bi2]) < 0)
                    goto done;
                b->rowptr[++b->nrows] = (int64_t)b->nnz;
            } else {
                PyObject *val = decode_node(&d, root->child[f]);
                if (val == NULL
                    || PyDict_SetItem(rec, root->names[f], val) < 0) {
                    Py_XDECREF(val);
                    goto done;
                }
                Py_DECREF(val);
            }
        }
    }
    if (d.pos != d.len) {
        PyErr_Format(PyExc_ValueError,
                     "block not fully consumed (%zd of %zd bytes)",
                     d.pos, d.len);
        goto done;
    }

    /* package: (records, ((rowptr, ids, vals, keys), ...)) */
    {
        PyObject *bags_out = PyTuple_New(nbags);
        if (bags_out == NULL) goto done;
        for (Py_ssize_t bi = 0; bi < nbags; bi++) {
            Bag *b = &bags[bi];
            if (b->nrows == 0) {   /* no records decoded */
                if (grow((void **)&b->rowptr, &b->rows_cap, 1,
                         sizeof(int64_t)) < 0) {
                    Py_DECREF(bags_out); goto done;
                }
                b->rowptr[0] = 0;
            }
            PyObject *rp = PyBytes_FromStringAndSize(
                (const char *)b->rowptr,
                (Py_ssize_t)((b->nrows + 1) * sizeof(int64_t)));
            PyObject *ids = PyBytes_FromStringAndSize(
                (const char *)b->ids, (Py_ssize_t)(b->nnz * sizeof(int32_t)));
            PyObject *vals = PyBytes_FromStringAndSize(
                (const char *)b->vals, (Py_ssize_t)(b->nnz * sizeof(double)));
            PyObject *keys = PyList_New((Py_ssize_t)b->nkeys);
            if (rp == NULL || ids == NULL || vals == NULL || keys == NULL) {
                Py_XDECREF(rp); Py_XDECREF(ids); Py_XDECREF(vals);
                Py_XDECREF(keys); Py_DECREF(bags_out);
                goto done;
            }
            int ok = 1;
            for (size_t kix = 0; kix < b->nkeys; kix++) {
                PyObject *s = PyUnicode_DecodeUTF8(
                    (const char *)b->arena + b->key_off[kix],
                    (Py_ssize_t)b->key_len[kix], NULL);
                if (s == NULL) { ok = 0; break; }
                PyList_SET_ITEM(keys, (Py_ssize_t)kix, s);
            }
            if (!ok) {
                Py_DECREF(rp); Py_DECREF(ids); Py_DECREF(vals);
                Py_DECREF(keys); Py_DECREF(bags_out);
                goto done;
            }
            PyObject *packed = Py_BuildValue("(NNNN)", rp, ids, vals, keys);
            if (packed == NULL) {   /* N-refs consumed even on failure */
                Py_DECREF(bags_out);
                goto done;
            }
            PyTuple_SET_ITEM(bags_out, bi, packed);
        }
        result = Py_BuildValue("(NN)", records, bags_out);
        records = NULL;   /* ownership moved */
    }

done:
    if (bags != NULL) {
        for (Py_ssize_t bi = 0; bi < nbags; bi++) bag_free(&bags[bi]);
        PyMem_Free(bags);
    }
    PyMem_Free(field_mode);
    PyMem_Free(bag_roles);
    PyMem_Free(bag_nub);
    PyMem_Free(bag_nbranch);
    Py_XDECREF(records);
    PyBuffer_Release(&buf);
    return result;
}

static PyMethodDef methods[] = {
    {"compile_program", py_compile_program, METH_VARARGS,
     "Compile a schema program tree into a decoder capsule."},
    {"decode_block", py_decode_block, METH_VARARGS,
     "Decode `count` records from a decompressed Avro block."},
    {"decode_columnar", py_decode_columnar, METH_VARARGS,
     "Decode a block with feature bags going straight to CSR buffers."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_avrodec",
    "Native Avro binary block decoder for photon_tpu.", -1, methods,
};

PyMODINIT_FUNC PyInit__avrodec(void) {
    return PyModule_Create(&moduledef);
}
