"""Native (C) runtime components, built on demand with the system compiler.

The reference's runtime rides the JVM (Breeze/Spark/PalDB all JIT-compiled);
this package is the equivalent native layer for the TPU build's HOST side —
currently the Avro binary block decoder that feeds ingest
(``photon_tpu/io/avro.py``). Everything here is optional: import failures
or compile failures degrade to the pure-Python implementations.

Build: a single ``cc -O2 -shared -fPIC`` invocation against the running
interpreter's headers, cached next to the source; no pip, no setuptools.
Set ``PHOTON_TPU_NO_NATIVE=1`` to disable entirely.
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
import sysconfig
from typing import Any, Optional, Tuple

logger = logging.getLogger(__name__)

_DIR = os.path.dirname(os.path.abspath(__file__))
_SENTINEL_BROKEN = object()
_mods: dict = {}          # stem -> module | _SENTINEL_BROKEN


def _build_extension(stem: str) -> Optional[str]:
    """Compile <stem>.c -> _<stem><ext_suffix>.so next to the source.
    Returns the path, or None when no compiler / unwritable directory."""
    suffix = sysconfig.get_config_var("EXT_SUFFIX") or ".so"
    out = os.path.join(_DIR, f"_{stem}{suffix}")
    src = os.path.join(_DIR, f"{stem}.c")
    if os.path.exists(out) and os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    include = sysconfig.get_paths()["include"]
    cc = os.environ.get("CC", "cc")
    # compile to a process-unique temp path and rename into place:
    # concurrent first runs must never truncate a .so another process has
    # already mapped (SIGBUS), and a half-written file must never be
    # importable; rename is atomic on the same filesystem
    tmp = f"{out}.build-{os.getpid()}"
    cmd = [cc, "-O2", "-shared", "-fPIC", f"-I{include}", src, "-o", tmp]
    try:
        r = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        if r.returncode != 0:
            logger.warning("native %s build failed:\n%s", stem,
                           r.stderr[-2000:])
            return None
        os.replace(tmp, out)
    except (OSError, subprocess.TimeoutExpired) as e:
        logger.info("native %s build unavailable: %r", stem, e)
        return None
    finally:
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass
    return out


def _load_ext(stem: str):
    cached = _mods.get(stem)
    if cached is not None:
        return None if cached is _SENTINEL_BROKEN else cached
    if os.environ.get("PHOTON_TPU_NO_NATIVE"):
        _mods[stem] = _SENTINEL_BROKEN
        return None
    path = _build_extension(stem)
    if path is None:
        _mods[stem] = _SENTINEL_BROKEN
        return None
    try:
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            f"photon_tpu.native._{stem}", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _mods[stem] = mod
        return mod
    except Exception as e:  # noqa: BLE001 — optional accelerator
        logger.warning("native %s load failed: %r", stem, e)
        _mods[stem] = _SENTINEL_BROKEN
        return None


def _load():
    return _load_ext("avrodec")


def libsvm_parser():
    """The native LibSVM tokenizer (libsvmdec.c), or None. Returns a
    callable ``parse(data: bytes, zero_based: int) -> (labels, indptr,
    cols, vals)`` raw little-endian buffers (f64 / i64 / i32 / f64)."""
    mod = _load_ext("libsvmdec")
    return None if mod is None else mod.parse


# -- schema program compiler --------------------------------------------------

_PRIM_OPS = {"null": (0,), "boolean": (1,), "int": (2,), "long": (2,),
             "float": (3,), "double": (4,), "bytes": (5,), "string": (6,)}


def _program_of(schema, names, ns, depth=0) -> Tuple:
    """Resolved schema (photon_tpu.io.avro _Names conventions) -> opcode
    tree for the C decoder. Raises ValueError on anything unsupported
    (caller falls back to the Python decoder)."""
    if depth > 48:
        raise ValueError("schema too deep (recursive types unsupported)")
    schema = names.resolve(schema, ns)
    if isinstance(schema, list):
        return (11, tuple(_program_of(b, names, ns, depth + 1)
                          for b in schema))
    if isinstance(schema, str):
        if schema in _PRIM_OPS:
            return _PRIM_OPS[schema]
        raise ValueError(f"unresolved named type {schema!r}")
    t = schema["type"]
    if t in _PRIM_OPS:
        return _PRIM_OPS[t]
    if t == "record":
        rec_ns = schema.get("namespace", ns)
        return (12, tuple(
            (f["name"], _program_of(f["type"], names, rec_ns, depth + 1))
            for f in schema["fields"]))
    if t == "enum":
        return (8, tuple(schema["symbols"]))
    if t == "fixed":
        return (7, int(schema["size"]))
    if t == "array":
        return (9, _program_of(schema["items"], names, ns, depth + 1))
    if t == "map":
        return (10, _program_of(schema["values"], names, ns, depth + 1))
    raise ValueError(f"unsupported schema {t!r}")


class BlockDecoder:
    """Compiled native decoder for one (schema, names) pair; ``None``-like
    (falsy) when the native path is unavailable for this schema."""

    def __init__(self, schema, names, ns=None):
        self._program = None
        mod = _load()
        if mod is None:
            return
        try:
            tree = _program_of(schema, names, ns)
            self._program = mod.compile_program(tree)
            self._decode = mod.decode_block
        except ValueError as e:
            logger.info("native decoder unavailable for schema: %s", e)
            self._program = None

    def __bool__(self) -> bool:
        return self._program is not None

    def decode_block(self, raw: bytes, count: int) -> list:
        return self._decode(self._program, raw, count)
