"""Entity-sharded serving fleet: fixed effects local, random effects routed.

The GLMix score is additive — ``scorer.py`` computes

    total = ((offset + fixed_0 + ... + fixed_F) + re_0) + re_1 + ...

as a left-to-right float32 chain — so the fleet decomposes it exactly:

* a FRONT engine owns the (small, replicated) fixed effects and scores
  ``offset + fixed`` locally for every request;
* each of N SHARD engines owns the random-effect rows of the entities
  the canonical partitioner (`parallel/partition.entity_shard` — the
  same hash training placement and the cold-store splitter use) assigns
  it, serving them from its own cold store / `TwoTierCoeffStore` hot
  tier behind its own circuit breaker;
* the router turns each request into a hop chain: the running total so
  far rides as the next hop's ``offset``, so the owning shard's engine
  computes ``(running + re_j) + re_k`` with exactly the additions the
  single-host program would have issued. With every routed coordinate
  on one shard (always true for single-random-effect models, the GLMix
  serving shape), the fleet score is BITWISE equal to the single-host
  engine's — the parity tests pin this. Only a request whose coordinate
  ownership interleaves across shards in model order reassociates the
  chain (ulp-level, deterministic).

Degradation is data, never a hot-path exception: a shard that is down
(`chaos.shard_killed`, a dead client), past its deadline, or refusing
(breaker open, draining, shedding) contributes nothing and the response
carries a typed ``SHARD_UNAVAILABLE`` fallback per unavailable shard —
the score degrades to the fixed margin plus every shard that DID
answer. Slow shards are hedged: a hop that has not returned within
``FleetConfig.hedge_timeout_s`` gets a second attempt, first answer
wins (`chaos.shard_response_delay` drives the race in tests).

Per-shard observability (qps, p50/p99, hot-tier hit rate, breaker
state, unavailable/hedge counts) is kept at the router and merged into
one fleet view via the existing ``obs/metrics.merge_snapshots`` — the
same aggregation the multi-process RunReport path uses.

Elastic (v2) fleets route through a two-level partition instead:
entity -> fixed power-of-two virtual bucket (`partition.entity_bucket`)
-> shard via the manifest's versioned ``BucketMap``. A v1 manifest
reads as the identity map, so the composed route is bitwise the old
``entity_shard`` hash. Live resharding (`serving/migrate.BucketMigrator`)
opens a DOUBLE-READ window on one bucket: the router keeps serving the
source shard's answer (authoritative, bitwise-unchanged) while
mirroring the same hop to the destination and comparing scores
bit-for-bit; any mismatch poisons the window so cutover is refused
typed. Cutover itself is one assignment swap under the router lock +
an atomic manifest version bump — requests never see more than a typed
``BUCKET_MIGRATING`` fallback.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor, TimeoutError as _FutTimeout
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from photon_tpu.obs.metrics import merge_snapshots, registry as _metrics
from photon_tpu.obs.timeseries import series as _series
from photon_tpu.parallel.partition import BucketMap
from photon_tpu.resilience import chaos
from photon_tpu.serving.engine import LATENCY_BUCKETS, ServingEngine
from photon_tpu.serving.model_state import DeviceResidentModel
from photon_tpu.serving.types import (
    Fallback,
    FallbackReason,
    ScoreRequest,
    ScoreResponse,
    ServingConfig,
)

__all__ = [
    "DoubleReadWindow",
    "FleetConfig",
    "LocalShardClient",
    "ShardedServingFleet",
    "build_front_engine",
    "build_shard_engine",
]


class DoubleReadWindow:
    """Router-side state for one bucket mid-migration: every request in
    the bucket fans to BOTH shards; the source answer is served, the
    destination answer only compared bitwise. All counters are guarded
    by the router lock (mutated on the serve path)."""

    def __init__(self, bucket: int, src: int, dst: int):
        self.bucket = int(bucket)
        self.src = int(src)
        self.dst = int(dst)
        self.double_reads = 0     # hops mirrored AND compared
        self.skipped = 0          # mirrored but not comparable (see serve)
        self.mismatches = 0
        self.aborted = False
        self.mismatch_detail = ""

    def view(self) -> dict:
        return {"bucket": self.bucket, "src": self.src, "dst": self.dst,
                "double_reads": self.double_reads,
                "skipped": self.skipped,
                "mismatches": self.mismatches,
                "aborted": self.aborted,
                "mismatch_detail": self.mismatch_detail}


@dataclasses.dataclass(frozen=True)
class FleetConfig:
    """Router knobs. Engine-level behavior (ladder, breaker, SLO,
    two-tier store) stays in the per-engine ``ServingConfig``s."""

    #: per-hop wall ceiling for a routed shard call when the request
    #: carries no deadline of its own; None = wait for the shard
    shard_timeout_s: Optional[float] = None
    #: resubmit a hop that has not answered within this window (first
    #: answer wins); None disables hedging
    hedge_timeout_s: Optional[float] = None
    #: shard engines' config (each shard gets its own engine instance)
    serving: ServingConfig = dataclasses.field(default_factory=ServingConfig)
    #: front (fixed-effect) engine config; None = same as ``serving``
    #: minus the coeff store (fixed effects are always resident)
    front_serving: Optional[ServingConfig] = None
    #: sliding per-shard window for qps / latency quantiles
    stats_window: int = 4096

    def __post_init__(self):
        if self.shard_timeout_s is not None and self.shard_timeout_s <= 0:
            raise ValueError("shard_timeout_s must be positive")
        if self.hedge_timeout_s is not None and self.hedge_timeout_s <= 0:
            raise ValueError("hedge_timeout_s must be positive")
        if self.stats_window < 2:
            raise ValueError("stats_window must be >= 2")


class LocalShardClient:
    """In-process shard: a `ServingEngine` over one shard's stores.

    The client boundary is where a real fleet would put the RPC; chaos'
    ``shard_killed`` / ``shard_response_delay`` hook here so the router
    sees exactly what a dead or lagging remote would produce. ``serve``
    returns None for "no answer" — the router's typed-degradation
    signal; it NEVER raises on the request path."""

    def __init__(self, shard_id: int, engine: ServingEngine):
        self.shard_id = int(shard_id)
        self.engine = engine
        self.alive = True
        self._lock = threading.Lock()

    def serve(self, requests: Sequence[ScoreRequest]
              ) -> Optional[List[ScoreResponse]]:
        if not self.alive or chaos.shard_killed(self.shard_id):
            return None
        delay = chaos.shard_response_delay(self.shard_id)
        if delay > 0:
            time.sleep(delay)
        with self._lock:
            if not self.alive:   # killed while this attempt queued
                return None
            try:
                return self.engine.serve(requests)
            except Exception:    # a crashed shard is an unavailable
                return None      # shard, not a router exception

    def warmup(self) -> dict:
        with self._lock:
            return self.engine.warmup()

    def kill(self) -> None:
        self.alive = False

    def revive(self) -> None:
        self.alive = True

    def breaker_state(self) -> str:
        return self.engine.breaker.state()

    def hot_hit_rate(self) -> Optional[float]:
        cs = self.engine.model.coeff_store_stats()
        if not cs:
            return None
        rates = [s["hit_rate"] for s in cs.values()
                 if s.get("hit_rate") is not None]
        return float(np.mean(rates)) if rates else None

    def shutdown(self) -> None:
        with self._lock:
            self.engine.shutdown(drain_budget_s=0.0, reason="fleet shutdown")


class _ShardStats:
    """Router-side per-shard window: qps, latency quantiles, counts, and
    a LATENCY_BUCKETS histogram (snapshot-shaped for merge_snapshots)."""

    def __init__(self, window: int, shard_id: int = -1, clock=None):
        self.lock = threading.Lock()
        # injectable clock (the fleet's): windows/qps spans computed on a
        # virtual clock replay the same way every run
        self.clock = clock or time.monotonic
        self.shard_id = int(shard_id)
        self.requests = 0
        self.unavailable = 0
        self.hedges = 0
        self.lat = deque(maxlen=window)
        self.times = deque(maxlen=window)
        self.bucket_counts = [0] * (len(LATENCY_BUCKETS) + 1)
        self.lat_sum = 0.0

    def record(self, seconds: float, n_requests: int) -> None:
        with self.lock:
            self.requests += n_requests
            now = self.clock()
            for _ in range(n_requests):
                self.lat.append(seconds)
                self.times.append(now)
            self.bucket_counts[int(np.searchsorted(
                LATENCY_BUCKETS, seconds))] += n_requests
            self.lat_sum += seconds * n_requests
        shard = str(self.shard_id)
        _series.quantile("fleet.shard.latency", shard=shard).observe(
            now, seconds)
        _series.counter("fleet.shard.responses", shard=shard).inc(
            now, n_requests)

    def view(self) -> dict:
        with self.lock:
            lat = list(self.lat)
            times = list(self.times)
            out = {"requests": self.requests,
                   "unavailable": self.unavailable,
                   "hedges": self.hedges}
        if lat:
            out["p50_s"] = float(np.percentile(lat, 50))
            out["p99_s"] = float(np.percentile(lat, 99))
        span = times[-1] - times[0] if len(times) > 1 else 0.0
        out["qps"] = round(len(times) / span, 1) if span > 0 else 0.0
        return out

    def snapshot(self) -> dict:
        """One shard's metrics in ``registry.snapshot()`` shape — the
        unit ``merge_snapshots`` aggregates into the fleet view (and
        the same shape a remote shard process would ship)."""
        with self.lock:
            counts = list(self.bucket_counts)
            total = sum(counts)
            return {
                "counters": {"fleet.shard.requests": self.requests,
                             "fleet.shard.unavailable": self.unavailable,
                             "fleet.shard.hedges": self.hedges},
                "gauges": {},
                "histograms": {"fleet.shard.latency_seconds": {
                    "buckets": list(LATENCY_BUCKETS),
                    "counts": counts[:-1] + [counts[-1]],
                    "sum": self.lat_sum, "count": total}},
            }


#: one hop of a request's routing chain
_Hop = Tuple[int, Dict[str, str]]      # (shard_id, {re_type: entity_id})


def _load_base(manifest: dict, model_dir: Optional[str] = None):
    """(base model, manifest-covered random effects in MODEL order).
    Model order fixes the float accumulation chain, so every consumer —
    front, shards, router — derives it from the same load."""
    from photon_tpu.io.model_io import load_for_serving

    src_dir = model_dir or manifest["model_dir"]
    base = load_for_serving(src_dir)
    coord_meta = manifest["coordinates"]
    ordered = [re for re in base.random if re.coordinate_id in coord_meta]
    missing = set(coord_meta) - {re.coordinate_id for re in ordered}
    if missing:
        raise ValueError(f"manifest coordinates {sorted(missing)} not in "
                         f"model {src_dir!r}")
    return base, ordered


def build_front_engine(manifest: dict, config: FleetConfig,
                       model_dir: Optional[str] = None,
                       base=None, clock=None) -> ServingEngine:
    """Fixed-effects-only engine — the replicated front every router
    instance scores locally before fanning random effects out."""
    from photon_tpu.io.model_io import ServingGameModel

    if base is None:
        base, _ = _load_base(manifest, model_dir)
    front_cfg = config.front_serving or dataclasses.replace(
        config.serving, coeff_store=None)
    front_model = ServingGameModel(base.task, list(base.fixed), [],
                                   base.index_maps, base.metadata)
    return ServingEngine(
        DeviceResidentModel(front_model, feature_pad=front_cfg.feature_pad),
        front_cfg, clock=clock, obs_labels={"shard": "front"})


def build_shard_engine(fleet_dir: str, shard_id: int,
                       serving: Optional[ServingConfig] = None,
                       manifest: Optional[dict] = None,
                       model_dir: Optional[str] = None,
                       base=None, clock=None) -> ServingEngine:
    """Random-effects-only engine over ONE shard's split cold stores —
    the unit a shard host runs (``cli/serve --fleet-manifest --shard-id``
    boots exactly this)."""
    from photon_tpu.io.fleet_store import (read_fleet_manifest,
                                           shard_store_path)
    from photon_tpu.io.model_io import ServingGameModel, ServingRandomEffect

    if manifest is None:
        manifest = read_fleet_manifest(fleet_dir)
    if not any(sh["shard_id"] == shard_id for sh in manifest["shards"]):
        raise ValueError(f"shard {shard_id} not in fleet manifest "
                         f"(num_shards={manifest['num_shards']})")
    if base is None:
        base, _ = _load_base(manifest, model_dir)
    _, ordered = (base, [re for re in base.random
                         if re.coordinate_id in manifest["coordinates"]])
    serving = serving or ServingConfig()
    res = [ServingRandomEffect(
               re.coordinate_id, re.random_effect_type,
               re.feature_shard_id,
               cold_store_path=shard_store_path(fleet_dir, shard_id,
                                                re.coordinate_id))
           for re in ordered]
    m = ServingGameModel(base.task, [], res, base.index_maps, base.metadata)
    return ServingEngine(
        DeviceResidentModel(m, feature_pad=serving.feature_pad,
                            coeff_store=serving.coeff_store),
        serving, clock=clock, obs_labels={"shard": str(shard_id)})


class ShardedServingFleet:
    """Front-end router over a front (fixed-effect) engine plus N shard
    clients. Synchronous ``serve`` mirrors `ServingEngine.serve` —
    responses in request order, every degradation typed."""

    def __init__(self, front: ServingEngine,
                 clients: Sequence[LocalShardClient],
                 coordinates: Sequence[Tuple[str, str]],
                 config: Optional[FleetConfig] = None,
                 clock=None,
                 bucket_map: Optional[BucketMap] = None):
        """``coordinates`` is the model-order list of
        (coordinate_id, random_effect_type) the fleet routes — the order
        fixes the float accumulation chain, so it must match the
        single-host model's ``random`` order.

        ``clock`` (None = ``time.monotonic``) drives request deadlines
        and per-shard stats timestamps, so a replay on a virtual clock
        is wall-clock-independent at the router too. Hedge racing in
        ``_supervised_call`` deliberately stays on the wall clock — it
        supervises REAL thread liveness, which no virtual clock can.

        ``bucket_map`` (None = the identity map, i.e. v1 single-level
        routing, bitwise-unchanged) is the versioned virtual-bucket ->
        shard assignment the v2 manifest carries."""
        self.front = front
        self.clients = list(clients)
        self.num_shards = len(self.clients)
        if self.num_shards < 1:
            raise ValueError("fleet needs at least one shard")
        self.coordinates = list(coordinates)
        self.config = config or FleetConfig()
        self.clock = clock or time.monotonic
        self.bucket_map = bucket_map or BucketMap.identity(self.num_shards)
        self._stats = {c.shard_id: _ShardStats(self.config.stats_window,
                                               shard_id=c.shard_id,
                                               clock=self.clock)
                       for c in self.clients}
        self._by_id = {c.shard_id: c for c in self.clients}
        # supervisors (<= shards) + two attempts each can be in flight
        self._pool = ThreadPoolExecutor(
            max_workers=2 * self.num_shards + 4,
            thread_name_prefix="fleet")
        self._closed = False
        # elastic state: the router lock guards the bucket_map reference,
        # open double-read windows, and shard add/remove. RLock — ops
        # like commit_bucket are called by the migrator while it already
        # holds the lock for the cutover sequence.
        self._router_lock = threading.RLock()
        self._migrations: Dict[int, DoubleReadWindow] = {}
        # per-bucket request counters (autoscaler input: which buckets
        # make a shard hot). Separate small lock — serve() touches it
        # per hop member.
        self._load_lock = threading.Lock()
        self._bucket_load: Dict[int, int] = {}
        # set by from_fleet_dir; None for directly-constructed fleets
        self.fleet_dir: Optional[str] = None
        self.manifest: Optional[dict] = None

    # ------------------------------------------------------------ build

    @classmethod
    def from_fleet_dir(cls, fleet_dir: str,
                       config: Optional[FleetConfig] = None,
                       model_dir: Optional[str] = None,
                       clock=None,
                       ) -> "ShardedServingFleet":
        """Build the whole fleet from a split directory
        (`io/fleet_store.build_fleet_dir`): front engine from the source
        model's fixed effects, one shard engine per manifest shard over
        its per-shard cold stores. Refuses a torn/corrupt manifest
        (``FleetManifestError``) — routing never boots on guesses.
        ``clock`` threads one injectable clock through the router, the
        front engine, and every shard engine (replay determinism)."""
        from photon_tpu.io.fleet_store import read_fleet_manifest

        config = config or FleetConfig()
        manifest = read_fleet_manifest(fleet_dir)
        base, ordered = _load_base(manifest, model_dir)
        front = build_front_engine(manifest, config, base=base, clock=clock)
        clients = [
            LocalShardClient(sh["shard_id"], build_shard_engine(
                fleet_dir, sh["shard_id"], config.serving,
                manifest=manifest, base=base, clock=clock))
            for sh in manifest["shards"]]
        coords = [(re.coordinate_id, re.random_effect_type)
                  for re in ordered]
        fleet = cls(front, clients, coords, config, clock=clock,
                    bucket_map=BucketMap.from_json(manifest["bucket_map"]))
        fleet.fleet_dir = fleet_dir
        fleet.manifest = manifest
        fleet._model_dir = model_dir
        return fleet

    # ---------------------------------------------------------- routing

    def route(self, request: ScoreRequest) -> List[_Hop]:
        """The request's hop chain: routed coordinates grouped by owning
        shard, groups ordered by first coordinate in model order (the
        float-chain order). Pure function of the canonical hash composed
        with the current bucket map (identity map == the old
        ``entity_shard`` hash bitwise) — exposed so tests can pin
        routing == training placement."""
        bmap = self.bucket_map    # one read: the assignment is immutable
        owners: List[Tuple[int, str, str]] = []  # (coord idx, re_type, eid)
        for i, (_cid, re_type) in enumerate(self.coordinates):
            eid = request.entity_ids.get(re_type)
            if eid is not None:
                owners.append((i, re_type, eid))
        hops: List[_Hop] = []
        seen: Dict[int, int] = {}
        for i, re_type, eid in owners:
            shard = bmap.shard_for_entity(eid)
            if shard in seen:
                hops[seen[shard]][1][re_type] = eid
            else:
                seen[shard] = len(hops)
                hops.append((shard, {re_type: eid}))
        return hops

    # ---------------------------------------------------------- serving

    def warmup(self) -> dict:
        infos = [self.front.warmup()] + [c.warmup() for c in self.clients]
        return {
            "programs": sum(i["programs"] for i in infos),
            "seconds": round(sum(i["seconds"] for i in infos), 3),
            "front_programs": infos[0]["programs"],
            "per_shard_programs": [i["programs"] for i in infos[1:]],
        }

    def score(self, request: ScoreRequest) -> ScoreResponse:
        return self.serve([request])[0]

    def serve(self, requests: Sequence[ScoreRequest]
              ) -> List[ScoreResponse]:
        cfg = self.config
        t_in = self.clock()
        deadlines = [t_in + r.timeout_s if r.timeout_s is not None else None
                     for r in requests]
        # fixed effects local: ids stripped (the front model has no
        # random effects; its refusal ladder still applies)
        front_resps = self.front.serve([
            ScoreRequest(r.uid, r.features, {}, r.offset, r.timeout_s)
            for r in requests])
        _metrics.counter("fleet.requests").inc(len(requests))

        totals: List[Optional[np.float32]] = []
        fallbacks: List[List[Fallback]] = []
        chains: List[List[_Hop]] = []
        for r, fr in zip(requests, front_resps):
            fallbacks.append(list(fr.fallbacks))
            if fr.score is None:          # typed refusal — no routing
                totals.append(None)
                chains.append([])
            else:
                totals.append(np.float32(fr.score))
                chains.append(self.route(r))

        # elastic snapshot for this serve call: the assignment swap is
        # atomic (one reference), windows are copied under the lock
        bmap = self.bucket_map
        with self._router_lock:
            windows = dict(self._migrations)
        bucket_hits: Dict[int, int] = {}

        depth = 0
        while True:
            # (shard -> [(req index, ids)]) for this hop depth
            groups: Dict[int, List[Tuple[int, Dict[str, str]]]] = {}
            for i, chain in enumerate(chains):
                if depth < len(chain) and totals[i] is not None:
                    shard, ids = chain[depth]
                    groups.setdefault(shard, []).append((i, ids))
            if not groups:
                break
            futs = {}
            for shard, members in groups.items():
                subreqs, idxs, budget = [], [], None
                mirrors: Dict[int, List[Tuple[int, DoubleReadWindow]]] = {}
                now = self.clock()
                for i, ids in members:
                    remaining = None if deadlines[i] is None \
                        else deadlines[i] - now
                    if remaining is not None:
                        budget = remaining if budget is None \
                            else min(budget, remaining)
                    pos = len(subreqs)
                    subreqs.append(ScoreRequest(
                        requests[i].uid, requests[i].features, ids,
                        offset=float(totals[i]), timeout_s=remaining))
                    idxs.append(i)
                    for eid in ids.values():
                        b = bmap.bucket_of(eid)
                        bucket_hits[b] = bucket_hits.get(b, 0) + 1
                        w = windows.get(b)
                        if w is not None and w.src == shard:
                            # typed visibility: the bucket is mid-
                            # migration; the served score stays the
                            # source shard's
                            fallbacks[i].append(Fallback(
                                FallbackReason.BUCKET_MIGRATING, None,
                                f"bucket {b} migrating "
                                f"{w.src}->{w.dst}"))
                            if not w.aborted and w.dst in self._by_id:
                                mirrors.setdefault(w.dst, []).append(
                                    (pos, w))
                            break
                if budget is None:
                    budget = cfg.shard_timeout_s
                # mirrors go straight to the destination client (one
                # batched call per destination, NO nested supervisor:
                # a supervisor-per-mirror can starve the fixed pool) —
                # best-effort by design, an unanswered mirror is a
                # skipped comparison, never a served degradation
                mfuts = [
                    (pw, self._pool.submit(
                        self._by_id[dst].serve,
                        [subreqs[p] for p, _ in pw]))
                    for dst, pw in mirrors.items()]
                futs[shard] = (idxs, self._pool.submit(
                    self._supervised_call, self._by_id[shard],
                    subreqs, budget), mfuts)
            for shard, (idxs, fut, mfuts) in futs.items():
                resps = fut.result()   # supervisor never raises
                self._check_mirrors(resps, mfuts)
                st = self._stats[shard]
                if resps is None:
                    with st.lock:
                        st.unavailable += len(idxs)
                    _metrics.counter("fleet.shard_unavailable",
                                     shard=str(shard)).inc(len(idxs))
                    _series.counter("fleet.shard.unavailable",
                                    shard=str(shard)).inc(self.clock(),
                                                          len(idxs))
                    for i in idxs:
                        fallbacks[i].append(Fallback(
                            FallbackReason.SHARD_UNAVAILABLE, None,
                            f"shard {shard} gave no answer"))
                    continue
                for i, resp in zip(idxs, resps):
                    fallbacks[i].extend(resp.fallbacks)
                    if resp.score is None:
                        # shard answered with a typed refusal (breaker
                        # open, shedding, deadline): its margins are
                        # unavailable, the chain total stands
                        st_reasons = {f.reason for f in resp.fallbacks}
                        if FallbackReason.DEADLINE_EXCEEDED not in \
                                st_reasons:
                            fallbacks[i].append(Fallback(
                                FallbackReason.SHARD_UNAVAILABLE, None,
                                f"shard {shard} refused"))
                        with st.lock:
                            st.unavailable += 1
                        _metrics.counter("fleet.shard_unavailable",
                                         shard=str(shard)).inc()
                        _series.counter("fleet.shard.unavailable",
                                        shard=str(shard)).inc(self.clock())
                    else:
                        totals[i] = np.float32(resp.score)
            depth += 1

        if bucket_hits:
            with self._load_lock:
                for b, n in bucket_hits.items():
                    self._bucket_load[b] = self._bucket_load.get(b, 0) + n

        out: List[ScoreResponse] = []
        for r, fr, total, fbs in zip(requests, front_resps, totals,
                                     fallbacks):
            if total is None:
                out.append(ScoreResponse(r.uid, None, True, tuple(fbs)))
            else:
                out.append(ScoreResponse(r.uid, float(total),
                                         fr.degraded or bool(fbs),
                                         tuple(fbs)))
        return out

    def _supervised_call(self, client: LocalShardClient,
                         subreqs: List[ScoreRequest],
                         budget: Optional[float]
                         ) -> Optional[List[ScoreResponse]]:
        """One hop with hedging: primary attempt, a second attempt if the
        primary lags past ``hedge_timeout_s``, first answer wins; None
        past the budget. Records the hop latency per shard.

        A shard KNOWN to be dead (killed client, chaos-killed, breaker
        open) never gets a hedge: the second attempt would burn a pool
        slot racing an answer that cannot come — the hop goes straight
        to the typed ``SHARD_UNAVAILABLE`` path instead."""
        cfg = self.config
        st = self._stats[client.shard_id]
        t0 = time.monotonic()
        fut1 = self._pool.submit(client.serve, subreqs)
        hedge = cfg.hedge_timeout_s
        first_wait = budget
        if hedge is not None and (budget is None or hedge < budget):
            first_wait = hedge
        try:
            resps = fut1.result(timeout=first_wait)
            st.record(time.monotonic() - t0, len(subreqs))
            return resps
        except _FutTimeout:
            pass
        except Exception:
            return None
        if hedge is None or (budget is not None
                             and time.monotonic() - t0 >= budget):
            return None
        if (not client.alive or chaos.shard_killed(client.shard_id)
                or client.breaker_state() == "open"):
            # known-dead: a hedge cannot win, don't arm one
            if fut1.done():
                try:
                    resps = fut1.result()
                except Exception:
                    return None
                if resps is not None:
                    st.record(time.monotonic() - t0, len(subreqs))
                return resps
            return None
        # hedge: second attempt races the lagging primary
        with st.lock:
            st.hedges += 1
        _metrics.counter("fleet.hedges",
                         shard=str(client.shard_id)).inc()
        fut2 = self._pool.submit(client.serve, subreqs)
        remaining = None if budget is None \
            else max(budget - (time.monotonic() - t0), 0.0)
        end = None if remaining is None else time.monotonic() + remaining
        while True:
            for fut in (fut1, fut2):
                if fut.done():
                    try:
                        resps = fut.result()
                    except Exception:
                        resps = None
                    if resps is not None:
                        st.record(time.monotonic() - t0, len(subreqs))
                        return resps
            if fut1.done() and fut2.done():
                return None
            if end is not None and time.monotonic() >= end:
                return None
            time.sleep(0.0005)

    def _check_mirrors(self, primary: Optional[List[ScoreResponse]],
                       mfuts) -> None:
        """Resolve one hop's double-read mirrors: compare the
        destination copy's score BITWISE against the served (source)
        score. A comparison only counts when both sides produced a full,
        undegraded score — a cold-miss / unknown-entity / refusal on
        either side proves nothing about the copy and is counted as
        ``skipped``. Any bitwise mismatch poisons the window: cutover
        will be refused typed and the new copy is never served."""
        for pw, mfut in mfuts:
            try:
                mresps = mfut.result()   # client.serve never raises, but
            except Exception:            # stay typed if that ever changes
                mresps = None
            for k, (pos, w) in enumerate(pw):
                p = primary[pos] if primary is not None \
                    and pos < len(primary) else None
                m = mresps[k] if mresps is not None \
                    and k < len(mresps) else None
                comparable = (p is not None and m is not None
                              and p.score is not None
                              and m.score is not None
                              and not p.fallbacks and not m.fallbacks)
                with self._router_lock:
                    if not comparable:
                        w.skipped += 1
                        continue
                    w.double_reads += 1
                    if np.float32(p.score).tobytes() != \
                            np.float32(m.score).tobytes():
                        w.mismatches += 1
                        w.aborted = True
                        w.mismatch_detail = (
                            f"bucket {w.bucket} hop {w.src}->{w.dst}: "
                            f"src={np.float32(p.score)!r} "
                            f"dst={np.float32(m.score)!r}")
                        _metrics.counter("fleet.double_read_mismatch",
                                         bucket=str(w.bucket)).inc()

    # ---------------------------------------------------- elastic ops

    def begin_double_read(self, bucket: int, dst: int) -> DoubleReadWindow:
        """Open the double-read window for one bucket: requests keep
        being served off the current (source) owner while the same hop
        is mirrored to ``dst`` and compared bitwise. Called by the
        migrator once the destination copy is in place."""
        with self._router_lock:
            if int(bucket) in self._migrations:
                raise ValueError(f"bucket {bucket} already migrating")
            src = self.bucket_map.shard_of(int(bucket))
            if dst not in self._by_id:
                raise ValueError(f"destination shard {dst} not in fleet")
            if src == int(dst):
                raise ValueError(
                    f"bucket {bucket} already on shard {dst}")
            w = DoubleReadWindow(bucket, src, dst)
            self._migrations[int(bucket)] = w
            return w

    def end_double_read(self, bucket: int) -> Optional[DoubleReadWindow]:
        with self._router_lock:
            return self._migrations.pop(int(bucket), None)

    def commit_bucket(self, bucket: int, dst: int) -> BucketMap:
        """Atomically reassign one bucket — the in-router half of
        cutover (the durable half is the manifest version bump the
        migrator writes first). The assignment swap is one reference
        store, so in-flight serve() calls finish on whichever map they
        snapshotted — both route to shards holding the rows."""
        with self._router_lock:
            self.bucket_map = self.bucket_map.with_assignment(bucket, dst)
            return self.bucket_map

    def add_shard(self, client: LocalShardClient) -> None:
        """Grow the fleet live (scale-out): register an already-built,
        already-warmed shard client. The pool is swapped for a larger
        one; submissions in flight keep running on the old pool."""
        with self._router_lock:
            if client.shard_id in self._by_id:
                raise ValueError(f"shard {client.shard_id} already in fleet")
            self.clients.append(client)
            self._by_id[client.shard_id] = client
            self._stats[client.shard_id] = _ShardStats(
                self.config.stats_window, shard_id=client.shard_id,
                clock=self.clock)
            self.num_shards = len(self.clients)
            old_pool = self._pool
            self._pool = ThreadPoolExecutor(
                max_workers=2 * self.num_shards + 4,
                thread_name_prefix="fleet")
            old_pool.shutdown(wait=False)

    def remove_shard(self, shard_id: int) -> None:
        """Shrink the fleet live (drain): refuse while any bucket is
        still assigned to (or migrating toward) the shard."""
        with self._router_lock:
            sid = int(shard_id)
            if sid not in self._by_id:
                raise ValueError(f"shard {sid} not in fleet")
            owned = self.bucket_map.buckets_on(sid)
            if owned:
                raise ValueError(
                    f"shard {sid} still owns buckets {list(owned)[:8]}"
                    f"{'...' if len(owned) > 8 else ''}")
            inbound = [b for b, w in self._migrations.items()
                       if w.dst == sid or w.src == sid]
            if inbound:
                raise ValueError(
                    f"shard {sid} has open double-read windows on "
                    f"buckets {inbound}")
            client = self._by_id.pop(sid)
            self.clients.remove(client)
            self._stats.pop(sid, None)
            self.num_shards = len(self.clients)
        client.shutdown()

    def bucket_loads(self, top: Optional[int] = None
                     ) -> List[Tuple[int, int]]:
        """(bucket, request count) since boot, hottest first — the
        autoscaler's 'which buckets make this shard hot' input."""
        with self._load_lock:
            items = sorted(self._bucket_load.items(),
                           key=lambda kv: (-kv[1], kv[0]))
        return items[:top] if top is not None else items

    def migration_windows(self) -> Dict[int, dict]:
        with self._router_lock:
            return {b: w.view() for b, w in self._migrations.items()}

    # -------------------------------------------------------------- ops

    def kill_shard(self, shard_id: int) -> None:
        self._by_id[shard_id].kill()

    def revive_shard(self, shard_id: int) -> None:
        self._by_id[shard_id].revive()

    def stats(self) -> dict:
        """Per-shard view + the merged fleet view. ``merged`` is
        ``merge_snapshots`` over the per-shard snapshot dicts — the
        exact aggregation a multi-process fleet ships to its router."""
        per_shard = {}
        snaps = []
        for c in self.clients:
            st = self._stats[c.shard_id]
            view = st.view()
            view["alive"] = c.alive and not chaos.shard_killed(c.shard_id)
            view["breaker_state"] = c.breaker_state()
            hr = c.hot_hit_rate()
            if hr is not None:
                view["hot_hit_rate"] = round(hr, 4)
            per_shard[c.shard_id] = view
            snaps.append(st.snapshot())
        merged = merge_snapshots(snaps)
        return {
            "num_shards": self.num_shards,
            "coordinates": [cid for cid, _ in self.coordinates],
            "per_shard": per_shard,
            "merged": merged,
            "front_breaker_state": self.front.breaker.state(),
        }

    def shutdown(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.front.shutdown(drain_budget_s=0.0, reason="fleet shutdown")
        for c in self.clients:
            c.shutdown()
        self._pool.shutdown(wait=False)
