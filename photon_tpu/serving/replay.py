"""Traffic capture and deterministic replay for the serving stack.

Three pieces:

* **Recorder** (:class:`CaptureWriter` / :func:`read_capture`) — an
  append-only JSONL capture of admitted requests. Each line carries a
  monotone sequence number, the request's VIRTUAL-clock offset from
  capture start, the request in the exact wire shape
  ``ScoreRequest.from_json`` accepts, and a crc32 frame over the
  record's canonical bytes. The reader is torn-tail tolerant the same
  way ``nearline/events.py`` is: a record whose final line is
  incomplete (a recorder killed mid-append — ``chaos.capture_kill_at``
  or :func:`chaos.replay_torn_capture`) is held back and reported as a
  typed ``CAPTURE_TRUNCATED`` count, never parsed or guessed at.

* **Generators** (:class:`TrafficProfile` / :func:`generate`) —
  counter-derived synthetic traffic at millions-of-entities scale.
  Entity choice is Zipf-skewed (inverse-CDF on a splitmix64 stream, so
  an "entities=10_000_000" profile costs O(n_requests), not O(entities));
  the arrival rate is shaped per profile kind: constant (``zipf``),
  sinusoidal (``diurnal``), step (``burst``), or a ramping flash crowd
  that also CONCENTRATES traffic onto a hot entity subset
  (``flash_crowd``). Everything is integer/float arithmetic off
  splitmix64 counters — no RNG object, no platform-dependent library
  sampling — so identical (seed, profile) is bitwise-identical request
  streams, across runs and across hosts. ``stream_digest`` pins that.

* **Replayer** (:class:`Replayer`) — drives any engine kind on an
  injectable :class:`VirtualClock`. Targets with an async admission
  protocol (``submit``/``pump``: ServingEngine, MultiTenantEngine) get
  per-record virtual arrival: the clock advances to each record's
  offset, the request is submitted, and micro-batches form exactly as
  the coalescing rules dictate in virtual time. Serve-only targets
  (ShardedServingFleet) get tick-grouped arrivals. Scheduled actions
  (kill a shard, publish a model) fire when the virtual clock crosses
  their time, so an incident scenario replays identically run to run.
  Per-request latency is accounted in VIRTUAL time (completion minus
  arrival on the virtual clock) into windowed ``replay.*`` series —
  which is what makes two replays of one capture produce identical
  qps/p99 timelines, something wall-clock latencies can never do.

``chaos.replay_clock_skew`` injects per-record recorded-offset skew;
the replayer clamps any resulting non-monotone timestamp (a virtual
clock never runs backwards) and reports the clamps as a typed
``CLOCK_SKEW_CLAMPED`` count.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import threading
import zlib
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from photon_tpu.obs.metrics import registry as _metrics
from photon_tpu.obs.timeseries import WindowedRegistry, series as _series
from photon_tpu.resilience import chaos as _chaos
from photon_tpu.serving.types import ScoreRequest, ScoreResponse

__all__ = [
    "CAPTURE_SCHEMA",
    "CaptureRecord",
    "CaptureWriter",
    "Replayer",
    "ReplayResult",
    "TrafficProfile",
    "VirtualClock",
    "generate",
    "read_capture",
    "stream_digest",
    "timeline_digest",
]

CAPTURE_SCHEMA = "photon_tpu.capture.v1"

#: typed accounting keys (mirrors FallbackReason's style: string values
#: that land verbatim in counters and result dicts)
CAPTURE_TRUNCATED = "capture_truncated"
CLOCK_SKEW_CLAMPED = "clock_skew_clamped"


class VirtualClock:
    """Injectable monotone clock for deterministic replay.

    Drop-in for the ``clock`` seams that already exist across serving
    (``MicroBatcher``, ``CircuitBreaker``, swap probation,
    ``ShardedServingFleet``): calling the instance returns virtual
    seconds. Time only moves via ``advance``/``advance_to`` — never by
    itself — so everything driven by it is wall-clock-independent."""

    def __init__(self, start: float = 0.0):
        self._now = float(start)
        self._lock = threading.Lock()

    def __call__(self) -> float:
        return self._now

    def now(self) -> float:
        return self._now

    def advance(self, dt: float) -> float:
        if dt < 0:
            raise ValueError(f"virtual clock cannot go backwards (dt={dt})")
        with self._lock:
            self._now += float(dt)
            return self._now

    def advance_to(self, t: float) -> float:
        """Move to ``t`` if it is in the future; no-op otherwise (the
        monotone clamp callers rely on under injected skew)."""
        with self._lock:
            if t > self._now:
                self._now = float(t)
            return self._now


# --------------------------------------------------------------------------
# capture: crc32-framed append-only JSONL
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class CaptureRecord:
    seq: int
    t: float                     # virtual-clock offset from capture start
    request: ScoreRequest


def _request_wire(req: ScoreRequest) -> dict:
    """The exact shape ``ScoreRequest.from_json`` round-trips."""
    out: Dict[str, object] = {
        "uid": req.uid,
        "features": {sid: [[n, term, v] for n, term, v in rows]
                     for sid, rows in req.features.items()},
        "ids": dict(req.entity_ids),
        "offset": req.offset,
    }
    if req.timeout_s is not None:
        out["timeout_ms"] = req.timeout_s * 1000.0
    if req.tenant is not None:
        out["tenant"] = req.tenant
    return out


def _frame(record: dict) -> bytes:
    """One capture line: the record plus a crc32 over its canonical
    (sorted-key, tight-separator) JSON bytes — the same envelope idiom
    the nearline checkpoints use."""
    body = json.dumps(record, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")
    record = dict(record)
    record["crc"] = zlib.crc32(body) & 0xFFFFFFFF
    return json.dumps(record, sort_keys=True,
                      separators=(",", ":")).encode("utf-8") + b"\n"


def _check_frame(obj: dict) -> bool:
    crc = obj.pop("crc", None)
    if crc is None:
        return False
    body = json.dumps(obj, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")
    return (zlib.crc32(body) & 0xFFFFFFFF) == crc


class CaptureWriter:
    """Append-only traffic recorder. ``append`` flushes+fsyncs per
    record (the event-log durability contract: a record either fully
    exists or is a detectable torn tail, never a silent half)."""

    def __init__(self, path: str):
        self.path = str(path)
        self._f = open(self.path, "ab")
        self.seq = 0

    def append(self, t: float, request: ScoreRequest) -> int:
        record = {"schema": CAPTURE_SCHEMA, "seq": self.seq,
                  "t": float(t), "req": _request_wire(request)}
        line = _frame(record)
        if _chaos.should_kill_capture(self.seq):
            # a kill mid-append: half the bytes land, no newline
            self._f.write(line[:max(1, len(line) // 2)])
            self._f.flush()
            os.fsync(self._f.fileno())
            self._f.close()
            raise _chaos.SimulatedKill(
                f"chaos: capture writer killed mid-append of record "
                f"{self.seq}")
        self._f.write(line)
        self._f.flush()
        os.fsync(self._f.fileno())
        self.seq += 1
        _metrics.counter("replay.capture_records").inc()
        return self.seq - 1

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()

    def __enter__(self) -> "CaptureWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def record_capture(path: str, records: Sequence[Tuple[float, ScoreRequest]]
                   ) -> int:
    """Record a whole (t, request) stream; returns records written."""
    with CaptureWriter(path) as w:
        for t, req in records:
            w.append(t, req)
        return w.seq


def read_capture(path: str) -> Tuple[List[CaptureRecord], dict]:
    """Read a capture, holding back the torn tail.

    Returns ``(records, stats)`` where stats carries the typed counts:
    ``capture_truncated`` (1 when the final record is incomplete or
    fails its crc/parse — the mid-append kill shape; also counted into
    the ``replay.capture_truncated`` registry counter) and
    ``bad_records`` (interior lines that fail parse/crc — skipped,
    like the event reader's interior-corruption handling)."""
    records: List[CaptureRecord] = []
    stats = {CAPTURE_TRUNCATED: 0, "bad_records": 0}
    try:
        with open(path, "rb") as f:
            data = f.read()
    except FileNotFoundError:
        return records, stats

    if not data:
        return records, stats
    complete = data.endswith(b"\n")
    lines = data.split(b"\n")
    if lines and lines[-1] == b"":
        lines.pop()
    tail_torn = not complete
    if tail_torn and lines:
        lines.pop()                      # the partial final line
    n = len(lines)
    for i, line in enumerate(lines):
        ok = False
        try:
            obj = json.loads(line)
            if _check_frame(dict(obj)):
                records.append(CaptureRecord(
                    seq=int(obj["seq"]), t=float(obj["t"]),
                    request=ScoreRequest.from_json(obj["req"])))
                ok = True
        except (ValueError, KeyError, TypeError):
            ok = False
        if not ok:
            if i == n - 1:
                # an unparseable FINAL complete record is indistinguishable
                # from a torn append whose newline made it out: held back
                # as truncation, same as the event reader
                tail_torn = True
            else:
                stats["bad_records"] += 1
    if tail_torn:
        stats[CAPTURE_TRUNCATED] = 1
        _metrics.counter("replay.capture_truncated").inc()
    return records, stats


# --------------------------------------------------------------------------
# synthetic traffic: counter-derived, bitwise deterministic
# --------------------------------------------------------------------------

# the splitmix64 stream machinery moved to utils/seeds.py (PR 20) so the
# Thompson scorer shares it; these aliases are bit-for-bit the PR 18
# functions — pinned by tests/test_seeds.py forever-vectors
from photon_tpu.utils.seeds import U64 as _U64  # noqa: E402
from photon_tpu.utils.seeds import splitmix64 as _splitmix64  # noqa: E402
from photon_tpu.utils.seeds import stream_u as _u  # noqa: E402


@dataclasses.dataclass(frozen=True)
class TrafficProfile:
    """One synthetic traffic shape. Frozen: the profile (with the seed)
    IS the identity of the stream — the bitwise-determinism contract is
    ``generate(profile, seed)`` equal byte for byte, run to run.

    ``kind`` shapes the arrival RATE; entity skew is always Zipf:

      * ``zipf``        — constant ``base_qps``
      * ``diurnal``     — ``base_qps * (1 + amp * sin(2π t / period))``
      * ``burst``       — ``base_qps * burst_factor`` inside
                          ``[burst_at_s, burst_at_s + burst_len_s)``
      * ``flash_crowd`` — rate ramps to ``flash_factor ×`` over
                          ``flash_ramp_s`` from ``flash_at_s`` AND
                          traffic concentrates onto the hottest
                          ``flash_entity_frac`` of the entity space
    """

    kind: str = "zipf"
    n_requests: int = 1000
    #: entity-space size — a modulus, not an allocation: 10M is free
    entities: int = 1_000_000
    zipf_a: float = 1.5
    base_qps: float = 1000.0
    feature_dim: int = 8
    nnz: int = 4
    feature_shard: str = "g"
    re_type: str = "userId"
    entity_format: str = "e{:09d}"
    timeout_ms: Optional[float] = None
    tenant: Optional[str] = None
    # diurnal
    diurnal_period_s: float = 60.0
    diurnal_amplitude: float = 0.5
    # burst
    burst_at_s: float = 2.0
    burst_len_s: float = 2.0
    burst_factor: float = 4.0
    # flash crowd
    flash_at_s: float = 2.0
    flash_ramp_s: float = 2.0
    flash_factor: float = 8.0
    flash_entity_frac: float = 1e-4

    def __post_init__(self):
        if self.kind not in ("zipf", "diurnal", "burst", "flash_crowd"):
            raise ValueError(f"unknown traffic kind {self.kind!r}")
        if self.zipf_a <= 1.0:
            raise ValueError("zipf_a must be > 1")
        if self.n_requests < 1 or self.entities < 1 or self.base_qps <= 0:
            raise ValueError("n_requests/entities/base_qps must be positive")

    def rate(self, t: float) -> float:
        if self.kind == "diurnal":
            return self.base_qps * max(
                1e-6, 1.0 + self.diurnal_amplitude
                * math.sin(2.0 * math.pi * t / self.diurnal_period_s))
        if self.kind == "burst":
            in_burst = self.burst_at_s <= t < self.burst_at_s \
                + self.burst_len_s
            return self.base_qps * (self.burst_factor if in_burst else 1.0)
        if self.kind == "flash_crowd":
            ramp = min(max((t - self.flash_at_s) / self.flash_ramp_s, 0.0),
                       1.0)
            return self.base_qps * (1.0 + (self.flash_factor - 1.0) * ramp)
        return self.base_qps


def _zipf_rank(u: float, a: float) -> int:
    """Inverse-CDF Zipf over an unbounded rank space: rank 1 is the
    hottest entity. Power-law tail ``P(rank > r) ~ r^(1-a)``."""
    return int(u ** (-1.0 / (a - 1.0)))


def generate(profile: TrafficProfile, seed: int
             ) -> List[Tuple[float, ScoreRequest]]:
    """The bitwise-deterministic stream: ``[(t, request), ...]`` with
    strictly increasing ``t`` (exponential inter-arrivals under the
    profile's rate shape)."""
    out: List[Tuple[float, ScoreRequest]] = []
    t = 0.0
    hot = max(1, int(profile.entities * profile.flash_entity_frac))
    for i in range(profile.n_requests):
        rate = profile.rate(t)
        t += -math.log(_u(seed, "arrival", i)) / rate
        # entity: Zipf rank folded into the entity space
        ue = _u(seed, "entity", i)
        idx = (_zipf_rank(ue, profile.zipf_a) - 1) % profile.entities
        if profile.kind == "flash_crowd" and t >= profile.flash_at_s:
            ramp = min((t - profile.flash_at_s) / profile.flash_ramp_s, 1.0)
            if _u(seed, "flash", i) < 0.9 * ramp:
                idx = int(_u(seed, "flash_pick", i) * hot) % hot
        eid = profile.entity_format.format(idx)
        # features: nnz DISTINCT (index, gaussian value) pairs, Box-Muller
        # off the counter streams — library-free, so bitwise across
        # platforms (distinct: the assembler's slot packing expects one
        # column per feature per request)
        rows = []
        used = set()
        for j in range(min(profile.nnz, profile.feature_dim)):
            fidx = int(_u(seed, f"feat{j}", i) * profile.feature_dim) \
                % profile.feature_dim
            while fidx in used:
                fidx = (fidx + 1) % profile.feature_dim
            used.add(fidx)
            u1 = _u(seed, f"val_a{j}", i)
            u2 = _u(seed, f"val_b{j}", i)
            val = math.sqrt(-2.0 * math.log(u1)) \
                * math.cos(2.0 * math.pi * u2)
            rows.append((f"f{fidx}", "", val))
        req = ScoreRequest(
            uid=f"r{i:08d}",
            features={profile.feature_shard: rows},
            entity_ids={profile.re_type: eid},
            timeout_s=(profile.timeout_ms / 1000.0
                       if profile.timeout_ms is not None else None),
            tenant=profile.tenant)
        out.append((t, req))
    return out


def stream_digest(records: Sequence[Tuple[float, ScoreRequest]]) -> str:
    """crc32 chain over the stream's canonical bytes — the cheap bitwise
    identity two generated (or captured) streams are compared by."""
    crc = 0
    for t, req in records:
        body = json.dumps({"t": t, "req": _request_wire(req)},
                          sort_keys=True,
                          separators=(",", ":")).encode("utf-8")
        crc = zlib.crc32(body, crc)
    return f"{crc & 0xFFFFFFFF:08x}"


# --------------------------------------------------------------------------
# replay
# --------------------------------------------------------------------------


@dataclasses.dataclass
class ReplayResult:
    requests: int = 0
    responses: int = 0
    refusals: int = 0
    degraded: int = 0
    clock_skew_clamped: int = 0
    virtual_seconds: float = 0.0
    #: crc32 chain over (uid, repr(score), sorted fallback reasons) in
    #: completion order — bitwise identity of the replay's OUTPUT
    response_digest: str = "00000000"
    degraded_reasons: Dict[str, int] = dataclasses.field(default_factory=dict)

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


class Replayer:
    """Deterministic replay of a (t, request) stream into a target.

    ``target`` is either an async engine (``submit``/``pump``/``drain``:
    ServingEngine, MultiTenantEngine) or a serve-only router
    (ShardedServingFleet). The target must have been built on the SAME
    ``clock`` instance passed here — the existing injectable-clock seams
    (MicroBatcher coalescing, breaker cooldowns, swap probation, fleet
    deadlines) then all advance in virtual time and the whole replay is
    wall-clock-independent.

    ``actions`` to :meth:`run` is a list of ``(t, callable)`` incident
    hooks (kill a shard, publish a model, flip chaos) fired exactly when
    the virtual clock first reaches ``t``.
    """

    def __init__(self, target, clock: VirtualClock,
                 registry: Optional[WindowedRegistry] = None,
                 labels: Optional[Dict[str, str]] = None,
                 tick_s: float = 0.05):
        if tick_s <= 0:
            raise ValueError("tick_s must be positive")
        self.target = target
        self.clock = clock
        self.registry = registry if registry is not None else _series
        self.labels = dict(labels or {})
        self.tick_s = float(tick_s)
        self._async = hasattr(target, "submit") and hasattr(target, "pump")

    # -- telemetry helpers ------------------------------------------------

    def _observe(self, resp: ScoreResponse, t_arrival: Optional[float],
                 t_done: float, result: ReplayResult, crc: int) -> int:
        reg = self.registry
        result.responses += 1
        reg.counter("replay.responses", **self.labels).inc(t_done)
        if t_arrival is not None:
            reg.quantile("replay.latency", **self.labels).observe(
                t_done, max(t_done - t_arrival, 0.0))
        reasons = sorted({f.reason.value for f in resp.fallbacks})
        if resp.degraded or resp.score is None:
            result.degraded += 1
            for r in reasons:
                result.degraded_reasons[r] = \
                    result.degraded_reasons.get(r, 0) + 1
                reg.counter("replay.degraded", reason=r,
                            **self.labels).inc(t_done)
        if resp.score is None:
            result.refusals += 1
        body = f"{resp.uid}|{resp.score!r}|{','.join(reasons)}".encode()
        return zlib.crc32(body, crc)

    # -- main entry -------------------------------------------------------

    def run(self, records: Sequence, actions: Sequence[Tuple[float,
            Callable[[], None]]] = ()) -> ReplayResult:
        """Replay ``records`` — either ``CaptureRecord``s or plain
        ``(t, request)`` pairs — against the target."""
        norm: List[Tuple[int, float, ScoreRequest]] = []
        for i, rec in enumerate(records):
            if isinstance(rec, CaptureRecord):
                norm.append((rec.seq, rec.t, rec.request))
            else:
                t, req = rec
                norm.append((i, float(t), req))
        pending_actions = sorted(actions, key=lambda a: a[0])
        result = ReplayResult(requests=len(norm))
        t0 = self.clock.now()
        if self._async:
            crc = self._run_async(norm, pending_actions, result)
        else:
            crc = self._run_sync(norm, pending_actions, result)
        result.response_digest = f"{crc & 0xFFFFFFFF:08x}"
        result.virtual_seconds = self.clock.now() - t0
        if result.clock_skew_clamped:
            _metrics.counter("replay.clock_skew_clamped").inc(
                result.clock_skew_clamped)
        return result

    def _fire_actions(self, pending: List[Tuple[float, Callable]],
                      upto: float) -> None:
        while pending and pending[0][0] <= upto:
            t_act, fn = pending.pop(0)
            self.clock.advance_to(t_act)
            fn()

    def _arrival_time(self, seq: int, t: float, base: float,
                      result: ReplayResult) -> float:
        """Record offset -> absolute virtual time, with injected skew
        applied and the monotone clamp (typed) enforced."""
        t_abs = base + t + _chaos.replay_clock_skew(seq)
        now = self.clock.now()
        if t_abs < now:
            result.clock_skew_clamped += 1
            self.registry.counter("replay.clock_skew_clamped",
                                  **self.labels).inc(now)
            return now
        return t_abs

    def _run_async(self, norm, pending_actions, result: ReplayResult) -> int:
        target, reg = self.target, self.registry
        base = self.clock.now()
        submits: Dict[str, List[float]] = {}
        crc = 0
        for seq, t, req in norm:
            t_abs = self._arrival_time(seq, t, base, result)
            self._fire_actions(pending_actions, t_abs)
            self.clock.advance_to(t_abs)
            reg.counter("replay.requests", **self.labels).inc(t_abs)
            submits.setdefault(req.uid, []).append(t_abs)
            refusal = target.submit(req)
            if refusal is not None:
                submits[req.uid].pop()
                crc = self._observe(refusal, t_abs, t_abs, result, crc)
            while True:
                got = target.pump()
                if not got:
                    break
                t_done = self.clock.now()
                for resp in got:
                    ts = submits.get(resp.uid)
                    t_arr = ts.pop(0) if ts else None
                    crc = self._observe(resp, t_arr, t_done, result, crc)
        # drain: step virtual time forward so coalescing windows expire,
        # then flush whatever remains
        self._fire_actions(pending_actions, float("inf"))
        for _ in range(64):
            self.clock.advance(self.tick_s)
            got = target.pump()
            while got:
                t_done = self.clock.now()
                for resp in got:
                    ts = submits.get(resp.uid)
                    t_arr = ts.pop(0) if ts else None
                    crc = self._observe(resp, t_arr, t_done, result, crc)
                got = target.pump()
            if not self._target_depth():
                break
        if self._target_depth() and hasattr(target, "drain"):
            t_done = self.clock.now()
            for resp in target.drain():
                ts = submits.get(resp.uid)
                t_arr = ts.pop(0) if ts else None
                crc = self._observe(resp, t_arr, t_done, result, crc)
        return crc

    def _target_depth(self) -> int:
        batcher = getattr(self.target, "batcher", None)
        if batcher is not None:
            return batcher.depth()
        depth_fn = getattr(self.target, "depth", None)
        if callable(depth_fn):
            try:
                return int(depth_fn())
            except Exception:
                return 0
        return 0

    def _run_sync(self, norm, pending_actions, result: ReplayResult) -> int:
        """Serve-only targets (the fleet router): arrivals grouped into
        ``tick_s`` ticks, each tick served synchronously at its virtual
        end time; per-request latency = tick end − arrival (queueing
        delay in virtual time — the synchronous service itself is
        instantaneous on the virtual clock)."""
        target, reg = self.target, self.registry
        base = self.clock.now()
        crc = 0
        i = 0
        n = len(norm)
        while i < n:
            seq, t, req = norm[i]
            t_abs = self._arrival_time(seq, t, base, result)
            tick_end = (math.floor((t_abs - base) / self.tick_s) + 1) \
                * self.tick_s + base
            batch: List[ScoreRequest] = []
            arrivals: List[float] = []
            while i < n:
                seq, t, req = norm[i]
                t_abs = self._arrival_time(seq, t, base, result)
                if t_abs >= tick_end:
                    break
                reg.counter("replay.requests", **self.labels).inc(t_abs)
                batch.append(req)
                arrivals.append(t_abs)
                i += 1
            self._fire_actions(pending_actions, tick_end)
            self.clock.advance_to(tick_end)
            responses = target.serve(batch)
            t_done = self.clock.now()
            for resp, t_arr in zip(responses, arrivals):
                crc = self._observe(resp, t_arr, t_done, result, crc)
        self._fire_actions(pending_actions, float("inf"))
        return crc


def timeline_digest(snapshot: dict,
                    prefixes: Tuple[str, ...] = ("replay.",)) -> str:
    """crc32 over the canonical bytes of the snapshot's deterministic
    timeline series (default: the ``replay.*`` family, whose counts AND
    latencies live purely in virtual time). Series carrying wall-clock
    durations (``serving.latency``, ``fleet.shard.latency``) are
    excluded by default — their per-window counts replay identically
    but their sketch contents are genuine measured seconds."""
    ts = snapshot.get("timeseries", {})
    picked = {k: v for k, v in sorted(ts.items())
              if any(k.startswith(p) for p in prefixes)}
    body = json.dumps(picked, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")
    return f"{zlib.crc32(body) & 0xFFFFFFFF:08x}"
