"""Bucketed micro-batching: admission queue + power-of-two bucket ladder.

Snap ML's observation (PAPERS.md) carried to serving: the win comes from
keeping device state resident and feeding it *fixed-shape* work. Every
distinct batch shape is a separate XLA executable, so the batcher never
emits an arbitrary batch size — it coalesces queued requests into the
smallest ladder bucket that fits (padding the remainder with zero-weight
rows) and the ladder is finite, so the compile set is finite and fully
warmable at model-load time.

The clock is injected (``clock=``) so the coalescing policy is unit-
testable without sleeping: tests advance a fake clock and assert exactly
when a batch forms.
"""

from __future__ import annotations

import threading
from typing import Callable, List, NamedTuple, Optional, Sequence, Tuple

from photon_tpu.serving.types import ScoreRequest


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


class BucketLadder:
    """Fixed ladder of power-of-two batch sizes, ``min_bucket..max_batch``."""

    def __init__(self, max_batch: int = 64, min_bucket: int = 1):
        if min_bucket < 1 or max_batch < min_bucket:
            raise ValueError(f"bad ladder bounds [{min_bucket}, {max_batch}]")
        lo, hi = _next_pow2(min_bucket), _next_pow2(max_batch)
        b, buckets = lo, []
        while b <= hi:
            buckets.append(b)
            b *= 2
        self.buckets: Tuple[int, ...] = tuple(buckets)

    @property
    def max_batch(self) -> int:
        return self.buckets[-1]

    def bucket_for(self, n: int) -> int:
        """Smallest bucket that fits ``n`` requests (ladder top when
        ``n`` exceeds it — the caller takes at most ``max_batch``)."""
        if n <= 0:
            raise ValueError(f"bucket_for({n})")
        for b in self.buckets:
            if n <= b:
                return b
        return self.max_batch


class QueueClosedError(RuntimeError):
    """Submit after ``close()`` — the engine is draining. Library callers
    get this typed error; the engine turns it into a SHUTTING_DOWN
    response so the hot path never leaks an exception to clients."""


class Pending(NamedTuple):
    request: ScoreRequest
    t_submit: float
    #: absolute deadline on the batcher clock; None = never expires
    deadline: Optional[float] = None


class MicroBatcher:
    """Thread-safe admission queue with deadline-based coalescing.

    A batch is released when (a) the queue holds a full ladder-top batch,
    (b) the OLDEST queued request has waited ``max_wait_s`` (then
    everything pending ships in the smallest covering bucket — the
    padded-remainder case), or (c) a queued request's absolute deadline
    is close enough that waiting any longer would leave it less than
    ``deadline_headroom_s`` to assemble+score — the oldest-waiter wait
    never overrides a tighter per-request deadline. ``flush=True``
    overrides all of it, used at stream end and by synchronous
    ``serve()``.
    """

    def __init__(self, ladder: BucketLadder, max_wait_s: float = 0.002,
                 clock: Optional[Callable[[], float]] = None,
                 deadline_headroom_s: float = 0.0,
                 on_admit: Optional[Callable[[ScoreRequest], None]] = None):
        import time

        self.ladder = ladder
        self.max_wait_s = float(max_wait_s)
        self.deadline_headroom_s = float(deadline_headroom_s)
        self.clock = clock if clock is not None else time.monotonic
        # admission lookahead hook: called once per admitted request,
        # BEFORE it is queued — so by the time any release policy
        # (ladder-top fill, oldest-waiter wait, or a deadline override)
        # can pop the request, the hook has already seen it. The two-tier
        # coefficient store hangs its cold->hot prefetch here. Must be
        # cheap and non-blocking; exceptions are swallowed (a broken
        # lookahead must never refuse admission).
        self.on_admit = on_admit
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: List[Pending] = []
        # set lock-free: close() may run inside a signal handler that
        # interrupted a thread already holding _lock (a non-reentrant
        # acquire there would deadlock the main thread)
        self._closed = False

    def submit(self, request: ScoreRequest,
               deadline: Optional[float] = None) -> None:
        if self._closed:
            raise QueueClosedError("admission queue closed (draining)")
        if self.on_admit is not None:
            try:
                self.on_admit(request)
            except Exception:  # noqa: BLE001 — lookahead is best-effort
                from photon_tpu.obs import metrics as _metrics

                _metrics.counter("serving.admit_lookahead_errors").inc()
        with self._cond:
            self._queue.append(Pending(request, self.clock(), deadline))
            self._cond.notify()

    def close(self) -> None:
        """Stop admission (drain). Lock-free on purpose — safe to call
        from a signal handler; queued work remains poppable."""
        self._closed = True

    @property
    def closed(self) -> bool:
        return self._closed

    def depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def oldest_wait(self) -> Optional[float]:
        with self._lock:
            if not self._queue:
                return None
            return self.clock() - self._queue[0].t_submit

    def ready(self) -> bool:
        with self._lock:
            return self._ready_locked()

    def _ready_locked(self) -> bool:
        q = self._queue
        if not q:
            return False
        if len(q) >= self.ladder.max_batch:
            return True
        now = self.clock()
        if (now - q[0].t_submit) >= self.max_wait_s:
            return True
        # per-request deadlines can be tighter than the oldest-waiter
        # wait: release as soon as the tightest deadline has only the
        # score headroom left (popping exactly at the threshold keeps the
        # request servable — expiry in the engine is strict '>')
        for p in q:
            if (p.deadline is not None
                    and now >= p.deadline - self.deadline_headroom_s):
                return True
        return False

    def next_batch(self, flush: bool = False
                   ) -> Optional[Tuple[Sequence[Pending], int]]:
        """Pop one batch if the release policy allows; None otherwise.
        Returns (pending items, bucket size >= len(items))."""
        with self._lock:
            if not self._queue:
                return None
            if not (flush or self._ready_locked()):
                return None
            take = min(len(self._queue), self.ladder.max_batch)
            items = self._queue[:take]
            del self._queue[:take]
            return items, self.ladder.bucket_for(take)

    def pop_all(self) -> List[Pending]:
        """Take everything still queued (drain-budget exhaustion: the
        engine refuses these with typed SHUTTING_DOWN responses)."""
        with self._lock:
            items = self._queue[:]
            self._queue.clear()
            return items

    def wait_for_work(self, timeout: Optional[float] = None) -> bool:
        """Block until something is queued (background drain loops);
        returns queue non-emptiness. Never used by synchronous paths."""
        with self._cond:
            if self._queue:
                return True
            if self._closed:
                return False
            self._cond.wait(timeout)
            return bool(self._queue)
