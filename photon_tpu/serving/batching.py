"""Bucketed micro-batching: admission queue + power-of-two bucket ladder.

Snap ML's observation (PAPERS.md) carried to serving: the win comes from
keeping device state resident and feeding it *fixed-shape* work. Every
distinct batch shape is a separate XLA executable, so the batcher never
emits an arbitrary batch size — it coalesces queued requests into the
smallest ladder bucket that fits (padding the remainder with zero-weight
rows) and the ladder is finite, so the compile set is finite and fully
warmable at model-load time.

The clock is injected (``clock=``) so the coalescing policy is unit-
testable without sleeping: tests advance a fake clock and assert exactly
when a batch forms.
"""

from __future__ import annotations

import threading
from typing import Callable, List, NamedTuple, Optional, Sequence, Tuple

from photon_tpu.serving.types import ScoreRequest


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


class BucketLadder:
    """Fixed ladder of power-of-two batch sizes, ``min_bucket..max_batch``."""

    def __init__(self, max_batch: int = 64, min_bucket: int = 1):
        if min_bucket < 1 or max_batch < min_bucket:
            raise ValueError(f"bad ladder bounds [{min_bucket}, {max_batch}]")
        lo, hi = _next_pow2(min_bucket), _next_pow2(max_batch)
        b, buckets = lo, []
        while b <= hi:
            buckets.append(b)
            b *= 2
        self.buckets: Tuple[int, ...] = tuple(buckets)

    @property
    def max_batch(self) -> int:
        return self.buckets[-1]

    def bucket_for(self, n: int) -> int:
        """Smallest bucket that fits ``n`` requests (ladder top when
        ``n`` exceeds it — the caller takes at most ``max_batch``)."""
        if n <= 0:
            raise ValueError(f"bucket_for({n})")
        for b in self.buckets:
            if n <= b:
                return b
        return self.max_batch


class Pending(NamedTuple):
    request: ScoreRequest
    t_submit: float


class MicroBatcher:
    """Thread-safe admission queue with deadline-based coalescing.

    A batch is released when either (a) the queue holds a full ladder-top
    batch, or (b) the OLDEST queued request has waited ``max_wait_s``
    (then everything pending ships in the smallest covering bucket —
    the padded-remainder case). ``flush=True`` overrides the deadline,
    used at stream end and by synchronous ``serve()``.
    """

    def __init__(self, ladder: BucketLadder, max_wait_s: float = 0.002,
                 clock: Optional[Callable[[], float]] = None):
        import time

        self.ladder = ladder
        self.max_wait_s = float(max_wait_s)
        self.clock = clock if clock is not None else time.monotonic
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._queue: List[Pending] = []

    def submit(self, request: ScoreRequest) -> None:
        with self._cond:
            self._queue.append(Pending(request, self.clock()))
            self._cond.notify()

    def depth(self) -> int:
        with self._lock:
            return len(self._queue)

    def oldest_wait(self) -> Optional[float]:
        with self._lock:
            if not self._queue:
                return None
            return self.clock() - self._queue[0].t_submit

    def ready(self) -> bool:
        with self._lock:
            return self._ready_locked()

    def _ready_locked(self) -> bool:
        q = self._queue
        if not q:
            return False
        if len(q) >= self.ladder.max_batch:
            return True
        return (self.clock() - q[0].t_submit) >= self.max_wait_s

    def next_batch(self, flush: bool = False
                   ) -> Optional[Tuple[Sequence[Pending], int]]:
        """Pop one batch if the release policy allows; None otherwise.
        Returns (pending items, bucket size >= len(items))."""
        with self._lock:
            if not self._queue:
                return None
            if not (flush or self._ready_locked()):
                return None
            take = min(len(self._queue), self.ladder.max_batch)
            items = self._queue[:take]
            del self._queue[:take]
            return items, self.ladder.bucket_for(take)

    def wait_for_work(self, timeout: Optional[float] = None) -> bool:
        """Block until something is queued (background drain loops);
        returns queue non-emptiness. Never used by synchronous paths."""
        with self._cond:
            if self._queue:
                return True
            self._cond.wait(timeout)
            return bool(self._queue)
