"""The serving engine: admission, SLO ladder, dispatch, telemetry.

Request lifecycle::

    submit() ── draining? ──────────> typed SHUTTING_DOWN response
        │  ── breaker open? ────────> typed BREAKER_REJECTED response
        │  ── deadline infeasible? ─> typed DEADLINE_EXCEEDED response
        │  ── depth > reject? ──────> typed SLO_REJECTED response
        ▼ queue (MicroBatcher, deadline-aware release)
    pump() ── batch ready? ──> expire overdue ──> typed DEADLINE_EXCEEDED
        │                          │ survivors: assemble (host pack, pad)
        ▼                          ▼ depth > shed / breaker shed? fixed_only
    responses <── unpad <── compiled scorer (one dispatch per batch)
                                │ stage latency + ok ──> circuit breaker
                                └ breaker trip in probation? ──> rollback

Everything observable lands in the process metrics registry under the
``serving.*`` namespace; ``stats()`` folds the registry snapshot plus
compile-phase accounting into the dict that becomes the RunReport's
``serving`` section and the BENCH_SERVING payload.

Model state is versioned: ``publish_model`` atomically installs a staged
:class:`~photon_tpu.serving.model_state.DeviceResidentModel` between
micro-batches (serving/swap.py runs the validation gates first) and
keeps the prior version for ``rollback_model`` — which the engine calls
itself when the breaker trips inside the post-swap probation window.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from photon_tpu.obs.metrics import registry as _metrics
from photon_tpu.obs.timeseries import series as _series
from photon_tpu.resilience import chaos as _chaos
from photon_tpu.resilience.failures import record_failure
from photon_tpu.serving.batching import (
    BucketLadder,
    MicroBatcher,
    Pending,
    QueueClosedError,
)
from photon_tpu.serving.breaker import (
    OPEN,
    SHED,
    STATE_LEVELS,
    CircuitBreaker,
)
from photon_tpu.serving.model_state import DeviceResidentModel
from photon_tpu.serving.scorer import (INT8_MODE, THOMPSON_MODE,
                                       dispatch, serving_modes,
                                       warmup_scorers)
from photon_tpu.serving.types import (
    Fallback,
    FallbackReason,
    ScoreRequest,
    ScoreResponse,
    ServingConfig,
)
from photon_tpu.utils import compile_cache

# serving latencies live well under the DEFAULT_BUCKETS floor (5ms);
# ~1.3x geometric steps from 50us to ~5s keep the interpolated
# p50/p95/p99 honest at sub-millisecond scale
LATENCY_BUCKETS = tuple(50e-6 * 1.3 ** i for i in range(36))


class ServingEngine:
    """Online scorer over a device-resident GAME model."""

    def __init__(self, model: DeviceResidentModel,
                 config: Optional[ServingConfig] = None,
                 clock=None, obs_labels: Optional[dict] = None):
        self.model = model
        self.config = config or ServingConfig()
        # metric labels distinguishing this engine in a multi-engine
        # process (tenant=... in a MultiTenantEngine, shard=... in a
        # fleet) — without them every engine overwrites the same plain
        # warmup gauges; with them the per-engine values survive
        # ``obs.merge_snapshots`` as distinct labeled keys
        self.obs_labels = dict(obs_labels or {})
        self.ladder = BucketLadder(self.config.max_batch,
                                   self.config.min_bucket)
        self.batcher = MicroBatcher(
            self.ladder, self.config.max_wait_s, clock=clock,
            deadline_headroom_s=self.config.deadline.score_headroom_s,
            on_admit=self._prefetch_lookahead)
        self.clock = self.batcher.clock
        self.breaker = CircuitBreaker(self.config.breaker, clock=self.clock,
                                      on_transition=self._on_breaker)
        self._warmed = False
        self._warmup_seconds = 0.0
        self._warmup_programs = 0
        # model versioning (live swap): the lock orders publish/rollback
        # against batch dispatch; reads of self.model are a single
        # attribute load, so a swap lands exactly between micro-batches
        self._model_lock = threading.Lock()
        self.model_version = 1
        self.model_label = "initial"
        self._prior: Optional[Tuple[DeviceResidentModel, int, str]] = None
        self._probation_until: Optional[float] = None
        self.swap_history: List[dict] = []
        _metrics.gauge("serving.model_version").set(self.model_version)
        # shadow capture: the most recent admitted requests, the sample a
        # candidate model is validated against before publish
        self._capture: deque = deque(maxlen=self.config.swap.capture_size)
        # rows currently mid-delta-publish, as {(re_type, entity_id)}.
        # Swapped atomically (one attribute store of an immutable set) by
        # the nearline publisher; the admission lookahead consults it so
        # a request racing a publish never prefetches a half-published
        # entity — the publish stays atomic per batch boundary.
        self.pending_publish_rows: frozenset = frozenset()
        # drain state
        self._draining = False
        self._drain_reason: Optional[str] = None
        self._drain_info: Optional[dict] = None

    @classmethod
    def from_model_dir(cls, model_dir: str,
                       config: Optional[ServingConfig] = None,
                       mesh=None, clock=None,
                       coordinates_to_load=None,
                       obs_labels: Optional[dict] = None) -> "ServingEngine":
        from photon_tpu.io.model_io import load_for_serving

        serving_model = load_for_serving(
            model_dir, coordinates_to_load=coordinates_to_load)
        model = DeviceResidentModel(serving_model, mesh=mesh,
                                    feature_pad=(config.feature_pad
                                                 if config else None),
                                    coeff_store=(config.coeff_store
                                                 if config else None),
                                    append_reserve=(config.append_reserve
                                                    if config else 0),
                                    int8=(config.int8_serving
                                          if config else False),
                                    thompson=(config.thompson_serving
                                              if config else False),
                                    prior_variance=(config.prior_variance
                                                    if config else 1.0))
        return cls(model, config=config, clock=clock, obs_labels=obs_labels)

    def _prefetch_lookahead(self, request: ScoreRequest) -> None:
        """MicroBatcher ``on_admit`` hook: resolve the request's entities
        against the two-tier stores at admission so their cold->hot
        uploads are usually done by batch-pop time.

        Consults the pending-publish row set first: an entity whose cold
        row is mid-delta-publish must NOT be prefetched — the promotion
        could read a half-written cold row, or hoist a pre-publish row
        into the hot tier an instant before the commit remaps it. Those
        entities skip the lookahead (counted) and promote on their next
        natural miss after the publish commits."""
        model = self.model
        if not model.has_stores:
            return
        pending = self.pending_publish_rows
        if pending and any(
                (re_type, re_id) in pending
                for re_type, re_id in request.entity_ids.items()):
            _metrics.counter("serving.prefetch_publish_deferred").inc()
            model.prefetch_request(request, skip=pending)
            return
        model.prefetch_request(request)

    # -- warmup --------------------------------------------------------------

    def warmup(self) -> dict:
        """Compile-and-dispatch the whole (mode x bucket) ladder. After
        this returns, steady-state serving performs zero compiles — the
        contract ``scripts/check_serving_no_recompile.py`` enforces."""
        t0 = time.perf_counter()
        self._warmup_programs = warmup_scorers(self.model,
                                               self.ladder.buckets)
        self._warmup_seconds = time.perf_counter() - t0
        self._warmed = True
        _metrics.gauge("serving.warmup_seconds",
                       **self.obs_labels).set(self._warmup_seconds)
        _metrics.gauge("serving.warmup_programs",
                       **self.obs_labels).set(self._warmup_programs)
        return {"programs": self._warmup_programs,
                "buckets": list(self.ladder.buckets),
                "modes": list(serving_modes(self.model)),
                "seconds": self._warmup_seconds,
                "compile_counts": compile_cache.compile_counts()}

    # -- admission -----------------------------------------------------------

    def _refuse(self, request: ScoreRequest, reason: FallbackReason,
                detail: str = "") -> ScoreResponse:
        _metrics.counter("serving.degraded", reason=reason.value).inc()
        # windowed + labeled: per-engine typed-degradation rate over time
        # (the cumulative counter above stays as the run-total shim)
        _series.counter("serving.degraded", reason=reason.value,
                        **self.obs_labels).inc(self.clock())
        return ScoreResponse(
            request.uid, score=None, degraded=True,
            fallbacks=(Fallback(reason, detail=detail),))

    def submit(self, request: ScoreRequest) -> Optional[ScoreResponse]:
        """Admit one request. Returns an immediate typed refusal when the
        engine cannot serve it (draining, breaker open, infeasible
        deadline, queue past the reject threshold), else None (the
        response arrives from a later ``pump``)."""
        _metrics.counter("serving.requests").inc()
        _series.counter("serving.requests",
                        **self.obs_labels).inc(self.clock())
        if self._draining:
            return self._refuse(request, FallbackReason.SHUTTING_DOWN,
                                detail=self._drain_reason or "draining")
        if not self.breaker.admit():
            return self._refuse(request, FallbackReason.BREAKER_REJECTED,
                                detail="circuit breaker open")
        now = self.clock()
        timeout = (request.timeout_s if request.timeout_s is not None
                   else self.config.deadline.default_timeout_s)
        deadline = None
        if timeout is not None:
            if timeout < self.config.deadline.min_service_s:
                return self._refuse(
                    request, FallbackReason.DEADLINE_EXCEEDED,
                    detail=f"budget {timeout * 1e3:.1f}ms below service "
                           f"floor "
                           f"{self.config.deadline.min_service_s * 1e3:.1f}ms")
            deadline = now + timeout
        depth = self.batcher.depth()
        if depth >= self.config.slo.reject_queue_depth:
            return self._refuse(request, FallbackReason.SLO_REJECTED,
                                detail=f"queue depth {depth}")
        try:
            self.batcher.submit(request, deadline=deadline)
        except QueueClosedError:
            # drain began between the flag check and the enqueue (signal
            # handlers land anywhere): still a typed response, never a
            # raised exception to the client
            return self._refuse(request, FallbackReason.SHUTTING_DOWN,
                                detail=self._drain_reason or "draining")
        self._capture.append(request)
        _metrics.gauge("serving.queue_depth").set(self.batcher.depth())
        return None

    def recent_requests(self, n: Optional[int] = None) -> List[ScoreRequest]:
        """The newest admitted requests (shadow-scoring sample for swap)."""
        items = list(self._capture)
        return items if n is None else items[-n:]

    # -- dispatch ------------------------------------------------------------

    def pump(self, flush: bool = False) -> List[ScoreResponse]:
        """Form and score at most one batch; [] when none is ready.
        Drain loops call this repeatedly; ``flush`` overrides the
        coalescing deadline (stream end / synchronous serve)."""
        depth_before = self.batcher.depth()
        popped = self.batcher.next_batch(flush=flush)
        if popped is None:
            return []
        items, _bucket = popped
        # deadline enforcement at the queue->score boundary: requests that
        # can no longer make their deadline are refused instead of
        # occupying a slot; the rest of the batch still scores (in the
        # smallest covering bucket, which warmup has compiled)
        now = self.clock()
        headroom = self.config.deadline.score_headroom_s
        responses: List[ScoreResponse] = []
        live: List[Pending] = []
        for p in items:
            if p.deadline is not None and now > p.deadline - headroom:
                responses.append(self._refuse(
                    p.request, FallbackReason.DEADLINE_EXCEEDED,
                    detail=f"expired in queue after "
                           f"{(now - p.t_submit) * 1e3:.1f}ms"))
            else:
                live.append(p)
        if live:
            bucket = self.ladder.bucket_for(len(live))
            shed = depth_before > self.config.slo.shed_queue_depth
            t_start = self.clock()
            responses.extend(self._score_batch(live, bucket, shed, t_start))
        _metrics.gauge("serving.queue_depth").set(self.batcher.depth())
        return responses

    def _score_batch(self, items: Sequence[Pending], bucket: int,
                     shed: bool, t_start: float) -> List[ScoreResponse]:
        requests = [p.request for p in items]
        full_ok, probe = self.breaker.allow_full()
        breaker_shed = not full_ok
        shed_any = shed or breaker_shed
        model = self.model    # one read: a concurrent publish lands on
        # the next batch, never mid-batch
        if shed_any:
            mode = "fixed_only"
        elif getattr(model, "thompson_enabled", False):
            # explore/exploit IS the healthy-path program for a
            # variance-carrying model under thompson_serving; sheds
            # still drop to fixed_only above (no exploration under
            # pressure), and it outranks int8 (sampling needs f32 vars)
            mode = THOMPSON_MODE
        elif getattr(model, "int8_enabled", False):
            mode = INT8_MODE  # quantized arm IS the healthy-path program
        else:
            mode = "full"
        seeds = None
        if mode == THOMPSON_MODE:
            # per-request sampling keys from the uid alone: bitwise
            # replay-stable no matter how requests batch or arrive
            from photon_tpu.utils.seeds import request_key, split32

            hi = np.zeros(bucket, np.uint32)
            lo = np.zeros(bucket, np.uint32)
            for i, r in enumerate(requests):
                hi[i], lo[i] = split32(
                    request_key(self.config.thompson_seed, r.uid))
            seeds = (hi, lo)

        # two-tier consistency contract: assemble (slot lookups against the
        # host-side hot maps), the table read, and the scorer DISPATCH all
        # happen in ONE transfer_lock hold, so the transfer thread cannot
        # donate a table or remap a slot between the lookup and the gather
        # that consumes it. Only the dispatch is inside the lock — the
        # blocking np.asarray materialization happens after release, so
        # transfers overlap device compute. Full-resident models share the
        # same (uncontended) lock, keeping one code path.
        scorer_ok = True
        scores = None
        raw = None
        with model.transfer_lock:
            t0 = time.perf_counter()
            args, fallbacks, counters = model.assemble(
                requests, bucket, shed_random=shed_any,
                explore_unknown=(mode == THOMPSON_MODE))
            t_assemble = time.perf_counter() - t0

            t0 = time.perf_counter()
            try:
                delay = _chaos.scorer_delay()
                if delay > 0:
                    time.sleep(delay)
                raw = dispatch(model, mode, bucket, args, seeds=seeds)
            except Exception as e:  # device/dispatch fault: typed, counted
                scorer_ok = False
                record_failure("serving_scorer_error", error=repr(e),
                               bucket=bucket, mode=mode)
        if scorer_ok:
            try:
                scores = np.asarray(raw)
            except Exception as e:
                scorer_ok = False
                record_failure("serving_scorer_error", error=repr(e),
                               bucket=bucket, mode=mode)
        t_score = time.perf_counter() - t0

        n = len(requests)
        if scores is not None and not np.all(np.isfinite(scores[:n])):
            scorer_ok = False
            record_failure("serving_nonfinite_scores", bucket=bucket,
                           mode=mode,
                           count=int(np.sum(~np.isfinite(scores[:n]))))
        self.breaker.record(t_score, scorer_ok, probe=probe)
        self._check_probation()

        if not scorer_ok:
            _metrics.counter("serving.responses").inc(n)
            _metrics.counter("serving.batches", bucket=str(bucket),
                             mode=mode).inc()
            return [self._refuse(r, FallbackReason.SCORER_FAILURE,
                                 detail="scorer raised" if scores is None
                                 else "non-finite score")
                    for r in requests]

        if shed:
            for fb in fallbacks:
                fb.append(Fallback(FallbackReason.SLO_SHED_RANDOM_EFFECTS,
                                   detail=f"batch mode {mode}"))
        elif breaker_shed:
            for fb in fallbacks:
                fb.append(Fallback(
                    FallbackReason.BREAKER_SHED_RANDOM_EFFECTS,
                    detail="circuit breaker shed"))

        responses = []
        lat_series = _series.quantile("serving.latency", mode=mode,
                                      **self.obs_labels)
        resp_series = _series.counter("serving.responses", **self.obs_labels)
        for i, (pending, req) in enumerate(zip(items, requests)):
            fbs = tuple(fallbacks[i])
            responses.append(ScoreResponse(
                req.uid, score=float(scores[i]),
                degraded=bool(fbs), fallbacks=fbs))
            # queue time from the injected clock (deterministic in tests);
            # total = queue + host assemble + device score
            q = max(t_start - pending.t_submit, 0.0)
            _metrics.histogram("serving.latency_seconds", LATENCY_BUCKETS,
                               stage="queue").observe(q)
            _metrics.histogram("serving.latency_seconds", LATENCY_BUCKETS,
                               stage="total").observe(q + t_assemble + t_score)
            # per-label windowed quantiles: THIS engine's latency in THIS
            # window, so tenant/shard tails never pollute each other the
            # way the process-global histograms above do
            lat_series.observe(t_start, q + t_assemble + t_score)
            resp_series.inc(t_start)

        _metrics.counter("serving.responses").inc(len(responses))
        _metrics.counter("serving.batches", bucket=str(bucket),
                         mode=mode).inc()
        _metrics.counter("serving.padded_rows").inc(counters["padded_rows"])
        if counters["truncated_features"]:
            _metrics.counter("serving.degraded",
                             reason=FallbackReason.FEATURE_OVERFLOW.value
                             ).inc(counters["truncated_features"])
        if counters["unknown_entities"]:
            _metrics.counter("serving.degraded",
                             reason=FallbackReason.UNKNOWN_ENTITY.value
                             ).inc(counters["unknown_entities"])
        if counters.get("cold_misses"):
            _metrics.counter("serving.degraded",
                             reason=FallbackReason.COLD_MISS.value
                             ).inc(counters["cold_misses"])
        if counters.get("explored_cold_start"):
            _metrics.counter(
                "serving.degraded",
                reason=FallbackReason.EXPLORING_COLD_START.value
                ).inc(counters["explored_cold_start"])
        if shed:
            _metrics.counter(
                "serving.degraded",
                reason=FallbackReason.SLO_SHED_RANDOM_EFFECTS.value
                ).inc(len(responses))
        elif breaker_shed:
            _metrics.counter(
                "serving.degraded",
                reason=FallbackReason.BREAKER_SHED_RANDOM_EFFECTS.value
                ).inc(len(responses))
        _metrics.histogram("serving.latency_seconds", LATENCY_BUCKETS,
                           stage="assemble").observe(t_assemble)
        _metrics.histogram("serving.latency_seconds", LATENCY_BUCKETS,
                           stage="score").observe(t_score)
        return responses

    # -- circuit breaker wiring ----------------------------------------------

    def _on_breaker(self, frm: str, to: str, why: str) -> None:
        _metrics.gauge("serving.breaker_state").set(STATE_LEVELS[to])
        _metrics.counter("serving.breaker_transitions", to=to).inc()
        if to in (SHED, OPEN):
            record_failure("serving_breaker_trip", from_state=frm,
                           to_state=to, why=why)

    def _check_probation(self) -> None:
        """Post-swap guard: a breaker trip inside the probation window
        rolls the swap back automatically."""
        until = self._probation_until
        if until is None:
            return
        if self.clock() > until:
            self._probation_until = None
            return
        if self.breaker.state() in (SHED, OPEN):
            self.rollback_model("breaker tripped in post-swap probation")

    # -- live model swap (publish/rollback; gates live in serving/swap.py) ---

    def publish_model(self, staged: DeviceResidentModel,
                      label: str) -> dict:
        """Atomically install a staged (already warmed) model between
        micro-batches. The prior version is retained for rollback; the
        breaker watches the new model for ``swap.probation_s``."""
        with self._model_lock:
            self._prior = (self.model, self.model_version, self.model_label)
            self.model = staged
            self.model_version += 1
            self.model_label = label
            version = self.model_version
            if self.config.swap.probation_s > 0:
                self._probation_until = (self.clock()
                                         + self.config.swap.probation_s)
        _metrics.gauge("serving.model_version").set(version)
        _metrics.counter("serving.swap_published").inc()
        return {"version": version, "label": label}

    def rollback_model(self, why: str) -> bool:
        """Restore the pre-swap model (bitwise: the prior
        DeviceResidentModel object and its compiled programs are reused
        untouched). Returns False when there is nothing to roll back."""
        with self._model_lock:
            if self._prior is None:
                return False
            rolled_from = (self.model_version, self.model_label)
            self.model, self.model_version, self.model_label = self._prior
            self._prior = None
            self._probation_until = None
            version = self.model_version
        _metrics.gauge("serving.model_version").set(version)
        _metrics.counter("serving.swap_rollbacks").inc()
        record_failure("serving_swap_rollback", why=why,
                       from_version=rolled_from[0], from_label=rolled_from[1],
                       to_version=version, to_label=self.model_label)
        self.swap_history.append({
            "outcome": "rolled_back", "why": why,
            "from_version": rolled_from[0], "from_label": rolled_from[1],
            "to_version": version, "to_label": self.model_label,
            "gates": {},
        })
        return True

    # -- graceful drain ------------------------------------------------------

    def begin_drain(self, reason: str = "drain requested") -> None:
        """Flip to draining: admission refuses with typed SHUTTING_DOWN,
        queued work stays poppable. Lock-free flag flips only — safe from
        a signal handler."""
        if self._draining:
            return
        self._draining = True
        self._drain_reason = reason
        self.batcher.close()
        _metrics.gauge("serving.draining").set(1)

    @property
    def draining(self) -> bool:
        return self._draining

    def shutdown(self, drain_budget_s: Optional[float] = None,
                 reason: str = "shutdown") -> List[ScoreResponse]:
        """Graceful drain to completion: flush in-flight micro-batches
        within the drain budget, refuse the remainder with typed
        SHUTTING_DOWN, record the drain outcome for stats/RunReport.
        Returns every response produced (flushed + refused)."""
        self.begin_drain(reason)
        budget = (self.config.drain_budget_s if drain_budget_s is None
                  else drain_budget_s)
        t0 = self.clock()
        out: List[ScoreResponse] = []
        flushed = 0
        while self.batcher.depth() and (self.clock() - t0) < budget:
            got = self.pump(flush=True)
            flushed += sum(1 for r in got if r.score is not None
                           or FallbackReason.SHUTTING_DOWN not in
                           {f.reason for f in r.fallbacks})
            out.extend(got)
        refused = 0
        for p in self.batcher.pop_all():  # budget exhausted
            refused += 1
            out.append(self._refuse(
                p.request, FallbackReason.SHUTTING_DOWN,
                detail=f"drain budget {budget:.3f}s exhausted"))
        seconds = self.clock() - t0
        self._drain_info = {"reason": self._drain_reason or reason,
                            "budget_s": budget, "seconds": seconds,
                            "flushed": flushed, "refused": refused}
        _metrics.gauge("serving.drain_seconds").set(seconds)
        if refused:
            _metrics.counter("serving.drain_refused").inc(refused)
        # stop two-tier transfer threads with the drain: a drained engine
        # must not keep background threads uploading to the device
        self.model.close_stores()
        if self._prior is not None:
            self._prior[0].close_stores()
        return out

    # -- synchronous convenience --------------------------------------------

    def serve(self, requests: Sequence[ScoreRequest]) -> List[ScoreResponse]:
        """Score a request sequence synchronously, preserving input order.
        Rejected requests still get (typed) responses."""
        # FIFO queue per uid: duplicate uids stay well-defined because
        # batches pop in submission order
        by_uid: Dict[str, List[ScoreResponse]] = {}
        for r in requests:
            rejected = self.submit(r)
            if rejected is not None:
                by_uid.setdefault(r.uid, []).append(rejected)
            while True:
                got = self.pump(flush=self.batcher.depth()
                                >= self.ladder.max_batch)
                if not got:
                    break
                for resp in got:
                    by_uid.setdefault(resp.uid, []).append(resp)
        while self.batcher.depth():
            for resp in self.pump(flush=True):
                by_uid.setdefault(resp.uid, []).append(resp)
        return [by_uid[r.uid].pop(0) for r in requests]

    def drain(self) -> List[ScoreResponse]:
        """Flush every queued request to completion (stream end)."""
        out: List[ScoreResponse] = []
        while self.batcher.depth():
            out.extend(self.pump(flush=True))
        return out

    # -- reporting -----------------------------------------------------------

    def swap_stats(self) -> dict:
        """The ``swap`` section: versions, attempt history (gate outcomes,
        shadow deviations), rollback count — RunReport satellite."""
        hist = list(self.swap_history)
        return {
            "version": self.model_version,
            "label": self.model_label,
            "attempts": sum(1 for h in hist
                            if h.get("outcome") != "rolled_back"),
            "published": sum(1 for h in hist
                             if h.get("outcome") == "published"),
            "rejected": sum(1 for h in hist
                            if h.get("outcome") == "rejected"),
            "rollbacks": sum(1 for h in hist
                             if h.get("outcome") == "rolled_back"),
            "probation_active": self._probation_until is not None,
            "history": hist,
        }

    def stats(self) -> dict:
        """The serving section for RunReport / BENCH_SERVING: model shape,
        ladder, compile-phase accounting, and the latency quantiles."""
        snap = _metrics.snapshot()
        latencies = {}
        for key, h in snap["histograms"].items():
            if key.startswith("serving.latency_seconds{"):
                stage = key.split('stage="')[1].split('"')[0]
                latencies[stage] = {
                    k: h.get(k) for k in ("count", "sum", "p50", "p95", "p99")}
        counters = {k: v for k, v in snap["counters"].items()
                    if k.startswith("serving.")}
        out = {
            "model": self.model.describe(),
            "model_version": self.model_version,
            "model_label": self.model_label,
            "buckets": list(self.ladder.buckets),
            "modes": list(serving_modes(self.model)),
            "warmed": self._warmed,
            "warmup_seconds": self._warmup_seconds,
            "warmup_programs": self._warmup_programs,
            "compile_counts": compile_cache.compile_counts(),
            "queue_depth": self.batcher.depth(),
            "counters": counters,
            "latency_seconds": latencies,
            "slo": {"shed_queue_depth": self.config.slo.shed_queue_depth,
                    "reject_queue_depth": self.config.slo.reject_queue_depth},
            "deadline": {
                "default_timeout_s": self.config.deadline.default_timeout_s,
                "min_service_s": self.config.deadline.min_service_s,
                "score_headroom_s": self.config.deadline.score_headroom_s},
            "breaker": self.breaker.snapshot(),
            "draining": self._draining,
            "swap": self.swap_stats(),
        }
        cs = self.model.coeff_store_stats()
        if cs is not None:
            out["coeff_store"] = cs
        if self._drain_info is not None:
            out["drain"] = dict(self._drain_info)
        return out
