"""The serving engine: admission, SLO ladder, dispatch, telemetry.

Request lifecycle::

    submit() ── depth > reject? ──> typed SLO_REJECTED response
        │
        ▼ queue (MicroBatcher)
    pump() ── batch ready? ──> assemble (host pack, pad to bucket)
        │                          │ depth > shed? fixed_only mode
        ▼                          ▼
    responses <── unpad <── compiled scorer (one dispatch per batch)

Everything observable lands in the process metrics registry under the
``serving.*`` namespace; ``stats()`` folds the registry snapshot plus
compile-phase accounting into the dict that becomes the RunReport's
``serving`` section and the BENCH_SERVING payload.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from photon_tpu.obs.metrics import registry as _metrics
from photon_tpu.serving.batching import BucketLadder, MicroBatcher, Pending
from photon_tpu.serving.model_state import DeviceResidentModel
from photon_tpu.serving.scorer import MODES, get_scorer, warmup_scorers
from photon_tpu.serving.types import (
    Fallback,
    FallbackReason,
    ScoreRequest,
    ScoreResponse,
    ServingConfig,
)
from photon_tpu.utils import compile_cache

# serving latencies live well under the DEFAULT_BUCKETS floor (5ms);
# ~1.3x geometric steps from 50us to ~5s keep the interpolated
# p50/p95/p99 honest at sub-millisecond scale
LATENCY_BUCKETS = tuple(50e-6 * 1.3 ** i for i in range(36))


class ServingEngine:
    """Online scorer over a device-resident GAME model."""

    def __init__(self, model: DeviceResidentModel,
                 config: Optional[ServingConfig] = None,
                 clock=None):
        self.model = model
        self.config = config or ServingConfig()
        self.ladder = BucketLadder(self.config.max_batch,
                                   self.config.min_bucket)
        self.batcher = MicroBatcher(self.ladder, self.config.max_wait_s,
                                    clock=clock)
        self.clock = self.batcher.clock
        self._warmed = False
        self._warmup_seconds = 0.0
        self._warmup_programs = 0

    @classmethod
    def from_model_dir(cls, model_dir: str,
                       config: Optional[ServingConfig] = None,
                       mesh=None, clock=None,
                       coordinates_to_load=None) -> "ServingEngine":
        from photon_tpu.io.model_io import load_for_serving

        serving_model = load_for_serving(
            model_dir, coordinates_to_load=coordinates_to_load)
        model = DeviceResidentModel(serving_model, mesh=mesh,
                                    feature_pad=(config.feature_pad
                                                 if config else None))
        return cls(model, config=config, clock=clock)

    # -- warmup --------------------------------------------------------------

    def warmup(self) -> dict:
        """Compile-and-dispatch the whole (mode x bucket) ladder. After
        this returns, steady-state serving performs zero compiles — the
        contract ``scripts/check_serving_no_recompile.py`` enforces."""
        t0 = time.perf_counter()
        self._warmup_programs = warmup_scorers(self.model,
                                               self.ladder.buckets)
        self._warmup_seconds = time.perf_counter() - t0
        self._warmed = True
        _metrics.gauge("serving.warmup_seconds").set(self._warmup_seconds)
        _metrics.gauge("serving.warmup_programs").set(self._warmup_programs)
        return {"programs": self._warmup_programs,
                "buckets": list(self.ladder.buckets),
                "modes": list(MODES),
                "seconds": self._warmup_seconds,
                "compile_counts": compile_cache.compile_counts()}

    # -- admission -----------------------------------------------------------

    def submit(self, request: ScoreRequest) -> Optional[ScoreResponse]:
        """Admit one request. Returns an immediate typed rejection when
        the queue is past the reject threshold, else None (the response
        arrives from a later ``pump``)."""
        _metrics.counter("serving.requests").inc()
        depth = self.batcher.depth()
        if depth >= self.config.slo.reject_queue_depth:
            _metrics.counter("serving.degraded",
                             reason=FallbackReason.SLO_REJECTED.value).inc()
            return ScoreResponse(
                request.uid, score=None, degraded=True,
                fallbacks=(Fallback(FallbackReason.SLO_REJECTED,
                                    detail=f"queue depth {depth}"),))
        self.batcher.submit(request)
        _metrics.gauge("serving.queue_depth").set(self.batcher.depth())
        return None

    # -- dispatch ------------------------------------------------------------

    def pump(self, flush: bool = False) -> List[ScoreResponse]:
        """Form and score at most one batch; [] when none is ready.
        Drain loops call this repeatedly; ``flush`` overrides the
        coalescing deadline (stream end / synchronous serve)."""
        depth_before = self.batcher.depth()
        popped = self.batcher.next_batch(flush=flush)
        if popped is None:
            return []
        items, bucket = popped
        shed = depth_before > self.config.slo.shed_queue_depth
        t_start = self.clock()
        responses = self._score_batch(items, bucket, shed, t_start)
        _metrics.gauge("serving.queue_depth").set(self.batcher.depth())
        return responses

    def _score_batch(self, items: Sequence[Pending], bucket: int,
                     shed: bool, t_start: float) -> List[ScoreResponse]:
        requests = [p.request for p in items]
        mode = "fixed_only" if shed else "full"

        t0 = time.perf_counter()
        args, fallbacks, counters = self.model.assemble(
            requests, bucket, shed_random=shed)
        t_assemble = time.perf_counter() - t0

        t0 = time.perf_counter()
        scores = get_scorer(self.model, mode, bucket)(*args)
        scores = np.asarray(scores)
        t_score = time.perf_counter() - t0

        if shed:
            for fb in fallbacks:
                fb.append(Fallback(FallbackReason.SLO_SHED_RANDOM_EFFECTS,
                                   detail=f"batch mode {mode}"))

        responses = []
        for i, (pending, req) in enumerate(zip(items, requests)):
            fbs = tuple(fallbacks[i])
            responses.append(ScoreResponse(
                req.uid, score=float(scores[i]),
                degraded=bool(fbs), fallbacks=fbs))
            # queue time from the injected clock (deterministic in tests);
            # total = queue + host assemble + device score
            q = max(t_start - pending.t_submit, 0.0)
            _metrics.histogram("serving.latency_seconds", LATENCY_BUCKETS,
                               stage="queue").observe(q)
            _metrics.histogram("serving.latency_seconds", LATENCY_BUCKETS,
                               stage="total").observe(q + t_assemble + t_score)

        _metrics.counter("serving.responses").inc(len(responses))
        _metrics.counter("serving.batches", bucket=str(bucket),
                         mode=mode).inc()
        _metrics.counter("serving.padded_rows").inc(counters["padded_rows"])
        if counters["truncated_features"]:
            _metrics.counter("serving.degraded",
                             reason=FallbackReason.FEATURE_OVERFLOW.value
                             ).inc(counters["truncated_features"])
        if counters["unknown_entities"]:
            _metrics.counter("serving.degraded",
                             reason=FallbackReason.UNKNOWN_ENTITY.value
                             ).inc(counters["unknown_entities"])
        if shed:
            _metrics.counter(
                "serving.degraded",
                reason=FallbackReason.SLO_SHED_RANDOM_EFFECTS.value
                ).inc(len(responses))
        _metrics.histogram("serving.latency_seconds", LATENCY_BUCKETS,
                           stage="assemble").observe(t_assemble)
        _metrics.histogram("serving.latency_seconds", LATENCY_BUCKETS,
                           stage="score").observe(t_score)
        return responses

    # -- synchronous convenience --------------------------------------------

    def serve(self, requests: Sequence[ScoreRequest]) -> List[ScoreResponse]:
        """Score a request sequence synchronously, preserving input order.
        Rejected requests still get (typed) responses."""
        # FIFO queue per uid: duplicate uids stay well-defined because
        # batches pop in submission order
        by_uid: Dict[str, List[ScoreResponse]] = {}
        for r in requests:
            rejected = self.submit(r)
            if rejected is not None:
                by_uid.setdefault(r.uid, []).append(rejected)
            while True:
                got = self.pump(flush=self.batcher.depth()
                                >= self.ladder.max_batch)
                if not got:
                    break
                for resp in got:
                    by_uid.setdefault(resp.uid, []).append(resp)
        while self.batcher.depth():
            for resp in self.pump(flush=True):
                by_uid.setdefault(resp.uid, []).append(resp)
        return [by_uid[r.uid].pop(0) for r in requests]

    def drain(self) -> List[ScoreResponse]:
        """Flush every queued request to completion (stream end)."""
        out: List[ScoreResponse] = []
        while self.batcher.depth():
            out.extend(self.pump(flush=True))
        return out

    # -- reporting -----------------------------------------------------------

    def stats(self) -> dict:
        """The serving section for RunReport / BENCH_SERVING: model shape,
        ladder, compile-phase accounting, and the latency quantiles."""
        snap = _metrics.snapshot()
        latencies = {}
        for key, h in snap["histograms"].items():
            if key.startswith("serving.latency_seconds{"):
                stage = key.split('stage="')[1].split('"')[0]
                latencies[stage] = {
                    k: h.get(k) for k in ("count", "sum", "p50", "p95", "p99")}
        counters = {k: v for k, v in snap["counters"].items()
                    if k.startswith("serving.")}
        return {
            "model": self.model.describe(),
            "buckets": list(self.ladder.buckets),
            "modes": list(MODES),
            "warmed": self._warmed,
            "warmup_seconds": self._warmup_seconds,
            "warmup_programs": self._warmup_programs,
            "compile_counts": compile_cache.compile_counts(),
            "queue_depth": self.batcher.depth(),
            "counters": counters,
            "latency_seconds": latencies,
            "slo": {"shed_queue_depth": self.config.slo.shed_queue_depth,
                    "reject_queue_depth": self.config.slo.reject_queue_depth},
        }
