"""Live bucket migration for the elastic serving fleet.

One ``BucketMigrator`` moves ONE virtual bucket from its current owner
(the source shard) to a destination shard while the fleet keeps
serving, in four journaled phases:

``copy``
    Chunked copy of the bucket's rows off the source shard's cold
    store into the destination store, riding the SAME atomic in-place
    delta path nearline publishes use (`io/cold_store.
    apply_cold_store_delta`, chaos op ``bucket_copy``). A kill mid-copy
    leaves the destination file failing ``verify()`` typed — the old
    map keeps serving (the router never read the copy) and a resumed
    copy re-applies the identical append set, converging to the same
    bytes.
``double_read``
    The router (`ShardedServingFleet.begin_double_read`) fans every
    request in the bucket to BOTH shards: the source answer is served
    (authoritative, bitwise-unchanged), the destination answer is only
    compared bit-for-bit. Any mismatch poisons the window — cutover is
    refused typed and the new copy is never served.
``reconcile``
    Exactly-once coordination with nearline: rows the publisher
    row-published to the SOURCE mid-copy are re-read and replayed onto
    the destination (chaos op ``bucket_reconcile``), then the whole
    bucket is verified bitwise src == dst.
``cutover``
    Under the router lock: final bitwise parity check, one atomic
    ``fleet-manifest.json`` write (schema v2, version+1, the bucket
    reassigned — chaos op ``fleet_manifest``; a kill between the
    destination commit and the bump leaves the OLD manifest intact and
    ``read_fleet_manifest`` refusing the torn tmp), then the in-router
    assignment swap and window close. Steady-state requests never see
    more than a typed ``BUCKET_MIGRATING`` fallback.

The journal (``migration-journal.json``, crc'd like the fleet
manifest) makes the whole sequence restartable: ``resume_migration``
rolls an interrupted migration forward (copy is idempotent, the
manifest bump is consulted to decide whether cutover already became
durable) or the in-process ``abort`` rolls the destination back
bitwise via the stored undo records.
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from photon_tpu.io.cold_store import (
    ColdStore,
    ColdStoreCapacityError,
    apply_cold_store_delta,
    rollback_cold_store_delta,
    upgrade_cold_store,
)
from photon_tpu.io.fleet_store import (
    FLEET_MANIFEST_SCHEMA_V2,
    read_fleet_manifest,
    shard_store_path,
    write_fleet_manifest,
)
from photon_tpu.obs.metrics import registry as _metrics
from photon_tpu.parallel.partition import BucketMap, entity_shards
from photon_tpu.resilience import io as rio

__all__ = [
    "MIGRATION_JOURNAL_FILE",
    "MIGRATION_JOURNAL_SCHEMA",
    "BucketMigrator",
    "MigrationError",
    "read_migration_journal",
    "resume_migration",
]

MIGRATION_JOURNAL_FILE = "migration-journal.json"
MIGRATION_JOURNAL_SCHEMA = "photon_tpu.fleet.migration.v1"

#: journaled phases, in order
PHASES = ("copy", "double_read", "reconcile", "cutover")


class MigrationError(RuntimeError):
    """A bucket migration was refused or aborted: torn journal, parity
    mismatch, missing destination rows, or a poisoned double-read
    window. Always typed — the old bucket map keeps serving."""


def _journal_path(fleet_dir: str) -> str:
    return os.path.join(fleet_dir, MIGRATION_JOURNAL_FILE)


def _write_journal(fleet_dir: str, doc: dict) -> None:
    body = {k: v for k, v in doc.items() if k != "crc"}
    blob = json.dumps(body, sort_keys=True).encode("utf-8")
    out = dict(body)
    out["crc"] = zlib.crc32(blob) & 0xFFFFFFFF
    rio.atomic_write_bytes(_journal_path(fleet_dir),
                           json.dumps(out, sort_keys=True).encode("utf-8"),
                           op="migration_journal")


def read_migration_journal(fleet_dir: str) -> Optional[dict]:
    """The current migration journal, or None when no migration is in
    flight. A torn/corrupt/unknown-schema journal raises typed — a
    restarted migrator must never guess which phase died."""
    path = _journal_path(fleet_dir)
    if not os.path.exists(path):
        return None
    try:
        doc = json.loads(rio.read_bytes(path, op="migration_journal"))
    except (OSError, ValueError) as e:
        raise MigrationError(
            f"unreadable migration journal {path!r}: {e}") from e
    if doc.get("schema") != MIGRATION_JOURNAL_SCHEMA:
        raise MigrationError(
            f"migration journal {path!r}: unknown schema "
            f"{doc.get('schema')!r}")
    crc = doc.pop("crc", None)
    blob = json.dumps(doc, sort_keys=True).encode("utf-8")
    if crc != zlib.crc32(blob) & 0xFFFFFFFF:
        raise MigrationError(f"migration journal {path!r}: crc mismatch")
    if doc.get("phase") not in PHASES:
        raise MigrationError(
            f"migration journal {path!r}: unknown phase "
            f"{doc.get('phase')!r}")
    return doc


def _clear_journal(fleet_dir: str) -> None:
    path = _journal_path(fleet_dir)
    if os.path.exists(path):
        os.remove(path)


class BucketMigrator:
    """Moves one virtual bucket live. Step methods (``copy`` →
    ``open_double_read`` → ``reconcile`` → ``cutover``) are exposed so
    tests/benches can interleave traffic; ``migrate`` runs them in
    order with an optional ``drive`` callable between window-open and
    reconcile."""

    def __init__(self, fleet, bucket: int, dst: int, *,
                 fleet_dir: Optional[str] = None):
        self.fleet = fleet
        self.fleet_dir = fleet_dir or getattr(fleet, "fleet_dir", None)
        if self.fleet_dir is None:
            raise MigrationError(
                "fleet has no fleet_dir (not built via from_fleet_dir); "
                "pass fleet_dir= explicitly")
        self.bucket = int(bucket)
        self.dst = int(dst)
        bmap: BucketMap = fleet.bucket_map
        if not (0 <= self.bucket < bmap.num_buckets):
            raise MigrationError(
                f"bucket {bucket} out of range [0, {bmap.num_buckets})")
        self.src = int(bmap.shard_of(self.bucket))
        if self.src == self.dst:
            raise MigrationError(
                f"bucket {bucket} already on shard {dst}")
        if self.dst not in fleet._by_id:
            raise MigrationError(f"destination shard {dst} not in fleet")
        self.num_buckets = bmap.num_buckets
        self.coordinates: List[str] = [cid for cid, _ in fleet.coordinates]
        self.window = None
        self.phase = "plan"
        self.copied_rows = 0
        self.reconciled_rows = 0
        self._undo: Dict[str, dict] = {}
        # every coordinate on the destination must be two-tier: the
        # refresh-after-delta seam is how a serving engine sees appended
        # rows without a rebuild (full-resident tables are compiled
        # shapes and cannot grow live)
        for cid in self.coordinates:
            rs = self._random_state(self.dst, cid)
            if rs is not None and rs.store is None:
                raise MigrationError(
                    f"destination shard {dst} serves {cid!r} without a "
                    "two-tier coeff store; live migration needs "
                    "ServingConfig.coeff_store on shard engines")

    # ---------------------------------------------------------- helpers

    def _random_state(self, shard_id: int, cid: str):
        model = self.fleet._by_id[shard_id].engine.model
        for rs in model.random:
            if rs.coordinate_id == cid:
                return rs
        return None

    def _refresh(self, shard_id: int, cid: str) -> None:
        """Reopen a shard engine's cold file after a delta so serving
        sees the new rows (same seam the nearline publisher uses)."""
        rs = self._random_state(shard_id, cid)
        if rs is None or rs.store is None:
            return
        with rs.store.publish_lock:
            with rs.store.lock:
                rs.store.refresh_cold_locked()

    def _journal(self, phase: str) -> None:
        self.phase = phase
        _write_journal(self.fleet_dir, {
            "schema": MIGRATION_JOURNAL_SCHEMA,
            "bucket": self.bucket,
            "src": self.src,
            "dst": self.dst,
            "num_buckets": self.num_buckets,
            "phase": phase,
            "coordinates": self.coordinates,
        })

    def _bucket_rows(self, store: ColdStore
                     ) -> Tuple[List[str], np.ndarray]:
        """(entity ids, storage rows) of this bucket's rows in
        ``store`` — vectorized over the whole id table."""
        if not store.num_entities:
            return [], np.zeros(0, np.int64)
        ids = store.entity_ids_array()
        # same crc-mod math as entity_buckets, minus the power-of-two
        # gate (identity maps carry v1's any-N bucket count)
        buckets = entity_shards(ids, self.num_buckets)
        rows = np.nonzero(buckets == self.bucket)[0].astype(np.int64)
        sel = ids[rows]
        return [i.decode("utf-8") if isinstance(i, bytes) else str(i)
                for i in sel], rows

    # ------------------------------------------------------------ phases

    def copy(self) -> dict:
        """Phase 1: journal, then copy the bucket's rows into the
        destination stores via the atomic delta path. Idempotent — ids
        already present on the destination become bitwise row updates,
        so a resumed copy converges to the same file bytes."""
        self._journal("copy")
        copied = {}
        for cid in self.coordinates:
            copied[cid] = self._copy_coordinate(cid)
        self.copied_rows = sum(copied.values())
        _metrics.counter("fleet.migration.copied_rows").inc(
            self.copied_rows)
        return copied

    def _copy_coordinate(self, cid: str) -> int:
        src_path = shard_store_path(self.fleet_dir, self.src, cid)
        dst_path = shard_store_path(self.fleet_dir, self.dst, cid)
        src_store = ColdStore(src_path)
        ids, rows = self._bucket_rows(src_store)
        if not len(rows):
            return 0
        coef = src_store.read_rows(rows)
        proj = src_store.read_proj_rows(rows)
        dst_store = ColdStore(dst_path)
        upd_rows, upd_idx, app_idx = [], [], []
        for i, eid in enumerate(ids):
            r = dst_store.entity_row(eid)
            if r is None:
                app_idx.append(i)
            else:
                upd_rows.append(r)
                upd_idx.append(i)
        kw = dict(chaos_op="bucket_copy", normalize=True)
        if upd_idx:
            kw.update(update_rows=np.asarray(upd_rows, np.int64),
                      update_coef=coef[upd_idx],
                      update_proj=proj[upd_idx])
        if app_idx:
            kw.update(append_ids=[ids[i] for i in app_idx],
                      append_coef=coef[app_idx],
                      append_proj=proj[app_idx])
        try:
            undo = apply_cold_store_delta(dst_path, **kw)
        except ColdStoreCapacityError:
            blob_need = sum(len(ids[i].encode("utf-8")) for i in app_idx)
            cap = dst_store.num_entities + len(app_idx)
            upgrade_cold_store(
                dst_path,
                capacity=cap + max(16, cap // 4),
                id_blob_cap=2 * (dst_store._h["id_blob_used"]
                                 + blob_need) + 256)
            self._refresh(self.dst, cid)
            undo = apply_cold_store_delta(dst_path, **kw)
        self._undo[cid] = undo
        self._refresh(self.dst, cid)
        return len(rows)

    def open_double_read(self):
        """Phase 2: journal, then open the router's double-read window
        (source keeps serving, destination is mirrored + compared). An
        already-open window for this bucket (in-process resume) is
        adopted rather than re-opened."""
        self._journal("double_read")
        with self.fleet._router_lock:
            w = self.fleet._migrations.get(self.bucket)
            if w is not None:
                if w.dst != self.dst:
                    raise MigrationError(
                        f"bucket {self.bucket} already migrating to "
                        f"shard {w.dst}, not {self.dst}")
                self.window = w
                return w
        self.window = self.fleet.begin_double_read(self.bucket, self.dst)
        return self.window

    def reconcile(self) -> dict:
        """Phase 3: replay rows nearline published to the source
        mid-copy onto the destination, then verify the whole bucket
        bitwise src == dst. Raises typed on any missing or
        still-divergent row."""
        self._journal("reconcile")
        out = {}
        for cid in self.coordinates:
            out[cid] = self._reconcile_coordinate(cid)
        self.reconciled_rows = sum(out.values())
        diverged = self._parity_failures()
        if diverged:
            raise MigrationError(
                f"bucket {self.bucket} reconcile failed bitwise parity: "
                f"{diverged[:3]}")
        return out

    def _reconcile_coordinate(self, cid: str) -> int:
        src_path = shard_store_path(self.fleet_dir, self.src, cid)
        dst_path = shard_store_path(self.fleet_dir, self.dst, cid)
        src_store = ColdStore(src_path)
        ids, rows = self._bucket_rows(src_store)
        if not len(rows):
            return 0
        coef = src_store.read_rows(rows)
        proj = src_store.read_proj_rows(rows)
        dst_store = ColdStore(dst_path)
        upd_rows, upd_idx, app_idx = [], [], []
        for i, eid in enumerate(ids):
            r = dst_store.entity_row(eid)
            if r is None:
                app_idx.append(i)     # published mid-copy as a NEW row
                continue
            if (dst_store.read_rows(np.asarray([r])).tobytes()
                    != coef[i:i + 1].tobytes()
                    or dst_store.read_proj_rows(
                        np.asarray([r])).tobytes()
                    != proj[i:i + 1].tobytes()):
                upd_rows.append(r)
                upd_idx.append(i)
        if not upd_idx and not app_idx:
            return 0
        kw = dict(chaos_op="bucket_reconcile", normalize=True)
        if upd_idx:
            kw.update(update_rows=np.asarray(upd_rows, np.int64),
                      update_coef=coef[upd_idx],
                      update_proj=proj[upd_idx])
        if app_idx:
            kw.update(append_ids=[ids[i] for i in app_idx],
                      append_coef=coef[app_idx],
                      append_proj=proj[app_idx])
        apply_cold_store_delta(dst_path, **kw)
        self._refresh(self.dst, cid)
        return len(upd_idx) + len(app_idx)

    def _parity_failures(self) -> List[str]:
        """Bitwise src-vs-dst comparison of every bucket row, per
        coordinate — the pure check cutover repeats under the router
        lock. Returns typed failure strings, empty == parity."""
        fails: List[str] = []
        for cid in self.coordinates:
            src_store = ColdStore(
                shard_store_path(self.fleet_dir, self.src, cid))
            dst_store = ColdStore(
                shard_store_path(self.fleet_dir, self.dst, cid))
            ids, rows = self._bucket_rows(src_store)
            if not len(rows):
                continue
            coef = src_store.read_rows(rows)
            proj = src_store.read_proj_rows(rows)
            for i, eid in enumerate(ids):
                r = dst_store.entity_row(eid)
                if r is None:
                    fails.append(f"{cid}:{eid}: missing on dst")
                    continue
                if (dst_store.read_rows(np.asarray([r])).tobytes()
                        != coef[i:i + 1].tobytes()
                        or dst_store.read_proj_rows(
                            np.asarray([r])).tobytes()
                        != proj[i:i + 1].tobytes()):
                    fails.append(f"{cid}:{eid}: row bytes diverge")
        return fails

    def cutover(self) -> dict:
        """Phase 4, under the router lock: refuse a poisoned window,
        re-verify bitwise parity, write the v2 manifest bump (the ONE
        durable commit point — atomic, old manifest intact on a kill),
        swap the in-router assignment, close the window, clear the
        journal."""
        if self.window is None:
            raise MigrationError("cutover before open_double_read")
        fleet = self.fleet
        with fleet._router_lock:
            w = self.window
            if w.aborted or w.mismatches:
                raise MigrationError(
                    f"bucket {self.bucket} cutover refused: double-read "
                    f"window poisoned ({w.mismatches} mismatches: "
                    f"{w.mismatch_detail}) — new copy is never served")
            diverged = self._parity_failures()
            if diverged:
                raise MigrationError(
                    f"bucket {self.bucket} cutover refused: bitwise "
                    f"parity failed: {diverged[:3]}")
            self._journal("cutover")
            doc = read_fleet_manifest(self.fleet_dir)
            new_map = BucketMap.from_json(doc["bucket_map"]) \
                .with_assignment(self.bucket, self.dst)
            doc["schema"] = FLEET_MANIFEST_SCHEMA_V2
            doc["version"] = int(doc["version"]) + 1
            doc["bucket_map"] = new_map.to_json()
            write_fleet_manifest(self.fleet_dir, doc)
            fleet.commit_bucket(self.bucket, self.dst)
            fleet.manifest = doc
            fleet.end_double_read(self.bucket)
            _clear_journal(self.fleet_dir)
            self.phase = "done"
            _metrics.counter("fleet.migration.cutovers").inc()
            return {"bucket": self.bucket, "src": self.src,
                    "dst": self.dst, "version": doc["version"],
                    "double_reads": w.double_reads,
                    "skipped": w.skipped,
                    "copied_rows": self.copied_rows,
                    "reconciled_rows": self.reconciled_rows}

    def migrate(self, drive=None) -> dict:
        """Run all four phases in order. ``drive`` (optional callable)
        runs after the double-read window opens — the hook benches and
        tests use to push routed traffic through the window."""
        self.copy()
        self.open_double_read()
        if drive is not None:
            drive()
        self.reconcile()
        return self.cutover()

    def abort(self, reason: str = "") -> None:
        """In-process rollback: close the window, bitwise-restore every
        destination store from the stored undo records, drop the
        journal. The fleet is left serving the OLD map over the exact
        prior file bytes."""
        self.fleet.end_double_read(self.bucket)
        for cid, undo in reversed(list(self._undo.items())):
            rollback_cold_store_delta(
                shard_store_path(self.fleet_dir, self.dst, cid), undo)
            self._refresh(self.dst, cid)
        self._undo.clear()
        _clear_journal(self.fleet_dir)
        self.phase = "aborted"
        _metrics.counter("fleet.migration.aborts").inc()
        if reason:
            self.abort_reason = reason


def resume_migration(fleet, fleet_dir: Optional[str] = None,
                     drive=None) -> Optional[dict]:
    """Pick up a migration a killed migrator left behind.

    No journal → None (nothing in flight). A torn journal raises typed
    (``MigrationError``) and the fleet keeps serving whatever map the
    last GOOD manifest carries. Otherwise the on-disk manifest decides:
    if the bucket already reads as owned by the journal's destination,
    the manifest bump became durable before the kill — finish the
    bookkeeping; else roll the migration FORWARD (copy is idempotent:
    the re-applied delta converges to the same destination bytes) and
    complete reconcile + cutover."""
    fleet_dir = fleet_dir or getattr(fleet, "fleet_dir", None)
    if fleet_dir is None:
        raise MigrationError("resume needs a fleet_dir")
    doc = read_migration_journal(fleet_dir)
    if doc is None:
        return None
    bucket, dst = int(doc["bucket"]), int(doc["dst"])
    manifest = read_fleet_manifest(fleet_dir)
    on_disk = BucketMap.from_json(manifest["bucket_map"])
    if on_disk.shard_of(bucket) == dst:
        # cutover became durable; mirror it in the router + tidy up
        if fleet.bucket_map.shard_of(bucket) != dst:
            fleet.commit_bucket(bucket, dst)
        fleet.end_double_read(bucket)
        fleet.manifest = manifest
        _clear_journal(fleet_dir)
        return {"bucket": bucket, "src": int(doc["src"]), "dst": dst,
                "resumed_phase": doc["phase"], "completed": "durable"}
    m = BucketMigrator(fleet, bucket, dst, fleet_dir=fleet_dir)
    if m.src != int(doc["src"]):
        raise MigrationError(
            f"journal src {doc['src']} disagrees with manifest owner "
            f"{m.src} for bucket {bucket}")
    out = m.migrate(drive=drive)
    out["resumed_phase"] = doc["phase"]
    return out
