"""Two-tier per-coordinate coefficient store: HBM hot set over a
host-RAM cold tier.

Serving previously required every random-effect gather table fully
resident in device memory, capping entity count at HBM. This module puts
a fixed-capacity device gather table (the HOT tier) in front of an
``io/cold_store.ColdStore`` (the COLD tier: all N rows, mmapped host
RAM, sorted by entity id) so a 10M+-entity coordinate serves from a
fixed HBM budget with a traffic-adaptive LRU hot set — the photon_tpu
analog of Photon ML's PalDB off-heap coefficient index, with the
memory-hierarchy placement story of Snap ML / DuHL.

Hot-table layout (leading dim is a compiled-program shape, so capacity
is a power of two and never changes after construction)::

    rows 0..C-1   hot slots (LRU over entity traffic)
    row  C        the unknown/cold zero row — UNKNOWN_ENTITY and
                  COLD_MISS requests gather it, contributing exactly 0
    row  C+1      scratch row absorbing the padding writes of the
                  fixed-shape transfer scatter

Concurrency contract (the part that keeps "zero steady-state compiles"
AND "no hot-path stalls" true at once):

- Scoring threads hold the owning model's ``transfer_lock`` across
  assemble + scorer DISPATCH (not execution): lookups, the table
  reference read, and the jit call happen against one consistent
  (maps, table) snapshot.
- The background transfer thread reads cold rows and stages them on
  device OUTSIDE the lock (this is the only path allowed to touch the
  host), then under the lock commits: ONE donated fixed-shape scatter,
  table-reference swap, and slot-map updates — atomically, so a scorer
  can never see new maps with an old table or vice versa, and the
  donated buffer can never be consumed between a scorer's table read
  and its dispatch.
- A request whose entity is still cold at pop time gathers the zero row
  and gets typed ``COLD_MISS`` degradation; the miss (and the admission
  lookahead before it) promotes the rows for next time. The scoring
  path never performs a synchronous host->device upload.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from photon_tpu.io.cold_store import ColdStore
from photon_tpu.obs.metrics import registry as _metrics
from photon_tpu.serving.types import CoeffStoreConfig
from photon_tpu.utils import compile_cache, jitcache

#: lookup outcomes (status strings double as metrics labels)
HIT = "hit"
COLD = "cold_miss"
UNKNOWN = "unknown"

_PREFETCH_BUCKETS = tuple(50e-6 * 1.6 ** i for i in range(32))


def _pow2_floor(n: int) -> int:
    p = 1
    while p * 2 <= n:
        p *= 2
    return p


def _build_scatter(shape: Tuple[int, int], batch: int, dtype) -> object:
    """Fixed-shape donated row scatter: the one program every cold->hot
    transfer reuses. Keyed by (table shape, batch, dtype) in the
    process-wide jitcache so a swapped-in model with the same geometry
    shares the compiled executable."""
    import jax

    def build():
        def scatter(table, idx, rows):
            return table.at[idx].set(rows)

        return jax.jit(scatter, donate_argnums=0)

    return jitcache.get_or_build(
        ("coeff_scatter", shape[0], shape[1], batch, str(np.dtype(dtype))),
        build)


class TwoTierCoeffStore:
    """One coordinate's hot-set gather cache over its cold tier.

    All ``*_locked`` methods require the caller to hold ``lock`` (the
    owning model's transfer lock, shared by every store of that model so
    one critical section covers a whole multi-coordinate batch).
    """

    def __init__(self, cold: ColdStore, config: CoeffStoreConfig,
                 lock: Optional[threading.RLock] = None,
                 start_thread: bool = True, dtype=np.float32):
        import jax

        self.cold = cold
        self.config = config
        self.coordinate_id = cold.coordinate_id
        self.slot_width = cold.slot_width
        self.dtype = np.dtype(dtype)
        row_bytes = self.slot_width * self.dtype.itemsize
        cap = (config.hot_capacity if config.hot_capacity is not None
               else config.hbm_budget_bytes // row_bytes)
        if cap < 1:
            raise ValueError(
                f"hot budget below one row ({row_bytes}B) for coordinate "
                f"{self.coordinate_id!r}")
        self.capacity = _pow2_floor(cap)
        self.unknown_row = self.capacity           # explicit zero row
        self._scratch_row = self.capacity + 1      # absorbs scatter padding
        self.transfer_batch = min(config.transfer_batch, self.capacity)
        self.lock = lock if lock is not None else threading.RLock()

        # hot-tier host mirrors (mirroring model_state's host-side
        # (entity,feature)->slot maps): entity id -> hot slot in LRU
        # order, slot -> (entity id, cold row), and the per-slot
        # projection rows so assemble's slot replay never touches the
        # cold mmap for a hot entity
        self._hot: "collections.OrderedDict[str, int]" = \
            collections.OrderedDict()
        self._slot_info: List[Optional[Tuple[str, int]]] = \
            [None] * self.capacity
        self._free: List[int] = list(range(self.capacity - 1, -1, -1))
        self._hot_proj = np.full((self.capacity, self.slot_width), -1,
                                 dtype=np.int32)
        # pending promotions: entity id -> cold row, insertion-ordered
        self._pending: "collections.OrderedDict[str, int]" = \
            collections.OrderedDict()

        self._table = jax.device_put(
            np.zeros((self.capacity + 2, self.slot_width), self.dtype))
        # build AND warm the transfer program at store construction —
        # both inside the warmup phase, so the first real promotion is
        # compile-free and nothing here counts as a steady-state compile
        # (padding writes target the scratch row; the zero row stays zero)
        self._scatter = None
        compile_cache.warmup((self.transfer_batch,), self._warm_scatter)

        # held across one whole transfer cycle (all three phases) and by
        # the nearline publisher across an entire delta publish — pausing
        # the transfer thread at a cycle boundary without ever blocking
        # the scoring path, which only needs ``lock``. Acquire order is
        # always publish_lock -> lock, never the reverse.
        self._publish_lock = threading.Lock()

        self._stats_lock = threading.Lock()
        self._counts = {"hits": 0, "misses": 0, "cold_misses": 0,
                        "unknown": 0, "promotes": 0, "evictions": 0,
                        "transfers": 0}
        self._wakeup = threading.Event()
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        if start_thread:
            self._thread = threading.Thread(
                target=self._transfer_loop, daemon=True,
                name=f"coeff-transfer-{self.coordinate_id}")
            self._thread.start()

    # -- scoring-path API (caller holds self.lock) --------------------------

    @property
    def table(self):
        """Current device gather table [capacity + 2, slot_width]. Read
        under ``lock`` and used for the dispatch inside the same hold —
        the commit path swaps it atomically with the slot maps."""
        return self._table

    def lookup_locked(self, entity_id: str) -> Tuple[int, str]:
        """(gather row, status) for one request's entity.

        HIT: the hot slot (LRU-touched). COLD: the zero row now, plus a
        queued promotion so the next request finds the entity hot.
        UNKNOWN: the zero row, entity not in the model at all.
        """
        slot = self._hot.get(entity_id)
        if slot is not None:
            self._hot.move_to_end(entity_id)
            self._bump("hits")
            return slot, HIT
        row = self._pending.get(entity_id)
        if row is None:
            row = self.cold.entity_row(entity_id)
            if row is None:
                self._bump("unknown")
                return self.unknown_row, UNKNOWN
            self._pending[entity_id] = row
            self._wakeup.set()
        self._bump("misses")
        self._bump("cold_misses")
        return self.unknown_row, COLD

    def proj_row_locked(self, slot: int) -> np.ndarray:
        """Projection row (global col per local slot, -1 padded) for a
        HIT slot — host mirror, no cold-tier touch."""
        return self._hot_proj[slot]

    # -- admission lookahead ------------------------------------------------

    def prefetch(self, entity_id: str) -> None:
        """Admission-time lookahead: resolve the entity and queue its
        cold->hot upload so the rows are usually resident by batch-pop
        time. Cheap, non-blocking, safe from any thread."""
        if not self.config.prefetch:
            return
        with self.lock:
            if entity_id in self._hot:
                self._hot.move_to_end(entity_id)
                return
            if entity_id in self._pending:
                return
            row = self.cold.entity_row(entity_id)
            if row is None:
                return
            self._pending[entity_id] = row
        self._wakeup.set()

    # -- transfer thread ----------------------------------------------------

    def _warm_scatter(self, batch: int) -> None:
        import jax

        if self._scatter is None:
            self._scatter = _build_scatter(
                (self.capacity + 2, self.slot_width), batch, self.dtype)
        idx = jax.device_put(
            np.full(batch, self._scratch_row, dtype=np.int32))
        rows = jax.device_put(np.zeros((batch, self.slot_width),
                                       self.dtype))
        self._table = self._scatter(self._table, idx, rows)
        self._table.block_until_ready()  # host-sync-ok: warmup only

    def _transfer_loop(self) -> None:
        while not self._stop:
            self._wakeup.wait(timeout=0.05)
            self._wakeup.clear()
            if self._stop:
                return
            try:
                while self.drain_once():
                    pass
            except Exception:  # noqa: BLE001 — prefetch must never kill
                # the process; a failed transfer just leaves entities
                # cold (typed COLD_MISS), and the next cycle retries
                _metrics.counter("serving.coeff_store.transfer_errors",
                                 coordinate=self.coordinate_id).inc()

    def drain_once(self) -> int:
        """Run one coalesced transfer cycle; returns rows promoted.

        Phase 1 (locked): reserve up to ``transfer_batch`` pending
        entities and their slots — free slots first, then LRU eviction.
        An evicted victim disappears from the maps immediately (requests
        for it degrade to COLD_MISS until re-promoted; its stale device
        rows are unreachable because nothing maps to the slot).
        Phase 2 (unlocked): cold mmap read + ONE ``jax.device_put`` of
        the padded row block. Phase 3 (locked): one donated fixed-shape
        scatter + atomic map/table commit.

        The whole cycle runs under ``publish_lock`` so a nearline delta
        publish holding it sees a quiescent store: no cold-file read and
        no donated scatter can interleave with its staged-table build,
        cold rewrite, or commit.
        """
        with self._publish_lock:
            return self._drain_cycle()

    def _drain_cycle(self) -> int:
        import jax

        t0 = time.perf_counter()
        batch: List[Tuple[str, int, int]] = []  # (entity, cold row, slot)
        with self.lock:
            while self._pending and len(batch) < self.transfer_batch:
                entity_id, row = self._pending.popitem(last=False)
                if entity_id in self._hot:
                    continue
                if self._free:
                    slot = self._free.pop()
                else:
                    victim, slot = self._hot.popitem(last=False)
                    self._slot_info[slot] = None
                    self._bump("evictions")
                    _metrics.counter("serving.coeff_store.evictions",
                                     coordinate=self.coordinate_id).inc()
                batch.append((entity_id, row, slot))
        if not batch:
            return 0

        rows_idx = np.asarray([r for _, r, _ in batch], dtype=np.int64)
        coef_rows = self.cold.read_rows(rows_idx)
        proj_rows = self.cold.read_proj_rows(rows_idx)
        m = len(batch)
        buf = np.zeros((self.transfer_batch, self.slot_width), self.dtype)
        buf[:m] = coef_rows
        idx = np.full(self.transfer_batch, self._scratch_row,
                      dtype=np.int32)
        idx[:m] = [s for _, _, s in batch]
        dev_rows = jax.device_put(buf)
        dev_idx = jax.device_put(idx)

        with self.lock:
            self._table = self._scatter(self._table, dev_idx, dev_rows)
            for i, (entity_id, row, slot) in enumerate(batch):
                self._hot[entity_id] = slot
                self._hot.move_to_end(entity_id)
                self._slot_info[slot] = (entity_id, row)
                self._hot_proj[slot] = proj_rows[i]
            occupancy = len(self._hot)
        self._bump("promotes", m)
        self._bump("transfers")
        _metrics.counter("serving.coeff_store.promotes",
                         coordinate=self.coordinate_id).inc(m)
        _metrics.gauge("serving.coeff_store.hot_occupancy",
                       coordinate=self.coordinate_id).set(occupancy)
        _metrics.histogram("serving.coeff_store.prefetch_seconds",
                           buckets=_PREFETCH_BUCKETS,
                           coordinate=self.coordinate_id).observe(
            time.perf_counter() - t0)
        return m

    def drain_prefetch(self, timeout_s: float = 10.0) -> bool:
        """Block until every queued promotion has landed (tests, bench
        phase boundaries — never the scoring path). True on quiescence."""
        deadline = time.monotonic() + timeout_s
        while True:
            moved = self.drain_once()
            with self.lock:
                pending = len(self._pending)
            if moved == 0 and pending == 0:
                return True
            if time.monotonic() > deadline:
                return False

    # -- nearline delta publish --------------------------------------------

    @property
    def publish_lock(self) -> threading.Lock:
        """Cycle-granular transfer pause for the nearline publisher.
        Hold it (before ``lock``) across staging + commit so the staged
        table copy can never race a donated transfer scatter. The
        scoring path is untouched — it only takes ``lock``."""
        return self._publish_lock

    def hot_slot_locked(self, entity_id: str) -> Optional[int]:
        """Hot slot of ``entity_id`` without an LRU touch (publisher
        bookkeeping is not traffic), or None when not resident."""
        return self._hot.get(entity_id)

    def set_hot_proj_locked(self, slot: int, proj_row: np.ndarray) -> None:
        """Update the host projection mirror of a hot slot after its
        device row was republished."""
        self._hot_proj[slot] = np.asarray(proj_row, dtype=np.int32)

    def commit_table_locked(self, table) -> None:
        """Swap in a republished gather table (same shape; built by the
        publisher's non-donated scatter-copy)."""
        self._table = table

    def evict_locked(self, entity_id: str) -> bool:
        """Drop one entity from the hot tier (rollback of a published
        append). Its stale device rows become unreachable, exactly like
        an LRU eviction."""
        slot = self._hot.pop(entity_id, None)
        self._pending.pop(entity_id, None)
        if slot is None:
            return False
        self._slot_info[slot] = None
        self._hot_proj[slot] = -1
        self._free.append(slot)
        return True

    def refresh_cold_locked(self) -> int:
        """Reopen the cold file and remap every cached cold-row index by
        entity id — required after ``apply_cold_store_delta`` /
        ``upgrade_cold_store`` / rollback replaced or mutated the file
        (the old mmap may see a replaced inode). v2 storage rows are
        append-stable so remaps are usually identity; entities absent
        from the refreshed file (a rolled-back append) are evicted.
        Returns the number of entities dropped. Caller holds both
        ``publish_lock`` and ``lock``."""
        new_cold = ColdStore(self.cold.path)
        dropped = 0
        for slot, info in enumerate(self._slot_info):
            if info is None:
                continue
            entity_id, _old_row = info
            row = new_cold.entity_row(entity_id)
            if row is None:
                if self.evict_locked(entity_id):
                    dropped += 1
            else:
                self._slot_info[slot] = (entity_id, row)
        for entity_id in list(self._pending):
            row = new_cold.entity_row(entity_id)
            if row is None:
                del self._pending[entity_id]
                dropped += 1
            else:
                self._pending[entity_id] = row
        self.cold = new_cold
        return dropped

    # -- accounting ---------------------------------------------------------

    def _bump(self, key: str, n: int = 1) -> None:
        with self._stats_lock:
            self._counts[key] += n
        if key in ("hits", "misses"):
            _metrics.counter(f"serving.coeff_store.{key}",
                             coordinate=self.coordinate_id).inc(n)

    def stats(self) -> dict:
        with self._stats_lock:
            counts = dict(self._counts)
        with self.lock:
            occupancy = len(self._hot)
            pending = len(self._pending)
        lookups = counts["hits"] + counts["misses"] + counts["unknown"]
        return {
            "coordinate_id": self.coordinate_id,
            "capacity": self.capacity,
            "occupancy": occupancy,
            "pending": pending,
            "slot_width": self.slot_width,
            "hot_bytes": int((self.capacity + 2) * self.slot_width
                             * self.dtype.itemsize),
            "cold_bytes": self.cold.file_bytes,
            "num_entities": self.cold.num_entities,
            "hit_rate": (counts["hits"] / lookups) if lookups else None,
            **counts,
        }

    def close(self) -> None:
        self._stop = True
        self._wakeup.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
