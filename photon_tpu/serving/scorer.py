"""Compiled batch scorers for the serving engine.

One jitted program per (model, mode, bucket): the program closes over
the device-resident FIXED-effect arrays (static for a model's lifetime,
baked into the executable) but takes the random-effect gather tables as
explicit arguments. Tables must be arguments, not closures, because the
two-tier coefficient store (serving/coeff_store.py) replaces a
coordinate's hot table object on every cold->hot transfer (the donated
scatter produces a new array); same-shape/dtype arguments re-dispatch
the cached executable with zero retraces, where a closure would either
go stale or force a steady-state recompile. Fully-resident coordinates
pass the same table every call — one calling convention for both tiers.

The math is the offline ``game/scoring.GameScorer`` expressions verbatim
— fixed effects as a gathered dot over padded (index, value) pairs,
random effects as an entity-row gather followed by a slot-aligned dot —
which is what makes serving-vs-offline parity exact rather than
approximate.

Two optional hot-path arms layer on top of the same programs:

* ``PHOTON_TPU_PALLAS_SERVING=1`` routes the fixed-effect margins
  through the fused gather+margin Pallas kernel
  (ops/pallas_glm.fused_gather_margin): every fixed shard's padded
  slots concatenate against one coefficient vector, so the whole
  fixed-effect term is ONE single-HBM-pass kernel per batch instead of
  a gather + multiply + reduce per shard. Read at program-build time;
  refusals fall back to the XLA expressions and tick
  ``kernels.xla_fallbacks{path="serving"}``.
* ``ServingConfig.int8_serving`` adds a third mode, ``"full_int8"``:
  full-resident random-effect tables arrive as (int8 rows, per-row f32
  scales) pairs and dequantize inside the gather — half the
  random-effect HBM bytes. The mode is warmed alongside the others and
  guarded by the swap ladder's int8 shadow gate (serving/swap.py).

Programs are shared through ``utils/jitcache`` so every bucket compiles
once per process; ``warmup_scorers`` dispatches each (mode, bucket)
program on dummy inputs inside ``compile_cache.warmup`` so the full
ladder is compiled at model-load time and steady-state traffic never
traces.
"""

from __future__ import annotations

import os
from typing import Callable, Sequence, Tuple

from photon_tpu.serving.model_state import DeviceResidentModel
from photon_tpu.utils import compile_cache, jitcache

#: scoring modes; "fixed_only" is the SLO-shed ladder (random-effect
#: gathers skipped) and is warmed alongside "full" so entering shed mode
#: under load never triggers a compile
MODES = ("full", "fixed_only")

#: the opt-in quantized arm — only valid (and only warmed) for models
#: built with int8=True; its tables argument is
#: ``model.current_tables_int8()``
INT8_MODE = "full_int8"


def serving_modes(model: DeviceResidentModel) -> Tuple[str, ...]:
    """The modes this model warms and may dispatch: the base ladder,
    plus the int8 arm when the model carries quantized tables."""
    if getattr(model, "int8_enabled", False):
        return MODES + (INT8_MODE,)
    return MODES


def _fused_fixed_margin(model: DeviceResidentModel, thetas, fixed_pos):
    """Build-time routing for the fixed-effect term: returns a
    ``fn(fixed_idx, fixed_val, offsets) -> [B]`` using the fused Pallas
    gather+margin kernel when the env flag asks for it and the shapes
    qualify, else None (XLA expressions). Counted per compiled program
    into ``kernels.pallas_hits`` / ``kernels.xla_fallbacks`` with
    ``path="serving"`` — same telemetry contract as the training
    kernels (ops/aggregators.py)."""
    if os.environ.get("PHOTON_TPU_PALLAS_SERVING") != "1":
        return None
    import jax.numpy as jnp

    from photon_tpu.ops import pallas_glm
    from photon_tpu.ops.aggregators import (_kernel_counter,
                                            _warn_kernel_refused)

    k_total = sum(int(model.shard_pad[model.shard_order[p]])
                  for p in fixed_pos)
    dims = [int(t.shape[0]) for t in thetas]
    ok = (model.mesh is None and model.dtype == jnp.float32
          and len(thetas) > 0
          and all(t.dtype == jnp.float32 for t in thetas)
          and sum(dims) <= pallas_glm._MAX_SPARSE_DIM
          and k_total >= 1
          and not pallas_glm._TRACE_DISABLED.get())
    if not ok:
        _kernel_counter("xla_fallbacks", "serving")
        if not pallas_glm._TRACE_DISABLED.get():
            _warn_kernel_refused("serving")
        return None
    _kernel_counter("pallas_hits", "serving")
    theta_all = jnp.concatenate([t.astype(jnp.float32) for t in thetas])
    col_off = [0]
    for d in dims[:-1]:
        col_off.append(col_off[-1] + d)

    def fn(fixed_idx, fixed_val, offsets):
        idx = jnp.concatenate(
            [fixed_idx[p] + col_off[j] for j, p in enumerate(fixed_pos)],
            axis=1)
        val = jnp.concatenate([fixed_val[p] for p in fixed_pos], axis=1)
        return pallas_glm.fused_gather_margin(
            idx, val, offsets, theta_all)

    return fn


def get_scorer(model: DeviceResidentModel, mode: str,
               bucket: int) -> Callable:
    """Compiled scorer for one (model, mode, bucket); cached process-wide.

    Call as ``fn(*args, re_tables)`` where ``args`` is the assemble
    output and ``re_tables`` is ``model.current_tables()`` — or
    ``model.current_tables_int8()`` for the "full_int8" mode — read
    inside the same ``model.transfer_lock`` hold as the assemble (the
    two-tier store's consistency contract).
    """
    if mode not in serving_modes(model):
        raise ValueError(f"unknown serving mode {mode!r}")
    key = ("serving_scorer", model.token, mode, int(bucket))

    def builder():
        import jax
        import jax.numpy as jnp

        dtype = model.dtype
        shard_pos = {sid: i for i, sid in enumerate(model.shard_order)}
        thetas = tuple(f.theta for f in model.fixed)
        fixed_pos = tuple(shard_pos[f.feature_shard_id] for f in model.fixed)
        with_random = mode != "fixed_only"
        fused_fixed = _fused_fixed_margin(model, thetas, fixed_pos)

        @jax.jit
        def fn(fixed_idx, fixed_val, re_sidx, re_sval, re_ent, offsets,
               re_tables):
            if fused_fixed is not None:
                total = fused_fixed(fixed_idx, fixed_val, offsets) \
                    .astype(dtype)
            else:
                total = offsets.astype(dtype)
                for theta, pos in zip(thetas, fixed_pos):
                    # ops/features.matvec on the padded ELL layout: pad
                    # slots are (0, 0.0) so they contribute nothing
                    total = total + jnp.sum(
                        fixed_val[pos].astype(dtype)
                        * theta[fixed_idx[pos]],
                        axis=-1)
            if with_random:
                for coef, sidx, sval, ent in zip(re_tables, re_sidx,
                                                 re_sval, re_ent):
                    if isinstance(coef, tuple):
                        # int8 arm: (quantized rows, per-row scales) —
                        # gather both and dequantize in-register; the
                        # unknown/zero rows quantize to (0, scale 1.0)
                        # so they still contribute exactly nothing
                        q, s = coef
                        rows = (q.at[ent].get(mode="fill", fill_value=0)
                                .astype(dtype)
                                * s.at[ent].get(mode="fill",
                                                fill_value=0.0))
                    else:
                        rows = coef.at[ent].get(mode="fill",
                                                fill_value=0.0)
                    total = total + jnp.sum(
                        sval.astype(dtype)
                        * jnp.take_along_axis(rows, sidx, axis=1),
                        axis=-1)
            return total

        return fn

    return jitcache.get_or_build(key, builder)


def tables_for_mode(model: DeviceResidentModel, mode: str) -> tuple:
    """The re_tables argument matching ``mode`` — int8 pairs for the
    quantized arm, f32 tables otherwise. Same lock contract as
    ``current_tables``."""
    if mode == INT8_MODE:
        return model.current_tables_int8()
    return model.current_tables()


def warmup_scorers(model: DeviceResidentModel,
                   buckets: Sequence[int]) -> int:
    """Compile-and-dispatch every (mode, bucket) program under the warmup
    phase flag. Returns the number of programs warmed."""
    warmed = 0
    modes = serving_modes(model)

    def one_bucket(bucket):
        nonlocal warmed
        args = model.dummy_args(bucket)
        for mode in modes:
            tables = tables_for_mode(model, mode)
            out = get_scorer(model, mode, bucket)(*args, tables)
            out.block_until_ready()  # host-sync-ok: warmup only
            warmed += 1

    compile_cache.warmup(buckets, one_bucket)
    return warmed
