"""Compiled batch scorers for the serving engine.

One jitted program per (shape-signature, mode, bucket): EVERY model
parameter — the fixed-effect theta vectors and the random-effect gather
tables alike — is a program *argument*, never a closure. Tables had to
be arguments from the start, because the two-tier coefficient store
(serving/coeff_store.py) replaces a coordinate's hot table object on
every cold->hot transfer (the donated scatter produces a new array);
same-shape/dtype arguments re-dispatch the cached executable with zero
retraces, where a closure would either go stale or force a steady-state
recompile. The fixed-effect thetas now ride the same donation-safe
calling convention, which removes the last model-specific bake-in: the
jitcache key is ``model.shape_signature()`` (feature pads, theta
shapes, RE table shapes, dtypes, int8, mesh) instead of
``model.token``, so N same-shape tenants share ONE compiled bucket
ladder — tenant #2..N warm at near-zero compile cost, and a failed-over
replica can reuse an AOT-exported program bundle (serving/programs.py).

The math is the offline ``game/scoring.GameScorer`` expressions verbatim
— fixed effects as a gathered dot over padded (index, value) pairs,
random effects as an entity-row gather followed by a slot-aligned dot —
which is what makes serving-vs-offline parity exact rather than
approximate.

Two optional hot-path arms layer on top of the same programs:

* ``PHOTON_TPU_PALLAS_SERVING=1`` routes the fixed-effect margins
  through the fused gather+margin Pallas kernel
  (ops/pallas_glm.fused_gather_margin): every fixed shard's padded
  slots concatenate against one coefficient vector, so the whole
  fixed-effect term is ONE single-HBM-pass kernel per batch instead of
  a gather + multiply + reduce per shard. Read at program-build time;
  refusals fall back to the XLA expressions and tick
  ``kernels.xla_fallbacks{path="serving"}``.
* ``ServingConfig.int8_serving`` adds a third mode, ``"full_int8"``:
  full-resident random-effect tables arrive as (int8 rows, per-row f32
  scales) pairs and dequantize inside the gather — half the
  random-effect HBM bytes. The mode is warmed alongside the others and
  guarded by the swap ladder's int8 shadow gate (serving/swap.py).

Programs are shared through ``utils/jitcache`` so every bucket compiles
once per process; ``warmup_scorers`` dispatches each (mode, bucket)
program on dummy inputs inside ``compile_cache.warmup`` so the full
ladder is compiled at model-load time and steady-state traffic never
traces.
"""

from __future__ import annotations

import os
from typing import Callable, Optional, Sequence, Tuple

import numpy as np

from photon_tpu.serving.model_state import DeviceResidentModel
from photon_tpu.utils import compile_cache, jitcache

#: scoring modes; "fixed_only" is the SLO-shed ladder (random-effect
#: gathers skipped) and is warmed alongside "full" so entering shed mode
#: under load never triggers a compile
MODES = ("full", "fixed_only")

#: the opt-in quantized arm — only valid (and only warmed) for models
#: built with int8=True; its tables argument is
#: ``model.current_tables_int8()``
INT8_MODE = "full_int8"

#: the opt-in Thompson-sampling arm — only valid (and only warmed) for
#: models staged with thompson=True over posterior variances. Each
#: request row samples ``theta ~ N(mu, sigma^2)`` INSIDE the program
#: from its (seed_hi, seed_lo) counter pair: a murmur3-finalizer hash of
#: (request seed, coordinate tag, coefficient identity) feeds Box-Muller
#: so one coefficient gets ONE normal draw per request, duplicate
#: features agree, and a replay with the same seeds is bitwise. Extra
#: arguments beyond "full": seed_hi/seed_lo [B] uint32, the variance
#: mirrors (``current_var_thetas``/``current_var_tables``).
THOMPSON_MODE = "thompson"


def serving_modes(model: DeviceResidentModel) -> Tuple[str, ...]:
    """The modes this model warms and may dispatch: the base ladder,
    plus the int8 arm when the model carries quantized tables, plus the
    thompson arm when it carries posterior-variance mirrors."""
    modes = MODES
    if getattr(model, "int8_enabled", False):
        modes = modes + (INT8_MODE,)
    if getattr(model, "thompson_enabled", False):
        modes = modes + (THOMPSON_MODE,)
    return modes


def _fused_fixed_margin(mesh_local: bool, dtype, theta_dims, theta_dtypes,
                        fixed_pos, k_total: int):
    """Build-time routing for the fixed-effect term: returns a
    ``fn(fixed_idx, fixed_val, offsets, thetas) -> [B]`` using the fused
    Pallas gather+margin kernel when the env flag asks for it and the
    shapes qualify, else None (XLA expressions). Routing runs on static
    shape facts only (so the decision is a pure function of the scorer's
    shape key); the theta concatenation happens inside the trace, since
    thetas are now program arguments. Counted per compiled program into
    ``kernels.pallas_hits`` / ``kernels.xla_fallbacks`` with
    ``path="serving"`` — same telemetry contract as the training
    kernels (ops/aggregators.py)."""
    if os.environ.get("PHOTON_TPU_PALLAS_SERVING") != "1":
        return None
    import jax.numpy as jnp

    from photon_tpu.ops import pallas_glm
    from photon_tpu.ops.aggregators import (_kernel_counter,
                                            _warn_kernel_refused)

    ok = (mesh_local and dtype == jnp.float32
          and len(theta_dims) > 0
          and all(dt == "float32" for dt in theta_dtypes)
          and sum(theta_dims) <= pallas_glm._MAX_SPARSE_DIM
          and k_total >= 1
          and not pallas_glm._TRACE_DISABLED.get())
    if not ok:
        _kernel_counter("xla_fallbacks", "serving")
        if not pallas_glm._TRACE_DISABLED.get():
            _warn_kernel_refused("serving")
        return None
    _kernel_counter("pallas_hits", "serving")
    col_off = [0]
    for d in theta_dims[:-1]:
        col_off.append(col_off[-1] + d)

    def fn(fixed_idx, fixed_val, offsets, thetas):
        theta_all = jnp.concatenate(
            [t.astype(jnp.float32) for t in thetas])
        idx = jnp.concatenate(
            [fixed_idx[p] + col_off[j] for j, p in enumerate(fixed_pos)],
            axis=1)
        val = jnp.concatenate([fixed_val[p] for p in fixed_pos], axis=1)
        return pallas_glm.fused_gather_margin(
            idx, val, offsets, theta_all)

    return fn


def program_key(model: DeviceResidentModel, mode: str,
                bucket: int) -> tuple:
    """The jitcache key one (mode, bucket) scorer program lives under —
    shape-generic: equal for any model with the same
    ``shape_signature()``, so same-shape tenants resolve to one compiled
    program. The Pallas env flag is part of the key because it is read
    at build time and changes the traced computation."""
    return ("serving_scorer", mode, int(bucket), model.shape_signature(),
            os.environ.get("PHOTON_TPU_PALLAS_SERVING") == "1")


def build_scorer_fn(model: DeviceResidentModel, mode: str,
                    bucket: int) -> Callable:
    """Build a FRESH jitted scorer for (mode, bucket) — uncached. Normal
    callers want ``get_scorer`` (the process-wide shape-keyed cache);
    this entry exists for the AOT bundle exporter, which needs a
    lowerable jit function even when the cache slot holds a deserialized
    executable (a ``Compiled`` cannot be re-lowered or re-serialized)."""
    if mode not in serving_modes(model):
        raise ValueError(f"unknown serving mode {mode!r}")

    # static shape facts only — the builder must NOT capture the model
    # (a closure would pin every retired tenant's device arrays into the
    # process-wide cache for the program's lifetime)
    dtype = model.dtype
    mesh_local = model.mesh is None
    shard_pos = {sid: i for i, sid in enumerate(model.shard_order)}
    fixed_pos = tuple(shard_pos[f.feature_shard_id] for f in model.fixed)
    theta_dims = tuple(int(f.theta.shape[0]) for f in model.fixed)
    theta_dtypes = tuple(str(f.theta.dtype) for f in model.fixed)
    k_total = sum(int(model.shard_pad[model.shard_order[p]])
                  for p in fixed_pos)

    def builder():
        import jax
        import jax.numpy as jnp

        if mode == THOMPSON_MODE:
            # in-program posterior sampling. Randomness is a counter
            # hash, not a PRNG object: murmur3's 32-bit finalizer over
            # (per-request seed halves, a per-coordinate tag, the
            # coefficient's identity) yields the two uniforms Box-Muller
            # turns into ONE standard normal per (request, coefficient).
            # Keying on the coefficient identity (global column for
            # fixed effects, (entity row, slot) for random effects)
            # makes duplicate features sample the same theta-tilde draw
            # — this is sampling the PARAMETER, not per-slot noise — and
            # pad slots contribute nothing because their values are
            # zero. Everything is uint32/f32 inside the jit, so the
            # program runs without x64 and replays bitwise.
            M1 = jnp.uint32(0x85EBCA6B)
            M2 = jnp.uint32(0xC2B2AE35)
            S16, S13 = jnp.uint32(16), jnp.uint32(13)
            GOLD = jnp.uint32(0x9E3779B9)
            TWO_PI = 6.283185307179586
            INV_2_32 = 1.0 / 4294967296.0

            def _mix(x):
                x = x ^ (x >> S16)
                x = x * M1
                x = x ^ (x >> S13)
                x = x * M2
                return x ^ (x >> S16)

            @jax.jit
            def fn(fixed_idx, fixed_val, re_sidx, re_sval, re_ent,
                   offsets, seed_hi, seed_lo, thetas, var_thetas,
                   re_tables, re_var_tables):
                sh = seed_hi.astype(jnp.uint32)[:, None]
                sl = seed_lo.astype(jnp.uint32)[:, None]

                def z_normal(key, tag):
                    # key [B, P] uint32: coefficient identity
                    k = _mix(key ^ _mix(jnp.uint32(tag) ^ sl))
                    k = _mix(k ^ sh)
                    k2 = _mix(k ^ GOLD)
                    # +0.5 keeps both uniforms strictly inside (0, 1]
                    # after the f32 round, so log/sqrt never see 0
                    u1 = (k.astype(dtype) + 0.5) * INV_2_32
                    u2 = (k2.astype(dtype) + 0.5) * INV_2_32
                    return (jnp.sqrt(-2.0 * jnp.log(u1))
                            * jnp.cos(TWO_PI * u2))

                total = offsets.astype(dtype)
                for j, pos in enumerate(fixed_pos):
                    idx = fixed_idx[pos]
                    val = fixed_val[pos].astype(dtype)
                    theta = thetas[j][idx].astype(dtype)
                    sigma = jnp.sqrt(var_thetas[j][idx].astype(dtype))
                    z = z_normal(idx.astype(jnp.uint32), 2 * j + 1)
                    total = total + jnp.sum(
                        val * (theta + sigma * z), axis=-1)
                for j, (coef, vcoef, sidx, sval, ent) in enumerate(
                        zip(re_tables, re_var_tables, re_sidx,
                            re_sval, re_ent)):
                    rows = coef.at[ent].get(mode="fill", fill_value=0.0)
                    vrows = vcoef.at[ent].get(mode="fill", fill_value=0.0)
                    mu = jnp.take_along_axis(
                        rows, sidx, axis=1).astype(dtype)
                    sigma = jnp.sqrt(jnp.take_along_axis(
                        vrows, sidx, axis=1).astype(dtype))
                    key = (_mix(ent.astype(jnp.uint32)[:, None])
                           ^ sidx.astype(jnp.uint32))
                    z = z_normal(key, 2 * j + 2)
                    total = total + jnp.sum(
                        sval.astype(dtype) * (mu + sigma * z), axis=-1)
                return total

            return fn

        with_random = mode != "fixed_only"
        fused_fixed = _fused_fixed_margin(
            mesh_local, dtype, theta_dims, theta_dtypes, fixed_pos, k_total)

        @jax.jit
        def fn(fixed_idx, fixed_val, re_sidx, re_sval, re_ent, offsets,
               thetas, re_tables):
            if fused_fixed is not None:
                total = fused_fixed(fixed_idx, fixed_val, offsets,
                                    thetas).astype(dtype)
            else:
                total = offsets.astype(dtype)
                for theta, pos in zip(thetas, fixed_pos):
                    # ops/features.matvec on the padded ELL layout: pad
                    # slots are (0, 0.0) so they contribute nothing
                    total = total + jnp.sum(
                        fixed_val[pos].astype(dtype)
                        * theta[fixed_idx[pos]],
                        axis=-1)
            if with_random:
                for coef, sidx, sval, ent in zip(re_tables, re_sidx,
                                                 re_sval, re_ent):
                    if isinstance(coef, tuple):
                        # int8 arm: (quantized rows, per-row scales) —
                        # gather both and dequantize in-register; the
                        # unknown/zero rows quantize to (0, scale 1.0)
                        # so they still contribute exactly nothing
                        q, s = coef
                        rows = (q.at[ent].get(mode="fill", fill_value=0)
                                .astype(dtype)
                                * s.at[ent].get(mode="fill",
                                                fill_value=0.0))
                    else:
                        rows = coef.at[ent].get(mode="fill",
                                                fill_value=0.0)
                    total = total + jnp.sum(
                        sval.astype(dtype)
                        * jnp.take_along_axis(rows, sidx, axis=1),
                        axis=-1)
            return total

        return fn

    return builder()


def get_scorer(model: DeviceResidentModel, mode: str,
               bucket: int) -> Callable:
    """Compiled scorer for one (shape-signature, mode, bucket); cached
    process-wide and shared by every same-shape model.

    Call as ``fn(*args, thetas, re_tables)`` where ``args`` is the
    assemble output, ``thetas`` is ``model.current_thetas()`` and
    ``re_tables`` is ``model.current_tables()`` — or
    ``model.current_tables_int8()`` for the "full_int8" mode — read
    inside the same ``model.transfer_lock`` hold as the assemble (the
    two-tier store's consistency contract). ``dispatch`` wraps the
    whole convention.
    """
    key = program_key(model, mode, bucket)
    return jitcache.get_or_build(
        key, lambda: build_scorer_fn(model, mode, bucket))


def tables_for_mode(model: DeviceResidentModel, mode: str) -> tuple:
    """The re_tables argument matching ``mode`` — int8 pairs for the
    quantized arm, f32 tables otherwise. Same lock contract as
    ``current_tables``."""
    if mode == INT8_MODE:
        return model.current_tables_int8()
    return model.current_tables()


def mode_args(model: DeviceResidentModel, mode: str, args,
              seeds: Optional[tuple] = None) -> tuple:
    """The FULL positional argument tuple for one (mode, batch): the
    assemble output plus the mode's parameter arguments, in program
    order. ``seeds`` is the thompson arm's (seed_hi, seed_lo) uint32
    pair; None falls back to all-zero seeds of the batch width (warmup /
    AOT lowering — shape-correct, values irrelevant). Same transfer_lock
    contract as ``current_tables``."""
    if mode == THOMPSON_MODE:
        if seeds is None:
            z = np.zeros(args[5].shape[0], np.uint32)
            seeds = (z, z)
        return args + (seeds[0], seeds[1], model.current_thetas(),
                       model.current_var_thetas(), model.current_tables(),
                       model.current_var_tables())
    return args + (model.current_thetas(), tables_for_mode(model, mode))


def dispatch(model: DeviceResidentModel, mode: str, bucket: int, args,
             seeds: Optional[tuple] = None):
    """One scorer call with the model's current parameter arguments
    appended — the full calling convention in one place. Caller holds
    ``model.transfer_lock`` around assemble + this call (two-tier
    consistency)."""
    return get_scorer(model, mode, bucket)(
        *mode_args(model, mode, args, seeds))


def warmup_scorers(model: DeviceResidentModel,
                   buckets: Sequence[int]) -> int:
    """Compile-and-dispatch every (mode, bucket) program under the warmup
    phase flag. Returns the number of programs warmed (dispatched) — for
    tenant #2..N of a shape, each dispatch is a jitcache hit and warms
    at zero compile cost."""
    warmed = 0
    modes = serving_modes(model)

    def one_bucket(bucket):
        nonlocal warmed
        args = model.dummy_args(bucket)
        for mode in modes:
            out = dispatch(model, mode, bucket, args)
            out.block_until_ready()  # host-sync-ok: warmup only
            warmed += 1

    compile_cache.warmup(buckets, one_bucket)
    return warmed
