"""Compiled batch scorers for the serving engine.

One jitted program per (model, mode, bucket): the program closes over
the device-resident FIXED-effect arrays (static for a model's lifetime,
baked into the executable) but takes the random-effect gather tables as
explicit arguments. Tables must be arguments, not closures, because the
two-tier coefficient store (serving/coeff_store.py) replaces a
coordinate's hot table object on every cold->hot transfer (the donated
scatter produces a new array); same-shape/dtype arguments re-dispatch
the cached executable with zero retraces, where a closure would either
go stale or force a steady-state recompile. Fully-resident coordinates
pass the same table every call — one calling convention for both tiers.

The math is the offline ``game/scoring.GameScorer`` expressions verbatim
— fixed effects as a gathered dot over padded (index, value) pairs,
random effects as an entity-row gather followed by a slot-aligned dot —
which is what makes serving-vs-offline parity exact rather than
approximate.

Programs are shared through ``utils/jitcache`` so every bucket compiles
once per process; ``warmup_scorers`` dispatches each (mode, bucket)
program on dummy inputs inside ``compile_cache.warmup`` so the full
ladder is compiled at model-load time and steady-state traffic never
traces.
"""

from __future__ import annotations

from typing import Callable, Sequence

from photon_tpu.serving.model_state import DeviceResidentModel
from photon_tpu.utils import compile_cache, jitcache

#: scoring modes; "fixed_only" is the SLO-shed ladder (random-effect
#: gathers skipped) and is warmed alongside "full" so entering shed mode
#: under load never triggers a compile
MODES = ("full", "fixed_only")


def get_scorer(model: DeviceResidentModel, mode: str,
               bucket: int) -> Callable:
    """Compiled scorer for one (model, mode, bucket); cached process-wide.

    Call as ``fn(*args, re_tables)`` where ``args`` is the assemble
    output and ``re_tables`` is ``model.current_tables()`` read inside
    the same ``model.transfer_lock`` hold as the assemble (the two-tier
    store's consistency contract).
    """
    if mode not in MODES:
        raise ValueError(f"unknown serving mode {mode!r}")
    key = ("serving_scorer", model.token, mode, int(bucket))

    def builder():
        import jax
        import jax.numpy as jnp

        dtype = model.dtype
        shard_pos = {sid: i for i, sid in enumerate(model.shard_order)}
        thetas = tuple(f.theta for f in model.fixed)
        fixed_pos = tuple(shard_pos[f.feature_shard_id] for f in model.fixed)
        with_random = mode == "full"

        @jax.jit
        def fn(fixed_idx, fixed_val, re_sidx, re_sval, re_ent, offsets,
               re_tables):
            total = offsets.astype(dtype)
            for theta, pos in zip(thetas, fixed_pos):
                # ops/features.matvec on the padded ELL layout: pad slots
                # are (0, 0.0) so they contribute nothing
                total = total + jnp.sum(
                    fixed_val[pos].astype(dtype) * theta[fixed_idx[pos]],
                    axis=-1)
            if with_random:
                for coef, sidx, sval, ent in zip(re_tables, re_sidx,
                                                 re_sval, re_ent):
                    rows = coef.at[ent].get(mode="fill", fill_value=0.0)
                    total = total + jnp.sum(
                        sval.astype(dtype)
                        * jnp.take_along_axis(rows, sidx, axis=1),
                        axis=-1)
            return total

        return fn

    return jitcache.get_or_build(key, builder)


def warmup_scorers(model: DeviceResidentModel,
                   buckets: Sequence[int]) -> int:
    """Compile-and-dispatch every (mode, bucket) program under the warmup
    phase flag. Returns the number of programs warmed."""
    warmed = 0

    def one_bucket(bucket):
        nonlocal warmed
        args = model.dummy_args(bucket)
        tables = model.current_tables()
        for mode in MODES:
            out = get_scorer(model, mode, bucket)(*args, tables)
            out.block_until_ready()  # host-sync-ok: warmup only
            warmed += 1

    compile_cache.warmup(buckets, one_bucket)
    return warmed
