"""Validated live model swap with automatic rollback.

Replacing the model under live traffic is the serving half of the
nearline story: a retrained GAME model lands in a directory, and the
engine must start scoring with it without dropping requests, recompiling
on the hot path, or trusting it blindly. Every candidate runs a gate
ladder; the first failing gate rejects the swap and the live model keeps
serving, untouched::

    integrity   swap-manifest.json per-file crc32 (torn/corrupt copy)
    load        load_for_serving parses (schema errors, bad Avro)
    finite      every coefficient table is finite on the host (NaN/inf
                poison caught with zero traffic dependence)
    staging     DeviceResidentModel built + full (mode x bucket) ladder
                warmed — compiles happen HERE, tagged phase="warmup"
    shadow      the engine's captured recent requests scored through
                live and candidate; max abs deviation must stay within
                ``SwapConfig.max_shadow_deviation``
    compiles    zero steady-state compiles across staging + shadow
                (the no-recompile contract extends over swaps)

Only then does :meth:`ServingEngine.publish_model` install the candidate
— an attribute swap under the model lock, landing exactly between
micro-batches. The prior model object (and its compiled programs) is
retained; rollback is a pointer restore, so the restored tables are
bitwise-identical. Post-publish, the engine watches the circuit breaker
for ``SwapConfig.probation_s`` and rolls back automatically on a trip.

Every attempt lands in ``engine.swap_history`` (gate outcomes, shadow
stats), the ``serving.swap_*`` counters, and the RunReport ``swap``
section.
"""

from __future__ import annotations

import dataclasses
import json
import os
import zlib
from typing import Dict, List, Optional

import numpy as np

from photon_tpu.obs.metrics import registry as _metrics
from photon_tpu.resilience import chaos as _chaos
from photon_tpu.resilience import io as rio
from photon_tpu.resilience.failures import record_failure
from photon_tpu.serving.engine import ServingEngine
from photon_tpu.serving.model_state import DeviceResidentModel
from photon_tpu.serving.scorer import (INT8_MODE, get_scorer,
                                       tables_for_mode, warmup_scorers)
from photon_tpu.utils import compile_cache

MANIFEST_FILE = "swap-manifest.json"
MANIFEST_SCHEMA = "photon_tpu.swapmanifest.v1"

#: shadow |live - candidate| deviation histogram (log-spaced around the
#: parity scales that matter: fp32 epsilon up to order-1 disagreement)
DEVIATION_BUCKETS = tuple(1e-9 * 10 ** (0.5 * i) for i in range(20))


@dataclasses.dataclass
class SwapResult:
    """Outcome of one swap attempt."""

    accepted: bool
    label: str
    #: live version after the attempt (new version when accepted)
    version: int
    #: gate name -> "pass" | "fail" | "skip"
    gates: Dict[str, str]
    #: first failing gate's human-readable reason (empty when accepted)
    reason: str = ""
    #: shadow stats: requests compared, max abs deviation
    shadow_requests: int = 0
    shadow_max_deviation: Optional[float] = None
    #: ``publish=False`` (canary validation): the gate-passed, warmed
    #: DeviceResidentModel — held by the caller (a canary arm), never
    #: installed as the live model. None everywhere else, and excluded
    #: from to_json (it is device state, not a result record).
    staged_model: Optional[object] = dataclasses.field(
        default=None, repr=False, compare=False)

    def to_json(self) -> dict:
        # no dataclasses.asdict: it would deep-copy staged_model (device
        # arrays, lock-holding stores) — serialize the record fields only
        return {
            "accepted": self.accepted,
            "label": self.label,
            "version": self.version,
            "gates": dict(self.gates),
            "reason": self.reason,
            "shadow_requests": self.shadow_requests,
            "shadow_max_deviation": self.shadow_max_deviation,
        }


# -- integrity manifest ------------------------------------------------------


def write_swap_manifest(model_dir: str) -> str:
    """Stamp ``model_dir`` with per-file crc32 checksums (the checkpoint
    schema-v2 discipline applied to the exported model layout). The
    trainer/exporter calls this last, after every model file is final."""
    checksums: Dict[str, int] = {}
    for root, _dirs, names in os.walk(model_dir):
        for name in sorted(names):
            if name == MANIFEST_FILE:
                continue
            path = os.path.join(root, name)
            rel = os.path.relpath(path, model_dir)
            with open(path, "rb") as f:
                checksums[rel] = zlib.crc32(f.read())
    doc = {"schema": MANIFEST_SCHEMA, "files": checksums}
    path = os.path.join(model_dir, MANIFEST_FILE)
    rio.atomic_write_bytes(path, json.dumps(doc, indent=2).encode("utf-8"),
                           op="swap_manifest")
    return path


def verify_swap_manifest(model_dir: str) -> Dict[str, object]:
    """Check ``model_dir`` against its manifest. Returns
    ``{"present": bool, "ok": bool, "detail": str}`` — a missing manifest
    is ``present=False, ok=True`` (the require_manifest knob decides
    whether that refuses the swap)."""
    path = os.path.join(model_dir, MANIFEST_FILE)
    if not os.path.exists(path):
        return {"present": False, "ok": True, "detail": "no manifest"}
    try:
        with open(path, "rb") as f:
            doc = json.loads(f.read().decode("utf-8"))
        if doc.get("schema") != MANIFEST_SCHEMA:
            return {"present": True, "ok": False,
                    "detail": f"unknown manifest schema {doc.get('schema')!r}"}
        files = doc["files"]
    except (OSError, ValueError, KeyError) as e:
        return {"present": True, "ok": False,
                "detail": f"unreadable manifest: {e!r}"}
    for rel, want in sorted(files.items()):
        path = os.path.join(model_dir, rel)
        try:
            with open(path, "rb") as f:
                got = zlib.crc32(f.read())
        except OSError as e:
            return {"present": True, "ok": False,
                    "detail": f"missing file {rel!r}: {e!r}"}
        if got != int(want):
            return {"present": True, "ok": False,
                    "detail": f"crc mismatch on {rel!r}: "
                              f"{got:#010x} != {int(want):#010x}"}
    # files on disk but not in the manifest are torn-copy evidence too
    for root, _dirs, names in os.walk(model_dir):
        for name in names:
            if name == MANIFEST_FILE:
                continue
            rel = os.path.relpath(os.path.join(root, name), model_dir)
            if rel not in files:
                return {"present": True, "ok": False,
                        "detail": f"unmanifested file {rel!r}"}
    return {"present": True, "ok": True,
            "detail": f"{len(files)} files verified"}


# -- shadow scoring ----------------------------------------------------------


def _shadow_scores(model: DeviceResidentModel, requests: List,
                   ladder, mode: str = "full") -> np.ndarray:
    """Score ``requests`` through ``model`` full-effort, chunked over the
    engine's bucket ladder (every (mode, bucket) program is warmed, so
    this dispatches zero new compiles). ``mode`` selects the program arm
    — the int8 gate scores the SAME staged model through "full" and
    "full_int8" to bound the quantization error in score units.

    Two-tier models first promote the shadow sample's entities into the
    hot tier and drain the transfer queue — the shadow gate compares real
    coefficient scores, not COLD_MISS degradations, so live-vs-candidate
    deviation means what it says regardless of residency tier. Assemble +
    table read + dispatch hold the model's transfer lock, same contract
    as the engine hot path."""
    if model.has_stores:
        for r in requests:
            model.prefetch_request(r)
        model.drain_prefetch()
    out: List[np.ndarray] = []
    top = ladder.max_batch
    for lo in range(0, len(requests), top):
        chunk = requests[lo:lo + top]
        bucket = ladder.bucket_for(len(chunk))
        with model.transfer_lock:
            args, _fallbacks, _counters = model.assemble(chunk, bucket)
            raw = get_scorer(model, mode, bucket)(
                *args, model.current_thetas(),
                tables_for_mode(model, mode))
        out.append(np.asarray(raw)[:len(chunk)])
    return np.concatenate(out) if out else np.zeros(0, np.float32)


# -- the gate ladder ---------------------------------------------------------


def _reject(engine: ServingEngine, label: str, gates: Dict[str, str],
            gate: str, reason: str, shadow_requests: int = 0,
            shadow_max_deviation: Optional[float] = None) -> SwapResult:
    gates[gate] = "fail"
    _metrics.counter("serving.swap_rejected", gate=gate).inc()
    record_failure("serving_swap_rejected", label=label, gate=gate,
                   reason=reason)
    result = SwapResult(False, label, engine.model_version, dict(gates),
                        reason=reason, shadow_requests=shadow_requests,
                        shadow_max_deviation=shadow_max_deviation)
    engine.swap_history.append({
        "outcome": "rejected", "label": label, "gate": gate, "why": reason,
        "gates": dict(gates), "version": engine.model_version,
        "shadow_requests": shadow_requests,
        "shadow_max_deviation": shadow_max_deviation,
    })
    return result


def swap_staged(engine: ServingEngine, serving_model, label: str,
                mesh=None, publish: bool = True) -> SwapResult:
    """Run the in-memory half of the gate ladder (finite -> staging ->
    shadow -> compiles) over an already-loaded ServingGameModel, and
    publish on success. ``swap_from_dir`` is the on-disk front half.

    ``publish=False`` runs the identical ladder but stops short of
    installing the candidate: the returned result carries the warmed,
    gate-passed model in ``staged_model`` instead. This is the canary
    entry point (serving/tenants.py) — a canary arm must clear every
    gate a full swap would, it just receives a traffic fraction rather
    than the whole stream."""
    cfg = engine.config.swap
    gates: Dict[str, str] = {}
    _metrics.counter("serving.swap_attempts").inc()

    # finite: host-side scan of every coefficient table — a poisoned
    # candidate is refused before it touches the device, no traffic needed.
    # Cold-backed coordinates are scanned in streamed blocks off the mmap
    # (never materialized whole) after a crc verify, so a torn or poisoned
    # cold file is caught here even when the manifest was skipped.
    bad = []
    for fe in serving_model.fixed:
        if not np.all(np.isfinite(np.asarray(fe.coefficients))):
            bad.append(fe.coordinate_id)
    for re in serving_model.random:
        cold_path = getattr(re, "cold_store_path", None)
        if cold_path is not None:
            from photon_tpu.io.cold_store import (
                ColdStore,
                ColdStoreCorruptError,
            )
            try:
                cs = ColdStore(cold_path, verify=True)
                for _start, _ids, coef_block, _proj in cs.iter_blocks(262144):
                    if not np.all(np.isfinite(coef_block)):
                        bad.append(re.coordinate_id)
                        break
            except (ColdStoreCorruptError, OSError) as e:
                return _reject(engine, label, gates, "finite",
                               f"cold store unreadable for "
                               f"{re.coordinate_id!r}: {e!r}")
        elif not np.all(np.isfinite(np.asarray(re.coefficients))):
            bad.append(re.coordinate_id)
    if bad:
        return _reject(engine, label, gates, "finite",
                       f"non-finite coefficients in {bad}")
    gates["finite"] = "pass"

    steady0 = compile_cache.compile_counts().get("steady_state", 0)

    # staging: device residency + the full program ladder, compiled under
    # the warmup phase tag (a new model token = new logical programs, so
    # these compiles are expected and excluded from the steady-state gate)
    try:
        staged = DeviceResidentModel(
            serving_model, mesh=mesh if mesh is not None else engine.model.mesh,
            feature_pad=engine.config.feature_pad,
            coeff_store=engine.config.coeff_store,
            append_reserve=engine.config.append_reserve,
            int8=engine.config.int8_serving)
        warmup_scorers(staged, engine.ladder.buckets)
    except Exception as e:  # any staging fault refuses, live keeps serving
        return _reject(engine, label, gates, "staging",
                       f"staging failed: {e!r}")
    gates["staging"] = "pass"

    # shadow: recent captured traffic through both models
    sample = engine.recent_requests(cfg.capture_size)
    shadow_n = len(sample)
    max_dev: Optional[float] = None
    if shadow_n >= cfg.min_shadow_requests:
        try:
            live_scores = _shadow_scores(engine.model, sample, engine.ladder)
            cand_scores = _shadow_scores(staged, sample, engine.ladder)
        except Exception as e:
            staged.close_stores()
            return _reject(engine, label, gates, "shadow",
                           f"shadow scoring failed: {e!r}",
                           shadow_requests=shadow_n)
        if not np.all(np.isfinite(cand_scores)):
            staged.close_stores()
            return _reject(engine, label, gates, "shadow",
                           "candidate produced non-finite shadow scores",
                           shadow_requests=shadow_n)
        max_dev = float(np.max(np.abs(live_scores - cand_scores))) \
            if shadow_n else 0.0
        _metrics.histogram("serving.swap_shadow_deviation",
                           DEVIATION_BUCKETS).observe(max_dev)
        if max_dev > cfg.max_shadow_deviation:
            staged.close_stores()
            return _reject(engine, label, gates, "shadow",
                           f"shadow deviation {max_dev:.3e} > "
                           f"{cfg.max_shadow_deviation:.3e} "
                           f"over {shadow_n} requests",
                           shadow_requests=shadow_n,
                           shadow_max_deviation=max_dev)
        gates["shadow"] = "pass"
    else:
        gates["shadow"] = "skip"

    # int8_shadow: when the candidate was staged with the quantized arm,
    # bound the quantization error in SCORE units — the same captured
    # requests through the staged model's f32 ("full") and int8
    # ("full_int8") programs must agree within int8_max_deviation. Runs
    # inside the compile window: both arms were warmed in staging, so a
    # retrace here fails the compiles gate too.
    if getattr(staged, "int8_enabled", False):
        if shadow_n >= cfg.min_shadow_requests:
            try:
                f32_scores = _shadow_scores(staged, sample, engine.ladder)
                q_scores = _shadow_scores(staged, sample, engine.ladder,
                                          mode=INT8_MODE)
            except Exception as e:
                staged.close_stores()
                return _reject(engine, label, gates, "int8_shadow",
                               f"int8 shadow scoring failed: {e!r}",
                               shadow_requests=shadow_n,
                               shadow_max_deviation=max_dev)
            int8_dev = float(np.max(np.abs(f32_scores - q_scores))) \
                if shadow_n else 0.0
            _metrics.histogram("serving.swap_int8_deviation",
                               DEVIATION_BUCKETS).observe(int8_dev)
            if not np.all(np.isfinite(q_scores)) \
                    or int8_dev > cfg.int8_max_deviation:
                staged.close_stores()
                return _reject(engine, label, gates, "int8_shadow",
                               f"int8 deviation {int8_dev:.3e} > "
                               f"{cfg.int8_max_deviation:.3e} "
                               f"over {shadow_n} requests",
                               shadow_requests=shadow_n,
                               shadow_max_deviation=max_dev)
            gates["int8_shadow"] = "pass"
        else:
            gates["int8_shadow"] = "skip"

    # compiles: staging+shadow must not have compiled on the steady path
    steady1 = compile_cache.compile_counts().get("steady_state", 0)
    if steady1 != steady0:
        staged.close_stores()
        return _reject(engine, label, gates, "compiles",
                       f"{steady1 - steady0} steady-state compiles during "
                       f"staging/shadow", shadow_requests=shadow_n,
                       shadow_max_deviation=max_dev)
    gates["compiles"] = "pass"

    if not publish:
        engine.swap_history.append({
            "outcome": "validated", "label": label, "gates": dict(gates),
            "version": engine.model_version, "shadow_requests": shadow_n,
            "shadow_max_deviation": max_dev,
        })
        return SwapResult(True, label, engine.model_version, gates,
                          shadow_requests=shadow_n,
                          shadow_max_deviation=max_dev,
                          staged_model=staged)

    published = engine.publish_model(staged, label)
    engine.swap_history.append({
        "outcome": "published", "label": label, "gates": dict(gates),
        "version": published["version"], "shadow_requests": shadow_n,
        "shadow_max_deviation": max_dev,
    })
    return SwapResult(True, label, published["version"], gates,
                      shadow_requests=shadow_n, shadow_max_deviation=max_dev)


def swap_from_dir(engine: ServingEngine, model_dir: str,
                  label: Optional[str] = None, mesh=None,
                  coordinates_to_load=None) -> SwapResult:
    """Full gate ladder over an exported model directory: integrity ->
    load -> (chaos poison hook) -> swap_staged. The canonical entry point
    for the CLI control line and operator tooling."""
    from photon_tpu.io.model_io import load_for_serving

    label = label or os.path.basename(os.path.normpath(model_dir))
    gates: Dict[str, str] = {}

    verdict = verify_swap_manifest(model_dir)
    if not verdict["ok"]:
        _metrics.counter("serving.swap_attempts").inc()
        return _reject(engine, label, gates, "integrity",
                       str(verdict["detail"]))
    if not verdict["present"] and engine.config.swap.require_manifest:
        _metrics.counter("serving.swap_attempts").inc()
        return _reject(engine, label, gates, "integrity",
                       "manifest required but absent")
    gates["integrity"] = "pass" if verdict["present"] else "skip"

    try:
        serving_model = load_for_serving(
            model_dir, coordinates_to_load=coordinates_to_load)
    except Exception as e:  # torn dir past the manifest, schema drift
        _metrics.counter("serving.swap_attempts").inc()
        return _reject(engine, label, gates, "load",
                       f"load_for_serving failed: {e!r}")
    gates["load"] = "pass"

    if _chaos.should_poison_swap_candidate():
        for fe in serving_model.fixed:
            fe.coefficients = np.full_like(np.asarray(fe.coefficients), np.nan)

    result = swap_staged(engine, serving_model, label, mesh=mesh)
    # fold the on-disk gate outcomes into the ladder's result/history
    result.gates = {**gates, **result.gates}
    if engine.swap_history:
        engine.swap_history[-1]["gates"] = dict(result.gates)
    return result
