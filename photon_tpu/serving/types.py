"""Typed requests, responses, and configuration for online serving.

The reference has no online path (GameScoringDriver is batch-only); the
shapes here follow the GLMix serving story: a request is one sample —
per-shard (name, term, value) features plus the entity ids that select
per-entity random-effect models — and a response is one score plus a
*typed* account of every way the engine degraded it. Degradation is data,
not an exception (resilience-subsystem convention: typed reasons that
land in telemetry, never a stack trace on the hot path).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, Optional, Sequence, Tuple


class FallbackReason(str, enum.Enum):
    """Why a score is degraded. String-valued: serializes verbatim into
    JSONL responses, metrics labels, and the RunReport serving section."""

    #: entity id absent from the model vocabulary (cold user/item) —
    #: the coordinate contributes zero, matching the reference's
    #: missing-per-entity-model semantics
    UNKNOWN_ENTITY = "unknown_entity"
    #: admission queue above the shed threshold: random-effect gathers
    #: skipped for the whole batch, fixed-effect-only scores returned
    SLO_SHED_RANDOM_EFFECTS = "slo_shed_random_effects"
    #: admission queue above the reject threshold: request not scored
    SLO_REJECTED = "slo_rejected"
    #: request carried more features than the padded width for a shard;
    #: overflow features dropped (first-N kept, deterministic)
    FEATURE_OVERFLOW = "feature_overflow"


@dataclasses.dataclass(frozen=True)
class Fallback:
    """One typed degradation event on one request."""

    reason: FallbackReason
    coordinate: Optional[str] = None
    detail: str = ""

    def to_json(self) -> dict:
        out = {"reason": self.reason.value}
        if self.coordinate:
            out["coordinate"] = self.coordinate
        if self.detail:
            out["detail"] = self.detail
        return out


@dataclasses.dataclass
class ScoreRequest:
    """One sample to score.

    ``features``: shard id -> sequence of (name, term, value);
    ``entity_ids``: random-effect type -> entity id string.
    """

    uid: str
    features: Dict[str, Sequence[Tuple[str, str, float]]]
    entity_ids: Dict[str, str] = dataclasses.field(default_factory=dict)
    offset: float = 0.0

    @staticmethod
    def from_json(obj: dict) -> "ScoreRequest":
        feats = {
            str(sid): [(str(f[0]), str(f[1]), float(f[2])) for f in rows]
            for sid, rows in (obj.get("features") or {}).items()}
        return ScoreRequest(
            uid=str(obj.get("uid", "")),
            features=feats,
            entity_ids={str(k): str(v)
                        for k, v in (obj.get("ids") or {}).items()},
            offset=float(obj.get("offset", 0.0)))


@dataclasses.dataclass
class ScoreResponse:
    """One scored (or shed) request. ``score`` is None only for
    SLO_REJECTED; every other degradation still returns a usable score."""

    uid: str
    score: Optional[float]
    degraded: bool = False
    fallbacks: Tuple[Fallback, ...] = ()

    def to_json(self) -> dict:
        return {
            "uid": self.uid,
            "score": self.score,
            "degraded": self.degraded,
            "fallbacks": [f.to_json() for f in self.fallbacks],
        }


@dataclasses.dataclass(frozen=True)
class SLOConfig:
    """Load-shedding thresholds on admission-queue depth.

    Depth is the one signal that is both instantaneous and causal for
    tail latency (every queued request ahead of you is latency you will
    inherit), so the degradation ladder keys on it:

      depth <= shed_queue_depth                 full GAME scoring
      shed_queue_depth < depth <= reject_depth  fixed-effect-only batches
      depth > reject_queue_depth                typed rejection at admission
    """

    shed_queue_depth: int = 512
    reject_queue_depth: int = 4096

    def __post_init__(self):
        if self.shed_queue_depth < 1:
            raise ValueError("shed_queue_depth must be >= 1")
        if self.reject_queue_depth < self.shed_queue_depth:
            raise ValueError("reject_queue_depth < shed_queue_depth")


@dataclasses.dataclass(frozen=True)
class ServingConfig:
    """Engine knobs. Every shape-bearing value here is part of the
    compiled-program key: changing it after warmup would recompile, so
    the config is frozen."""

    #: top of the power-of-two bucket ladder (rounded up to a power of 2)
    max_batch: int = 64
    #: smallest bucket (1 keeps single-request latency honest)
    min_bucket: int = 1
    #: coalescing window: a batch forms when the ladder top fills OR the
    #: oldest queued request has waited this long
    max_wait_s: float = 0.002
    #: per-shard padded feature width; None = smallest power of two
    #: covering the shard dimension, capped at 256
    feature_pad: Optional[int] = None
    slo: SLOConfig = dataclasses.field(default_factory=SLOConfig)
