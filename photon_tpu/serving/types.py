"""Typed requests, responses, and configuration for online serving.

The reference has no online path (GameScoringDriver is batch-only); the
shapes here follow the GLMix serving story: a request is one sample —
per-shard (name, term, value) features plus the entity ids that select
per-entity random-effect models — and a response is one score plus a
*typed* account of every way the engine degraded it. Degradation is data,
not an exception (resilience-subsystem convention: typed reasons that
land in telemetry, never a stack trace on the hot path).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, Optional, Sequence, Tuple


class FallbackReason(str, enum.Enum):
    """Why a score is degraded. String-valued: serializes verbatim into
    JSONL responses, metrics labels, and the RunReport serving section."""

    #: entity id absent from the model vocabulary (cold user/item) —
    #: the coordinate contributes zero, matching the reference's
    #: missing-per-entity-model semantics
    UNKNOWN_ENTITY = "unknown_entity"
    #: admission queue above the shed threshold: random-effect gathers
    #: skipped for the whole batch, fixed-effect-only scores returned
    SLO_SHED_RANDOM_EFFECTS = "slo_shed_random_effects"
    #: admission queue above the reject threshold: request not scored
    SLO_REJECTED = "slo_rejected"
    #: request carried more features than the padded width for a shard;
    #: overflow features dropped (first-N kept, deterministic)
    FEATURE_OVERFLOW = "feature_overflow"
    #: the request's absolute deadline cannot be met — either refused at
    #: admission (budget below the service floor) or expired while queued;
    #: it never occupies a bucket slot it cannot use
    DEADLINE_EXCEEDED = "deadline_exceeded"
    #: the engine is draining (SIGTERM / operator drain): admission
    #: refuses instead of queueing work that may never score
    SHUTTING_DOWN = "shutting_down"
    #: circuit breaker tripped to fixed-effect-only scoring (stage
    #: latency or failure-rate breach; distinct from the SLO shed so
    #: operators can tell load from fault)
    BREAKER_SHED_RANDOM_EFFECTS = "breaker_shed_random_effects"
    #: circuit breaker open: admission refuses outright until the
    #: half-open probe succeeds
    BREAKER_REJECTED = "breaker_rejected"
    #: the compiled scorer raised or produced non-finite scores; the
    #: request gets a typed failure, never a hot-path exception
    SCORER_FAILURE = "scorer_failure"
    #: the entity exists in the model but its coefficient rows were still
    #: in the host-RAM cold tier at batch-pop time (two-tier store): the
    #: coordinate contributes zero for THIS request — like
    #: SLO_SHED_RANDOM_EFFECTS but per-entity — and the miss promotes the
    #: rows so the next request finds them hot. Never a synchronous
    #: host->device stall on the scoring path.
    COLD_MISS = "cold_miss"
    #: entity-sharded fleet: the shard owning this request's random-effect
    #: rows is down, past its deadline, or refusing (breaker open /
    #: draining) — the fleet returns the fixed-effect margin plus the
    #: margins of every shard that did answer, with this typed flag per
    #: unavailable shard. Never a hot-path exception at the router.
    SHARD_UNAVAILABLE = "shard_unavailable"
    #: multi-tenant engine: the request named a tenant this process does
    #: not host (or named none where no default is configured) — refused
    #: at routing, before any tenant's admission queue is touched
    UNKNOWN_TENANT = "unknown_tenant"
    #: multi-tenant engine: the tenant's own admission budget (its
    #: per-tenant requests-per-pump cap) is exhausted — THIS tenant's
    #: flood is bounded here so it cannot inflate its neighbors' tails
    TENANT_BUDGET_EXCEEDED = "tenant_budget_exceeded"
    #: Thompson serving mode: the entity id is absent from the model
    #: vocabulary, so the engine scored it with PRIOR-variance
    #: exploration noise (zero mean contribution + ``sqrt(prior_variance)``
    #: per feature) instead of silently at the mean — the explore half of
    #: explore/exploit for cold-start entities. Typed distinctly from
    #: UNKNOWN_ENTITY so operators can tell deliberate exploration from a
    #: vocabulary miss in mean-mode serving.
    EXPLORING_COLD_START = "exploring_cold_start"
    #: elastic fleet: the entity's virtual bucket is inside a live
    #: migration's double-read window — the request was scored off the
    #: source shard (authoritative) and mirrored to the destination for
    #: bitwise comparison. The score value is the source shard's, so the
    #: flag is the typed worst-case visibility the zero-downtime
    #: resharding contract allows (never a refusal, never the new copy)
    BUCKET_MIGRATING = "bucket_migrating"


@dataclasses.dataclass(frozen=True)
class Fallback:
    """One typed degradation event on one request."""

    reason: FallbackReason
    coordinate: Optional[str] = None
    detail: str = ""

    def to_json(self) -> dict:
        out = {"reason": self.reason.value}
        if self.coordinate:
            out["coordinate"] = self.coordinate
        if self.detail:
            out["detail"] = self.detail
        return out


@dataclasses.dataclass
class ScoreRequest:
    """One sample to score.

    ``features``: shard id -> sequence of (name, term, value);
    ``entity_ids``: random-effect type -> entity id string.
    """

    uid: str
    features: Dict[str, Sequence[Tuple[str, str, float]]]
    entity_ids: Dict[str, str] = dataclasses.field(default_factory=dict)
    offset: float = 0.0
    #: per-request latency budget in seconds; the engine turns it into an
    #: absolute deadline on its own clock at admission. None falls back
    #: to ``DeadlineConfig.default_timeout_s`` (which may also be None =
    #: no deadline).
    timeout_s: Optional[float] = None
    #: multi-tenant routing: which hosted model scores this request
    #: (``"tenant"`` in the JSONL protocol). None on a MultiTenantEngine
    #: routes to its default tenant when one is configured; a
    #: single-tenant ServingEngine ignores the field.
    tenant: Optional[str] = None

    @staticmethod
    def from_json(obj: dict) -> "ScoreRequest":
        feats = {
            str(sid): [(str(f[0]), str(f[1]), float(f[2])) for f in rows]
            for sid, rows in (obj.get("features") or {}).items()}
        return ScoreRequest(
            uid=str(obj.get("uid", "")),
            features=feats,
            entity_ids={str(k): str(v)
                        for k, v in (obj.get("ids") or {}).items()},
            offset=float(obj.get("offset", 0.0)),
            timeout_s=(float(obj["timeout_ms"]) / 1000.0
                       if obj.get("timeout_ms") is not None else None),
            tenant=(str(obj["tenant"])
                    if obj.get("tenant") is not None else None))


@dataclasses.dataclass
class ScoreResponse:
    """One scored (or shed) request. ``score`` is None only for
    SLO_REJECTED; every other degradation still returns a usable score."""

    uid: str
    score: Optional[float]
    degraded: bool = False
    fallbacks: Tuple[Fallback, ...] = ()
    #: multi-tenant attribution, set by MultiTenantEngine on the way out:
    #: which tenant scored it, and which traffic arm ("live"/"canary")
    #: its model came from. None from a single-tenant engine and omitted
    #: from the JSONL response.
    tenant: Optional[str] = None
    arm: Optional[str] = None

    def to_json(self) -> dict:
        out = {
            "uid": self.uid,
            "score": self.score,
            "degraded": self.degraded,
            "fallbacks": [f.to_json() for f in self.fallbacks],
        }
        if self.tenant is not None:
            out["tenant"] = self.tenant
        if self.arm is not None:
            out["arm"] = self.arm
        return out


@dataclasses.dataclass(frozen=True)
class SLOConfig:
    """Load-shedding thresholds on admission-queue depth.

    Depth is the one signal that is both instantaneous and causal for
    tail latency (every queued request ahead of you is latency you will
    inherit), so the degradation ladder keys on it:

      depth <= shed_queue_depth                 full GAME scoring
      shed_queue_depth < depth <= reject_depth  fixed-effect-only batches
      depth > reject_queue_depth                typed rejection at admission
    """

    shed_queue_depth: int = 512
    reject_queue_depth: int = 4096

    def __post_init__(self):
        if self.shed_queue_depth < 1:
            raise ValueError("shed_queue_depth must be >= 1")
        if self.reject_queue_depth < self.shed_queue_depth:
            raise ValueError("reject_queue_depth < shed_queue_depth")


@dataclasses.dataclass(frozen=True)
class DeadlineConfig:
    """Per-request deadline propagation: admission -> queue -> scoring.

    A request's absolute deadline is ``admission_time + timeout``; the
    per-stage budgets below decide where along the pipeline it is refused
    rather than scored late:

      admission  budget < min_service_s          DEADLINE_EXCEEDED now
      queue      now > deadline - score_headroom DEADLINE_EXCEEDED at pop
      release    a batch ships early enough that its tightest deadline
                 still has score_headroom_s left (overriding the
                 oldest-waiter coalescing wait)
    """

    #: deadline applied to requests that carry no ``timeout_s`` of their
    #: own; None = such requests never expire
    default_timeout_s: Optional[float] = None
    #: the assemble+score floor: a request whose whole budget is below
    #: this cannot be served in time no matter what, so admission refuses
    #: it immediately instead of letting it occupy a bucket slot
    min_service_s: float = 0.0
    #: time reserved for assemble+score after a request leaves the queue;
    #: a queued request is expired once ``now > deadline - this``
    score_headroom_s: float = 0.0

    def __post_init__(self):
        if self.min_service_s < 0 or self.score_headroom_s < 0:
            raise ValueError("deadline budgets must be >= 0")
        if self.default_timeout_s is not None and self.default_timeout_s <= 0:
            raise ValueError("default_timeout_s must be positive")


@dataclasses.dataclass(frozen=True)
class BreakerConfig:
    """Sliding-window circuit breaker over the scorer stage.

    State ladder: ``closed`` (full scoring) -> ``shed`` (fixed-effect
    only) -> ``open`` (reject at admission) -> ``half_open`` (bounded
    full-effort probes after ``cooldown_s``) -> ``closed`` again when the
    probes come back healthy. A breach is either the window's p99 scorer
    latency above ``latency_p99_s`` or its failure rate above
    ``failure_rate``, evaluated once ``min_samples`` observations exist.
    """

    #: number of most-recent scorer-stage observations kept
    window: int = 256
    #: observations required before the breaker may trip (a single slow
    #: batch on a cold window must not flap the state)
    min_samples: int = 16
    #: p99 scorer-stage latency threshold; inf disables the latency trip
    latency_p99_s: float = float("inf")
    #: scorer failure-rate threshold (exceptions / non-finite scores)
    failure_rate: float = 0.5
    #: time spent open before half-open probing starts
    cooldown_s: float = 1.0
    #: healthy full-effort probe batches required to close again
    probe_batches: int = 2

    def __post_init__(self):
        if self.window < 1 or self.min_samples < 1 or self.probe_batches < 1:
            raise ValueError("breaker window/min_samples/probe_batches >= 1")
        if not 0.0 < self.failure_rate <= 1.0:
            raise ValueError("failure_rate must be in (0, 1]")
        if self.cooldown_s < 0:
            raise ValueError("cooldown_s must be >= 0")


@dataclasses.dataclass(frozen=True)
class SwapConfig:
    """Gates for validated live model swap (serving/swap.py)."""

    #: how many recent admitted requests the engine captures for shadow
    #: scoring a candidate (ring buffer; also the shadow sample ceiling)
    capture_size: int = 256
    #: reject a candidate whose shadow scores deviate from the live
    #: model's by more than this (max abs); inf = only finiteness gates
    max_shadow_deviation: float = float("inf")
    #: minimum captured requests the shadow gate needs; below it the
    #: deviation gate is skipped (finite/compile gates still apply)
    min_shadow_requests: int = 1
    #: refuse candidates without a crc32 swap manifest (swap-manifest.json)
    require_manifest: bool = False
    #: int8 arm gate: reject a candidate staged with ``int8_serving``
    #: whose quantized ("full_int8") shadow scores deviate from its own
    #: f32 ("full") scores by more than this (max abs over the captured
    #: requests). inf = accept any quantization error that is finite.
    #: Only evaluated when the staged model actually has the int8 arm.
    int8_max_deviation: float = float("inf")
    #: post-publish probation: a breaker trip within this window triggers
    #: automatic rollback to the prior version; 0 disables the guard
    probation_s: float = 30.0

    def __post_init__(self):
        if self.capture_size < 1:
            raise ValueError("capture_size must be >= 1")
        if self.probation_s < 0:
            raise ValueError("probation_s must be >= 0")


@dataclasses.dataclass(frozen=True)
class CoeffStoreConfig:
    """Two-tier coefficient store: host-RAM cold tier + HBM hot set.

    The hot tier is a fixed-capacity device gather table per coordinate,
    LRU-managed over entity traffic with admission-time async prefetch.
    Capacity is ``hbm_budget_bytes / row_bytes`` rounded DOWN to a power
    of two (the table's leading dim is a compiled-program shape: pow2
    sizing keeps scorer programs stable so steady-state serving still
    performs zero compiles), or ``hot_capacity`` when given explicitly.
    """

    #: per-coordinate HBM budget for the hot gather table, in bytes;
    #: capacity = pow2_floor(budget / (slot_width * 4)). Exactly one of
    #: this and ``hot_capacity`` must be set.
    hbm_budget_bytes: Optional[int] = None
    #: explicit hot-row capacity (rounded down to a power of two)
    hot_capacity: Optional[int] = None
    #: rows per coalesced cold->hot upload: misses are batched into ONE
    #: ``jax.device_put`` + one fixed-shape donated scatter per cycle
    #: (the fixed shape keeps the transfer program compile-free too)
    transfer_batch: int = 256
    #: resolve entity ids at admission (MicroBatcher ``on_admit``
    #: lookahead) and schedule uploads before batch release; off =
    #: promotion only on COLD_MISS
    prefetch: bool = True

    def __post_init__(self):
        if (self.hbm_budget_bytes is None) == (self.hot_capacity is None):
            raise ValueError(
                "exactly one of hbm_budget_bytes / hot_capacity required")
        if self.hbm_budget_bytes is not None and self.hbm_budget_bytes < 4:
            raise ValueError("hbm_budget_bytes must cover at least one row")
        if self.hot_capacity is not None and self.hot_capacity < 1:
            raise ValueError("hot_capacity must be >= 1")
        if self.transfer_batch < 1:
            raise ValueError("transfer_batch must be >= 1")


@dataclasses.dataclass(frozen=True)
class ServingConfig:
    """Engine knobs. Every shape-bearing value here is part of the
    compiled-program key: changing it after warmup would recompile, so
    the config is frozen."""

    #: top of the power-of-two bucket ladder (rounded up to a power of 2)
    max_batch: int = 64
    #: smallest bucket (1 keeps single-request latency honest)
    min_bucket: int = 1
    #: coalescing window: a batch forms when the ladder top fills OR the
    #: oldest queued request has waited this long
    max_wait_s: float = 0.002
    #: per-shard padded feature width; None = smallest power of two
    #: covering the shard dimension, capped at 256
    feature_pad: Optional[int] = None
    slo: SLOConfig = dataclasses.field(default_factory=SLOConfig)
    deadline: DeadlineConfig = dataclasses.field(default_factory=DeadlineConfig)
    breaker: BreakerConfig = dataclasses.field(default_factory=BreakerConfig)
    swap: SwapConfig = dataclasses.field(default_factory=SwapConfig)
    #: two-tier coefficient store; None = every random-effect table fully
    #: device-resident (the pre-cold-tier behavior). When set, any
    #: coordinate loaded with a cold-store file serves from a hot-set
    #: gather cache under this budget.
    coeff_store: Optional[CoeffStoreConfig] = None
    #: graceful drain: after ``begin_drain`` the engine keeps flushing
    #: in-flight micro-batches for at most this long; whatever is still
    #: queued past the budget gets a typed SHUTTING_DOWN refusal
    drain_budget_s: float = 5.0
    #: nearline appends for FULL-RESIDENT coordinates: zero rows reserved
    #: after the unknown row at load time (part of the compiled table
    #: shape). Each row-level publish of a brand-new entity consumes one;
    #: when exhausted, appends to that coordinate fail the publisher's
    #: typed capacity gate until the next full swap. Two-tier coordinates
    #: ignore this — their cold file carries its own reserve.
    append_reserve: int = 0
    #: OPT-IN int8 serving arm: full-resident random-effect tables are
    #: additionally staged as (int8 rows, per-row f32 scales) at model
    #: load / swap-staging time, and healthy (non-shed) traffic scores
    #: through the dequantizing "full_int8" programs — halving the
    #: random-effect gather bytes. Guarded by the swap ladder's
    #: ``SwapConfig.int8_max_deviation`` shadow gate; two-tier
    #: coordinates keep their f32 hot tables (the cold tier is the
    #: capacity lever there). Off = exact f32 behavior, no extra tables.
    int8_serving: bool = False
    #: OPT-IN Thompson-sampling serving: when the loaded model carries
    #: posterior variances (bayes/laplace.py via the v3/v4 cold-store /
    #: Avro variance columns), healthy traffic scores through the
    #: "thompson" mode — each request samples ``theta ~ N(mu, sigma^2)``
    #: INSIDE the compiled program from a counter-derived per-request
    #: seed, so replays are bitwise and steady state stays zero-compile.
    #: Takes precedence over the int8 arm; sheds still drop to
    #: fixed_only. A var-less model under this flag serves the mean
    #: exactly as before (the mode never activates). Full-resident
    #: tables only: combining with a two-tier ``coeff_store`` on a
    #: variance-carrying model is a typed refusal at load.
    thompson_serving: bool = False
    #: base seed for the per-request sampling keys: a request's
    #: exploration draw is derived from ``request_key(thompson_seed,
    #: uid)`` (utils/seeds.py), so a replay with the same seed and uids
    #: reproduces every sampled score bitwise, independent of arrival
    #: order or batch packing
    thompson_seed: int = 0
    #: prior variance served to cold-start entities in thompson mode: an
    #: unknown entity's features get zero mean and this variance per
    #: coefficient (the typed EXPLORING_COLD_START path)
    prior_variance: float = 1.0
