"""Sliding-window circuit breaker for the serving scorer stage.

The SLO ladder (types.SLOConfig) sheds on *load* — queue depth is the
signal. The breaker sheds on *fault*: a scorer stage that got slow
(device contention, a pathological model) or started failing
(exceptions, non-finite scores) poisons every queued request behind it,
so the engine must stop feeding it full-effort work even when the queue
is shallow. State ladder::

    closed ──breach──> shed ──breach persists──> open
      ▲                                            │ cooldown_s
      └──── probes healthy ──── half_open <────────┘
                                    │ probe breaches
                                    └────────────> open (cooldown again)

``shed`` scores fixed-effect-only (cheap, no gathers — typed
BREAKER_SHED_RANDOM_EFFECTS fallback); ``open`` refuses at admission
(BREAKER_REJECTED). Half-open lets ``probe_batches`` full-effort batches
through and closes only when every probe is healthy. Breaches are
evaluated over a bounded window of the most recent observations
(latency p99 above threshold, or failure rate above threshold) and the
window clears on every transition so each state decides on evidence
gathered *in* that state — a breaker that tripped on stale samples
would flap.

The clock is injected (the engine passes its own), so cooldown and
probation tests run on a deterministic fake clock; latencies recorded
are real measured stage seconds.
"""

from __future__ import annotations

import logging
import math
import threading
from collections import deque
from typing import Callable, List, Optional, Tuple

from photon_tpu.serving.types import BreakerConfig

logger = logging.getLogger(__name__)

CLOSED = "closed"
SHED = "shed"
OPEN = "open"
HALF_OPEN = "half_open"

#: numeric encoding for the state gauge (monotone in severity)
STATE_LEVELS = {CLOSED: 0.0, HALF_OPEN: 1.0, SHED: 2.0, OPEN: 3.0}


def _p99(latencies: List[float]) -> float:
    if not latencies:
        return 0.0
    ordered = sorted(latencies)
    rank = max(math.ceil(0.99 * len(ordered)) - 1, 0)
    return ordered[rank]


class CircuitBreaker:
    """Fault breaker over (latency, ok) scorer-stage observations."""

    def __init__(self, config: Optional[BreakerConfig] = None,
                 clock: Optional[Callable[[], float]] = None,
                 on_transition: Optional[Callable[[str, str, str], None]]
                 = None):
        import time

        self.config = config or BreakerConfig()
        self.clock = clock if clock is not None else time.monotonic
        self.on_transition = on_transition
        self._lock = threading.Lock()
        self._state = CLOSED
        self._window: deque = deque(maxlen=self.config.window)
        self._opened_at: Optional[float] = None
        self._probes_left = 0
        self._probe_breached = False
        self.transitions = 0
        self.trips = 0

    # -- state machine --------------------------------------------------------

    def _transition_locked(self, to: str, why: str) -> None:
        frm = self._state
        if frm == to:
            return
        self._state = to
        self._window.clear()
        self.transitions += 1
        if to in (SHED, OPEN):
            self.trips += 1
        if to == OPEN:
            self._opened_at = self.clock()
        if to == HALF_OPEN:
            self._probes_left = self.config.probe_batches
            self._probe_breached = False
        logger.warning("serving breaker %s -> %s (%s)", frm, to, why)
        cb = self.on_transition
        if cb is not None:
            cb(frm, to, why)

    def _maybe_half_open_locked(self) -> None:
        if (self._state == OPEN and self._opened_at is not None
                and self.clock() - self._opened_at >= self.config.cooldown_s):
            self._transition_locked(HALF_OPEN, "cooldown elapsed")

    def _breach_locked(self) -> Optional[str]:
        """The breach description for the current window, or None."""
        n = len(self._window)
        if n < self.config.min_samples:
            return None
        failures = sum(1 for _, ok in self._window if not ok)
        rate = failures / n
        if rate > self.config.failure_rate:
            return (f"failure rate {rate:.2f} > "
                    f"{self.config.failure_rate:.2f} over {n} batches")
        p99 = _p99([lat for lat, _ in self._window])
        if p99 > self.config.latency_p99_s:
            return (f"scorer p99 {p99 * 1e3:.1f}ms > "
                    f"{self.config.latency_p99_s * 1e3:.1f}ms over {n} batches")
        return None

    # -- engine-facing API ----------------------------------------------------

    def state(self) -> str:
        with self._lock:
            self._maybe_half_open_locked()
            return self._state

    def admit(self) -> bool:
        """May a new request enter the queue at all? Only OPEN refuses
        (half-open admits: the probes need traffic)."""
        return self.state() != OPEN

    def allow_full(self) -> Tuple[bool, bool]:
        """(full-effort scoring allowed, this batch is a half-open probe).
        SHED forces fixed-effect-only; half-open grants full effort to a
        bounded number of probe batches and sheds the overflow."""
        with self._lock:
            self._maybe_half_open_locked()
            if self._state == SHED:
                return False, False
            if self._state == HALF_OPEN:
                if self._probes_left > 0:
                    self._probes_left -= 1
                    return True, True
                return False, False
            return True, False

    def record(self, latency_s: float, ok: bool,
               probe: bool = False) -> None:
        """One scorer-stage observation (one batch dispatch)."""
        with self._lock:
            self._maybe_half_open_locked()
            self._window.append((float(latency_s), bool(ok)))
            if self._state == HALF_OPEN:
                if probe:
                    breached = (not ok or latency_s
                                > self.config.latency_p99_s)
                    if breached:
                        self._probe_breached = True
                    if self._probe_breached:
                        self._transition_locked(OPEN, "probe breached")
                    elif self._probes_left == 0:
                        self._transition_locked(
                            CLOSED,
                            f"{self.config.probe_batches} healthy probes")
                return
            breach = self._breach_locked()
            if breach is None:
                return
            if self._state == CLOSED:
                self._transition_locked(SHED, breach)
            elif self._state == SHED:
                self._transition_locked(OPEN, breach)

    def snapshot(self) -> dict:
        with self._lock:
            self._maybe_half_open_locked()
            n = len(self._window)
            failures = sum(1 for _, ok in self._window if not ok)
            return {
                "state": self._state,
                "level": STATE_LEVELS[self._state],
                "window_samples": n,
                "window_failure_rate": failures / n if n else 0.0,
                "window_p99_s": _p99([lat for lat, _ in self._window]),
                "transitions": self.transitions,
                "trips": self.trips,
                "thresholds": {
                    # None = disabled (inf is not portable JSON)
                    "latency_p99_s": (None
                                      if math.isinf(self.config.latency_p99_s)
                                      else self.config.latency_p99_s),
                    "failure_rate": self.config.failure_rate,
                    "min_samples": self.config.min_samples,
                    "cooldown_s": self.config.cooldown_s,
                },
            }
