"""AOT-exported serving program bundles: instant cold start.

A serving replica's cold start is compile-dominated: the warmup ladder
traces and XLA-compiles one program per (mode, bucket). The persistent
XLA cache (utils/compile_cache) removes the *compile* on a restart but
still pays the trace + lowering per program. This module removes both:
after warmup, ``export_program_bundle`` lowers each warmed scorer with
``jax.jit(...).lower().compile()`` and serializes the executables
(jax.experimental.serialize_executable) into a crc32-verified bundle
directory next to the model; on the next boot — same host, same model
shapes, same jax — ``load_program_bundle`` deserializes them and seeds
``utils/jitcache`` under the exact shape-generic keys ``get_scorer``
computes, so the warmup ladder performs ZERO traces and ZERO compiles
(all three compile monitors read zero) and the replica reaches
first-score in deserialization time.

Refusal is typed and total: any mismatch (schema, shape signature, jax
version, host fingerprint, Pallas env, crc of any program file) or any
deserialization error refuses the WHOLE bundle — counted under
``serving.program_bundle_refused{reason=...}`` — and the caller falls
back to the ordinary tracing warmup. A corrupt bundle can cost a
re-trace, never a wrong score: executables only enter the process when
every byte checks out, and the shape signature pins them to models
whose programs would have traced identically.

Same manifest discipline as the swap/fleet dirs (serving/swap.py,
io/fleet_store.py): versioned schema string, per-file crc32, atomic
manifest-last write order.
"""

from __future__ import annotations

import json
import logging
import os
import pickle
import tempfile
import zlib
from typing import Optional, Sequence

from photon_tpu.obs.metrics import registry as _metrics
from photon_tpu.serving.model_state import DeviceResidentModel
from photon_tpu.serving.scorer import (build_scorer_fn, get_scorer,
                                       mode_args, program_key,
                                       serving_modes)
from photon_tpu.utils import compile_cache, jitcache

_logger = logging.getLogger("photon_tpu.serving.programs")

BUNDLE_SCHEMA = "photon_tpu.programbundle.v1"
MANIFEST_NAME = "bundle-manifest.json"


def _refuse(reason: str, detail: str = "") -> dict:
    _metrics.counter("serving.program_bundle_refused", reason=reason).inc()
    _logger.warning("program bundle refused (%s): %s — falling back to "
                    "tracing warmup", reason, detail)
    return {"loaded": 0, "refused": reason, "detail": detail}


def _jax_fingerprint() -> dict:
    """Everything an executable is pinned to besides model shapes: jax
    version, backend, device count, and the host CPU-feature fingerprint
    (XLA loads foreign-host executables with only a SIGILL warning —
    same reason the persistent cache dir is host-keyed)."""
    import jax

    return {
        "jax": jax.__version__,
        "backend": jax.default_backend(),
        "device_count": jax.device_count(),
        "host": compile_cache._host_fingerprint(),
        "pallas_serving": os.environ.get("PHOTON_TPU_PALLAS_SERVING") == "1",
    }


def _signature_token(model: DeviceResidentModel) -> str:
    return repr(model.shape_signature())


def _prog_name(mode: str, bucket: int) -> str:
    return f"prog-{mode}-b{int(bucket)}.bin"


def bundle_dir_for(base_dir: str, model: DeviceResidentModel) -> str:
    """One bundle subdirectory per distinct shape signature — same-shape
    tenants naturally share one exported ladder, different shapes get
    their own without colliding."""
    tok = _signature_token(model)
    return os.path.join(base_dir, f"sig-{zlib.crc32(tok.encode()):08x}")


def _unwrap(fn):
    """Reach the jit function under the telemetry first-call timer. A
    jit fn itself carries ``__wrapped__`` (the plain python fn), so test
    for the AOT API instead of unwrapping unconditionally."""
    if hasattr(fn, "lower"):
        return fn
    return getattr(fn, "__wrapped__", fn)


def export_program_bundle(model: DeviceResidentModel,
                          buckets: Sequence[int],
                          bundle_dir: str) -> dict:
    """AOT-compile and serialize the full warmed (mode × bucket) ladder
    into ``bundle_dir``. Call after ``warmup_scorers`` (the jit programs
    must exist; with the persistent XLA cache on, the AOT re-compile
    below is a disk hit, not a second XLA compile). Never raises: a
    program that refuses to serialize (e.g. the Pallas arm) skips the
    export and reports itself in the returned dict."""
    from jax.experimental.serialize_executable import serialize

    os.makedirs(bundle_dir, exist_ok=True)
    programs = {}
    skipped = []
    for bucket in buckets:
        args = model.dummy_args(bucket)
        for mode in serving_modes(model):
            fn = _unwrap(get_scorer(model, mode, bucket))
            if not hasattr(fn, "lower"):
                # the cache slot holds a bundle-seeded Compiled, which
                # can be executed but not re-lowered or re-serialized
                # (XLA drops the symbol table) — trace a fresh jit for
                # the export; serving keeps using the seeded executable
                fn = build_scorer_fn(model, mode, bucket)
            name = _prog_name(mode, bucket)
            try:
                compiled = fn.lower(
                    *mode_args(model, mode, args)).compile()
                payload, in_tree, out_tree = serialize(compiled)
                blob = pickle.dumps((payload, in_tree, out_tree),
                                    protocol=pickle.HIGHEST_PROTOCOL)
            except Exception as e:  # noqa: BLE001 — export is an optimization
                skipped.append({"mode": mode, "bucket": int(bucket),
                                "error": repr(e)})
                _logger.warning("program bundle: skipping (%s, b%d): %r",
                                mode, bucket, e)
                continue
            with open(os.path.join(bundle_dir, name), "wb") as f:
                f.write(blob)
            programs[name] = {"mode": mode, "bucket": int(bucket),
                              "crc32": zlib.crc32(blob),
                              "bytes": len(blob)}
    manifest = {
        "schema": BUNDLE_SCHEMA,
        "signature": _signature_token(model),
        "env": _jax_fingerprint(),
        "buckets": [int(b) for b in buckets],
        "modes": list(serving_modes(model)),
        "programs": programs,
    }
    # manifest written last, atomically: a crash mid-export leaves a
    # manifest-less (hence refused) directory, never a half-trusted one
    fd, tmp = tempfile.mkstemp(dir=bundle_dir, suffix=".tmp")
    with os.fdopen(fd, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
    os.replace(tmp, os.path.join(bundle_dir, MANIFEST_NAME))
    _metrics.gauge("serving.program_bundle_programs").set(len(programs))
    _logger.info("program bundle: exported %d programs (%d skipped) to %s",
                 len(programs), len(skipped), bundle_dir)
    return {"exported": len(programs), "skipped": skipped,
            "dir": bundle_dir}


def load_program_bundle(model: DeviceResidentModel,
                        buckets: Sequence[int],
                        bundle_dir: str) -> dict:
    """Verify and load a program bundle, seeding ``utils/jitcache`` so
    the subsequent warmup ladder dispatches without tracing. All-or-
    nothing: every expected (mode, bucket) must be present, byte-exact,
    and deserializable, or the whole bundle is refused and the caller
    warms by tracing."""
    manifest_path = os.path.join(bundle_dir, MANIFEST_NAME)
    if not os.path.exists(manifest_path):
        return _refuse("missing_manifest", bundle_dir)
    try:
        with open(manifest_path) as f:
            manifest = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return _refuse("unreadable_manifest", repr(e))
    if manifest.get("schema") != BUNDLE_SCHEMA:
        return _refuse("schema_mismatch", str(manifest.get("schema")))
    if manifest.get("signature") != _signature_token(model):
        return _refuse("signature_mismatch",
                       "model shapes differ from exported bundle")
    if manifest.get("env") != _jax_fingerprint():
        return _refuse("env_mismatch",
                       f"bundle env {manifest.get('env')}")
    if list(manifest.get("buckets", [])) != [int(b) for b in buckets]:
        return _refuse("bucket_mismatch", str(manifest.get("buckets")))
    if list(manifest.get("modes", [])) != list(serving_modes(model)):
        return _refuse("mode_mismatch", str(manifest.get("modes")))

    from jax.experimental.serialize_executable import deserialize_and_load

    # pass 1: verify every byte before ANY executable enters the process
    blobs = {}
    for bucket in buckets:
        for mode in serving_modes(model):
            name = _prog_name(mode, bucket)
            meta = manifest["programs"].get(name)
            if meta is None:
                return _refuse("missing_program", name)
            try:
                with open(os.path.join(bundle_dir, name), "rb") as f:
                    blob = f.read()
            except OSError as e:
                return _refuse("unreadable_program", f"{name}: {e!r}")
            if len(blob) != meta["bytes"] or \
                    zlib.crc32(blob) != meta["crc32"]:
                return _refuse("crc_mismatch", name)
            blobs[name] = blob

    # pass 2: deserialize + seed; any failure still refuses the bundle
    # (seeded keys from earlier iterations are evicted — all-or-nothing)
    seeded = []
    for bucket in buckets:
        for mode in serving_modes(model):
            name = _prog_name(mode, bucket)
            try:
                payload, in_tree, out_tree = pickle.loads(blobs[name])
                loaded = deserialize_and_load(payload, in_tree, out_tree)
            except Exception as e:  # noqa: BLE001 — refusal, not a crash
                _evict(seeded)
                return _refuse("deserialize_error", f"{name}: {e!r}")
            key = program_key(model, mode, bucket)
            if jitcache.seed(key, loaded):
                seeded.append(key)
    _metrics.gauge("serving.program_bundle_programs").set(len(seeded))
    _logger.info("program bundle: seeded %d programs from %s",
                 len(seeded), bundle_dir)
    return {"loaded": len(seeded), "refused": None, "dir": bundle_dir}


def _evict(keys) -> None:
    with jitcache._LOCK:
        for k in keys:
            jitcache._CACHE.pop(k, None)
        _metrics.gauge("jitcache.size").set(len(jitcache._CACHE))
