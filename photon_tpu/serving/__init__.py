"""Online serving: device-resident GAME models behind a micro-batcher.

The offline half of the repo trains and scores frames; this package
serves single requests at low latency. Design contract (ISSUE 5):

  * the model is staged onto the accelerator exactly once
    (:class:`DeviceResidentModel`), requests only ship [B, k] arrays;
  * batch shapes come from a fixed power-of-two ladder
    (:class:`BucketLadder` / :class:`MicroBatcher`), so the compile set
    is finite and fully warmed at model load — zero steady-state
    compiles (checked by ``scripts/check_serving_no_recompile.py``);
  * overload degrades through a typed ladder (full -> fixed-effect-only
    -> rejection), never an exception on the hot path.

The resilience layer (ISSUE 6) extends the contract under fault and
change:

  * every request can carry a deadline, enforced at admission and at the
    queue->score boundary (typed DEADLINE_EXCEEDED, never a late score);
  * a sliding-window :class:`CircuitBreaker` sheds to fixed-effect-only
    and then rejects when the scorer stage goes slow or faulty, with
    half-open probing to recover;
  * SIGTERM drains gracefully: typed SHUTTING_DOWN refusals at
    admission, in-flight micro-batches flushed within a drain budget;
  * live model swap (serving/swap.py) validates a candidate behind a
    gate ladder (crc manifest, finiteness, shadow parity, zero
    steady-state compiles) and publishes atomically between
    micro-batches, with automatic rollback on a post-swap breaker trip.

Multi-tenant serving (ISSUE 13) makes the compiled programs a shared
resource: scorer executables are keyed by the model's SHAPE signature
(parameters are arguments), so a :class:`MultiTenantEngine` hosts N
same-shape tenants behind one compiled ladder with per-tenant admission
budgets, breakers, and canary/A-B splitting — and serving/programs.py
AOT-exports the warmed ladder to a crc32-verified bundle a restarted
replica loads for a zero-trace, zero-compile cold start.
"""

from photon_tpu.serving.batching import (
    BucketLadder,
    MicroBatcher,
    QueueClosedError,
)
from photon_tpu.serving.autoscale import (
    AutoscaleConfig,
    HotShardAutoscaler,
    decommission_shard,
    provision_shard,
)
from photon_tpu.serving.breaker import CircuitBreaker
from photon_tpu.serving.coeff_store import TwoTierCoeffStore
from photon_tpu.serving.engine import LATENCY_BUCKETS, ServingEngine
from photon_tpu.serving.fleet import (
    DoubleReadWindow,
    FleetConfig,
    LocalShardClient,
    ShardedServingFleet,
)
from photon_tpu.serving.migrate import (
    BucketMigrator,
    MigrationError,
    read_migration_journal,
    resume_migration,
)
from photon_tpu.serving.model_state import DeviceResidentModel
from photon_tpu.serving.programs import (
    export_program_bundle,
    load_program_bundle,
)
from photon_tpu.serving.replay import (
    CaptureRecord,
    CaptureWriter,
    Replayer,
    ReplayResult,
    TrafficProfile,
    VirtualClock,
    generate,
    read_capture,
    record_capture,
    stream_digest,
    timeline_digest,
)
from photon_tpu.serving.scorer import MODES, get_scorer, warmup_scorers
from photon_tpu.serving.tenants import MultiTenantEngine
from photon_tpu.serving.swap import (
    SwapResult,
    swap_from_dir,
    swap_staged,
    verify_swap_manifest,
    write_swap_manifest,
)
from photon_tpu.serving.types import (
    BreakerConfig,
    CoeffStoreConfig,
    DeadlineConfig,
    Fallback,
    FallbackReason,
    ScoreRequest,
    ScoreResponse,
    ServingConfig,
    SLOConfig,
    SwapConfig,
)

__all__ = [
    "AutoscaleConfig",
    "BreakerConfig",
    "BucketLadder",
    "BucketMigrator",
    "CaptureRecord",
    "CaptureWriter",
    "CoeffStoreConfig",
    "CircuitBreaker",
    "DeadlineConfig",
    "DeviceResidentModel",
    "DoubleReadWindow",
    "Fallback",
    "FallbackReason",
    "FleetConfig",
    "HotShardAutoscaler",
    "LocalShardClient",
    "MigrationError",
    "ShardedServingFleet",
    "LATENCY_BUCKETS",
    "MODES",
    "MicroBatcher",
    "MultiTenantEngine",
    "QueueClosedError",
    "Replayer",
    "ReplayResult",
    "ScoreRequest",
    "ScoreResponse",
    "ServingConfig",
    "ServingEngine",
    "SLOConfig",
    "SwapConfig",
    "SwapResult",
    "TrafficProfile",
    "TwoTierCoeffStore",
    "VirtualClock",
    "decommission_shard",
    "export_program_bundle",
    "generate",
    "get_scorer",
    "load_program_bundle",
    "provision_shard",
    "read_capture",
    "read_migration_journal",
    "record_capture",
    "resume_migration",
    "serving_report_section",
    "stream_digest",
    "swap_from_dir",
    "swap_staged",
    "timeline_digest",
    "verify_swap_manifest",
    "warmup_scorers",
    "write_swap_manifest",
]

# the engine the RunReport describes; a process normally runs one engine,
# and obs/report.py picks this up without importing serving eagerly
_active_engine = None


def set_active_engine(engine) -> None:
    global _active_engine
    _active_engine = engine


def serving_report_section():
    """``stats()`` of the registered engine, or None when this process
    never served (keeps offline RunReports unchanged)."""
    return _active_engine.stats() if _active_engine is not None else None
