"""Online serving: device-resident GAME models behind a micro-batcher.

The offline half of the repo trains and scores frames; this package
serves single requests at low latency. Design contract (ISSUE 5):

  * the model is staged onto the accelerator exactly once
    (:class:`DeviceResidentModel`), requests only ship [B, k] arrays;
  * batch shapes come from a fixed power-of-two ladder
    (:class:`BucketLadder` / :class:`MicroBatcher`), so the compile set
    is finite and fully warmed at model load — zero steady-state
    compiles (checked by ``scripts/check_serving_no_recompile.py``);
  * overload degrades through a typed ladder (full -> fixed-effect-only
    -> rejection), never an exception on the hot path.
"""

from photon_tpu.serving.batching import BucketLadder, MicroBatcher
from photon_tpu.serving.engine import LATENCY_BUCKETS, ServingEngine
from photon_tpu.serving.model_state import DeviceResidentModel
from photon_tpu.serving.scorer import MODES, get_scorer, warmup_scorers
from photon_tpu.serving.types import (
    Fallback,
    FallbackReason,
    ScoreRequest,
    ScoreResponse,
    ServingConfig,
    SLOConfig,
)

__all__ = [
    "BucketLadder",
    "DeviceResidentModel",
    "Fallback",
    "FallbackReason",
    "LATENCY_BUCKETS",
    "MODES",
    "MicroBatcher",
    "ScoreRequest",
    "ScoreResponse",
    "ServingConfig",
    "ServingEngine",
    "SLOConfig",
    "get_scorer",
    "serving_report_section",
    "warmup_scorers",
]

# the engine the RunReport describes; a process normally runs one engine,
# and obs/report.py picks this up without importing serving eagerly
_active_engine = None


def set_active_engine(engine) -> None:
    global _active_engine
    _active_engine = engine


def serving_report_section():
    """``stats()`` of the registered engine, or None when this process
    never served (keeps offline RunReports unchanged)."""
    return _active_engine.stats() if _active_engine is not None else None
