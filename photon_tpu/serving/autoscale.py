"""Gauge-driven hot-shard autoscaling for the elastic serving fleet.

A ``HotShardAutoscaler`` closes the feedback loop PR 18 left open: it
polls the per-shard windowed response counters the router already
stamps (``fleet.shard.responses{shard=N}`` in `obs/timeseries`),
decides whether the fleet's load is skewed enough to act, and drives
the `serving/migrate.BucketMigrator` machinery:

* **split** — the hottest shard's share exceeds ``hot_factor`` × the
  mean: provision a fresh shard (empty per-coordinate cold stores, a
  manifest bump adding the shard entry, a warmed engine — warmed via
  jit-cache HITS, the scorer programs are shape-keyed so a same-shape
  shard engine compiles nothing new), then migrate the hot shard's
  top-load buckets onto it.
* **drain** — the coldest shard's share falls below ``cold_factor`` ×
  the mean: migrate its buckets to the least-loaded survivor, then
  decommission the shard (router removal + manifest bump).

Execution is two-phase on purpose: ``step()`` starts the work (shard
provisioning, bucket copy, double-read window open) and ``finish()``
completes it (reconcile, bitwise-parity cutover, decommission) — the
window in between is where live traffic flows through the double-read
comparison, which is the whole point. A deterministic replay
(`bench.py --mode elastic`) schedules ``step``/``finish`` as virtual-
clock actions mid-flash-crowd.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional

import numpy as np

from photon_tpu.io.cold_store import write_cold_store
from photon_tpu.io.fleet_store import (
    FLEET_MANIFEST_SCHEMA_V2,
    read_fleet_manifest,
    shard_dir,
    shard_store_path,
    write_fleet_manifest,
)
from photon_tpu.obs import timeseries as _tsmod
from photon_tpu.serving.fleet import LocalShardClient, build_shard_engine
from photon_tpu.serving.migrate import BucketMigrator, MigrationError

__all__ = [
    "AutoscaleConfig",
    "HotShardAutoscaler",
    "decommission_shard",
    "provision_shard",
]


@dataclasses.dataclass(frozen=True)
class AutoscaleConfig:
    """Controller thresholds. Shares are sums of each shard's last
    ``lookback_windows`` response-counter windows."""

    #: split when the hottest shard's share > hot_factor * mean share
    hot_factor: float = 1.75
    #: drain when the coldest shard's share < cold_factor * mean share
    cold_factor: float = 0.25
    min_shards: int = 1
    max_shards: int = 8
    #: buckets migrated off the hot shard per split step
    buckets_per_step: int = 1
    #: response-counter windows summed per shard
    lookback_windows: int = 3
    #: below this fleet-wide total the controller holds (no signal)
    min_total: float = 1.0

    def __post_init__(self):
        if self.hot_factor <= 1.0:
            raise ValueError("hot_factor must be > 1")
        if not (0.0 <= self.cold_factor < 1.0):
            raise ValueError("cold_factor must be in [0, 1)")
        if self.min_shards < 1 or self.max_shards < self.min_shards:
            raise ValueError("need 1 <= min_shards <= max_shards")
        if self.buckets_per_step < 1:
            raise ValueError("buckets_per_step must be >= 1")


def provision_shard(fleet, shard_id: int, serving=None) -> dict:
    """Grow the fleet by one EMPTY shard: zero-row updatable cold
    stores for every routed coordinate, a manifest bump adding the
    shard entry (durable first — a kill after the bump leaves an idle
    shard, harmless), then a warmed engine registered with the router.
    Returns the new manifest document."""
    fleet_dir = fleet.fleet_dir
    if fleet_dir is None:
        raise MigrationError("fleet has no fleet_dir; cannot provision")
    doc = read_fleet_manifest(fleet_dir)
    if doc["schema"] != FLEET_MANIFEST_SCHEMA_V2:
        raise MigrationError(
            "provisioning needs the v2 virtual-bucket layout; this "
            f"fleet dir carries {doc['schema']!r} (rebuild with "
            "build_fleet_dir(num_buckets=...))")
    sid = int(shard_id)
    if any(sh["shard_id"] == sid for sh in doc["shards"]):
        raise MigrationError(f"shard {sid} already in manifest")
    os.makedirs(shard_dir(fleet_dir, sid), exist_ok=True)
    stores: Dict[str, dict] = {}
    for cid, meta in doc["coordinates"].items():
        k = int(meta["slot_width"])
        out = shard_store_path(fleet_dir, sid, cid)
        write_cold_store(out, cid, meta["random_effect_type"],
                         meta["feature_shard_id"],
                         np.zeros((0, k), np.float32),
                         np.zeros((0, k), np.int32), [],
                         updatable=True)
        stores[cid] = {"path": os.path.relpath(out, fleet_dir),
                       "entities": 0,
                       "bytes_at_split": int(os.path.getsize(out))}
    doc["shards"] = sorted(
        doc["shards"] + [{"shard_id": sid, "stores": stores}],
        key=lambda sh: sh["shard_id"])
    doc["num_shards"] = len(doc["shards"])
    doc["version"] = int(doc["version"]) + 1
    write_fleet_manifest(fleet_dir, doc)
    engine = build_shard_engine(
        fleet_dir, sid, serving or fleet.config.serving, manifest=doc,
        model_dir=getattr(fleet, "_model_dir", None), clock=fleet.clock)
    client = LocalShardClient(sid, engine)
    client.warmup()    # shape-keyed jit-cache hits: zero new compiles
    fleet.add_shard(client)
    fleet.manifest = doc
    return doc


def decommission_shard(fleet, shard_id: int) -> dict:
    """Shrink the fleet by one (already-drained) shard: router removal
    first (refuses typed while the shard still owns buckets), then the
    manifest bump dropping the entry."""
    fleet_dir = fleet.fleet_dir
    if fleet_dir is None:
        raise MigrationError("fleet has no fleet_dir; cannot decommission")
    sid = int(shard_id)
    fleet.remove_shard(sid)
    doc = read_fleet_manifest(fleet_dir)
    doc["shards"] = [sh for sh in doc["shards"]
                     if sh["shard_id"] != sid]
    if not doc["shards"]:
        raise MigrationError("refusing to decommission the last shard")
    doc["num_shards"] = len(doc["shards"])
    doc["version"] = int(doc["version"]) + 1
    write_fleet_manifest(fleet_dir, doc)
    fleet.manifest = doc
    return doc


class HotShardAutoscaler:
    """Two-phase feedback controller over the per-shard windowed
    gauges. ``step()`` makes one decision and starts it; ``finish()``
    completes the migrations it opened. At most one plan is in flight
    at a time (the controller never races its own cutovers)."""

    def __init__(self, fleet, config: Optional[AutoscaleConfig] = None,
                 registry=None, serving=None):
        self.fleet = fleet
        self.config = config or AutoscaleConfig()
        self.registry = registry or _tsmod.series
        self.serving = serving
        self._plan: Optional[dict] = None

    # --------------------------------------------------------- observe

    def shard_shares(self) -> Dict[int, float]:
        """Per-shard response counts summed over the last
        ``lookback_windows`` windows of
        ``fleet.shard.responses{shard=N}``."""
        snap = self.registry.snapshot()
        shares = {c.shard_id: 0.0 for c in self.fleet.clients}
        for key, s in snap.get("timeseries", {}).items():
            if not key.startswith("fleet.shard.responses{"):
                continue
            sh = s.get("labels", {}).get("shard")
            try:
                sid = int(sh)
            except (TypeError, ValueError):
                continue
            if sid not in shares:
                continue
            wins = s.get("windows", [])[-self.config.lookback_windows:]
            shares[sid] = float(sum(w["value"] for w in wins))
        return shares

    # ---------------------------------------------------------- decide

    def decide(self) -> Optional[dict]:
        """One control decision off the current gauges, or None (hold).
        Pure read — ``step`` executes it."""
        cfg = self.config
        fleet = self.fleet
        shares = self.shard_shares()
        if not shares:
            return None
        total = sum(shares.values())
        if total < cfg.min_total:
            return None
        mean = total / len(shares)
        hot = max(shares, key=lambda s: (shares[s], -s))
        cold = min(shares, key=lambda s: (shares[s], s))
        if (shares[hot] > cfg.hot_factor * mean
                and fleet.num_shards < cfg.max_shards
                and len(fleet.bucket_map.buckets_on(hot)) > 1):
            return {"action": "split", "shard": hot,
                    "share": shares[hot], "mean": mean}
        if (fleet.num_shards > cfg.min_shards
                and shares[cold] < cfg.cold_factor * mean):
            return {"action": "drain", "shard": cold,
                    "share": shares[cold], "mean": mean}
        return None

    # --------------------------------------------------------- execute

    def step(self, decision: Optional[dict] = None) -> Optional[dict]:
        """Execute the start half of one decision: provision/choose the
        destination, copy the chosen buckets, open their double-read
        windows. Returns the in-flight plan (None = held)."""
        if self._plan is not None:
            raise MigrationError(
                "previous autoscale step not finished; call finish()")
        decision = decision or self.decide()
        if decision is None:
            return None
        if decision["action"] == "split":
            plan = self._start_split(int(decision["shard"]))
        else:
            plan = self._start_drain(int(decision["shard"]))
        plan.update(share=decision.get("share"),
                    mean=decision.get("mean"))
        self._plan = plan
        return plan

    def _start_split(self, hot: int) -> dict:
        fleet = self.fleet
        new_id = max(c.shard_id for c in fleet.clients) + 1
        provision_shard(fleet, new_id, serving=self.serving)
        loads = dict(fleet.bucket_loads())
        owned = fleet.bucket_map.buckets_on(hot)
        # hottest buckets first; never take the LAST bucket off a shard
        ranked = sorted(owned, key=lambda b: (-loads.get(b, 0), b))
        take = ranked[:min(self.config.buckets_per_step,
                           len(ranked) - 1)]
        migrators: List[BucketMigrator] = []
        for b in take:
            m = BucketMigrator(fleet, b, new_id)
            m.copy()
            m.open_double_read()
            migrators.append(m)
        return {"action": "split", "shard": hot, "new_shard": new_id,
                "buckets": list(take), "migrators": migrators}

    def _start_drain(self, cold: int) -> dict:
        fleet = self.fleet
        shares = self.shard_shares()
        dst = min((s for s in shares if s != cold),
                  key=lambda s: (shares[s], s))
        owned = fleet.bucket_map.buckets_on(cold)
        migrators: List[BucketMigrator] = []
        for b in owned:
            m = BucketMigrator(fleet, b, dst)
            m.copy()
            m.open_double_read()
            migrators.append(m)
        return {"action": "drain", "shard": cold, "dst": dst,
                "buckets": list(owned), "migrators": migrators}

    def finish(self) -> Optional[dict]:
        """Complete the in-flight plan: reconcile + bitwise-parity
        cutover for every opened migration, then decommission on a
        drain. Returns the completed plan (None = nothing in flight).
        A poisoned double-read window raises typed and leaves the old
        map serving (callers abort the plan's migrators)."""
        plan, self._plan = self._plan, None
        if plan is None:
            return None
        results = []
        for m in plan["migrators"]:
            m.reconcile()
            results.append(m.cutover())
        if plan["action"] == "drain":
            decommission_shard(self.fleet, plan["shard"])
        plan["results"] = results
        return plan

    def abort(self) -> None:
        """Abort the in-flight plan: roll back every opened migration
        (bitwise restore) and close windows. The provisioned shard, if
        any, stays registered but idle."""
        plan, self._plan = self._plan, None
        if plan is None:
            return
        for m in plan["migrators"]:
            m.abort("autoscale abort")
