"""Device-resident GAME model state + host-side batch assembly.

The load-once / serve-forever half of the serving engine: coefficient
arrays go to the accelerator exactly once at model load — the fixed-
effect vectors replicated, the per-entity random-effect blocks laid out
as gather tables (optionally sharded over the mesh's entity axis) — and
every request batch only ships its own [B, k] feature arrays. This is
the Snap ML resident-state discipline applied to GLMix: per-request work
is a gather + dot, never a model re-stage.

Host side, the model keeps the lookup tables that turn a request into
device arrays: per-shard feature IndexMaps (request (name, term) ->
column), per-coordinate entity vocabularies (REId string -> block row),
and the (entity, feature) -> local-slot tables that replay
``game/random_effect.project_for_scoring``'s projection per batch — the
same math as offline scoring, so serving scores are bitwise-comparable.

Two-tier placement: a random-effect coordinate loaded with a cold-store
file (io/cold_store.py) and a ``CoeffStoreConfig`` does NOT stage its
full table; it serves through a ``serving/coeff_store.TwoTierCoeffStore``
— a fixed-budget HBM hot set over the host-RAM cold tier, with the
entity->hot-slot map playing the role the full-resident path's
entity_rows dict plays. Assembly then resolves entities against the hot
map (HIT -> hot slot, COLD -> typed ``COLD_MISS`` + queued promotion,
UNKNOWN -> zero row), and the scorer receives the hot table as an
argument under ``transfer_lock`` so concurrent cold->hot transfers can
never tear a batch.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from photon_tpu.io.model_io import ServingGameModel
from photon_tpu.serving.types import (CoeffStoreConfig, Fallback,
                                      FallbackReason, ScoreRequest)

_model_counter = itertools.count()


@dataclasses.dataclass
class _FixedState:
    coordinate_id: str
    feature_shard_id: str
    theta: object                     # device [D_pad] (replicated on a mesh)
    # thompson arm: posterior variances aligned with theta ([D_pad],
    # zeros where the model carried none). None unless the model was
    # built with thompson=True and carries variances somewhere.
    var_theta: Optional[object] = None


@dataclasses.dataclass
class _RandomState:
    coordinate_id: str
    random_effect_type: str
    feature_shard_id: str
    coef: object                      # device [E_pad, K] gather table
    num_entities: int                 # E (pre-padding)
    unknown_row: int                  # index scoring as all-zeros
    slot_width: int                   # K
    entity_rows: Dict[str, int]       # REId -> row
    # (entity * D + global_col) -> local slot, as sorted parallel arrays
    # (the project_for_scoring lookup, built once at load)
    pkeys_sorted: np.ndarray          # [P] int64
    pslots_sorted: np.ndarray         # [P] int64
    # two-tier mode: the hot-set gather cache; coef/entity_rows/pkeys
    # are unused and the gather table is read via store.table instead
    store: Optional[object] = None    # TwoTierCoeffStore
    # full-resident nearline appends: reserve rows AFTER the zero row
    # (rows unknown_row+1 .. unknown_row+append_reserve). Appending an
    # entity takes the next reserve row, so existing rows, the zero row,
    # and the table shape (a compiled-program shape!) never change.
    append_reserve: int = 0
    append_used: int = 0
    # int8 serving arm (full-resident coordinates only): row-quantized
    # mirror of ``coef`` plus the per-row dequantization scales, staged
    # at load/publish time. None everywhere unless the model was built
    # with int8=True; two-tier coordinates never quantize.
    coef_q: Optional[object] = None      # device [E_pad, K] int8
    scales: Optional[object] = None      # device [E_pad, 1] float32
    # thompson arm: posterior-variance gather table mirroring ``coef``
    # row for row — real entities carry their Laplace variances (zeros
    # when the model has none for this coordinate), the unknown row
    # carries ``prior_variance`` (cold-start exploration; its MEAN row
    # stays zero), and the append reserve rows are zero until a nearline
    # publish hands them a variance row (appended-without-variance
    # entities serve the mean). None unless thompson staging is on.
    var_coef: Optional[object] = None    # device [E_pad, K] float32


class AssembledBatch(Tuple):
    pass


def quantize_rows(rows: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Symmetric per-row int8 quantization: ``q = round(row / scale)``
    with ``scale = max|row| / 127`` (all-zero rows get scale 1.0 so the
    dequantized row is exactly zero). Deterministic and row-local, so a
    row-level nearline publish can requantize only the touched rows and
    stay bitwise-consistent with a from-scratch staging."""
    rows = np.asarray(rows, np.float32)
    amax = np.abs(rows).max(axis=-1, keepdims=True)
    scales = np.where(amax > 0.0, amax / 127.0, np.float32(1.0))
    q = np.clip(np.rint(rows / scales), -127, 127).astype(np.int8)
    return q, scales.astype(np.float32)


def _pad_width(dim: int, requested: Optional[int]) -> int:
    if requested is not None:
        return max(int(requested), 1)
    p = 1
    while p < dim and p < 256:
        p *= 2
    return p


class DeviceResidentModel:
    """A ServingGameModel staged onto the accelerator, plus assembly."""

    def __init__(self, model: ServingGameModel, mesh=None,
                 feature_pad: Optional[int] = None, dtype=None,
                 coeff_store: Optional[CoeffStoreConfig] = None,
                 append_reserve: int = 0, int8: bool = False,
                 thompson: bool = False, prior_variance: float = 1.0):
        import jax
        import jax.numpy as jnp

        self.task = model.task
        self.index_maps = model.index_maps
        self.dtype = dtype or jnp.float32
        self.token = f"servmodel-{next(_model_counter)}"
        self.mesh = mesh
        self._shape_sig: Optional[tuple] = None
        #: int8 serving arm requested: full-resident coordinates carry a
        #: (coef_q, scales) mirror and "full_int8" programs are warmed
        self.int8_enabled = bool(int8)
        #: thompson arm: active only when it was REQUESTED and the model
        #: actually carries posterior variances somewhere — a var-less
        #: model under the flag stages nothing extra, keeps its pre-
        #: thompson shape signature, and serves the mean bitwise as
        #: before. When active, every coordinate gets a variance mirror
        #: (zeros where a coordinate has none) and "thompson" programs
        #: are warmed.
        self.prior_variance = float(prior_variance)
        has_var = (any(getattr(fe, "variances", None) is not None
                       for fe in model.fixed)
                   or any(getattr(re, "has_variances", False)
                          for re in model.random))
        self.thompson_enabled = bool(thompson) and has_var
        if self.thompson_enabled and coeff_store is not None and any(
                getattr(re, "cold_store_path", None) is not None
                for re in model.random):
            # the variance mirror must be a full-resident program
            # argument — a hot-set slice of it would explore with
            # whichever rows happen to be hot. Typed refusal, at load,
            # never a silent mean fallback.
            raise ValueError(
                "thompson serving requires full-resident random-effect "
                "tables; this model serves through a two-tier coeff_store "
                "— drop the CoeffStoreConfig or disable thompson_serving")
        # serializes batch assembly + scorer dispatch against the
        # two-tier stores' cold->hot transfer commits; recursive so the
        # engine can nest assemble inside its own hold. A model with no
        # stores pays one uncontended acquire per batch.
        self.transfer_lock = threading.RLock()
        self.coeff_store_config = coeff_store

        put_rep, put_ent = self._placers(mesh)

        # one request-feature column space per shard, shared by every
        # coordinate on that shard
        self.shard_order: Tuple[str, ...] = tuple(sorted(model.index_maps))
        self.shard_dims = {sid: m.feature_dimension
                           for sid, m in model.index_maps.items()}
        self.shard_pad = {sid: _pad_width(self.shard_dims[sid], feature_pad)
                          for sid in self.shard_order}

        self.fixed: List[_FixedState] = []
        for fe in model.fixed:
            theta = np.asarray(fe.coefficients, np.dtype(self.dtype.dtype.name
                               if hasattr(self.dtype, "dtype") else self.dtype))
            # gather indices are always < shard dim; pad the vector up so
            # a shard whose map grew (external index maps) still gathers
            dim = max(self.shard_dims.get(fe.feature_shard_id, 0), len(theta), 1)
            if len(theta) < dim:
                theta = np.concatenate([theta, np.zeros(dim - len(theta),
                                                        theta.dtype)])
            var_theta = None
            if self.thompson_enabled:
                v = getattr(fe, "variances", None)
                var = (np.zeros(dim, theta.dtype) if v is None
                       else np.asarray(v, theta.dtype))
                if len(var) < dim:
                    var = np.concatenate(
                        [var, np.zeros(dim - len(var), var.dtype)])
                var_theta = put_rep(var[:dim])
            self.fixed.append(_FixedState(
                fe.coordinate_id, fe.feature_shard_id, put_rep(theta),
                var_theta=var_theta))

        self.random: List[_RandomState] = []
        for re in model.random:
            cold_path = getattr(re, "cold_store_path", None)
            if coeff_store is not None and cold_path is not None:
                # two-tier: the table never fully materializes — a
                # fixed-budget hot set fronts the mmapped cold tier
                from photon_tpu.io.cold_store import ColdStore
                from photon_tpu.serving.coeff_store import TwoTierCoeffStore

                store = TwoTierCoeffStore(
                    ColdStore(cold_path), coeff_store,
                    lock=self.transfer_lock)
                self.random.append(_RandomState(
                    re.coordinate_id, re.random_effect_type,
                    re.feature_shard_id, None, store.cold.num_entities,
                    store.unknown_row, store.slot_width, {},
                    np.empty(0, np.int64), np.empty(0, np.int64),
                    store=store))
                continue
            coef = np.asarray(re.coefficients)
            E, K = coef.shape
            D = max(self.shard_dims.get(re.feature_shard_id, 1), 1)
            proj = np.asarray(re.projection)
            valid = proj >= 0
            pe, ps = np.nonzero(valid)
            pkeys = pe.astype(np.int64) * D + proj[pe, ps].astype(np.int64)
            order = np.argsort(pkeys, kind="stable")
            # one explicit zero row after the real entities: unknown
            # entities gather it and contribute exactly nothing. The
            # optional append reserve follows it — zero rows the nearline
            # publisher can hand to new entities without a table reshape.
            reserve = max(int(append_reserve), 0)
            coef = np.concatenate(
                [coef, np.zeros((1 + reserve, K), coef.dtype)])
            coef_q = scales = None
            if self.int8_enabled:
                q, s = quantize_rows(coef)
                coef_q, scales = put_ent(q), put_ent(s)
            var_coef = None
            if self.thompson_enabled:
                vtab = np.zeros((E + 1 + reserve, K), np.float32)
                rv = getattr(re, "variances", None)
                if rv is not None:
                    rv = np.asarray(rv, np.float32)
                    vtab[:E] = rv[:E]
                # the unknown row's MEAN stays zero but its VARIANCE is
                # the prior: cold-start entities explore instead of
                # silently scoring the mean. Reserve rows stay zero —
                # appended entities explore only once a publish hands
                # them a variance row.
                vtab[E] = self.prior_variance
                var_coef = put_ent(vtab)
            self.random.append(_RandomState(
                re.coordinate_id, re.random_effect_type, re.feature_shard_id,
                put_ent(coef.astype(np.float32) if self.dtype == jnp.float32
                        else coef),
                E, E, K, dict(re.entity_rows),
                pkeys[order], ps[order].astype(np.int64),
                append_reserve=reserve, coef_q=coef_q, scales=scales,
                var_coef=var_coef))

    # -- two-tier store plumbing --------------------------------------------

    @property
    def has_stores(self) -> bool:
        return any(rs.store is not None for rs in self.random)

    def current_tables(self) -> tuple:
        """The random-effect gather tables the scorer takes as arguments
        — the live hot table for two-tier coordinates, the static full
        table otherwise. Two-tier reads must happen under
        ``transfer_lock``, in the same hold as the assemble and the
        scorer dispatch that consume them (the donated transfer scatter
        invalidates superseded table objects)."""
        return tuple(rs.store.table if rs.store is not None else rs.coef
                     for rs in self.random)

    def current_tables_int8(self) -> tuple:
        """Gather tables for the "full_int8" programs: full-resident
        coordinates pass their ``(coef_q, scales)`` pair, two-tier
        coordinates pass the live f32 hot table (mixed-precision by
        design — the cold tier is the capacity story there). Same
        transfer_lock contract as ``current_tables``."""
        return tuple(rs.store.table if rs.store is not None
                     else (rs.coef_q, rs.scales)
                     for rs in self.random)

    def current_thetas(self) -> tuple:
        """The fixed-effect coefficient vectors the scorer takes as
        arguments — one device array per fixed coordinate, in coordinate
        order. Passing them as arguments (not closures) is what lets N
        same-shape tenants dispatch ONE compiled program: same
        shape/dtype arguments re-dispatch with zero retraces, exactly
        the random-effect tables' calling convention."""
        return tuple(f.theta for f in self.fixed)

    def current_var_thetas(self) -> tuple:
        """Posterior-variance vectors for the "thompson" programs, one
        per fixed coordinate (zeros where the model carried none). Only
        meaningful when ``thompson_enabled``."""
        return tuple(f.var_theta for f in self.fixed)

    def current_var_tables(self) -> tuple:
        """Posterior-variance gather tables for the "thompson" programs,
        one per random coordinate, row-aligned with ``current_tables()``
        (thompson is full-resident only, so these are static device
        arrays — nearline publishes scatter into them like the mean
        tables). Only meaningful when ``thompson_enabled``."""
        return tuple(rs.var_coef for rs in self.random)

    def shape_signature(self) -> tuple:
        """Canonical shape signature: everything a scorer trace depends
        on EXCEPT the parameter values — feature-shard pads, fixed
        coordinate positions and theta shapes/dtypes, random-effect
        table shapes (two-tier hot capacity or full-resident rows),
        int8 mirrors, compute dtype, and mesh layout. Two models with
        equal signatures produce bitwise-identical traces, so compiled
        (mode, bucket) programs are keyed by this signature instead of
        ``model.token`` and shared across tenants. Stable for a model's
        lifetime: two-tier transfers swap table *objects* at fixed
        shape, and nearline appends spend pre-reserved rows."""
        if self._shape_sig is not None:
            return self._shape_sig

        def _dt(x) -> str:
            return np.dtype(getattr(x, "dtype", x)).name

        mesh_tok = None
        if self.mesh is not None:
            mesh_tok = (tuple(str(a) for a in self.mesh.axis_names),
                        tuple(int(s) for s in self.mesh.devices.shape),
                        tuple(int(d.id) for d in self.mesh.devices.flat))
        shard_pos = {sid: i for i, sid in enumerate(self.shard_order)}
        fixed_sig = tuple(
            (shard_pos[f.feature_shard_id],
             tuple(int(s) for s in f.theta.shape), _dt(f.theta))
            for f in self.fixed)
        rand_sig = []
        for rs in self.random:
            table = rs.store.table if rs.store is not None else rs.coef
            entry = (shard_pos[rs.feature_shard_id], int(rs.slot_width),
                     tuple(int(s) for s in table.shape), _dt(table),
                     rs.store is not None)
            if rs.coef_q is not None:
                entry += (tuple(int(s) for s in rs.coef_q.shape),
                          _dt(rs.coef_q),
                          tuple(int(s) for s in rs.scales.shape))
            rand_sig.append(entry)
        sig = (
            "servshape", _dt(self.dtype), int(self.int8_enabled), mesh_tok,
            tuple(int(self.shard_pad[sid]) for sid in self.shard_order),
            fixed_sig, tuple(rand_sig))
        if self.thompson_enabled:
            # appended ONLY when variance mirrors are staged: a var-less
            # (or thompson-off) model keeps its pre-thompson signature
            # bitwise, so its compiled programs and AOT bundles stay
            # shared with pre-variance builds
            sig = sig + (("thompson",
                          tuple((tuple(int(s) for s in f.var_theta.shape),
                                 _dt(f.var_theta)) for f in self.fixed),
                          tuple((tuple(int(s) for s in rs.var_coef.shape),
                                 _dt(rs.var_coef)) for rs in self.random)),)
        self._shape_sig = sig
        return self._shape_sig

    def prefetch_request(self, request: ScoreRequest,
                         skip: frozenset = frozenset()) -> None:
        """Admission lookahead: queue cold->hot promotion for every
        two-tier entity this request names. Non-blocking. ``skip`` holds
        ``(random_effect_type, entity_id)`` pairs currently mid-publish —
        prefetching one of those could promote a half-published cold row
        into the hot tier, so they are deferred to the next natural miss
        after the publish commits (see engine._prefetch_lookahead)."""
        for rs in self.random:
            if rs.store is None:
                continue
            re_id = request.entity_ids.get(rs.random_effect_type)
            if re_id is not None and \
                    (rs.random_effect_type, re_id) not in skip:
                rs.store.prefetch(re_id)

    def coeff_store_stats(self) -> Optional[dict]:
        stats = {rs.coordinate_id: rs.store.stats()
                 for rs in self.random if rs.store is not None}
        return stats or None

    def drain_prefetch(self, timeout_s: float = 10.0) -> bool:
        """Flush every store's pending promotions (tests / bench phase
        boundaries — never the scoring path)."""
        ok = True
        for rs in self.random:
            if rs.store is not None:
                ok = rs.store.drain_prefetch(timeout_s) and ok
        return ok

    def close_stores(self) -> None:
        for rs in self.random:
            if rs.store is not None:
                rs.store.close()

    # -- device placement ---------------------------------------------------

    @staticmethod
    def _placers(mesh):
        """(replicate, entity-shard) placement functions. Without a mesh
        (or with a trivial one) both are a plain device transfer."""
        import jax
        import jax.numpy as jnp

        if mesh is None:
            return jnp.asarray, jnp.asarray
        from jax.sharding import NamedSharding, PartitionSpec as P

        from photon_tpu.parallel.mesh import ENTITY_AXIS, pad_to_multiple

        axis = ENTITY_AXIS if ENTITY_AXIS in mesh.axis_names else None
        n_ent = dict(zip(mesh.axis_names, mesh.devices.shape)).get(axis, 1)

        def put_rep(a):
            return jax.device_put(a, NamedSharding(mesh, P()))

        def put_ent(a):
            if axis is None or n_ent <= 1:
                return put_rep(a)
            rows = pad_to_multiple(a.shape[0], n_ent)
            if rows != a.shape[0]:
                a = np.concatenate(
                    [a, np.zeros((rows - a.shape[0],) + a.shape[1:], a.dtype)])
            return jax.device_put(
                a, NamedSharding(mesh, P(axis, *([None] * (a.ndim - 1)))))

        return put_rep, put_ent

    # -- batch assembly (host) ----------------------------------------------

    def assemble(self, requests: Sequence[ScoreRequest], bucket: int,
                 shed_random: bool = False, explore_unknown: bool = False):
        """Pack <=bucket requests into the padded device arrays one scorer
        call consumes. Returns (args tuple, per-request fallback lists,
        counters dict). Pad rows beyond ``len(requests)`` carry zero
        features and the unknown-entity sentinel, so they score to their
        (zero) offset and are discarded by the engine.

        ``explore_unknown`` (thompson mode only): an unknown entity's
        request features are packed into its slot lanes against the
        unknown row — whose MEAN row is zero (no mean contribution, same
        score center as before) and whose VARIANCE row is the prior, so
        the thompson program draws prior-variance exploration noise for
        it. Typed EXPLORING_COLD_START instead of UNKNOWN_ENTITY."""
        n = len(requests)
        if n > bucket:
            raise ValueError(f"{n} requests > bucket {bucket}")
        fallbacks: List[List[Fallback]] = [[] for _ in range(n)]
        counters = {"unknown_features": 0, "truncated_features": 0,
                    "unknown_entities": 0, "cold_misses": 0,
                    "explored_cold_start": 0,
                    "padded_rows": bucket - n}

        offsets = np.zeros(bucket, np.float32)
        for i, r in enumerate(requests):
            offsets[i] = r.offset

        # per-shard global-column views, reused by every coordinate below
        shard_cols: Dict[str, List[np.ndarray]] = {}
        shard_vals: Dict[str, List[np.ndarray]] = {}
        for sid in self.shard_order:
            imap = self.index_maps[sid]
            cols_l, vals_l = [], []
            for i, r in enumerate(requests):
                feats = r.features.get(sid) or ()
                cols = np.fromiter(
                    (imap.index_of(name, term) for name, term, _ in feats),
                    np.int64, count=len(feats))
                vals = np.fromiter((v for _, _, v in feats), np.float64,
                                   count=len(feats))
                keep = cols >= 0
                dropped = int(len(cols) - keep.sum())
                if dropped:
                    counters["unknown_features"] += dropped
                    cols, vals = cols[keep], vals[keep]
                pad = self.shard_pad[sid]
                if len(cols) > pad:
                    counters["truncated_features"] += len(cols) - pad
                    fallbacks[i].append(Fallback(
                        FallbackReason.FEATURE_OVERFLOW, coordinate=sid,
                        detail=f"{len(cols)} features > pad {pad}"))
                    cols, vals = cols[:pad], vals[:pad]
                cols_l.append(cols)
                vals_l.append(vals)
            shard_cols[sid] = cols_l
            shard_vals[sid] = vals_l

        fixed_idx, fixed_val = [], []
        for sid in self.shard_order:
            pad = self.shard_pad[sid]
            idx = np.zeros((bucket, pad), np.int32)
            val = np.zeros((bucket, pad), np.float32)
            for i in range(n):
                c, v = shard_cols[sid][i], shard_vals[sid][i]
                idx[i, :len(c)] = c
                val[i, :len(c)] = v
            fixed_idx.append(idx)
            fixed_val.append(val)

        re_slot_idx, re_slot_val, re_ent = [], [], []
        for rs in self.random:
            ent = np.full(bucket, rs.unknown_row, np.int32)
            sidx = np.zeros((bucket, rs.slot_width), np.int32)
            sval = np.zeros((bucket, rs.slot_width), np.float32)
            if not shed_random and rs.store is not None:
                from photon_tpu.serving import coeff_store as _cs

                for i, r in enumerate(requests):
                    re_id = r.entity_ids.get(rs.random_effect_type)
                    if re_id is None:
                        counters["unknown_entities"] += 1
                        fallbacks[i].append(Fallback(
                            FallbackReason.UNKNOWN_ENTITY,
                            coordinate=rs.coordinate_id, detail="None"))
                        continue
                    slot, status = rs.store.lookup_locked(re_id)
                    if status == _cs.UNKNOWN:
                        counters["unknown_entities"] += 1
                        fallbacks[i].append(Fallback(
                            FallbackReason.UNKNOWN_ENTITY,
                            coordinate=rs.coordinate_id, detail=re_id))
                        continue
                    if status == _cs.COLD:
                        # rows still in the cold tier at pop time: typed
                        # degradation (the zero row scores this request
                        # fixed-effect-only for this coordinate); the
                        # lookup already queued the promotion
                        counters["cold_misses"] += 1
                        fallbacks[i].append(Fallback(
                            FallbackReason.COLD_MISS,
                            coordinate=rs.coordinate_id, detail=re_id))
                        continue
                    ent[i] = slot
                    cols = shard_cols[rs.feature_shard_id][i]
                    if not len(cols):
                        continue
                    # replay project_for_scoring against the hot slot's
                    # projection row (ascending global cols, -1 pad), a
                    # host mirror — the cold mmap is never touched here
                    prow = rs.store.proj_row_locked(slot)
                    pvalid = prow[prow >= 0]
                    if not len(pvalid):
                        continue
                    rank = np.searchsorted(pvalid, cols)
                    rank = np.minimum(rank, len(pvalid) - 1)
                    kept = pvalid[rank] == cols
                    k = int(kept.sum())
                    sidx[i, :k] = rank[kept]
                    sval[i, :k] = shard_vals[rs.feature_shard_id][i][kept]
            elif not shed_random:
                D = max(self.shard_dims.get(rs.feature_shard_id, 1), 1)
                for i, r in enumerate(requests):
                    re_id = r.entity_ids.get(rs.random_effect_type)
                    e = rs.entity_rows.get(re_id) if re_id is not None else None
                    if e is None:
                        if explore_unknown:
                            # cold-start exploration: pack this request's
                            # shard features into slots 0..k against the
                            # unknown row (zero mean, prior variance) —
                            # the slot ORDER is immaterial because every
                            # slot of that row shares the prior
                            counters["explored_cold_start"] += 1
                            fallbacks[i].append(Fallback(
                                FallbackReason.EXPLORING_COLD_START,
                                coordinate=rs.coordinate_id,
                                detail=str(re_id)))
                            cvals = shard_vals[rs.feature_shard_id][i]
                            k = min(len(cvals), rs.slot_width)
                            if k:
                                sidx[i, :k] = np.arange(k)
                                sval[i, :k] = cvals[:k]
                            continue
                        counters["unknown_entities"] += 1
                        fallbacks[i].append(Fallback(
                            FallbackReason.UNKNOWN_ENTITY,
                            coordinate=rs.coordinate_id,
                            detail=str(re_id)))
                        continue
                    ent[i] = e
                    cols = shard_cols[rs.feature_shard_id][i]
                    if not len(cols) or not len(rs.pkeys_sorted):
                        continue
                    # replay project_for_scoring: (e, g) -> local slot via
                    # binary search over the load-time sorted key table
                    keys = e * D + cols
                    rank = np.searchsorted(rs.pkeys_sorted, keys)
                    rank = np.minimum(rank, len(rs.pkeys_sorted) - 1)
                    kept = rs.pkeys_sorted[rank] == keys
                    k = int(kept.sum())
                    sidx[i, :k] = rs.pslots_sorted[rank[kept]]
                    sval[i, :k] = shard_vals[rs.feature_shard_id][i][kept]
            re_slot_idx.append(sidx)
            re_slot_val.append(sval)
            re_ent.append(ent)

        args = (tuple(fixed_idx), tuple(fixed_val), tuple(re_slot_idx),
                tuple(re_slot_val), tuple(re_ent), offsets)
        return args, fallbacks, counters

    def dummy_args(self, bucket: int):
        """Zero-filled arrays of the exact shapes/dtypes ``assemble``
        produces for this bucket — warmup dispatches these so steady-state
        calls hit the identical compiled program."""
        args, _, _ = self.assemble([], bucket)
        return args

    def describe(self) -> dict:
        return {
            "task": self.task.value,
            "fixed": [{"coordinate": f.coordinate_id,
                       "shard": f.feature_shard_id,
                       "dim": int(self.shard_dims.get(f.feature_shard_id, 0))}
                      for f in self.fixed],
            "random": [{"coordinate": r.coordinate_id,
                        "type": r.random_effect_type,
                        "shard": r.feature_shard_id,
                        "entities": r.num_entities,
                        "slot_width": r.slot_width,
                        "two_tier": r.store is not None,
                        **({"hot_capacity": r.store.capacity}
                           if r.store is not None else {})}
                       for r in self.random],
            "shard_pad": dict(self.shard_pad),
            "entity_sharded": self.mesh is not None,
            "int8": self.int8_enabled,
            "thompson": self.thompson_enabled,
        }
